#!/usr/bin/env python
"""Render slow-request trace dumps and flight-recorder dumps.

The serving app writes one JSON file per over-threshold request
(``telemetry.slow-request-ms`` / ``slow-request-dir``); this renders
them human-readable::

    python scripts/trace_report.py slow-traces/3f2a... .json
    python scripts/trace_report.py slow-traces/          # newest N
    python scripts/trace_report.py --limit 3 slow-traces/
    python scripts/trace_report.py flight-recorder/flight-*.json

Each span prints its offset from the request start, its duration, and a
proportional bar, so "where did 2.6 s go?" is answered by eye: a wide
``wire.fetch`` bar is link weather, a wide ``batcher.queueWait`` bar is
backlog, a wide first-request ``Renderer.renderAsPackedInt.batch`` bar
with a compile-event bump on /metrics is a missed prewarm shape.  A
trace that carries a cost ledger prints it under the waterfall (the
attribution the access log and /debug/costs record).

Flight-recorder dumps (``{"flight_recorder": true, "events": [...]}``
— written on SIGTERM, SLO breach, or /debug/flightrecorder?dump=1)
render as an event timeline instead: seconds-before-dump offsets, one
event per line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BAR_WIDTH = 40


def _load_bundle(path):
    """A sentinel incident bundle directory -> one renderable doc:
    the manifest plus whichever artifacts parse (best-effort — the
    manifest's presence IS the bundle-complete signal, individual
    artifacts may be null)."""
    try:
        with open(os.path.join(path, "manifest.json")) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError):
        return None
    if manifest.get("kind") != "sentinel_incident":
        return None
    doc = {"sentinel_bundle": True, "path": path,
           "manifest": manifest}
    for key in ("sketch_diff", "flight", "costs"):
        fname = (manifest.get("files") or {}).get(key)
        if not fname:
            continue
        try:
            with open(os.path.join(path, fname)) as fh:
                doc[key] = json.load(fh)
        except (OSError, ValueError):
            pass
    return doc


def load_traces(paths, limit):
    files = []
    bundles = []
    for p in paths:
        if os.path.isdir(p):
            bundle = _load_bundle(p)
            if bundle is not None:
                bundles.append(bundle)
                continue
            files += [os.path.join(p, f) for f in os.listdir(p)
                      if f.endswith(".json")]
        else:
            files.append(p)
    files.sort(key=lambda f: os.path.getmtime(f), reverse=True)
    docs = list(bundles)
    for f in files[:limit]:
        try:
            with open(f) as fh:
                docs.append(json.load(fh))
        except (OSError, ValueError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
    return docs


def render_trace(doc) -> str:
    """One request's waterfall.  Multi-member traces (fleet hops /
    sidecar spans carrying a ``member`` dimension) gain a per-hop
    LANE column: every span line names the member whose process ran
    it, fleet hops print as ``hop:member`` markers, and a footer sums
    per-member time — the stitched cross-member story at a glance."""
    total = float(doc.get("total_ms") or max(
        (s["start_ms"] + s["dur_ms"] for s in doc.get("spans", ())),
        default=1.0))
    total = max(total, 1e-6)
    ts = doc.get("ts")
    when = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
            if ts else "?")
    spans = sorted(doc.get("spans", ()), key=lambda s: s["start_ms"])
    members = []
    hosts = []
    for s in spans:
        m = s.get("member")
        if m and m not in members:
            members.append(m)
        h = s.get("host")
        if h and h not in hosts:
            hosts.append(h)
    lane_w = max([len(m) for m in members] + [4]) if members else 0
    head = (f"trace {doc.get('trace_id', '?')}  route="
            f"{doc.get('route', '?')}  status={doc.get('status', '?')}"
            f"  total={total:.1f} ms  at {when}")
    if members:
        head += f"  members={','.join(members)}"
    lane_head = f"{'lane':<{lane_w}}  " if members else ""
    lines = [
        head,
        f"  {'start':>9}  {'dur':>9}  {lane_head}"
        f"{'waterfall':<{BAR_WIDTH}}  span",
    ]
    member_ms = {}
    host_ms = {}
    for s in spans:
        x0 = int(BAR_WIDTH * max(s["start_ms"], 0.0) / total)
        x1 = int(BAR_WIDTH * min(s["start_ms"] + s["dur_ms"], total)
                 / total)
        x0 = min(x0, BAR_WIDTH - 1)
        bar = (" " * x0 + "#" * max(x1 - x0, 1)).ljust(BAR_WIDTH)
        extra = {k: v for k, v in s.items()
                 if k not in ("name", "start_ms", "dur_ms", "member")}
        name = s["name"]
        member = s.get("member", "")
        host = s.get("host", "")
        if name == "fleet.hop":
            # Hop markers read as their own vocabulary: hop:member.
            name = f"hop:{extra.pop('hop', '?')}"
        elif name == "fed.hop":
            # Cross-host federation hops: fed:kind@host — the wire
            # exchange (and its clock-anchored remote graft) named by
            # what crossed and which host it landed on.
            extra.pop("host", None)
            name = f"fed:{extra.pop('kind', '?')}@{host or '?'}"
        if member:
            member_ms[member] = member_ms.get(member, 0.0) \
                + float(s["dur_ms"])
        if host:
            host_ms[host] = host_ms.get(host, 0.0) \
                + float(s["dur_ms"])
        suffix = f"  {extra}" if extra else ""
        lane = f"{member:<{lane_w}}  " if members else ""
        lines.append(f"  {s['start_ms']:>8.1f}m {s['dur_ms']:>8.1f}m  "
                     f"{lane}{bar}  {name}{suffix}")
    if len(members) > 1:
        pretty = "  ".join(f"{m}={member_ms.get(m, 0.0):.1f}ms"
                           for m in members)
        lines.append(f"  members: {pretty}")
    if hosts:
        # Per-HOST time footer (the multi-host stitched story): every
        # span carrying a ``host`` dimension — fed.hop exchanges and
        # remote-anchored grafts — summed by the host it names.
        pretty = "  ".join(f"{h}={host_ms.get(h, 0.0):.1f}ms"
                           for h in hosts)
        lines.append(f"  hosts: {pretty}")
    cost = doc.get("cost")
    if cost:
        pretty = "  ".join(
            f"{k}={cost[k]:g}" for k in sorted(cost))
        lines.append(f"  cost: {pretty}")
    prov = doc.get("prov")
    if prov:
        pretty = "  ".join(f"{k}={prov[k]}" for k in sorted(prov))
        lines.append(f"  provenance: {pretty}")
    return "\n".join(lines)


# Self-preservation event kinds (pressure governor, watchdog, rolling
# drains): flagged on the timeline and rolled into a summary footer so
# a post-incident dump answers "what did the service DO about it"
# at a glance.
_ROBUSTNESS_KINDS = ("pressure.level", "pressure.step",
                     "watchdog.fire", "watchdog.escalate",
                     "drain.phase", "autoscale.up", "autoscale.down",
                     "autoscale.blocked",
                     # Partition-tolerant control plane: quorum
                     # fence/restore transitions and the two-phase
                     # epoch roll — the netsplit half of the
                     # degrade-by-choice story.
                     "quorum.fence", "quorum.restore",
                     "epoch.propose", "epoch.commit",
                     # Perf sentinel: confirmed drift, its forensic
                     # capture, and the all-clear — the live
                     # regression story on the same timeline the
                     # incident's other events tell theirs.
                     "sentinel.drift", "sentinel.capture",
                     "sentinel.recovered")

# Session-serving event kinds (per-session fairness sheds, viewport
# predictions, pressure-scaled prefetch budget moves): marked with
# ``*`` and rolled into their own footer so a dump answers "who was
# shed and what did prefetch do" alongside the robustness story.
_SESSION_KINDS = ("qos.shed", "prefetch.predict", "prefetch.budget")

# Device-workload event kinds (background pyramid job lifecycle,
# animation streams): marked with ``~`` and summed into their own
# footer so a dump answers "what batch/stream work was in flight"
# next to the interactive-serving story.
_WORKLOAD_KINDS = ("pyramid.submit", "pyramid.level",
                   "pyramid.deferred", "pyramid.done",
                   "animation.stream", "animation.cancelled")

# Control-plane decision records (utils.decisions): every ledger
# append mirrors onto the flight ring as ``decision.<kind>`` — flagged
# and summed separately so a dump answers "what did the control plane
# DECIDE" next to what the data plane did about it.
_DECISION_PREFIX = "decision."


def render_flight(doc) -> str:
    """Flight-recorder dump -> event timeline (newest events last,
    offsets in seconds before the dump instant).  Self-preservation
    events (ladder steps, watchdog fires, drain phases) are marked
    with ``!`` and summarized under the timeline — the
    degrade-by-choice story of the incident."""
    events = doc.get("events", ())
    t_dump = float(doc.get("ts") or (events[-1]["ts"] if events
                                     else 0.0))
    lines = [
        f"flight recorder  reason={doc.get('reason', '?')}  "
        f"pid={doc.get('pid', '?')}  events={len(events)}",
        f"  {'t-dump':>9}  event",
    ]
    rob_counts: dict = {}
    session_counts: dict = {}
    workload_counts: dict = {}
    decision_counts: dict = {}
    member_counts: dict = {}
    for e in events:
        kind = e.get("kind", "?")
        if e.get("member"):
            member_counts[e["member"]] = \
                member_counts.get(e["member"], 0) + 1
        extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
        suffix = ("  " + " ".join(f"{k}={v}" for k, v in
                                  sorted(extra.items()))
                  if extra else "")
        offset = float(e.get("ts", t_dump)) - t_dump
        mark = ("!" if kind in _ROBUSTNESS_KINDS
                else "*" if kind in _SESSION_KINDS
                else "~" if kind in _WORKLOAD_KINDS
                else "+" if kind.startswith(_DECISION_PREFIX)
                else " ")
        if kind in _ROBUSTNESS_KINDS:
            label = kind
            if kind == "pressure.step":
                label = (f"pressure.step:{e.get('action', '?')}"
                         f":{e.get('step', '?')}")
            elif kind == "watchdog.fire":
                label = f"watchdog.fire:{e.get('action', '?')}"
            elif kind == "drain.phase":
                label = f"drain:{e.get('phase', '?')}"
            elif kind == "autoscale.blocked":
                label = f"autoscale.blocked:{e.get('reason', '?')}"
            elif kind in ("autoscale.up", "autoscale.down"):
                label = f"{kind}:{e.get('member', '?')}"
            elif kind in ("quorum.fence", "quorum.restore"):
                label = (f"{kind}:{e.get('reachable', '?')}"
                         f"/{e.get('hosts', '?')}")
            elif kind in ("epoch.propose", "epoch.commit"):
                label = f"{kind}:v{e.get('epoch', '?')}"
            elif kind == "sentinel.drift":
                keys = e.get("keys")
                label = (f"sentinel.drift:{','.join(keys)}"
                         if isinstance(keys, list) and keys
                         else "sentinel.drift")
            elif kind == "sentinel.capture":
                label = f"sentinel.capture:{e.get('dir', '?')}"
            rob_counts[label] = rob_counts.get(label, 0) + 1
        elif kind in _SESSION_KINDS:
            label = kind
            if kind == "qos.shed":
                label = f"qos.shed:{e.get('cls', '?')}"
            elif kind == "prefetch.budget":
                label = f"prefetch.budget:{e.get('scale', '?')}"
            session_counts[label] = session_counts.get(label, 0) + 1
        elif kind in _WORKLOAD_KINDS:
            label = kind
            if kind == "pyramid.level":
                label = (f"pyramid.level:{e.get('level', '?')}"
                         f"/{e.get('of', '?')}")
            elif kind == "pyramid.done":
                label = f"pyramid.done:{e.get('levels', '?')}lvl"
            elif kind == "animation.stream":
                label = f"animation.stream:{e.get('frames', '?')}f"
            elif kind == "animation.cancelled":
                label = (f"animation.cancelled:{e.get('served', '?')}"
                         f"/{e.get('cancelled', '?')}")
            workload_counts[label] = workload_counts.get(label, 0) + 1
        elif kind.startswith(_DECISION_PREFIX):
            label = f"{kind}:{e.get('verdict', '?')}"
            decision_counts[label] = decision_counts.get(label, 0) + 1
        lines.append(f"  {offset:>8.2f}s {mark} {kind}{suffix}")
    if rob_counts:
        pretty = "  ".join(f"{k}={v}" for k, v in
                           sorted(rob_counts.items()))
        lines.append(f"  self-preservation: {pretty}")
    if session_counts:
        pretty = "  ".join(f"{k}={v}" for k, v in
                           sorted(session_counts.items()))
        lines.append(f"  session-serving: {pretty}")
    if workload_counts:
        pretty = "  ".join(f"{k}={v}" for k, v in
                           sorted(workload_counts.items()))
        lines.append(f"  device-workloads: {pretty}")
    if decision_counts:
        pretty = "  ".join(f"{k}={v}" for k, v in
                           sorted(decision_counts.items()))
        lines.append(f"  control-plane: {pretty}")
    if member_counts:
        # Fleet identity footer: a merged fleet ring (or a member-
        # stamped process ring) sums its events per member, so a
        # post-incident dump answers "whose last seconds are these".
        pretty = "  ".join(f"{k}={v}" for k, v in
                           sorted(member_counts.items()))
        lines.append(f"  members: {pretty}")
    return "\n".join(lines)


def _is_stats_table(doc) -> bool:
    """A per-stage stats mapping: {span: {count, mean_ms, ...}} — the
    bench record's ``service_waterfall`` export, or that record itself."""
    if not isinstance(doc, dict) or not doc:
        return False
    if "service_waterfall" in doc:
        return True
    return all(isinstance(v, dict) and "count" in v and "mean_ms" in v
               for v in doc.values())


def render_stats(doc) -> str:
    """Per-stage stats table with the tail breakdown: mean vs p50 vs
    p95/p99/max per stage, so heavy-tail queueing (BENCH_r05:
    batcher.queueWait mean 2276 ms, p50 2.2 ms) is visible per stage
    instead of hidden in the mean."""
    stats = doc.get("service_waterfall", doc)
    lines = [
        "stage waterfall (per-span stats)",
        f"  {'span':<36} {'count':>7} {'mean':>9} {'p50':>9} "
        f"{'p95':>9} {'p99':>9} {'max':>9}",
    ]
    for name in sorted(stats):
        s = stats[name]
        if not isinstance(s, dict) or "count" not in s:
            continue

        def col(key):
            v = s.get(key)
            return f"{v:>8.1f}m" if isinstance(v, (int, float)) else \
                f"{'-':>9}"

        lines.append(
            f"  {name:<36} {s.get('count', 0):>7} {col('mean_ms')} "
            f"{col('p50_ms')} {col('p95_ms')} {col('p99_ms')} "
            f"{col('max_ms')}")
        mean, p50 = s.get("mean_ms"), s.get("p50_ms")
        if (isinstance(mean, (int, float)) and isinstance(p50,
                                                          (int, float))
                and p50 > 0 and mean > 10 * p50 and mean > 50.0):
            lines.append(f"  {'':<36} ^ heavy tail: mean {mean:.0f} ms "
                         f"is {mean / p50:.0f}x p50 — see p95/p99/max")
    return "\n".join(lines)


def render_bundle(doc) -> str:
    """Sentinel incident bundle -> the drifted-quantile summary
    (live vs baseline per key, worst first) above the bundle's own
    flight timeline — "how far off normal, and what was the service
    doing" in one read."""
    manifest = doc.get("manifest", {})
    lines = [
        f"sentinel incident  member={manifest.get('member', '?')}  "
        f"at={time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(manifest.get('ts', 0)))}"
        f"  dir={doc.get('path', '?')}",
    ]
    drifting = manifest.get("drifting") or []
    if drifting:
        lines.append(f"  drifting: {', '.join(drifting)}")
    if manifest.get("throughput_drift"):
        lines.append(
            f"  throughput: {manifest.get('tiles_per_s', '?')} "
            f"tiles/s against watermark "
            f"{manifest.get('watermark_tiles_per_s', '?')}")
    keys = (doc.get("sketch_diff") or {}).get("keys") or {}
    if keys:
        lines.append(
            f"  {'key':<34} {'n':>6} {'p50':>9} {'p99':>9} "
            f"{'base p50':>9} {'base p99':>9}  drift")

        def _ratio(state):
            p99 = state.get("p99_ms")
            base = state.get("baseline_p99_ms")
            if isinstance(p99, (int, float)) \
                    and isinstance(base, (int, float)) and base > 0:
                return p99 / base
            return 0.0

        def col(v):
            return (f"{v:>8.1f}m"
                    if isinstance(v, (int, float)) else f"{'-':>9}")

        for key in sorted(
                keys, key=lambda k: -_ratio(keys[k].get("state")
                                            or {})):
            st = keys[key].get("state") or {}
            ratio = _ratio(st)
            tail = (f"{ratio:.2f}x" if ratio else "-") \
                + ("  <-- DRIFTING" if st.get("drifting") else "")
            lines.append(
                f"  {key:<34} {st.get('n', 0):>6} "
                f"{col(st.get('p50_ms'))} {col(st.get('p99_ms'))} "
                f"{col(st.get('baseline_p50_ms'))} "
                f"{col(st.get('baseline_p99_ms'))}  {tail}")
    files = manifest.get("files") or {}
    present = sorted(k for k, v in files.items() if v)
    absent = sorted(k for k, v in files.items() if not v)
    lines.append(f"  artifacts: {', '.join(present) or 'none'}"
                 + (f"  (absent: {', '.join(absent)})" if absent
                    else ""))
    flight = doc.get("flight")
    if isinstance(flight, dict) and flight.get("events"):
        lines.append("")
        lines.append(render_flight(flight))
    return "\n".join(lines)


def render_doc(doc) -> str:
    if doc.get("sentinel_bundle"):
        return render_bundle(doc)
    if doc.get("flight_recorder"):
        return render_flight(doc)
    if _is_stats_table(doc):
        return render_stats(doc)
    return render_trace(doc)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Render slow-request trace dumps as waterfalls "
                    "and flight-recorder dumps as event timelines")
    parser.add_argument("paths", nargs="+",
                        help="dump file(s) or spool directory")
    parser.add_argument("--limit", type=int, default=5,
                        help="newest N traces when given a directory "
                             "(default 5)")
    args = parser.parse_args(argv)
    docs = load_traces(args.paths, args.limit)
    if not docs:
        print("no trace dumps found", file=sys.stderr)
        return 1
    print("\n\n".join(render_doc(d) for d in docs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
