"""Component-level timing of the flagship JPEG path on the real chip.

Breaks one batch of the config-3 workload into stages and times each:
dispatch+device compute, wire fetch (prefetched and cold), host entropy
encode — plus wire-compressibility probes (zeros vs noise payloads of the
same shape) to see whether the tunnel collapses the sparse buffers' zero
tails.  Not part of the bench; a diagnostic for optimization work.
"""

import statistics
import time

import numpy as np

from omero_ms_image_region_tpu.flagship import (
    batched_args, flagship_settings, synthetic_wsi_tiles,
)
from omero_ms_image_region_tpu.ops.jpegenc import (
    default_sparse_cap, encode_sparse_buffers, quant_tables,
    render_to_jpeg_sparse,
)

import jax
import jax.numpy as jnp


def t(fn, n=5):
    fn()
    xs = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        xs.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(xs), min(xs)


def main():
    rng = np.random.default_rng(7)
    B, C, H, W = 8, 4, 1024, 1024
    quality = 85
    cap = default_sparse_cap(H, W)
    _, settings = flagship_settings()
    raw = synthetic_wsi_tiles(rng, B, C, H, W)
    args_suffix = batched_args(settings, raw)[1:]
    qy, qc = (tt.astype(np.int32) for tt in quant_tables(quality))
    dev_raw = jax.device_put(raw)
    jax.block_until_ready(dev_raw)

    buf = render_to_jpeg_sparse(dev_raw, *args_suffix, qy, qc, cap=cap)
    buf.block_until_ready()
    host = np.asarray(buf)
    print("wire buffer shape/bytes per batch:", buf.shape, buf.nbytes)
    nb = (H // 8) * (W // 8) + 2 * (H // 16) * (W // 16)
    totals = host[:, :4].copy().view(np.int32).ravel()
    print("per-tile nonzero entries:", totals.tolist(), "cap:", cap)

    # 1. dispatch + device compute + implicit sync via tiny fetch
    def dispatch_sync():
        b = render_to_jpeg_sparse(dev_raw, *args_suffix, qy, qc, cap=cap)
        np.asarray(b[0, :4])  # sync on 4 bytes
    print("dispatch+device (tiny fetch sync): %.1f / %.1f ms" % t(dispatch_sync))

    # 2. full fetch after async prefetch
    def fetch_prefetched():
        b = render_to_jpeg_sparse(dev_raw, *args_suffix, qy, qc, cap=cap)
        b.copy_to_host_async()
        return b
    b = fetch_prefetched()
    time.sleep(1.0)
    t0 = time.perf_counter()
    host = np.asarray(b)
    print("np.asarray after 1s-old prefetch: %.1f ms" % ((time.perf_counter() - t0) * 1e3))

    def fetch_cold():
        b = render_to_jpeg_sparse(dev_raw, *args_suffix, qy, qc, cap=cap)
        np.asarray(b)
    print("dispatch+full fetch (no prefetch gap): %.1f / %.1f ms" % t(fetch_cold))

    # 3. host entropy encode only
    def encode_only():
        encode_sparse_buffers(host, W, H, quality, cap)
    print("host encode (serial): %.1f / %.1f ms" % t(encode_only))
    import concurrent.futures as cf
    pool = cf.ThreadPoolExecutor(max_workers=8)
    def encode_pool():
        encode_sparse_buffers(host, W, H, quality, cap, executor=pool)
    print("host encode (8 threads): %.1f / %.1f ms" % t(encode_pool))

    # 4. wire compressibility probe: same nbytes, zeros vs random
    nbytes = buf.nbytes
    zeros = jnp.zeros((nbytes,), jnp.uint8)
    noise = jax.device_put(
        np.random.default_rng(0).integers(0, 255, nbytes, dtype=np.uint8))
    jax.block_until_ready([zeros, noise])
    def fz():
        np.asarray(zeros + jnp.uint8(0))
    def fn_():
        np.asarray(noise + jnp.uint8(0))
    print("fetch %d MB zeros: %.1f / %.1f ms" % ((nbytes // 1_000_000,) + t(fz)))
    print("fetch %d MB noise: %.1f / %.1f ms" % ((nbytes // 1_000_000,) + t(fn_)))

    # 5. fetch size sweep (latency floor + bandwidth)
    for mb in (0.01, 0.1, 1, 4, 16):
        n = int(mb * 1e6)
        a = jax.device_put(np.zeros(n, np.uint8))
        jax.block_until_ready(a)
        med, best = t(lambda a=a: np.asarray(a[:]), n=3)
        print("fetch %6.2f MB (device zeros): %.1f ms -> %.1f MB/s"
              % (mb, med, n / 1e6 / (med / 1e3)))


if __name__ == "__main__":
    main()
