#!/usr/bin/env python
"""Bench regression gate: diff a pair of BENCH_r*.json records and
exit non-zero on a service-rate regression, so the round-over-round
trajectory becomes a GATE instead of a log entry someone may read.

Usage::

    python scripts/bench_gate.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_gate.py --dir .          # newest pair by name
    python scripts/bench_gate.py --key service_tiles_per_sec \
        --max-regression 0.10 old.json new.json

Exit codes: 0 pass (or nothing to judge — see --strict), 1 regression
over the threshold, 2 usage/input error.

The default keys are the full-HTTP-stack service rate AND its p50
latency ex-RTT (latency regressions must not hide behind a flat
throughput headline; ``_ms`` keys are judged in the opposite
direction — up is the regression).  Tunnel weather can null either
out for a round, so an absent/None value SKIPS that key's gate (with
a printed verdict) rather than failing the build — ``--strict`` turns
skips into failures for CI postures that must always measure.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

DEFAULT_KEYS = ("service_tiles_per_sec", "p50_service_tile_ms_ex_rtt")
_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def lower_is_better(key: str) -> bool:
    """Latency keys regress UPWARD — without direction awareness a
    latency regression would read as an improvement (and a flat
    throughput headline could hide it entirely)."""
    return key.endswith("_ms") or "_ms_" in key


def load_record(path: str) -> dict:
    """One bench record: a JSON object, or the last JSON line of the
    file (bench.py prints ONE line; drivers may append logs)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: no JSON object found")
    return doc


def newest_pair(directory: str):
    """The two highest-numbered BENCH_r*.json records in ``directory``
    (old, new) — the pair the driver's latest round produced."""
    rounds = []
    for name in os.listdir(directory):
        m = _BENCH_RE.match(name)
        if m:
            rounds.append((int(m.group(1)),
                           os.path.join(directory, name)))
    rounds.sort()
    if len(rounds) < 2:
        raise ValueError(
            f"{directory}: need at least two BENCH_r*.json records, "
            f"found {len(rounds)}")
    return rounds[-2][1], rounds[-1][1]


def judge(old: dict, new: dict, keys, max_regression: float):
    """Per-key verdicts: ``pass`` / ``regression`` / ``skipped``
    (value absent or null on either side — congestion weather)."""
    verdicts = []
    for key in keys:
        v_old, v_new = old.get(key), new.get(key)
        if not isinstance(v_old, (int, float)) \
                or not isinstance(v_new, (int, float)) or v_old <= 0:
            verdicts.append({"key": key, "verdict": "skipped",
                             "old": v_old, "new": v_new})
            continue
        change = (v_new - v_old) / v_old
        # Inclusive: a dead-on 10% move against the default threshold
        # is a failure, not a float-equality pass.  Direction depends
        # on the key: throughput regresses down, latency regresses up.
        if lower_is_better(key):
            verdict = ("regression" if change >= max_regression
                       else "pass")
        else:
            verdict = ("regression" if change <= -max_regression
                       else "pass")
        verdicts.append({"key": key, "verdict": verdict,
                         "old": round(float(v_old), 2),
                         "new": round(float(v_new), 2),
                         "change": round(change, 4)})
    return verdicts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Fail on a bench-record service-rate regression")
    parser.add_argument("paths", nargs="*",
                        help="old.json new.json (in that order)")
    parser.add_argument("--dir",
                        help="scan for the newest BENCH_r*.json pair")
    parser.add_argument("--key", action="append", default=None,
                        help="record key(s) to judge (default "
                             "service_tiles_per_sec)")
    parser.add_argument("--max-regression", type=float, default=0.10,
                        help="fail when new < old by this fraction or "
                             "more (default 0.10)")
    parser.add_argument("--strict", action="store_true",
                        help="treat skipped (absent/null) keys as "
                             "failures")
    args = parser.parse_args(argv)

    try:
        if args.dir:
            old_path, new_path = newest_pair(args.dir)
        elif len(args.paths) == 2:
            old_path, new_path = args.paths
        else:
            parser.error("give exactly two record paths, or --dir")
        old, new = load_record(old_path), load_record(new_path)
    except (OSError, ValueError) as e:
        print(json.dumps({"gate": "bench", "error": str(e)}))
        return 2

    keys = tuple(args.key) if args.key else DEFAULT_KEYS
    verdicts = judge(old, new, keys, args.max_regression)
    regressed = [v for v in verdicts if v["verdict"] == "regression"]
    skipped = [v for v in verdicts if v["verdict"] == "skipped"]
    failed = bool(regressed) or (args.strict and bool(skipped))
    print(json.dumps({
        "gate": "bench",
        "old": os.path.basename(old_path),
        "new": os.path.basename(new_path),
        "max_regression": args.max_regression,
        "verdict": "fail" if failed else "pass",
        "keys": verdicts,
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
