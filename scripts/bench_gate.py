#!/usr/bin/env python
"""Bench regression gate: judge BENCH_r*.json records and exit
non-zero on a service-rate regression, so the round-over-round
trajectory becomes a GATE instead of a log entry someone may read.

Two modes:

* **Pairwise** (default) — diff the newest record against the previous
  one.  Catches step regressions, but a -10% drift per round compounds
  to -37% over four rounds without ever tripping a pairwise gate —
  which is exactly what BENCH_r02 -> r05 did (41 -> 26 tiles/s).
* **Watermark** (``--watermark``) — gate the newest record against the
  BEST value each key ever recorded across every earlier
  ``BENCH_r*.json`` (max for throughput keys, min for ``_ms`` latency
  keys).  Slow-burn regressions cannot hide: the gate re-anchors to
  the best round, not the latest.

Usage::

    python scripts/bench_gate.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_gate.py --dir .              # newest pair
    python scripts/bench_gate.py --watermark --dir .  # newest vs best
    python scripts/bench_gate.py --key service_tiles_per_sec \
        --max-regression 0.10 old.json new.json

Exit codes: 0 pass (or nothing to judge — see --strict), 1 regression
over the threshold, 2 usage/input error.

The default keys are the full-HTTP-stack service rate, its p50 latency
ex-RTT (latency regressions must not hide behind a flat throughput
headline; ``_ms`` keys are judged in the opposite direction — up is
the regression) AND the raw host->HBM upload rate (the r01 -> r05
524 -> 4.8 MB/s collapse shipped in pieces no pairwise service-rate
gate could see).  Tunnel weather can null any of them out for a round,
so an absent/None value SKIPS that key's gate (with a printed verdict)
rather than failing the build — ``--strict`` turns skips into failures
for CI postures that must always measure.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

DEFAULT_KEYS = ("service_tiles_per_sec", "p50_service_tile_ms_ex_rtt",
                "raw_upload_mb_per_sec", "p50_first_tile_byte_ms")
# --multichip: judge MULTICHIP_r*.json records on the fleet scaling
# curve (__graft_entry__.fleet_scaling_curve prints it into the
# driver's tail).  Rounds that predate the curve — every record that
# only said `ok: true` — skip on null instead of failing.  The
# multi-PROCESS federated keys (bench.py --smoke --federation: real
# spawned sidecar processes behind an agreed manifest) joined the
# family in PR 15 — rounds that predate them skip on null the same
# way, so in-process-only history keeps judging.  PR 16 added the
# control-plane forensics keys (``fed_trace_stitched`` — the
# two-process waterfall stitched with per-host clock anchoring —
# and ``decision_records`` — autoscaler ledger verdicts carrying
# measured outcomes); both skip on null for older rounds too.
MULTICHIP_KEYS = ("fleet_tiles_per_sec_m8", "fleet_tiles_per_sec_m4",
                  "fleet_scaling_efficiency",
                  "fed_tiles_per_sec_p2",
                  "fed_process_scaling_efficiency",
                  "fed_trace_stitched",
                  "decision_records")
# --sessions: judge SESSIONS_r*.json records (bench.py --smoke
# --sessions) on the multi-user serving keys.  Direction-aware by
# name: the per-session p99 is a ``_ms`` key (regresses UP), the
# fairness index and predictive hit rate regress DOWN.
SESSIONS_KEYS = ("sessions_interactive_p99_ms",
                 "sessions_fairness_index", "prefetch_hit_rate")
# --offload: judge OFFLOAD_r*.json records (bench.py --smoke
# --offload) on the repeat-viewer offload keys.  Direction-aware by
# name: the offload ratio and peer hit rate regress DOWNWARD (less
# traffic absorbed off the origin), the 304 latency is a ``_ms`` key
# and regresses UPWARD.
OFFLOAD_KEYS = ("origin_offload_ratio", "peer_hit_rate",
                "p50_304_ms")
# --capacity: judge CAPACITY_r*.json records (bench.py --smoke
# --capacity — the open-loop offered-load sweep) on the capacity
# knee.  Direction-aware by name: the knee (offered tps where p99
# crosses the SLO or shed crosses 5%) and the fleet-size scaling
# efficiency regress DOWNWARD; the p99 AT the knee is a ``_ms`` key
# and regresses UPWARD.  ``--watermark`` covers the family like every
# other: the newest round is judged against the best knee any round
# ever measured.
CAPACITY_KEYS = ("capacity_knee_offered_tps", "p99_at_knee_ms",
                 "capacity_scaling_efficiency")
# --hotkey: judge HOTKEY_r*.json records (bench.py --smoke --hotkey —
# the hot-plane replication drill) on the viral-image keys.
# Direction-aware by name: the storm's throughput retention vs the
# uniform mix and the replication gain over the disabled A/B both
# regress DOWNWARD (a gain falling toward 1.0 means the tier stopped
# earning its keep); storm throughput itself regresses DOWNWARD too.
# ``hotkey_duplicate_staged`` is judged separately below: any value
# above zero fails outright — duplicate staging is a correctness
# bug, not a trend.  Rounds that predate the family skip on null.
HOTKEY_KEYS = ("hotkey_storm_ratio", "hotkey_replication_gain",
               "hotkey_storm_tps")
# --partition: judge PARTITION_r*.json records (bench.py --smoke
# --partition — the netsplit chaos drill) on the partition-tolerance
# latencies: how long the minority takes to FENCE after the links go
# dark, and to RESTORE after heal (both ``_ms`` keys, regress UP).
# The drill's availability and split-brain guarantees are judged
# separately below as correctness riders on the NEW record alone:
# any majority-side failure that was not counted shed, a post-heal
# agreement/byte round-trip that is not bit-exact, an aborted
# majority roll, or a fenced minority that refused NOTHING all fail
# outright — they are contracts, not trends.
PARTITION_KEYS = ("part_fence_ms", "part_restore_ms")

# Device-workloads drill (``bench.py --smoke --workloads``), PR 20:
# the batched mask/overlay/animation latencies and the pyramid build
# are ``_ms`` keys (regress UP); mask renders in the parity mix
# regress DOWN (fewer exercised = a shrunken drill, not a win).
WORKLOADS_KEYS = ("mask_device_ms", "overlay_device_ms",
                  "pyramid_build_ms", "anim_first_frame_ms",
                  "anim_total_ms", "mask_renders")
_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")
_MULTICHIP_RE = re.compile(r"^MULTICHIP_r(\d+)\.json$")
_SESSIONS_RE = re.compile(r"^SESSIONS_r(\d+)\.json$")
_OFFLOAD_RE = re.compile(r"^OFFLOAD_r(\d+)\.json$")
_CAPACITY_RE = re.compile(r"^CAPACITY_r(\d+)\.json$")
_HOTKEY_RE = re.compile(r"^HOTKEY_r(\d+)\.json$")
_PARTITION_RE = re.compile(r"^PARTITION_r(\d+)\.json$")
_WORKLOADS_RE = re.compile(r"^WORKLOADS_r(\d+)\.json$")

# Every committed record family in one table: (name, filename
# pattern, trend keys, pairwise/watermark threshold).  ``--all``
# iterates it, and ``load_watermarks`` (the importable parser the
# live perf sentinel shares) walks the same table so a family added
# here is automatically judged by CI AND learned by the sentinel.
FAMILIES = (
    ("bench", _BENCH_RE, DEFAULT_KEYS, 0.10),
    ("multichip", _MULTICHIP_RE, MULTICHIP_KEYS, 0.10),
    ("offload", _OFFLOAD_RE, OFFLOAD_KEYS, 0.10),
    ("sessions", _SESSIONS_RE, SESSIONS_KEYS, 0.10),
    ("capacity", _CAPACITY_RE, CAPACITY_KEYS, 0.10),
    ("hotkey", _HOTKEY_RE, HOTKEY_KEYS, 0.10),
    ("partition", _PARTITION_RE, PARTITION_KEYS, 0.50),
    ("workloads", _WORKLOADS_RE, WORKLOADS_KEYS, 0.50),
)


def lower_is_better(key: str) -> bool:
    """Latency keys regress UPWARD — without direction awareness a
    latency regression would read as an improvement (and a flat
    throughput headline could hide it entirely)."""
    return key.endswith("_ms") or "_ms_" in key


def load_record(path: str) -> dict:
    """One bench record: a JSON object, or the last JSON line of the
    file (bench.py prints ONE line; drivers may append logs).  Driver
    wrappers ({"parsed": {...}} / {"tail": "..."} envelopes) unwrap to
    the bench line itself."""
    with open(path) as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: no JSON object found")
    if "metric" not in doc:
        # Driver envelope: prefer the pre-parsed bench line; fall back
        # to scanning the captured tail for it.
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            return parsed
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if '"metric"' not in line:
                    continue
                # Driver tails are length-capped from the FRONT, which
                # can shear the bench line's opening brace off (seen in
                # BENCH_r05); a line that starts mid-object is repaired
                # rather than dropped — the watermark gate must be able
                # to read every historical round.
                for candidate_text in (line, "{" + line):
                    try:
                        candidate = json.loads(candidate_text)
                    except ValueError:
                        continue
                    if isinstance(candidate, dict) and "metric" in \
                            candidate:
                        return candidate
    return doc


def all_records(directory: str, pattern=_BENCH_RE):
    """Every matching record in ``directory``, round order
    (ascending).  ``pattern`` selects the record family — BENCH by
    default, MULTICHIP under ``--multichip``."""
    rounds = []
    for name in os.listdir(directory):
        m = pattern.match(name)
        if m:
            rounds.append((int(m.group(1)),
                           os.path.join(directory, name)))
    rounds.sort()
    return [path for _, path in rounds]


def newest_pair(directory: str, pattern=_BENCH_RE):
    """The two highest-numbered records in ``directory`` (old, new) —
    the pair the driver's latest round produced."""
    rounds = all_records(directory, pattern)
    if len(rounds) < 2:
        raise ValueError(
            f"{directory}: need at least two matching records, "
            f"found {len(rounds)}")
    return rounds[-2], rounds[-1]


def judge(old: dict, new: dict, keys, max_regression: float):
    """Per-key verdicts: ``pass`` / ``regression`` / ``skipped``
    (value absent or null on either side — congestion weather)."""
    verdicts = []
    for key in keys:
        v_old, v_new = old.get(key), new.get(key)
        if not isinstance(v_old, (int, float)) \
                or not isinstance(v_new, (int, float)) or v_old <= 0:
            verdicts.append({"key": key, "verdict": "skipped",
                             "old": v_old, "new": v_new})
            continue
        change = (v_new - v_old) / v_old
        # Inclusive: a dead-on 10% move against the default threshold
        # is a failure, not a float-equality pass.  Direction depends
        # on the key: throughput regresses down, latency regresses up.
        if lower_is_better(key):
            verdict = ("regression" if change >= max_regression
                       else "pass")
        else:
            verdict = ("regression" if change <= -max_regression
                       else "pass")
        verdicts.append({"key": key, "verdict": verdict,
                         "old": round(float(v_old), 2),
                         "new": round(float(v_new), 2),
                         "change": round(change, 4)})
    return verdicts


def watermark(records, keys):
    """Best-ever value per key across ``records`` (list of parsed
    record dicts): max for throughput keys, min for latency keys;
    absent/null values are ignored.  Returns {key: (value, index)}
    with the index of the record that set the mark."""
    marks = {}
    for i, rec in enumerate(records):
        for key in keys:
            v = rec.get(key)
            if not isinstance(v, (int, float)) or v <= 0:
                continue
            if key not in marks:
                marks[key] = (float(v), i)
                continue
            best, _ = marks[key]
            better = (v < best) if lower_is_better(key) else (v > best)
            if better:
                marks[key] = (float(v), i)
    return marks


def judge_watermark(records, names, new, keys,
                    max_regression: float):
    """Judge ``new`` against each key's best-ever watermark over
    ``records``; verdict rows carry which round set the mark."""
    marks = watermark(records, keys)
    synthetic_old = {key: value for key, (value, _) in marks.items()}
    verdicts = judge(synthetic_old, new, keys, max_regression)
    for v in verdicts:
        mark = marks.get(v["key"])
        v["watermark_record"] = (os.path.basename(names[mark[1]])
                                 if mark else None)
    return verdicts


def load_watermarks(root: str = "."):
    """Best-ever marks across EVERY committed record family in
    ``root``: ``{family: {key: {"value": v, "record": basename}}}``.

    The importable half of the watermark gate — the live perf
    sentinel (``server.sentinel``) calls this at startup so the marks
    a human would check with ``--watermark`` become drift floors the
    serving fleet enforces continuously.  Strictly best-effort:
    absent families, unreadable records and null keys are skipped,
    never raised — a cold repo yields ``{}`` and the sentinel learns
    from live traffic alone."""
    marks_by_family = {}
    for name, pattern, keys, _ in FAMILIES:
        try:
            paths = all_records(root, pattern)
        except OSError:
            continue
        records, names = [], []
        for p in paths:
            try:
                records.append(load_record(p))
                names.append(os.path.basename(p))
            except (OSError, ValueError):
                continue
        if not records:
            continue
        marks = watermark(records, keys)
        if marks:
            marks_by_family[name] = {
                key: {"value": value, "record": names[idx]}
                for key, (value, idx) in marks.items()}
    return marks_by_family


def hotkey_riders(new_record: dict):
    """Correctness rider, judged on the NEW record alone (no trend,
    no threshold): a single duplicate-staged plane means the
    digest-dedup staging contract broke.  Absent/null skips like
    every other key (rounds that predate the family)."""
    dup = new_record.get("hotkey_duplicate_staged")
    if not isinstance(dup, (int, float)):
        return [{"key": "hotkey_duplicate_staged",
                 "verdict": "skipped", "old": None, "new": dup}]
    return [{"key": "hotkey_duplicate_staged",
             "verdict": "regression" if dup > 0 else "pass",
             "old": 0, "new": int(dup)}]


def partition_riders(new_record: dict):
    """Correctness riders, judged on the NEW record alone (no trend,
    no threshold) — each is a partition-tolerance CONTRACT: the
    majority must never fail a request without counting it shed, the
    quorate side's roll must commit, the healed fleet must agree
    bit-exactly (manifest digest + probe owners + byte round-trip),
    and a fenced minority that refused nothing means the fence gates
    never engaged.  Absent/null skips (rounds that predate the
    family)."""
    riders = (
        ("part_majority_5xx", lambda v: v == 0, 0),
        ("part_roll_committed", lambda v: v == 1, 1),
        ("part_rejoin_epoch", lambda v: v >= 2, 2),
        ("part_postheal_agree", lambda v: v == 1, 1),
        ("part_byte_agree", lambda v: v == 1, 1),
        ("part_minority_refusals", lambda v: v >= 1, 1),
    )
    out = []
    for key, ok, want in riders:
        val = new_record.get(key)
        if not isinstance(val, (int, float)):
            out.append({"key": key, "verdict": "skipped",
                        "old": None, "new": val})
        else:
            out.append({"key": key,
                        "verdict": "pass" if ok(val)
                        else "regression",
                        "old": want, "new": val})
    return out


_RIDERS = {"hotkey": hotkey_riders, "partition": partition_riders}


def judge_all(directory: str, strict: bool = False) -> int:
    """``--all``: one invocation over every record family — newest
    pair judged pairwise AND newest-vs-best watermark, riders
    included — printing one verdict row per family plus a combined
    JSON summary line.  Families with fewer than two committed
    records print ``skipped`` (that is data absence, not a
    regression); the combined exit code is 1 when ANY family
    regressed (or, under ``--strict``, skipped)."""
    rows = []
    any_fail = False
    any_skip = False
    for name, pattern, keys, max_regression in FAMILIES:
        paths = all_records(directory, pattern)
        if len(paths) < 2:
            rows.append((name, "skipped",
                         f"{len(paths)} record(s)"))
            any_skip = True
            continue
        try:
            records = [load_record(p) for p in paths]
        except (OSError, ValueError) as e:
            rows.append((name, "error", str(e)))
            any_fail = True
            continue
        new_record = records[-1]
        verdicts = judge(records[-2], new_record, keys,
                         max_regression)
        verdicts += judge_watermark(records[:-1], paths[:-1],
                                    new_record, keys, max_regression)
        rider = _RIDERS.get(name)
        if rider:
            verdicts += rider(new_record)
        regressed = [v["key"] for v in verdicts
                     if v["verdict"] == "regression"]
        if regressed:
            any_fail = True
            rows.append((name, "fail", ",".join(sorted(
                set(regressed)))))
        else:
            rows.append((name, "pass",
                         f"{len(verdicts)} key verdicts, "
                         f"new={os.path.basename(paths[-1])}"))
    width = max(len(name) for name, _, _ in rows)
    for name, verdict, detail in rows:
        print(f"{name:<{width}}  {verdict:<7}  {detail}",
              file=sys.stderr)
    failed = any_fail or (strict and any_skip)
    print(json.dumps({
        "gate": "bench", "mode": "all",
        "verdict": "fail" if failed else "pass",
        "families": [{"family": name, "verdict": verdict,
                      "detail": detail}
                     for name, verdict, detail in rows],
    }))
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Fail on a bench-record service-rate regression")
    parser.add_argument("paths", nargs="*",
                        help="old.json new.json (pairwise), or "
                             "old1.json ... new.json (--watermark: "
                             "the LAST record is judged)")
    parser.add_argument("--dir",
                        help="scan BENCH_r*.json records (pairwise: "
                             "newest pair; --watermark: newest vs "
                             "best-ever across the rest)")
    parser.add_argument("--watermark", action="store_true",
                        help="gate the newest record against each "
                             "key's best-ever value across all prior "
                             "records, not just the previous run "
                             "(pairwise -10%% per round compounds to "
                             "-37%% over four rounds undetected)")
    parser.add_argument("--multichip", action="store_true",
                        help="judge MULTICHIP_r*.json records on the "
                             "fleet scaling-curve keys (tiles/s at "
                             "the widest member counts + "
                             "fleet_scaling_efficiency); rounds that "
                             "predate the curve skip on null")
    parser.add_argument("--sessions", action="store_true",
                        help="judge SESSIONS_r*.json records (bench "
                             "--smoke --sessions) on the multi-user "
                             "serving keys: interactive per-session "
                             "p99 (regresses up), Jain's fairness "
                             "index and predictive prefetch hit rate "
                             "(regress down)")
    parser.add_argument("--offload", action="store_true",
                        help="judge OFFLOAD_r*.json records (bench "
                             "--smoke --offload) on the repeat-viewer "
                             "offload keys: origin offload ratio and "
                             "peer byte-fetch hit rate (regress "
                             "down), 304 latency (regresses up)")
    parser.add_argument("--capacity", action="store_true",
                        help="judge CAPACITY_r*.json records (bench "
                             "--smoke --capacity, the open-loop "
                             "offered-load sweep) on the capacity "
                             "knee: knee offered tps and scaling "
                             "efficiency regress down, p99-at-knee "
                             "regresses up")
    parser.add_argument("--hotkey", action="store_true",
                        help="judge HOTKEY_r*.json records (bench "
                             "--smoke --hotkey, the hot-plane "
                             "replication drill) on the viral-image "
                             "keys: storm/uniform throughput ratio, "
                             "replication gain over the disabled A/B "
                             "and storm throughput (all regress "
                             "down); any duplicate-staged count "
                             "above zero fails outright")
    parser.add_argument("--partition", action="store_true",
                        help="judge PARTITION_r*.json records (bench "
                             "--smoke --partition, the netsplit chaos "
                             "drill) on fence/restore latency (regress "
                             "up); majority 5xx-without-shed, aborted "
                             "rolls, failed post-heal agreement/byte "
                             "round-trips and a refusal-free fence "
                             "all fail outright")
    parser.add_argument("--workloads", action="store_true",
                        help="judge WORKLOADS_r*.json records (bench "
                             "--smoke --workloads, the device mask/"
                             "overlay/pyramid/animation drill) on the "
                             "batched-latency keys (regress up) and "
                             "the parity-mix size (regresses down)")
    parser.add_argument("--all", action="store_true",
                        help="judge EVERY committed record family "
                             "(BENCH/MULTICHIP/OFFLOAD/SESSIONS/"
                             "CAPACITY/HOTKEY/PARTITION/WORKLOADS) "
                             "in --dir "
                             "(default .) pairwise AND against its "
                             "watermark, riders included; prints one "
                             "verdict row per family and exits "
                             "non-zero if any family regressed — the "
                             "single CI entrypoint")
    parser.add_argument("--key", action="append", default=None,
                        help="record key(s) to judge (default "
                             "service_tiles_per_sec, "
                             "p50_service_tile_ms_ex_rtt, "
                             "raw_upload_mb_per_sec, "
                             "p50_first_tile_byte_ms; --multichip: "
                             "the fleet scaling keys)")
    parser.add_argument("--max-regression", type=float, default=None,
                        help="fail when new < old by this fraction or "
                             "more (default 0.10; --partition "
                             "defaults to 0.50 — fence/restore are "
                             "quantized by the gossip tick)")
    parser.add_argument("--strict", action="store_true",
                        help="treat skipped (absent/null) keys as "
                             "failures")
    args = parser.parse_args(argv)
    if args.max_regression is None:
        # Partition fence/restore latency is quantized by the gossip
        # tick (~0.3 s of honest jitter on a ~1.2 s measurement): a
        # 10% relative bar fails identical code about half the time,
        # so the family bar is a tick-sized 50%.  Real regressions
        # (a lost tick loop, a widened suspect window) move 2-3x.
        # Workloads shares the wide bar: smoke-scale batched renders
        # are a few ms, so scheduler jitter dwarfs a 10% band.
        args.max_regression = (0.50 if args.partition or args.workloads
                               else 0.10)

    if args.all:
        try:
            return judge_all(args.dir or ".", strict=args.strict)
        except OSError as e:
            print(json.dumps({"gate": "bench", "error": str(e)}))
            return 2

    if args.key:
        keys = tuple(args.key)
    elif args.multichip:
        keys = MULTICHIP_KEYS
    elif args.sessions:
        keys = SESSIONS_KEYS
    elif args.offload:
        keys = OFFLOAD_KEYS
    elif args.capacity:
        keys = CAPACITY_KEYS
    elif args.hotkey:
        keys = HOTKEY_KEYS
    elif args.partition:
        keys = PARTITION_KEYS
    elif args.workloads:
        keys = WORKLOADS_KEYS
    else:
        keys = DEFAULT_KEYS
    pattern = (_MULTICHIP_RE if args.multichip
               else _SESSIONS_RE if args.sessions
               else _OFFLOAD_RE if args.offload
               else _CAPACITY_RE if args.capacity
               else _HOTKEY_RE if args.hotkey
               else _PARTITION_RE if args.partition
               else _WORKLOADS_RE if args.workloads else _BENCH_RE)
    try:
        if args.watermark:
            if args.dir:
                paths = all_records(args.dir, pattern)
            else:
                paths = list(args.paths)
            if len(paths) < 2:
                raise ValueError(
                    "watermark mode needs at least two records "
                    f"(got {len(paths)})")
            records = [load_record(p) for p in paths]
            new_record = records[-1]
            verdicts = judge_watermark(
                records[:-1], paths[:-1], new_record,
                keys, args.max_regression)
            doc = {
                "gate": "bench", "mode": "watermark",
                "records": len(paths) - 1,
                "new": os.path.basename(paths[-1]),
                "max_regression": args.max_regression,
            }
        else:
            if args.dir:
                old_path, new_path = newest_pair(args.dir, pattern)
            elif len(args.paths) == 2:
                old_path, new_path = args.paths
            else:
                parser.error("give exactly two record paths, or --dir")
            old, new = load_record(old_path), load_record(new_path)
            new_record = new
            verdicts = judge(old, new, keys, args.max_regression)
            doc = {
                "gate": "bench", "mode": "pairwise",
                "old": os.path.basename(old_path),
                "new": os.path.basename(new_path),
                "max_regression": args.max_regression,
            }
    except (OSError, ValueError) as e:
        print(json.dumps({"gate": "bench", "error": str(e)}))
        return 2

    if args.hotkey:
        verdicts += hotkey_riders(new_record)

    if args.partition:
        verdicts += partition_riders(new_record)

    regressed = [v for v in verdicts if v["verdict"] == "regression"]
    skipped = [v for v in verdicts if v["verdict"] == "skipped"]
    failed = bool(regressed) or (args.strict and bool(skipped))
    doc["verdict"] = "fail" if failed else "pass"
    doc["keys"] = verdicts
    print(json.dumps(doc))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
