#!/usr/bin/env python
"""Metric-cardinality budget check (standalone + tier-1-tested).

The exposition lint in tests/test_telemetry.py holds the CLOSED set of
label KEYS; this tool holds the other half of the cardinality
contract: which families may use which labels, how many series each
family may produce (label-value bounds multiplied out), and what the
whole exposition may add up to — against a COMMITTED budget file
(conf/metrics_budget.json).  A new family that smuggles an unbounded
label, or a label-value explosion that multiplies past its budget,
fails here mechanically before it melts a Prometheus.

Two modes::

    python scripts/metrics_lint.py                 # registry check
    python scripts/metrics_lint.py metrics.txt ... # + exposition lint

* **Registry mode** validates the budget against the live
  ``telemetry.METRIC_TYPES`` registry: every budgeted family exists,
  every referenced label key has a committed value bound, every
  family's label product fits its ``max_series`` (histograms get the
  bucket multiplier), and the fleet-wide total fits
  ``max_total_series``.
* **Exposition mode** additionally parses scraped text: every series'
  family must be registered, its label keys must be a subset of the
  family's budgeted labels (plus ``le`` on histograms and the
  sidecar-merge ``process`` dimension), and the distinct-series count
  must fit the total budget.  OpenMetrics exemplar tails are stripped
  before parsing.

Exit status 0 = clean; 1 = findings (printed one per line).
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGET = os.path.join(REPO_ROOT, "conf", "metrics_budget.json")

_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s")
_LABEL_KEY_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)=')

# Labels every family may carry without declaring them: ``le`` on
# histogram series, ``process`` from the sidecar /metrics merge.
_IMPLICIT_HIST = ("le",)
_IMPLICIT_ALL = ("process",)


def load_budget(path: str = DEFAULT_BUDGET) -> dict:
    with open(path) as f:
        return json.load(f)


def _metric_types() -> Dict[str, str]:
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from omero_ms_image_region_tpu.utils.telemetry import METRIC_TYPES
    return METRIC_TYPES


def _family_of(name: str, types: Dict[str, str]) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return name


def _family_budget(budget: dict, family: str) -> dict:
    return budget.get("families", {}).get(family, {"labels": []})


def lint_registry(budget: dict) -> List[str]:
    """Budget <-> registry consistency + the multiplied-out bounds."""
    findings: List[str] = []
    types = _metric_types()
    bounds = budget.get("label_bounds", {})
    default_max = int(budget.get("default_max_series", 64))
    total = 0
    for family, spec in sorted(budget.get("families", {}).items()):
        if family not in types:
            findings.append(
                f"budget names unknown family {family!r} (stale "
                f"entry? METRIC_TYPES has no such family)")
            continue
        product = 1
        for key in spec.get("labels", []):
            if key not in bounds:
                findings.append(
                    f"{family}: label {key!r} has no committed value "
                    f"bound in label_bounds")
                continue
            product *= int(bounds[key])
        allowed = int(spec.get("max_series", default_max))
        if product > allowed:
            findings.append(
                f"{family}: label product {product} exceeds its "
                f"max_series {allowed} — either shrink a label's "
                f"bound or raise the family budget DELIBERATELY")
        total += product * ((int(bounds.get("le", 20)) + 3)
                            if types.get(family) == "histogram"
                            else 1)
    # Unlabeled registry families each contribute one series.
    total += sum(1 for f in types if f not in
                 budget.get("families", {}))
    max_total = int(budget.get("max_total_series", 0))
    if max_total and total > max_total:
        findings.append(
            f"estimated fleet-wide series total {total} exceeds "
            f"max_total_series {max_total}")
    return findings


def lint_exposition(text: str, budget: dict) -> List[str]:
    """Scraped exposition text vs the budget: label keys per family,
    unknown families, distinct-series total."""
    findings: List[str] = []
    types = _metric_types()
    seen_series = set()
    flagged = set()
    for line in text.rstrip("\n").split("\n"):
        if not line or line.startswith("#"):
            continue
        # Strip an OpenMetrics exemplar tail before parsing.
        line = line.split(" # ", 1)[0] + " "
        m = _SERIES_RE.match(line)
        if m is None:
            findings.append(f"unparseable series line: {line!r}")
            continue
        name, labels = m.group(1), m.group(3) or ""
        family = _family_of(name, types)
        if family not in types:
            if family not in flagged:
                flagged.add(family)
                findings.append(
                    f"family {family!r} is not registered in "
                    f"METRIC_TYPES (register it + budget it)")
            continue
        spec = _family_budget(budget, family)
        allowed = set(spec.get("labels", [])) | set(_IMPLICIT_ALL)
        if types.get(family) == "histogram":
            allowed |= set(_IMPLICIT_HIST)
        for key in _LABEL_KEY_RE.findall(labels):
            if key not in allowed and (family, key) not in flagged:
                flagged.add((family, key))
                findings.append(
                    f"{family}: label {key!r} is not in its budgeted "
                    f"label set {sorted(allowed)} — a new label is a "
                    f"deliberate budget change, never a drive-by")
        seen_series.add((name, labels))
    max_total = int(budget.get("max_total_series", 0))
    if max_total and len(seen_series) > max_total:
        findings.append(
            f"exposition carries {len(seen_series)} distinct series, "
            f"over max_total_series {max_total}")
    return findings


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="Metric-cardinality budget check (registry "
                    "consistency + optional exposition lint)")
    parser.add_argument("expositions", nargs="*",
                        help="scraped /metrics text files to lint")
    parser.add_argument("--budget", default=DEFAULT_BUDGET,
                        help="budget JSON (default: "
                             "conf/metrics_budget.json)")
    args = parser.parse_args(argv)
    budget = load_budget(args.budget)
    findings = lint_registry(budget)
    for path in args.expositions:
        with open(path) as f:
            for finding in lint_exposition(f.read(), budget):
                findings.append(f"{path}: {finding}")
    for finding in findings:
        print(f"METRICS-LINT: {finding}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("metrics budget: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
