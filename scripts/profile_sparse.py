"""Compare device implementations of the sparse stream compaction."""

import functools
import statistics
import time

import numpy as np

from omero_ms_image_region_tpu.flagship import (
    batched_args, flagship_settings, synthetic_wsi_tiles,
)
from omero_ms_image_region_tpu.ops.jpegenc import (
    default_sparse_cap, quant_tables, render_to_jpeg_coefficients,
)

import jax
import jax.numpy as jnp


def sync(x):
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(leaf.ravel()[:1])


def t(fn, n=4):
    fn()
    xs = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        xs.append((time.perf_counter() - t0) * 1e3)
    return min(xs)


def make_flat(B=8, H=1024, W=1024):
    rng = np.random.default_rng(7)
    C = 4
    _, settings = flagship_settings()
    raw = synthetic_wsi_tiles(rng, B, C, H, W)
    args_suffix = batched_args(settings, raw)[1:]
    qy, qc = (tt.astype(np.int32) for tt in quant_tables(85))
    y, cb, cr = render_to_jpeg_coefficients(
        jax.device_put(raw), *args_suffix, qy, qc)
    flat = jnp.concatenate(
        [y.reshape(B, -1), cb.reshape(B, -1), cr.reshape(B, -1)], axis=1)
    flat.block_until_ready()
    return np.asarray(flat)  # host i16 [B, N]


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_scatter(flat, cap: int):
    B, N = flat.shape
    nb = N // 64
    mask = flat != 0
    counts = mask.reshape(B, nb, 64).sum(-1).astype(jnp.uint8)
    wi = jnp.cumsum(mask, axis=1) - 1
    pos = (jnp.arange(N, dtype=jnp.int32) % 64).astype(jnp.uint8)

    def one(m, w, v):
        tgt = jnp.where(m & (w < cap), w, cap)
        p = jnp.zeros(cap + 1, jnp.uint8).at[tgt].set(pos, mode="drop")
        vv = jnp.zeros(cap + 1, jnp.int16).at[tgt].set(v, mode="drop")
        return p[:cap], vv[:cap]

    ps, vs = jax.vmap(one)(mask, wi, flat)
    return ps, vs, counts


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_blocksort(flat, cap: int):
    """Per-block 64-lane sort compaction + block-offset binary search."""
    B, N = flat.shape
    nb = N // 64
    blocks = flat.reshape(B, nb, 64).astype(jnp.int32)
    mask = blocks != 0
    counts = mask.sum(-1)                              # [B, nb] i32
    pos = jnp.arange(64, dtype=jnp.int32)
    # Pack (zero-flag, pos, value) into one u32 so one sort carries all:
    # key bits [22]=zero flag, [21:16]=pos, [15:0]=value.
    key = (jnp.where(mask, 0, 1 << 22)
           | (pos << 16)
           | (blocks & 0xFFFF)).astype(jnp.int32)
    srt = jax.lax.sort(key, dimension=-1)              # [B, nb, 64]
    stage_pos = ((srt >> 16) & 0x3F).astype(jnp.uint8)
    stage_val = (srt & 0xFFFF).astype(jnp.uint16).astype(jnp.int16)

    S = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), jnp.cumsum(counts, axis=1)], axis=1)
    qs = jnp.arange(cap, dtype=jnp.int32)

    def one(S_row, sp, sv):
        # rightmost block with S[b] <= j  (15-step binary search over S)
        lo = jnp.zeros(cap, jnp.int32)
        hi = jnp.full((cap,), nb, jnp.int32)
        for _ in range(int(np.ceil(np.log2(nb + 1)))):
            mid = (lo + hi + 1) >> 1
            go = S_row[mid] <= qs
            lo = jnp.where(go, mid, lo)
            hi = jnp.where(go, hi, mid - 1)
        b = lo
        r = qs - S_row[b]
        f = b * 64 + r
        valid = qs < S_row[-1]
        f = jnp.where(valid, f, 0)
        return (jnp.where(valid, sp.reshape(-1)[f], 0),
                jnp.where(valid, sv.reshape(-1)[f], 0))

    ps, vs = jax.vmap(one)(S, stage_pos, stage_val)
    return ps, vs, counts.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_searchsorted(flat, cap: int):
    B, N = flat.shape
    nb = N // 64
    mask = flat != 0
    counts = mask.reshape(B, nb, 64).sum(-1).astype(jnp.uint8)
    c = jnp.cumsum(mask.astype(jnp.int32), axis=1)
    ranks = jnp.arange(1, cap + 1, dtype=jnp.int32)

    def one(c_row, v_row):
        src = jnp.searchsorted(c_row, ranks, side="left")
        valid = src < N
        src = jnp.minimum(src, N - 1)
        p = jnp.where(valid, src % 64, 0).astype(jnp.uint8)
        v = jnp.where(valid, v_row[src], 0).astype(jnp.int16)
        return p, v

    ps, vs = jax.vmap(one)(c, flat)
    return ps, vs, counts


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_blockscatter(flat, cap: int):
    """Per-block 64-lane sort + windowed scatter-add of 64-wide rows."""
    B, N = flat.shape
    nb = N // 64
    blocks = flat.reshape(B, nb, 64).astype(jnp.int32)
    mask = blocks != 0
    counts = mask.sum(-1)                              # [B, nb] i32
    pos = jnp.arange(64, dtype=jnp.int32)
    key = (jnp.where(mask, 0, 1 << 22)
           | (pos << 16)
           | (blocks & 0xFFFF)).astype(jnp.int32)
    srt = jax.lax.sort(key, dimension=-1)              # [B, nb, 64]
    lane = jnp.arange(64, dtype=jnp.int32)
    staged = jnp.where(lane < counts[..., None], srt, 0)

    S = jnp.cumsum(counts, axis=1) - counts            # exclusive [B, nb]

    def one(S_row, st):
        out = jnp.zeros(cap + 64, jnp.int32)
        out = out.at[S_row[:, None] + lane[None, :]].add(st, mode="drop")
        return out[:cap]

    out32 = jax.vmap(one)(S, staged)
    ps = ((out32 >> 16) & 0x3F).astype(jnp.uint8)
    vs = (out32 & 0xFFFF).astype(jnp.uint16).astype(jnp.int16)
    return ps, vs, counts.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_blockscatter_win(flat, cap: int):
    """Like blockscatter but a true windowed scatter (indices [nb, 1])."""
    import jax.lax as lax
    B, N = flat.shape
    nb = N // 64
    blocks = flat.reshape(B, nb, 64).astype(jnp.int32)
    mask = blocks != 0
    counts = mask.sum(-1)
    pos = jnp.arange(64, dtype=jnp.int32)
    key = (jnp.where(mask, 0, 1 << 22)
           | (pos << 16)
           | (blocks & 0xFFFF)).astype(jnp.int32)
    srt = jax.lax.sort(key, dimension=-1)
    lane = jnp.arange(64, dtype=jnp.int32)
    staged = jnp.where(lane < counts[..., None], srt, 0)
    S = jnp.cumsum(counts, axis=1) - counts

    dn = lax.ScatterDimensionNumbers(
        update_window_dims=(1,), inserted_window_dims=(),
        scatter_dims_to_operand_dims=(0,))

    def one(S_row, st):
        out = jnp.zeros(cap + 64, jnp.int32)
        out = lax.scatter_add(out, S_row[:, None], st, dn,
                              mode=lax.GatherScatterMode.FILL_OR_DROP)
        return out[:cap]

    out32 = jax.vmap(one)(S, staged)
    ps = ((out32 >> 16) & 0x3F).astype(jnp.uint8)
    vs = (out32 & 0xFFFF).astype(jnp.uint16).astype(jnp.int16)
    return ps, vs, counts.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_scatter_unique(flat, cap: int):
    """One combined u32 set-scatter, unique targets, OOB-dropped tails."""
    B, N = flat.shape
    nb = N // 64
    mask = flat != 0
    counts = mask.reshape(B, nb, 64).sum(-1).astype(jnp.uint8)
    wi = jnp.cumsum(mask, axis=1) - 1
    pos = (jnp.arange(N, dtype=jnp.int32) % 64)
    comb = (pos << 16) | (flat.astype(jnp.int32) & 0xFFFF)

    def one(m, w, v):
        tgt = jnp.where(m & (w < cap), w, jnp.int32(1 << 30))
        out = jnp.zeros(cap, jnp.int32).at[tgt].set(
            v, mode="drop", unique_indices=True)
        return out

    out32 = jax.vmap(one)(mask, wi, comb)
    ps = ((out32 >> 16) & 0x3F).astype(jnp.uint8)
    vs = (out32 & 0xFFFF).astype(jnp.uint16).astype(jnp.int16)
    return ps, vs, counts


@functools.partial(jax.jit, static_argnames=("cap",))
def pack_blockscatter_unique(flat, cap: int):
    """Sorted staging + ascending unique set-scatter."""
    B, N = flat.shape
    nb = N // 64
    blocks = flat.reshape(B, nb, 64).astype(jnp.int32)
    mask = blocks != 0
    counts = mask.sum(-1)
    pos = jnp.arange(64, dtype=jnp.int32)
    key = (jnp.where(mask, 0, 1 << 22)
           | (pos << 16)
           | (blocks & 0xFFFF)).astype(jnp.int32)
    srt = jax.lax.sort(key, dimension=-1)
    lane = jnp.arange(64, dtype=jnp.int32)
    S = jnp.cumsum(counts, axis=1) - counts

    def one(S_row, st, c_row):
        valid = lane[None, :] < c_row[:, None]
        tgt = jnp.where(valid, S_row[:, None] + lane[None, :],
                        jnp.int32(1 << 30))
        out = jnp.zeros(cap, jnp.int32).at[tgt.reshape(-1)].set(
            st.reshape(-1), mode="drop", unique_indices=True)
        return out

    out32 = jax.vmap(one)(S, srt, counts)
    ps = ((out32 >> 16) & 0x3F).astype(jnp.uint8)
    vs = (out32 & 0xFFFF).astype(jnp.uint16).astype(jnp.int16)
    return ps, vs, counts.astype(jnp.uint8)


@functools.partial(jax.jit)
def sort_only(flat):
    B, N = flat.shape
    nb = N // 64
    blocks = flat.reshape(B, nb, 64).astype(jnp.int32)
    mask = blocks != 0
    pos = jnp.arange(64, dtype=jnp.int32)
    key = (jnp.where(mask, 0, 1 << 22) | (pos << 16)
           | (blocks & 0xFFFF)).astype(jnp.int32)
    return jax.lax.sort(key, dimension=-1)


def check(name, fn, flat, cap, ref):
    ps, vs, counts = [np.asarray(a) for a in fn(jax.device_put(flat), cap)]
    rps, rvs, rcounts = ref
    tot = int(rcounts.astype(np.int64).sum(1)[0])
    ok = (np.array_equal(ps[0, :tot], rps[0, :tot])
          and np.array_equal(vs[0, :tot], rvs[0, :tot]))
    print(f"{name}: match={ok}")


def main():
    flat = make_flat()
    cap = default_sparse_cap(1024, 1024)
    dev = jax.device_put(flat)
    sync(dev)

    ref = [np.asarray(a) for a in pack_scatter(dev, cap)]
    check("blocksort", pack_blocksort, flat, cap, ref)
    check("searchsorted", pack_searchsorted, flat, cap, ref)
    check("blockscatter", pack_blockscatter, flat, cap, ref)
    check("scatter_unique", pack_scatter_unique, flat, cap, ref)
    check("blockscatter_unique", pack_blockscatter_unique, flat, cap, ref)

    print("sort_only: %.1f ms" % t(lambda: sync(sort_only(dev))))
    for name, fn in (("scatter", pack_scatter),
                     ("blockscatter", pack_blockscatter),
                     ("scatter_unique", pack_scatter_unique),
                     ("blockscatter_unique", pack_blockscatter_unique)):
        ms = t(lambda fn=fn: sync(fn(dev, cap)))
        print(f"{name}: {ms:7.1f} ms for B=8 ({ms/8:5.1f} ms/tile)")


if __name__ == "__main__":
    main()
