"""Wire-size accounting: 18-bit sparse prefix vs finished JPEG bytes."""

import statistics
import time

import numpy as np

from omero_ms_image_region_tpu.flagship import (
    batched_args, flagship_settings, synthetic_wsi_tiles,
)
from omero_ms_image_region_tpu.ops.jpegenc import (
    SparseWireFetcher, default_sparse_cap, encode_sparse_buffers,
    quant_tables, render_to_jpeg_sparse, sparse_prefix_bytes,
)

import jax


def main():
    rng = np.random.default_rng(7)
    B, C, H, W = 8, 4, 1024, 1024
    _, settings = flagship_settings()
    raw = synthetic_wsi_tiles(rng, B, C, H, W)
    args = batched_args(settings, raw)[1:]
    qy, qc = (t.astype(np.int32) for t in quant_tables(85))
    cap = default_sparse_cap(H, W)
    dev = jax.device_put(raw)
    f = SparseWireFetcher(H, W, cap)
    host = f.fetch(render_to_jpeg_sparse(dev, *args, qy, qc, cap=cap))
    totals = host[:, :4].copy().view(np.int32).ravel()
    jpegs = encode_sparse_buffers(host, W, H, 85, cap)
    for t, j in zip(totals, jpegs):
        print(f"entries={t}  prefix={sparse_prefix_bytes(t, H, W)}  "
              f"jpeg={len(j)}  ratio={sparse_prefix_bytes(t, H, W)/len(j):.2f}")
    print("fetched row bytes:", host.shape[1])

    # config4-style single small dispatch timing (diagnose the 14->8 drop)
    from omero_ms_image_region_tpu.models.rendering import Projection
    from omero_ms_image_region_tpu.ops.projection import project_stack
    import jax.numpy as jnp

    def _settings_for3():
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        r = flagship_rdef(3)
        return pack_settings(r)

    s3 = _settings_for3()
    stacks = jax.device_put(synthetic_wsi_tiles(rng, 3, 32, 512, 512))
    args3 = batched_args(s3, np.zeros((1, 3, 1, 1), np.float32))[1:]
    cap4 = default_sparse_cap(512, 512)
    f4 = SparseWireFetcher(512, 512, cap4)

    @jax.jit
    def project_render(stacks_):
        planes = jax.vmap(
            lambda st: project_stack(st, Projection.MAXIMUM_INTENSITY,
                                     0, 31, 1, 65535.0)
        )(stacks_.astype(jnp.float32))
        return render_to_jpeg_sparse(planes[None], *args3, qy, qc, cap=cap4)

    def run():
        buf = f4.fetch(project_render(stacks))
        encode_sparse_buffers(buf, 512, 512, 85, cap4)

    run()
    xs = []
    for _ in range(6):
        t0 = time.perf_counter()
        run()
        xs.append((time.perf_counter() - t0) * 1e3)
    print("config4 run ms:", [round(x, 1) for x in xs],
          "median", round(statistics.median(xs), 1))
    # split: device+sync only
    def sync_only():
        b = project_render(stacks)
        np.asarray(b[0, :4])
    sync_only()
    xs = []
    for _ in range(5):
        t0 = time.perf_counter()
        sync_only()
        xs.append((time.perf_counter() - t0) * 1e3)
    print("config4 dispatch+sync ms:", [round(x, 1) for x in xs])


if __name__ == "__main__":
    main()
