"""A/B: batcher target_inflight split policy vs max_batch convoys.

Interleaved windows in one process so tunnel weather hits both arms
alike; round 0 is compile warm-up and discounted.

Usage: python scripts/exp_inflight.py [rounds] [window_s] [engine]
"""

import asyncio
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    window = float(sys.argv[2]) if len(sys.argv) > 2 else 8.0
    engine = sys.argv[3] if len(sys.argv) > 3 else "huffman"

    import jax
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)

    import bench

    rng = np.random.default_rng(int.from_bytes(os.urandom(8), "little"))
    results = {1: [], 3: []}
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 4, 1, 4096, 4096).reshape(
            4, 1, 4096, 4096)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        for r in range(rounds):
            for ti in (1, 3):
                config = AppConfig(
                    data_dir=tmp,
                    batcher=BatcherConfig(enabled=True, linger_ms=3.0,
                                          target_inflight=ti),
                    raw_cache=RawCacheConfig(enabled=True,
                                             prefetch=False),
                    renderer=RendererConfig(cpu_fallback_max_px=0,
                                            jpeg_engine=engine))
                tps, p50 = asyncio.run(
                    bench._service_run(config, duration_s=window))
                results[ti].append(tps)
                print(f"round {r} target_inflight={ti}: "
                      f"{tps:.1f} tiles/s  p50={p50:.0f} ms",
                      flush=True)
    for ti, vals in results.items():
        steady = vals[1:] or vals
        print(f"target_inflight={ti}: best={max(steady):.1f} "
              f"mean_steady={sum(steady) / len(steady):.1f}")


if __name__ == "__main__":
    main()
