"""Mutation fuzz over the hostile-input decoders (TIFF / JPEG / JP2K /
NGFF-zarr).

Takes valid files produced by the repo's own writers, applies random
byte flips, splice-deletes, truncations and noise insertions, and runs
each decoder (native fast paths live, where built).  The contract under
fuzz: decode successfully OR raise the decoder's clean error classes —
anything else (TypeError, segfault, hang) is a bug.  Round-4 catches:
a spliced-out ImageLength crashing `read_segment` with TypeError, and
a missing TileOffsets tag crashing with `'NoneType' is not
subscriptable` (both fixed in `io/tiff.py` with regression tests in
`tests/test_tiff.py`).

Not part of the pytest suite (runs minutes, nondeterministic volume);
invoke directly:

    JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/fuzz_decoders.py [seed] [iters]
"""

import os
import struct
import sys
import tempfile
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from omero_ms_image_region_tpu.io.jp2k import Jp2kError, decode_jp2k
from omero_ms_image_region_tpu.io.jpegdec import JpegError, decode_tiff_jpeg
from omero_ms_image_region_tpu.io.tiff import TiffFile

# The decoders' clean error contract.  MemoryError is allowed: a
# mutated header may legally declare a huge-but-capped allocation.
OK_ERRORS = (Jp2kError, JpegError, ValueError, KeyError, EOFError,
             OSError, MemoryError, struct.error)


def _corpus(rng):
    from test_jp2k import _enc as jp2k_enc

    import io as _io

    from PIL import Image

    gray = rng.integers(0, 256, (48, 48), dtype=np.uint8)
    rgb = rng.integers(0, 256, (48, 48, 3), dtype=np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(rgb).save(buf, "JPEG", quality=80)
    jpeg = buf.getvalue()
    buf = _io.BytesIO()
    Image.fromarray(rgb).save(buf, "JPEG", quality=80, progressive=True)
    jpeg_prog = buf.getvalue()
    buf = _io.BytesIO()
    Image.fromarray(rgb).save(buf, "TIFF", compression="tiff_lzw")
    tiff = buf.getvalue()
    return {
        "jp2k": [jp2k_enc(gray, irreversible=False),
                 jp2k_enc(rgb, irreversible=True)],
        "jpeg": [jpeg, jpeg_prog],
        "tiff": [tiff, _pred3_tiff(rng)],
    }


def _ngff_corpus(rng, root: str) -> list:
    """A small valid NGFF group; returns its file list (the mutation
    targets: metadata JSON and chunk payloads alike)."""
    from omero_ms_image_region_tpu.io.ngff import write_ngff

    planes = rng.integers(0, 60000, size=(1, 1, 2, 48, 48)).astype(
        np.uint16)
    write_ngff(planes, root, chunk=(32, 32), n_levels=1)
    files = []
    for dirpath, _dirs, names in os.walk(root):
        files += [os.path.join(dirpath, n) for n in names]
    return sorted(files)


def _try_ngff(root: str, files, rng) -> bool:
    """Mutate ONE file of a pristine copy and open+read the group."""
    import shutil

    from omero_ms_image_region_tpu.io.ngff import NgffZarrSource
    from omero_ms_image_region_tpu.server.region import RegionDef

    with tempfile.TemporaryDirectory() as tmp:
        dst = os.path.join(tmp, "z")
        shutil.copytree(root, dst)
        rel = os.path.relpath(files[int(rng.integers(0, len(files)))],
                              root)
        target = os.path.join(dst, rel)
        if rng.integers(0, 8) == 0:
            os.unlink(target)           # missing file class
        else:
            blob = mutate(rng, open(target, "rb").read())
            open(target, "wb").write(blob)
        src = NgffZarrSource(dst)
        # Read EVERY channel: a mutation landing in any chunk file must
        # actually be decoded, not just survive metadata parsing.
        for c in range(src.size_c):
            src.get_region(0, c, 0, RegionDef(0, 0, 48, 48), 0)
        return True


def _pred3_tiff(rng) -> bytes:
    """Deflate + predictor-3 float TIFF (the TechNote 3 byte-transform
    path is parse logic fed by hostile data too).  Built with the SAME
    helpers as tests/test_tiff.py so seed and test cannot drift."""
    import io as _io
    import zlib

    from test_tiff import encode_pred3, write_float_tiff

    h, w, spp = 24, 32, 3
    img = (rng.standard_normal((h, w * spp)) * 50).astype(np.float32)
    payload = zlib.compress(encode_pred3(img, spp=spp))
    buf = _io.BytesIO()
    write_float_tiff(buf, 3, payload, h, w, spp)
    return buf.getvalue()


def mutate(rng, data: bytes) -> bytes:
    b = bytearray(data)
    for _ in range(int(rng.integers(1, 9))):
        kind = rng.integers(0, 4)
        if kind == 0 and len(b) > 4:           # flip byte
            b[int(rng.integers(0, len(b)))] = int(rng.integers(0, 256))
        elif kind == 1 and len(b) > 16:        # truncate
            del b[int(rng.integers(8, len(b))):]
        elif kind == 2 and len(b) > 16:        # splice-delete
            i = int(rng.integers(4, len(b) - 4))
            del b[i:i + int(rng.integers(1, 16))]
        else:                                  # insert noise
            i = int(rng.integers(0, len(b)))
            b[i:i] = rng.integers(
                0, 256, int(rng.integers(1, 8)), dtype=np.uint8).tobytes()
    return bytes(b)


def _try_tiff(blob: bytes) -> bool:
    with tempfile.NamedTemporaryFile(suffix=".tif", delete=False) as f:
        f.write(blob)
        path = f.name
    try:
        tf = TiffFile(path)
        try:
            tf.read_segment(tf.ifds[0], 0, 0)
        finally:
            tf.close()
        return True
    finally:
        os.unlink(path)


def main() -> int:
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 1500
    rng = np.random.default_rng(seed)
    corpus = _corpus(rng)
    ngff_root = tempfile.mkdtemp(prefix="fuzz_ngff_")
    ngff_files = _ngff_corpus(rng, ngff_root)
    corpus["ngff"] = []                 # disk-based: mutated in-place
    runners = {
        "jp2k": lambda m: decode_jp2k(m),
        "jpeg": lambda m: decode_tiff_jpeg(m, None, 6),
        "tiff": _try_tiff,
        "ngff": lambda m: _try_ngff(ngff_root, ngff_files, rng),
    }
    stats = {k: [0, 0] for k in runners}
    crashes = 0
    # A hang is a contract escape too (the pure-Python decode paths
    # loop over hostile-controlled counts): bound every decode call.
    import signal

    def _alarm(signum, frame):
        raise TimeoutError("decode exceeded the per-call bound")

    signal.signal(signal.SIGALRM, _alarm)
    for i in range(iters):
        for kind, run in runners.items():
            seeds = corpus[kind]
            # Disk-based targets (empty seed list) mutate in-place
            # inside their runner; blob targets mutate here.
            m = (mutate(rng, seeds[i % len(seeds)]) if seeds
                 else None)
            try:
                signal.alarm(30)
                run(m)
                stats[kind][0] += 1
            except OK_ERRORS:
                stats[kind][1] += 1
            except Exception:
                crashes += 1
                print(f"--- {kind} ESCAPED ERROR CONTRACT (iter {i}) ---")
                traceback.print_exc()
            finally:
                signal.alarm(0)
    import shutil
    shutil.rmtree(ngff_root, ignore_errors=True)
    print(f"seed {seed}, {iters} iters/decoder — "
          f"[decoded, clean-error]: {stats}")
    print("OK" if crashes == 0 else f"{crashes} CONTRACT ESCAPES")
    return 1 if crashes else 0


if __name__ == "__main__":
    sys.exit(main())
