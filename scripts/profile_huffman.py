"""Device cost + wire bytes: sparse vs compacted-entry Huffman."""

import statistics
import time

import numpy as np

from omero_ms_image_region_tpu.flagship import (
    batched_args, flagship_settings, synthetic_wsi_tiles,
)
from omero_ms_image_region_tpu.ops.jpegenc import (
    HuffmanWireFetcher, SparseWireFetcher,
    default_sparse_cap, default_words_cap, encode_sparse_buffers,
    finish_huffman_batch, huffman_spec_arrays, quant_tables,
    render_to_jpeg_huffman, render_to_jpeg_sparse,
)

import jax


def sync(x):
    np.asarray(x.ravel()[:1])


def t(fn, n=5):
    fn()
    xs = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        xs.append((time.perf_counter() - t0) * 1e3)
    return min(xs), statistics.median(xs)


def main():
    rng = np.random.default_rng(7)
    B, C, H, W = 8, 4, 1024, 1024
    _, settings = flagship_settings()
    raw = synthetic_wsi_tiles(rng, B, C, H, W)
    args = batched_args(settings, raw)[1:]
    qy, qc = (tt.astype(np.int32) for tt in quant_tables(85))
    cap = default_sparse_cap(H, W)
    cap_words = default_words_cap(H, W)
    spec = huffman_spec_arrays()
    dev = jax.device_put(raw)
    sync(dev)

    # device-only cost
    ms = t(lambda: sync(render_to_jpeg_sparse(
        dev, *args, qy, qc, cap=cap)))
    print(f"sparse  dispatch+sync: {ms[0]:6.1f} ms ({ms[0]/B:4.1f}/tile)")
    ms = t(lambda: sync(render_to_jpeg_huffman(
        dev, *args, qy, qc, *spec, h16=H // 16, w16=W // 16,
        cap=cap, cap_words=cap_words)))
    print(f"huffman dispatch+sync: {ms[0]:6.1f} ms ({ms[0]/B:4.1f}/tile)")

    # wire + host end-to-end
    sf = SparseWireFetcher(H, W, cap)
    hf = HuffmanWireFetcher(H, W, cap, cap_words)

    def run_sparse():
        host = sf.fetch(render_to_jpeg_sparse(dev, *args, qy, qc, cap=cap))
        jpegs = encode_sparse_buffers(host, W, H, 85, cap)
        assert jpegs[0][:2] == b"\xff\xd8"
        return host

    def run_huff():
        host = hf.fetch(render_to_jpeg_huffman(
            dev, *args, qy, qc, *spec, h16=H // 16, w16=W // 16,
            cap=cap, cap_words=cap_words))
        jpegs = finish_huffman_batch(host, [(W, H)] * B, H, W, 85, cap,
                                     cap_words)
        assert jpegs[0][:2] == b"\xff\xd8"
        return host

    hs = run_sparse()
    hh = run_huff()
    bits = hh[:, 4:8].copy().view(np.int32).ravel()
    print("sparse fetched bytes/batch:", hs.shape[1] * B,
          " huffman:", hh.shape[1] * B)
    print("huffman stream KB/tile:",
          [int(b) // 8192 for b in bits])
    ms = t(run_sparse)
    print(f"sparse  e2e batch: {ms[0]:6.1f} ms min / {ms[1]:6.1f} med")
    ms = t(run_huff)
    print(f"huffman e2e batch: {ms[0]:6.1f} ms min / {ms[1]:6.1f} med")


if __name__ == "__main__":
    main()
