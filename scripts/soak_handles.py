"""Handle/memory soak over the DEPLOYED server stack (AppRunner +
TCPSite + a real aiohttp client): sustained serving over more images
than the pixel-source LRU holds (handle churn drives the deferred-close
path), asserting fd count and live RSS stay flat.

Measured here (round 4): 480 measured requests over 60 images with a
12-slot LRU (every request cycles sources through eviction and the
deferred-close drain) at 0 KB/request RSS growth and a flat fd count.
NOTE: aiohttp's TestClient/TestServer
harness accumulates ~20-30 KB/request of its own state — soaks must
run through a real server or they measure the harness, not the
service.

Not part of the pytest suite (runs ~1-2 min); invoke directly:

    JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/soak_handles.py
"""

import asyncio
import gc
import os
import sys
import tempfile


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1])
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import numpy as np

    import aiohttp
    from aiohttp import web
    from omero_ms_image_region_tpu.io.tiffwrite import write_ome_tiff
    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.server.config import AppConfig

    n_images = 60
    rounds = 8
    port = 9191

    tmp = tempfile.mkdtemp(prefix="soak_")
    rng = np.random.default_rng(0)
    for i in range(1, n_images + 1):
        d = os.path.join(tmp, str(i))
        os.makedirs(d)
        planes = rng.integers(0, 60000, (1, 1, 96, 96)).astype(
            np.uint16)
        write_ome_tiff(planes, os.path.join(d, "img.ome.tiff"),
                       tile=(48, 48), n_levels=1)

    # A small LRU forces constant eviction: every request cycles
    # sources through the deferred-close path this soak exists to
    # exercise (the default 128 would hold all 60 images resident).
    config = AppConfig(data_dir=tmp, port=port)
    from omero_ms_image_region_tpu.io.service import PixelsService
    from omero_ms_image_region_tpu.server.app import build_services
    services = build_services(config)
    services.pixels_service.close()
    services.pixels_service = PixelsService(tmp, max_open=12)
    app = create_app(config, services=services)

    async def run() -> tuple:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, port=port)
        await site.start()
        try:
            async with aiohttp.ClientSession() as sess:
                async def one(i):
                    url = (f"http://127.0.0.1:{port}/webgateway/"
                           f"render_image_region/{i}/0/0"
                           f"?region=0,0,96,96&c=1|0:60000$FF0000"
                           f"&m=g&format=png")
                    async with sess.get(url) as r:
                        assert r.status == 200, (i, r.status)
                        await r.read()

                # Warm with the SAME 8-way concurrency as the measured
                # phase: the client pool opens one connection per
                # concurrent request (2 fds per in-process pair), and
                # the baseline must include the filled pool.
                for chunk in range(0, n_images, 8):
                    await asyncio.gather(*[
                        one(i + 1)
                        for i in range(chunk,
                                       min(chunk + 8, n_images))])
                gc.collect()
                fd0, rss0 = _fd_count(), _rss_kb()
                served = 0
                for _ in range(rounds):
                    for chunk in range(0, n_images, 8):
                        await asyncio.gather(*[
                            one(i + 1)
                            for i in range(chunk,
                                           min(chunk + 8, n_images))])
                        served += min(8, n_images - chunk)
                gc.collect()
                return served, fd0, _fd_count(), rss0, _rss_kb()
        finally:
            await runner.cleanup()

    try:
        served, fd0, fd1, rss0, rss1 = asyncio.run(run())
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    print(f"served {served} requests over {n_images} images "
          f"(pixel-source LRU churn)")
    print(f"fds: {fd0} -> {fd1} (delta {fd1 - fd0})")
    print(f"VmRSS: {rss0 // 1024} MB -> {rss1 // 1024} MB "
          f"(delta {(rss1 - rss0) // 1024} MB)")
    assert fd1 - fd0 <= 8, f"fd leak: {fd0} -> {fd1}"
    assert rss1 - rss0 <= 64 * 1024, f"RSS leak: {rss0} -> {rss1}"
    print("soak OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
