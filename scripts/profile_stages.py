"""Stage and batch-size scaling of the device pipeline (diagnostic)."""

import statistics
import time

import numpy as np

from omero_ms_image_region_tpu.flagship import (
    batched_args, flagship_settings, synthetic_wsi_tiles,
)
from omero_ms_image_region_tpu.ops.jpegenc import (
    default_sparse_cap, packed_to_jpeg_coefficients, quant_tables,
    render_to_jpeg_sparse, render_to_jpeg_coefficients, sparse_pack,
)
from omero_ms_image_region_tpu.ops.render import render_tile_batch_packed

import jax
import jax.numpy as jnp


def sync(x):
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(leaf.ravel()[:1])


def t(fn, n=5):
    fn()
    xs = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        xs.append((time.perf_counter() - t0) * 1e3)
    return min(xs)


def main():
    rng = np.random.default_rng(7)
    C, H, W = 4, 1024, 1024
    quality = 85
    cap = default_sparse_cap(H, W)
    _, settings = flagship_settings()
    qy, qc = (tt.astype(np.int32) for tt in quant_tables(quality))

    for B in (8, 16, 32):
        raw = synthetic_wsi_tiles(rng, B, C, H, W)
        args_suffix = batched_args(settings, raw)[1:]
        dev_raw = jax.device_put(raw)
        sync(dev_raw)

        render = jax.jit(render_tile_batch_packed)
        ms_render = t(lambda: sync(render(dev_raw, *args_suffix)))
        ms_coeff = t(lambda: sync(render_to_jpeg_coefficients(
            dev_raw, *args_suffix, qy, qc)))
        ms_sparse = t(lambda: sync(render_to_jpeg_sparse(
            dev_raw, *args_suffix, qy, qc, cap=cap)))
        print(f"B={B:3d}: render={ms_render:7.1f}ms  +dct={ms_coeff:7.1f}ms "
              f" +sparse={ms_sparse:7.1f}ms  per-tile sparse="
              f"{ms_sparse / B:5.1f}ms")

    # empty dispatch: round-trip floor for a no-op jitted fn
    f = jax.jit(lambda x: x + 1)
    a = jax.device_put(np.zeros(8, np.float32))
    sync(a)
    print("noop dispatch+sync: %.1f ms" % t(lambda: sync(f(a))))


if __name__ == "__main__":
    main()
