"""Experiment: service-level throughput sweep on the real device.

Sweeps (engine, max_batch, pipeline_depth, linger) through the full
HTTP stack on one synthetic WSI and prints tiles/s per combo plus the
span timings from /metrics, to find where wave time goes.
"""

import asyncio
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from aiohttp.test_utils import TestClient, TestServer

from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.server.app import create_app
from omero_ms_image_region_tpu.server.config import (
    AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)


_SEQ = [0]


def run_combo(tmp, engine, max_batch, depth, linger, n_requests=16):
    config = AppConfig(
        data_dir=tmp,
        batcher=BatcherConfig(enabled=True, linger_ms=linger,
                              max_batch=max_batch,
                              pipeline_depth=depth),
        raw_cache=RawCacheConfig(enabled=True, prefetch=False),
        renderer=RendererConfig(cpu_fallback_max_px=0,
                                jpeg_engine=engine))

    async def run():
        app = create_app(config)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            def url(i):
                # Every request gets a unique window so the relay's
                # dispatch memoization can never serve a cached reply
                # (same discipline as bench._service_run).
                _SEQ[0] += 1
                w = 20000 + (_SEQ[0] % 5000) * 9
                x, y = i % 4, (i // 4) % 4
                return (f"/webgateway/render_image_region/1/0/0"
                        f"?tile=0,{x},{y},1024,1024&format=jpeg&m=c"
                        f"&c=1|0:{w}$FF0000,2|0:{w - 1000}$00FF00,"
                        f"3|0:{w - 2000}$0000FF,4|0:{w - 3000}$FFFF00")
            await asyncio.gather(*(client.get(url(i))
                                   for i in range(n_requests)))
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                resps = await asyncio.gather(
                    *(client.get(url(i)) for i in range(n_requests)))
                assert all(r.status == 200 for r in resps)
                for r in resps:
                    await r.read()
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            m = await (await client.get("/metrics")).text()
            return n_requests / best, m
        finally:
            await client.close()

    return asyncio.run(run())


def main():
    rng = np.random.default_rng(
        int.from_bytes(os.urandom(8), "little"))
    tmp = tempfile.mkdtemp()
    planes = synthetic_wsi_tiles(rng, 4, 1, 4096, 4096).reshape(
        4, 1, 4096, 4096)
    build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)

    combos = [
        ("sparse", 8, 2, 3.0),
        ("huffman", 8, 2, 3.0),
        ("huffman", 16, 2, 3.0),
        ("huffman", 16, 3, 3.0),
        ("sparse", 16, 2, 3.0),
        ("sparse", 16, 3, 3.0),
        ("sparse", 8, 3, 3.0),
    ]
    for engine, mb, depth, linger in combos:
        tps, metrics = run_combo(tmp, engine, mb, depth, linger)
        print(f"{engine:8s} mb={mb:3d} depth={depth} linger={linger}: "
              f"{tps:6.1f} tiles/s", flush=True)
        if os.environ.get("SHOW_SPANS"):
            for line in metrics.splitlines():
                if "span" in line and ("renderAsPackedInt" in line
                                       or "getPixelBuffer" in line):
                    print("   ", line)


if __name__ == "__main__":
    main()
