"""Experiment: service-level throughput vs batcher pipeline depth.

The batcher overlaps up to ``pipeline-depth`` group renders (dispatch /
wire fetch / host entropy encode).  On a high-RTT tunnel each group's
fetch pays the ~100 ms round-trip floor, so depth 2 may leave the wire
idle between groups; this measures the closed-loop service rate at
several depths under the link of the moment.

Usage: python scripts/exp_pipeline_depth.py [depth ...]
"""

import asyncio
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.server.config import (
    AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)

import bench  # noqa: E402  (repo-root harness: reuse _service_run)


def main() -> None:
    # Args: colon-separated max_batch:depth pairs, e.g. 8:2 16:4; bare
    # ints are depths with max_batch 8.
    combos = []
    for a in sys.argv[1:]:
        mb, _, d = a.partition(":")
        combos.append((int(mb), int(d)) if d else (8, int(mb)))
    combos = combos or [(8, 2), (8, 4), (16, 2), (16, 4)]
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 4, 1, 4096, 4096).reshape(
            4, 1, 4096, 4096)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        for engine in ("huffman", "sparse"):
            for max_batch, depth in combos:
                config = AppConfig(
                    data_dir=tmp,
                    batcher=BatcherConfig(enabled=True, linger_ms=3.0,
                                          max_batch=max_batch,
                                          pipeline_depth=depth),
                    raw_cache=RawCacheConfig(enabled=True, prefetch=False),
                    renderer=RendererConfig(cpu_fallback_max_px=0,
                                            jpeg_engine=engine))
                t0 = time.perf_counter()
                tps, _p50 = asyncio.run(bench._service_run(config))
                print(f"engine={engine} batch={max_batch} depth={depth}: "
                      f"{tps:.1f} tiles/s "
                      f"(window {time.perf_counter() - t0:.1f}s)", flush=True)


if __name__ == "__main__":
    main()
