"""Service-level stage waterfall (diagnostic for the 10x close).

Runs the bench's exact closed-loop service workload (16 clients,
1024^2 4-ch tiles, k-varied windows) against the real app while
recording where each group's wall time goes:

  queue_wait   request enqueue -> group pop
  group_size   tiles per dispatched group (pad waste shows here)
  dispatch     group pop -> device dispatch returned
  fetch        wire fetch wall (start -> all prefix bytes on host)
  fetch2       under-predicted second fetch (each pays ~1 RTT)
  encode       host entropy/framing tail
  settle       encode done -> futures resolved

Usage: python scripts/profile_service.py [duration_s] [engine]
"""

import asyncio
import os
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class Recorder:
    def __init__(self):
        self.events = {}

    def add(self, name, value):
        self.events.setdefault(name, []).append(value)

    def summary(self):
        out = {}
        for name, vals in sorted(self.events.items()):
            vs = sorted(vals)
            out[name] = {
                "n": len(vs),
                "p50": vs[len(vs) // 2],
                "p90": vs[int(len(vs) * 0.9)],
                "sum": sum(vs),
            }
        return out


REC = Recorder()


def patch():
    """Per-group wall-time split; everything finer-grained (queue wait,
    wire fetch/fetch2, encode) is read from the production REGISTRY
    spans the serving path records itself."""
    from omero_ms_image_region_tpu.ops import jpegenc
    from omero_ms_image_region_tpu.server import batcher as batcher_mod

    orig_jpeg = batcher_mod.BatchingRenderer._render_group_jpeg

    def render_group_jpeg(self, group):
        t0 = time.perf_counter()
        REC.add("group_size", len(group))
        out = orig_jpeg(self, group)
        REC.add("group_total_ms", (time.perf_counter() - t0) * 1e3)
        return out

    batcher_mod.BatchingRenderer._render_group_jpeg = render_group_jpeg


def main():
    duration = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    engine = sys.argv[2] if len(sys.argv) > 2 else "huffman"
    max_batch = int(sys.argv[3]) if len(sys.argv) > 3 else 8

    import jax
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))

    patch()

    from omero_ms_image_region_tpu.ops import jpegenc as _je

    def observe(nbytes, seconds, conflated=False):
        REC.add("wire_bytes", nbytes)

    _je.set_fetch_observer(observe)

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)

    import bench

    rng = np.random.default_rng(int.from_bytes(os.urandom(8), "little"))
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 4, 1, 4096, 4096).reshape(
            4, 1, 4096, 4096)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=True, linger_ms=3.0,
                                  max_batch=max_batch),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0,
                                    jpeg_engine=engine))
        t0 = time.perf_counter()
        tps, p50 = asyncio.run(
            bench._service_run(config, duration_s=duration))
        wall = time.perf_counter() - t0

    from omero_ms_image_region_tpu.utils.linkprobe import \
        measure_fetch_mb_s
    link = measure_fetch_mb_s(nbytes=2 << 20, repeats=2)
    tiles = sum(REC.events.get("group_size", []))
    wire_mb = sum(REC.events.get("wire_bytes", [])) / 1e6
    per_tile = wire_mb / max(tiles, 1)
    print(f"\nengine={engine} window={duration}s wall={wall:.1f}s "
          f"tiles/s={tps:.1f} p50={p50:.0f}ms")
    print(f"  link_adjacent={link:.1f} MB/s  wire={wire_mb:.1f} MB "
          f"({per_tile * 1000:.0f} KB/tile)  "
          f"wire_bound_ceiling={link / max(per_tile, 1e-9):.1f} tiles/s")
    for name, s in REC.summary().items():
        if name.endswith("_ms"):
            print(f"  {name:22s} n={s['n']:4d} p50={s['p50']:8.1f} "
                  f"p90={s['p90']:8.1f} sum={s['sum'] / 1e3:7.2f}s")
        else:
            print(f"  {name:22s} n={s['n']:4d} p50={s['p50']:8.0f} "
                  f"p90={s['p90']:8.0f} sum={s['sum']:.0f}")
    sizes = REC.events.get("group_size", [])
    if sizes:
        from collections import Counter
        print("  group size histogram:", dict(sorted(
            Counter(sizes).items())))
    from omero_ms_image_region_tpu.utils.stopwatch import REGISTRY
    print("  -- registry spans --")
    for name, s in sorted(REGISTRY.snapshot().items()):
        print(f"  {name:34s} n={s['count']:5d} mean={s['mean_ms']:8.1f} "
              f"p50={s['p50_ms']:8.1f}")


if __name__ == "__main__":
    main()
