"""Benchmark harness: the 5 BASELINE.md configs, TPU vs CPU reference.

The reference publishes no numbers (BASELINE.md), so the baseline is our own
faithful CPU implementation of the Java ``Renderer`` semantics
(``omero_ms_image_region_tpu.refimpl``) run on the same workload.

Headline metric (BASELINE.json): tiles/sec on 4-channel uint16 1024x1024
tiles (config 3, batched deep-zoom pan).  ``vs_baseline`` = TPU tiles/sec
divided by CPU-reference tiles/sec on identical tiles.  The other four
configs report as extras in the same JSON line.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np


def _settings_for(C, ptype="uint16", window=(100.0, 40000.0), model="rgb"):
    from omero_ms_image_region_tpu.flagship import FLAGSHIP_COLORS
    from omero_ms_image_region_tpu.models.pixels import Pixels
    from omero_ms_image_region_tpu.models.rendering import (
        RenderingModel, default_rendering_def,
    )
    from omero_ms_image_region_tpu.ops.render import pack_settings

    pixels = Pixels(image_id=1, pixels_type=ptype, size_x=8192, size_y=8192,
                    size_c=C)
    rdef = default_rendering_def(pixels)
    rdef.model = (RenderingModel.RGB if model == "rgb"
                  else RenderingModel.GREYSCALE)
    for i, cb in enumerate(rdef.channel_bindings):
        cb.active = True
        cb.red, cb.green, cb.blue = FLAGSHIP_COLORS[i % len(FLAGSHIP_COLORS)]
        cb.input_start, cb.input_end = window
    return rdef, pack_settings(rdef)


def _timed(fn, *args, repeats=3, warmup=True):
    """Best-of-N wall time for fn(*args) with one warm-up call."""
    if warmup:
        fn(*args)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn(*args)
        times.append(time.perf_counter() - t0)
    return min(times)


def telemetry_wire_frames_per_flush():
    """Process-global wire coalescing mean, None when the run never
    crossed the sidecar wire (combined posture)."""
    try:
        from omero_ms_image_region_tpu.utils import telemetry
        return telemetry.WIRE.frames_per_flush()
    except Exception:
        return None


def telemetry_wire_ring_hit_rate():
    try:
        from omero_ms_image_region_tpu.utils import telemetry
        return telemetry.WIRE.ring_hit_rate()
    except Exception:
        return None


def _opt_round(v, nd):
    return None if v is None else round(v, nd)


def _cpu_jpeg(rgba, quality=85):
    """The CPU comparators' shared encode convention: PIL/libjpeg RGB."""
    import io

    from PIL import Image

    out = io.BytesIO()
    Image.fromarray(np.ascontiguousarray(rgba[..., :3])).save(
        out, format="JPEG", quality=quality)
    return out.getvalue()


# ----------------------------------------------------------- config 3 (HEAD)

def bench_flagship(rng):
    """4-ch uint16 1024^2 batched pan, raw -> JPEG bytes, TPU vs CPU.

    The deliverable of the hot path is an encoded tile (the reference
    renders packed ints then JPEG-compresses them on the CPU,
    ``ImageRegionRequestHandler.java:559,580-582``).  TPU path: uint16
    host batch -> fused render + JPEG DCT/quantize kernel (one dispatch,
    packed RGBA never leaves HBM) -> async coefficient fetch -> native
    C++ entropy coder on a thread pool.  CPU path: the numpy reference
    renderer + PIL (libjpeg) encode on identical tiles.
    """
    import concurrent.futures as cf

    from omero_ms_image_region_tpu.flagship import (
        batched_args, flagship_settings, synthetic_wsi_tiles,
    )
    from omero_ms_image_region_tpu.ops.jpegenc import (
        quant_tables, render_to_jpeg_coefficients,
    )
    from omero_ms_image_region_tpu.refimpl import render_ref

    from omero_ms_image_region_tpu.native import jpeg_native_available
    if jpeg_native_available():
        from omero_ms_image_region_tpu.native import (
            jpeg_encode_native as entropy_encode,
        )
    else:
        from omero_ms_image_region_tpu.jfif import (
            encode_jfif as entropy_encode,
        )

    from omero_ms_image_region_tpu.ops.jpegenc import (
        compact_fetcher, default_sparse_cap, default_words_cap,
        encode_sparse_buffers, finish_huffman_batch,
        render_to_jpeg_coefficients, render_to_jpeg_huffman_compact,
        render_to_jpeg_sparse_compact, spec_kernel_arrays,
    )

    import jax

    rdef, settings = flagship_settings()
    B, C, H, W = 8, 4, 1024, 1024
    n_batches = 4
    quality = 85
    cap = default_sparse_cap(H, W)
    cap_words = default_words_cap(H, W)
    raw_batches = [synthetic_wsi_tiles(rng, B, C, H, W)
                   for _ in range(n_batches)]
    args_suffix = batched_args(settings, raw_batches[0])[1:]
    qy, qc = (t.astype(np.int32) for t in quant_tables(quality))
    # Tune the huffman wire to the workload before sampling — the same
    # tables the serving path's background tuner would publish after
    # its first group (one dense-coefficient sample, outside the timed
    # windows); the framing below must declare them.
    from omero_ms_image_region_tpu.jfif import (
        symbol_frequencies, tuned_huffman_spec)
    _one = tuple(a[:1] if getattr(a, "ndim", 0) else a
                 for a in args_suffix)
    _y0, _cb0, _cr0 = (np.asarray(a)[0] for a in
                       render_to_jpeg_coefficients(
                           raw_batches[0][:1], *_one, qy, qc))
    tuned8 = tuned_huffman_spec(*symbol_frequencies(_y0, _cb0, _cr0))
    spec = spec_kernel_arrays(tuned8)
    pool = cf.ThreadPoolExecutor(max_workers=8)
    # Compacted wire (the serving path's format): the fetch carries
    # exactly the batch's used bytes behind a lengths header.
    fetchers = {"sparse": compact_fetcher("sparse", H, W, cap, 0, B),
                "huffman": compact_fetcher("huffman", H, W, cap,
                                           cap_words, B)}

    # Stage the pan's raw tiles into HBM once — the warm interactive
    # posture (the service keeps hot tiles device-resident and re-renders
    # on settings/pan changes).  Upload is reported separately, and the
    # cold number below charges it end to end.
    t0 = time.perf_counter()
    dev_raw = [jax.device_put(r) for r in raw_batches]
    jax.block_until_ready(dev_raw)
    # block_until_ready does NOT wait for remote completion on tunnel
    # transports (dispatch is fully async); fetching one element of each
    # array is what forces the transfer to have landed.  Dispatch every
    # probe slice first, then materialize, so the forced landings
    # overlap and the window absorbs ~1 RTT instead of n_batches RTTs.
    probes = [r.ravel()[:1] for r in dev_raw]
    for p in probes:
        np.asarray(p)
    upload_s = time.perf_counter() - t0
    upload_mb_s = sum(r.nbytes for r in raw_batches) / 1e6 / upload_s

    def dense_fallback(raw, i):
        y, cb, cr = render_to_jpeg_coefficients(
            raw[i:i + 1].astype(np.float32), *(
                a[i:i + 1] if getattr(a, "ndim", 0) else a
                for a in args_suffix), qy, qc)
        return entropy_encode(np.asarray(y)[0], np.asarray(cb)[0],
                              np.asarray(cr)[0], W, H, quality)

    def dispatch(raw, engine):
        """One device dispatch of the chosen wire engine for a batch."""
        if engine == "sparse":
            return render_to_jpeg_sparse_compact(
                raw, *args_suffix, qy, qc, np.int32(B), cap=cap)
        return render_to_jpeg_huffman_compact(
            raw, *args_suffix, qy, qc, *spec, np.int32(B),
            h16=H // 16, w16=W // 16, cap=cap, cap_words=cap_words)

    def run_once(batches, engine="sparse"):
        """One full pan: all batches raw -> JPEG bytes; returns p50 ms.

        Device: fused render + JPEG front end + wire packing — 18-bit
        sparse entries or the device fixed-table Huffman stream (one
        dispatch per batch, all dispatched up-front so the device
        pipelines).  Wire: predictive prefix fetch — only the
        entropy-bearing bytes cross the link, started async for every
        batch before the first host encode.  Host: entropy coding
        (sparse) or 0xFF-stuff + framing (huffman), overlapping later
        batches' wire time.
        """
        starter = fetchers[engine]
        handles = [starter.start(dispatch(raw, engine))
                   for raw in batches]
        batch_ms, jpegs = [], []
        # `batches`, not the closure's raw_batches: the cold path passes
        # perturbed arrays and the dense fallback must see those pixels.
        for raw, h in zip(batches, handles):
            t0 = time.perf_counter()
            rows = starter.finish(h)
            if engine == "sparse":
                jpegs.extend(encode_sparse_buffers(
                    rows, W, H, quality, cap, executor=pool,
                    dense_fallback=lambda i, raw=raw:
                        dense_fallback(raw, i)))
            else:
                jpegs.extend(finish_huffman_batch(
                    rows, [(W, H)] * B, H, W, quality, cap, cap_words,
                    dense_fallback=lambda i, raw=raw:
                        dense_fallback(raw, i), spec=tuned8))
            batch_ms.append((time.perf_counter() - t0) * 1000.0)
        assert all(j[:2] == b"\xff\xd8" for j in jpegs)
        return statistics.median(batch_ms)

    # The tunnel's throughput swings with multi-second relay congestion
    # windows; sample each engine (alternating, up to 7 rounds each)
    # until its best stops improving, then let the better engine carry
    # the headline — both are supported serving configurations
    # (renderer.jpeg-engine), picked per deployment link.
    # Engine rounds INTERLEAVE (sparse, huffman, sparse, ...) so the
    # minute-scale congestion weather hits both engines alike — engine-
    # by-engine sampling would hand the win to whichever engine drew the
    # calmer minutes.  Each engine stops once its best stops improving.
    engines = ("sparse", "huffman")
    for e in engines:
        run_once(dev_raw, e)        # warm-up/compile + prefix prediction
    times = {e: [] for e in engines}
    p50s = {e: [] for e in engines}
    stale = {e: 0 for e in engines}
    for _round in range(7):
        live = [e for e in engines
                if not (len(times[e]) >= 4 and stale[e] >= 3)]
        if not live:
            break
        for e in live:
            t0 = time.perf_counter()
            p50s[e].append(run_once(dev_raw, e))
            times[e].append(time.perf_counter() - t0)
            if times[e][-1] <= min(times[e]) * 1.02:
                stale[e] = 0
            else:
                stale[e] += 1
    results = {
        e: ((B * n_batches) / min(times[e]), statistics.median(p50s[e]))
        for e in engines
    }
    engine = max(results, key=lambda e: results[e][0])
    tiles_per_sec, p50_batch_ms = results[engine]

    # Cold path: charge host->HBM staging too (fresh uploads feeding
    # the same pipeline, twice; best of 2) through the serving path's
    # packed staging (io.staging.stage — block-packed deltas, ~1.4x
    # fewer wire bytes on this content class, decoded on device).
    # Every rep ships DISTINCT bytes (xor perturbation, outside the
    # timed window) so a content-memoizing relay cannot serve the
    # upload from cache.
    from omero_ms_image_region_tpu.io.staging import stage as _stage
    _stage(raw_batches[0] ^ np.uint16(77))   # compile the unpack kernel
    cold_times = []
    for rep in range(2):
        fresh = [r ^ np.uint16(rep + 1) for r in raw_batches]
        t0 = time.perf_counter()
        run_once([_stage(r) for r in fresh], engine)
        cold_times.append(time.perf_counter() - t0)
    cold_tiles_per_sec = (B * n_batches) / min(cold_times)
    # Overlap honesty: cold throughput expressed as staged bytes/s over
    # the raw upload rate measured ADJACENT to the cold window (the
    # startup upload_mb_s is minutes old by now and the tunnel swings
    # 5-700 MB/s — a stale denominator would make the ratio
    # meaningless).  ~1.0 = staging hides everything but the wire (the
    # wire IS the floor); well below 1.0 = staging serializes against
    # upload and double-buffering has room.
    cold_bytes_per_sec = (B * n_batches * raw_batches[0][0].nbytes
                          / min(cold_times))
    probe_raw = raw_batches[0] ^ np.uint16(101)
    t0 = time.perf_counter()
    probe_dev = jax.device_put(probe_raw)
    np.asarray(probe_dev.ravel()[:1])
    cold_window_upload_mb_s = probe_raw.nbytes / 1e6 \
        / (time.perf_counter() - t0)

    # The tunnel's dispatch+fetch round-trip floor, measured with a no-op
    # kernel: co-located hardware does not pay it, so single-tile latency
    # is reported both as wall time and with the floor subtracted.
    noop = jax.jit(lambda x: x + 1)
    rtts = []
    for k in range(5):
        # Distinct content per rep so a memoizing relay cannot serve a
        # cached reply and understate the floor.
        tiny = jax.device_put(np.full(8, float(k), np.float32))
        np.asarray(tiny.ravel()[:1])
        t0 = time.perf_counter()
        np.asarray(noop(tiny).ravel()[:1])
        rtts.append((time.perf_counter() - t0) * 1000.0)
    rtt_floor_ms = statistics.median(rtts[1:])

    # Device-capability ceiling, weather-independent: per-batch execution
    # time with the link RTT interleaved and subtracted (a 1-element
    # fetch forces completion; ``block_until_ready`` does not actually
    # block on tunnel transports and repeated identical dispatches can be
    # memoized relay-side, so each repeat uses fresh content).  This is
    # the tiles/sec a co-located deployment's device pipeline sustains
    # before the (local, fast) wire even matters.
    tick = jax.jit(lambda x: x.ravel()[:1] + 1)
    # Content varies per (engine, rep) WITHOUT re-uploading: a jitted
    # XOR perturbs the already-resident batches on device (only the
    # scalar mask crosses the link), so a content-memoizing relay never
    # sees a repeat and the probe costs no upload bandwidth.  XOR keeps
    # the uint16 content class (no saturation wrap).
    perturb = jax.jit(lambda x, m: x ^ m)
    exec_ms = {}
    for ei, eng in enumerate(("sparse", "huffman")):
        deltas = []
        for k in range(5):
            mask = np.uint16(1 + k + 8 * ei)   # unique across both loops
            fresh = perturb(dev_raw[k % n_batches], mask)
            # Force the perturbation to complete BEFORE the timing
            # window — otherwise the RTT tick absorbs it and the
            # subtraction goes negative.
            np.asarray(fresh.ravel()[:1])
            t0 = time.perf_counter()
            np.asarray(tick(fresh))
            t1 = time.perf_counter()
            np.asarray(dispatch(fresh, eng).ravel()[:1])
            t2 = time.perf_counter()
            if k:   # first rep carries compile
                deltas.append((t2 - t1) - (t1 - t0))
        # Congestion swings can push a delta negative (the RTT window
        # happened to be the slow one); those reps carry no signal.
        valid = [d for d in deltas if d > 0]
        exec_ms[eng] = (statistics.median(valid) * 1000.0 if valid
                        else None)
    measurable = [v for v in exec_ms.values() if v]
    device_ceiling_tps = (B / (min(measurable) / 1000.0)
                          if measurable else None)

    # Interactive single-tile latency (warm, B=1): raw resident -> JPEG
    # bytes on host.  BOTH wire engines measured — on a congested link
    # the huffman wire's ~3.6x fewer bytes win the single-tile race too,
    # and the adaptive engine (utils.adaptive) serves exactly that
    # choice — with per-rep on-device content perturbation so a
    # memoizing relay cannot serve cached dispatches.
    one = dev_raw[0][:1]
    one_args = tuple(a[:1] if getattr(a, "ndim", 0) else a
                     for a in args_suffix)
    one_fetchers = {
        "sparse": compact_fetcher("sparse", H, W, cap, 0, 1),
        "huffman": compact_fetcher("huffman", H, W, cap, cap_words, 1)}
    perturb1 = jax.jit(lambda x, m: x ^ m)

    def one_tile(x, eng):
        if eng == "sparse":
            rows = one_fetchers[eng].fetch(render_to_jpeg_sparse_compact(
                x, *one_args, qy, qc, np.int32(1), cap=cap))
            encode_sparse_buffers(rows, W, H, quality, cap)
        else:
            rows = one_fetchers[eng].fetch(render_to_jpeg_huffman_compact(
                x, *one_args, qy, qc, *spec, np.int32(1),
                h16=H // 16, w16=W // 16, cap=cap,
                cap_words=cap_words))
            finish_huffman_batch(rows, [(W, H)], H, W, quality, cap,
                                 cap_words,
                                 dense_fallback=lambda i:
                                     dense_fallback(raw_batches[0], i),
                                 spec=tuned8)
    p50_by_engine = {}
    for ei, eng in enumerate(("sparse", "huffman")):
        lat = []
        for k in range(8):
            fresh = perturb1(one, np.uint16(32 + k + 16 * ei))
            np.asarray(fresh.ravel()[:1])   # land the perturbation
            t0 = time.perf_counter()
            one_tile(fresh, eng)
            lat.append((time.perf_counter() - t0) * 1000.0)
        # Reps 0-1 carry compile AND the fetcher's prefix-prediction
        # warm-up (measured ~1.2 s vs ~0.2 s steady); the steady-state
        # interactive latency is what the metric means.
        p50_by_engine[eng] = statistics.median(lat[2:])
    p50_tile_ms = min(p50_by_engine.values())
    p50_tile_ms_ex_rtt = max(0.0, p50_tile_ms - rtt_floor_ms)

    # CPU reference on identical tiles: render + PIL JPEG (libjpeg).
    # Fixed >=18 s window so the denominator is stable run to run.
    def cpu_tile(raw_tile):
        return _cpu_jpeg(render_ref(raw_tile.astype(np.float32), rdef),
                         quality)

    n, t0 = 0, time.perf_counter()
    while True:
        cpu_tile(raw_batches[n // B % n_batches][n % B])
        n += 1
        dt = time.perf_counter() - t0
        if dt >= 18.0:
            break
    cpu_tps = n / dt
    return {
        "tiles_per_sec": tiles_per_sec,
        "engine": engine,
        "sparse_tiles_per_sec": results["sparse"][0],
        "huffman_tiles_per_sec": results["huffman"][0],
        "cold_tiles_per_sec": cold_tiles_per_sec,
        "cold_overlap_efficiency": (cold_bytes_per_sec / 1e6
                                    / cold_window_upload_mb_s),
        "p50_batch_ms": p50_batch_ms,
        "p50_tile_ms": p50_tile_ms,
        "p50_tile_ms_ex_rtt": p50_tile_ms_ex_rtt,
        "p50_tile_ms_sparse": p50_by_engine["sparse"],
        "p50_tile_ms_huffman": p50_by_engine["huffman"],
        "rtt_floor_ms": rtt_floor_ms,
        "cpu_tps": cpu_tps,
        "upload_mb_s": upload_mb_s,
        "sparse_exec_ms_batch": exec_ms["sparse"],
        "huffman_exec_ms_batch": exec_ms["huffman"],
        "device_ceiling_tps": device_ceiling_tps,
    }


# ------------------------------------------------------- service level

def bench_service_level(rng):
    """Config-3 pan through the FULL HTTP stack (routes, ctx parsing,
    caches, batcher, device dispatch, JPEG wire, entropy encode):
    sustained closed-loop load — 16 in-flight clients issuing 1024^2
    4-channel tile renders against the real app for a fixed window.

    Every request varies its channel windows, so each is a DISTINCT
    render (no byte-cache hit, and no relay-side dispatch memoization
    can serve a cached device reply); raw tiles stay device-resident
    after first touch — the honest warm interactive posture.  Both wire
    engines are measured and the better one carries the number,
    mirroring what a linkprobe-``auto`` deployment would pick for the
    link of the day.

    Returns (tiles/s, per-engine dict) or (None, {}) if the app stack
    cannot boot here."""
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)

    from omero_ms_image_region_tpu.services.cache import CacheConfig

    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 4, 1, 4096, 4096).reshape(
            4, 1, 4096, 4096)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        per_engine = {}
        for engine in ("sparse", "huffman"):
            config = AppConfig(
                data_dir=tmp,
                # Byte caches ON (the serving posture): the throughput
                # window's k-varied requests never repeat a key, so the
                # headline is unchanged, and the warm-repeat probe can
                # prove the acceptance path (second identical request
                # answers from the byte cache with no device span).
                caches=CacheConfig.enabled_all(),
                batcher=BatcherConfig(enabled=True, linger_ms=3.0),
                raw_cache=RawCacheConfig(enabled=True, prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0,
                                        jpeg_engine=engine))
            per_engine[engine] = asyncio.run(_service_run(config))
        best = max(v[0] for v in per_engine.values())
        return best, per_engine


async def _service_run(config, concurrency: int = 16,
                       duration_s: float = 8.0, grid: int = 4,
                       tile_edge: int = 1024, channels: int = 4,
                       fmt: str = "jpeg"):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.utils.stopwatch import (
        REGISTRY as _REG)

    app = create_app(config)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        seq = 0
        colors = ("FF0000", "00FF00", "0000FF", "FFFF00")

        def url(i, k):
            x, y = i % grid, (i // grid) % grid
            # k-varied windows: every request is a distinct render of
            # the SAME device-resident raw tile.  k comes from a shared
            # monotone counter (period 5000 — far beyond any realistic
            # request count in the window), so no (tile, window) pair
            # repeats and a dispatch-memoizing relay can never serve a
            # cached device reply.
            w = 20000 + (k % 5000) * 9
            chans = ",".join(
                f"{c + 1}|0:{w - 1000 * c}${colors[c % len(colors)]}"
                for c in range(channels))
            return (f"/webgateway/render_image_region/1/0/0"
                    f"?tile=0,{x},{y},{tile_edge},{tile_edge}"
                    f"&format={fmt}&m=c&c={chans}")
        # Warm: stage raw tiles into HBM + compile both grid shapes.
        resps = await asyncio.gather(
            *(client.get(url(i, i)) for i in range(grid * grid)))
        assert all(r.status == 200 for r in resps)
        snap0 = _REG.snapshot()
        t_stop = time.perf_counter() + duration_s
        done = 0
        failed = 0
        latencies_ms: list = []
        first_byte_ms: list = []

        async def worker(i: int) -> None:
            nonlocal done, seq, failed
            while time.perf_counter() < t_stop:
                seq += 1
                t_req = time.perf_counter()
                r = await client.get(url(i, 16 + seq))
                # First body bytes (the progressive-wire headline),
                # then the rest: with streaming on, chunked responses
                # surface the first tile bytes before the batch tail.
                await r.content.readany()
                t_first = time.perf_counter()
                await r.read()
                if r.status == 200:
                    done += 1
                    first_byte_ms.append((t_first - t_req) * 1000.0)
                    latencies_ms.append(
                        (time.perf_counter() - t_req) * 1000.0)
                else:
                    # A relay-transport drop that survived the group
                    # retry: count it (failures don't add to done) and
                    # only fail the window when errors aren't rare.
                    failed += 1
                    if failed > 5:
                        raise AssertionError(
                            f"service window: {failed} failed requests "
                            f"(last status {r.status})")

        t0 = time.perf_counter()
        # return_exceptions: one worker's failure must not strand the
        # other 15 mid-request while the client closes under them —
        # drain everyone (bounded by t_stop), then surface the error.
        results = await asyncio.gather(
            *(worker(i) for i in range(concurrency)),
            return_exceptions=True)
        errors = [r for r in results if isinstance(r, BaseException)]
        if errors:
            raise errors[0]
        wall_s = time.perf_counter() - t0
        tps = done / wall_s
        p50 = (statistics.median(latencies_ms) if latencies_ms
               else None)
        extras = await _hot_path_probes(app, client, url, seq,
                                        _REG.snapshot(), snap0, wall_s)
        extras["p50_first_tile_byte_ms"] = (
            round(statistics.median(first_byte_ms), 2)
            if first_byte_ms else None)
        return tps, p50, extras
    finally:
        await client.close()


async def _hot_path_probes(app, client, url, seq, snap1, snap0,
                           wall_s):
    """Dedup / plane-cache / overlap probes run right after a service
    window (same app instance, counters still live).

    * ``overlap_efficiency`` — device-execute span coverage of the
      window wall clock (exec_total_ms / wall_ms): 1.0 means the device
      never idled behind the fetch/stage half of the two-stage group
      pipeline; a regression back to serial fetch->render shows up as
      this falling with tiles/s.
    * ``dedup_hit_rate`` — of a burst of 8 concurrent IDENTICAL
      requests, the fraction coalesced by the single-flight table.
    * ``warm_repeat_cached`` — a repeated identical request answers
      from the byte cache with ZERO new device dispatches (the
      acceptance criterion's warm repeated-tile path).
    * ``planecache_hits/misses`` — content-digest staging skips.
    """
    import asyncio

    from omero_ms_image_region_tpu.server.app import SERVICES_KEY

    def total_ms(snap, name):
        return snap.get(name, {}).get("total_ms", 0.0)

    exec_ms = (total_ms(snap1, "Renderer.renderAsPackedInt.batch")
               - total_ms(snap0, "Renderer.renderAsPackedInt.batch"))
    stage_ms = (total_ms(snap1, "batcher.stage")
                - total_ms(snap0, "batcher.stage"))
    extras = {
        "overlap_efficiency": (round(exec_ms / (wall_s * 1000.0), 3)
                               if wall_s > 0 else None),
        "stage_ms_total": round(stage_ms, 1),
        "exec_ms_total": round(exec_ms, 1),
        "dedup_hit_rate": None,
        "warm_repeat_cached": None,
        "planecache_hits": None,
        "planecache_misses": None,
    }
    services = app[SERVICES_KEY]
    if services is None:
        return extras
    raw_cache = getattr(services, "raw_cache", None)
    if raw_cache is not None and hasattr(raw_cache, "plane_hits"):
        extras["planecache_hits"] = raw_cache.plane_hits
        extras["planecache_misses"] = raw_cache.plane_misses
    single_flight = getattr(services, "single_flight", None)
    renderer = services.renderer
    # Concurrent-identical burst: one render identity, 8 in flight.
    burst_url = url(0, seq + 2500)
    burst = 8
    hits0 = single_flight.hits if single_flight is not None else 0
    resps = await asyncio.gather(*(client.get(burst_url)
                                   for _ in range(burst)))
    bodies = [await r.read() for r in resps]
    if all(r.status == 200 for r in resps) and len(set(bodies)) == 1:
        if single_flight is not None:
            extras["dedup_hit_rate"] = round(
                (single_flight.hits - hits0) / burst, 3)
        # Warm repeat: the identical request again, now byte-cached —
        # zero new device dispatches proves no wire/device span ran.
        dispatched0 = getattr(renderer, "batches_dispatched", None)
        r = await client.get(burst_url)
        body = await r.read()
        extras["warm_repeat_cached"] = bool(
            r.status == 200 and body == bodies[0]
            and (dispatched0 is None
                 or renderer.batches_dispatched == dispatched0))
    return extras


def _overhead_table(n: int = 2000) -> dict:
    """ns/op of each cross-cutting feature's HOT-PATH guard cost —
    the per-request/per-tile tax of tracing, cost accounting, deadline
    checks, admission control and the disk write-behind enqueue,
    measured as tight micro-loops over the exact calls the serving
    path makes.

    This is the pay-for-what-you-use ledger for the feature layers
    PRs 1-5 added: each entry must stay ns-to-µs scale (the smoke gate
    asserts a budget in tests/test_bench_smoke.py), so a refactor that
    quietly puts a lock round-trip, a directory scan or a JSON encode
    on the hot path fails tier-1 instead of surfacing as the next
    BENCH round's -10%.
    """
    import queue as _queue
    import tempfile

    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.services.diskcache import (
        DiskByteCache)
    from omero_ms_image_region_tpu.utils import telemetry, transient
    from omero_ms_image_region_tpu.utils.stopwatch import (
        REGISTRY as _REG)

    def per_op(fn) -> float:
        fn()                                   # warm
        t0 = time.perf_counter_ns()
        for _ in range(n):
            fn()
        return round((time.perf_counter_ns() - t0) / n, 1)

    out = {}
    with telemetry.trace_scope(telemetry.new_trace_id(),
                               "bench.overhead"):
        # One stage span landing on a live trace's waterfall (the
        # stopwatch registry + histogram + trace attach).
        out["trace"] = per_op(
            lambda: _REG.record("bench.overhead", 0.01))
        # One batched cost-ledger flush (two fields, one lock).
        out["ledger"] = per_op(
            lambda: telemetry.add_costs({"device_ms": 0.01,
                                         "stage_ms": 0.01}))
        with transient.deadline_scope(30000.0):
            out["deadline"] = per_op(
                lambda: transient.check_deadline("bench"))
    adm = AdmissionController(max_queue=64)

    def admit_release():
        t = adm.admit()
        adm.release(t)

    out["admission"] = per_op(admit_release)
    with tempfile.TemporaryDirectory() as tmp:
        cache = DiskByteCache(tmp, max_bytes=1 << 20)

        def write_behind():
            # The request thread's share of a disk-cache set: enqueue
            # onto the bounded queue (a full queue drops + counts —
            # also the request thread's cost, never a block).
            try:
                cache._queue.put_nowait(("k", b"v"))
            except _queue.Full:
                telemetry.PERSIST.count_disk_write(dropped=True)
            try:
                cache._queue.get_nowait()
            except _queue.Empty:
                pass

        out["write_behind"] = per_op(write_behind)
    # The perf sentinel's per-request tax: one bounded-vocabulary key
    # probe + one sketch insert (bisect over ~350 bucket bounds).
    from omero_ms_image_region_tpu.server.sentinel import SentinelEngine
    eng = SentinelEngine(member="bench", bundle_dir="")
    out["sentinel"] = per_op(
        lambda: eng.observe("render_image_region", 65536, 12.5))
    return out


def _wire_smoke() -> dict:
    """Wire-transport probes at smoke scale (protocol v3): a REAL
    frontend -> sidecar hop over a unix socket with coalescing,
    chunked streaming and the same-host shm ring live.

    Three measurements, one JSON block merged into the smoke line:

    * ``p50_first_tile_byte_ms`` vs ``p50_batch_complete_ms`` — bursts
      of 4 concurrent distinct renders of one tile co-batch into one
      group; first-tile-out + chunk frames must land a request's first
      body byte strictly before the burst's last request completes
      (the v2 barrier settled everyone together at the tail).
    * ``wire_frames_per_flush`` — mean frames per vectored flush
      across the window; > 1 under concurrent load proves the
      coalescer amortizes syscalls/RTTs.
    * ``shm_ring_hit_rate`` + ``shm_upload_mb_per_sec`` vs
      ``socket_upload_mb_per_sec`` — the same bulk ``stage_planes``
      upload through a ring-negotiated client and a ring-disabled one
      (fresh content each, so digest dedup cannot short-circuit).
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid

    rng = np.random.default_rng(11)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, 512, 512).reshape(
            2, 1, 512, 512)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        sock = os.path.join(tmp, "wire.sock")
        return asyncio.run(_wire_run(tmp, sock, rng))


async def _wire_run(tmp: str, sock: str, rng) -> dict:
    import asyncio
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig,
        SidecarConfig, WireConfig)
    from omero_ms_image_region_tpu.server.sidecar import (SidecarClient,
                                                          run_sidecar)
    from omero_ms_image_region_tpu.utils import telemetry

    telemetry.WIRE.reset()
    sidecar_cfg = AppConfig(
        data_dir=tmp,
        # linger long enough that an 8-way burst forms ONE group (the
        # batch whose barrier the streaming path must beat — a bigger
        # group means a longer per-tile encode tail to get ahead of).
        batcher=BatcherConfig(enabled=True, linger_ms=15.0,
                              max_batch=8),
        raw_cache=RawCacheConfig(enabled=True, prefetch=False),
        renderer=RendererConfig(cpu_fallback_max_px=0))
    task = asyncio.create_task(run_sidecar(sidecar_cfg, sock))
    for _ in range(600):
        if task.done():
            raise RuntimeError(f"wire smoke sidecar died: "
                               f"{task.exception()!r}")
        if os.path.exists(sock):
            break
        await asyncio.sleep(0.05)
    front_cfg = AppConfig(data_dir=tmp,
                          sidecar=SidecarConfig(socket=sock,
                                                role="frontend"))
    app = create_app(front_cfg)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        colors = ("FF0000", "00FF00")

        def url(k: int) -> str:
            # k-varied windows: 4 DISTINCT renders of the same raw
            # tile (no byte-cache or single-flight short-circuit), all
            # in one bucket/batch key.
            w = 20000 + (k % 5000) * 9
            chans = ",".join(
                f"{c + 1}|0:{w - 1000 * c}${colors[c]}"
                for c in range(2))
            return (f"/webgateway/render_image_region/1/0/0"
                    f"?tile=0,0,0,256,256&format=jpeg&m=c&c={chans}")

        seq_box = [100]

        async def one(cl, k: int):
            t0 = time.perf_counter()
            r = await cl.get(url(k))
            await r.content.readany()
            t_first = time.perf_counter()
            await r.read()
            return (r.status, (t_first - t0) * 1000.0,
                    (time.perf_counter() - t0) * 1000.0)

        async def burst_stats(cl, n_bursts: int):
            # Warm: stage the tile + compile the burst's group shape
            # (the second stack reuses the in-process jit caches).
            warm = await asyncio.gather(*(cl.get(url(seq_box[0] + i))
                                          for i in range(8)))
            assert all(r.status == 200 for r in warm), \
                [r.status for r in warm]
            for r in warm:
                await r.read()
            seq_box[0] += 8
            firsts, completes = [], []
            for _ in range(n_bursts):
                rs = await asyncio.gather(*(one(cl, seq_box[0] + j)
                                            for j in range(8)))
                seq_box[0] += 8
                assert all(s == 200 for s, _, _ in rs), rs
                # The burst's first body byte vs its batch completion
                # (last member fully answered) — the gap IS the
                # first-tile-out + chunk-forwarding win.
                firsts.append(min(f for _, f, _ in rs))
                completes.append(max(t for _, _, t in rs))
            return firsts, completes

        firsts, batch_completes = await burst_stats(client, 12)

        # Upload-path A/B on the same live sidecar: ring-negotiated vs
        # ring-disabled client shipping the SAME MB-scale bodies.  The
        # bodies ride ``ping`` requests (whose body the server reads
        # and discards), so this isolates the WIRE leg the ring
        # replaces — ``stage_planes`` end-to-end would be dominated by
        # the server's digest + device staging, identical both ways
        # (and already measured by ``raw_upload_mb_per_sec``).
        body = rng.integers(0, 60000, size=(1024, 1024)) \
            .astype(np.uint16).tobytes()               # 2 MiB
        n_bodies = 8
        ring_client = SidecarClient(sock)
        sock_client = SidecarClient(sock, wire=WireConfig(ring_bytes=0))
        try:
            await ring_client.call("ping", {})     # handshakes +
            await sock_client.call("ping", {})     # connection setup

            async def upload_window(cl) -> float:
                t0 = time.perf_counter()
                rs = await asyncio.gather(
                    *(cl.call("ping", {}, body=body)
                      for _ in range(n_bodies)))
                assert all(s == 200 for s, _ in rs)
                return (n_bodies * len(body) / 1e6
                        / (time.perf_counter() - t0))

            rates = {"socket": 0.0, "ring": 0.0}
            # Interleaved best-of-3 per path: single-rep ordering (and
            # this box's scheduler) otherwise decides the A/B.
            for _ in range(3):
                for name, cl in (("socket", sock_client),
                                 ("ring", ring_client)):
                    rates[name] = max(rates[name],
                                      await upload_window(cl))
        finally:
            await ring_client.close()
            await sock_client.close()

        # Barrier A/B (informational, not gated: the CPU-smoke margin
        # is a few ms and CI jitter would flake a strict ordering):
        # the same bursts against a streaming-OFF stack, where the v2
        # barrier settles everyone at the batch tail.  The mechanism
        # itself is gated deterministically in
        # tests/test_wire_v3.py::test_first_tile_out_settles_before_barrier.
        p50_first_barrier = None
        sock2 = sock + ".barrier"
        barrier_cfg = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=True, linger_ms=15.0,
                                  max_batch=8),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0),
            wire=WireConfig(streaming=False))
        task2 = asyncio.create_task(run_sidecar(barrier_cfg, sock2))
        client2 = None
        try:
            for _ in range(600):
                if task2.done():
                    raise RuntimeError(f"barrier sidecar died: "
                                       f"{task2.exception()!r}")
                if os.path.exists(sock2):
                    break
                await asyncio.sleep(0.05)
            app2 = create_app(AppConfig(
                data_dir=tmp,
                sidecar=SidecarConfig(socket=sock2, role="frontend"),
                wire=WireConfig(streaming=False)))
            client2 = TestClient(TestServer(app2))
            await client2.start_server()
            b_firsts, _ = await burst_stats(client2, 6)
            p50_first_barrier = round(statistics.median(b_firsts), 2)
        except Exception:
            pass     # informational only: never fail the smoke on it
        finally:
            if client2 is not None:
                await client2.close()
            task2.cancel()
            try:
                await task2
            except (asyncio.CancelledError, Exception):
                pass

        wire = telemetry.WIRE
        hit_rate = wire.ring_hit_rate()
        return {
            "p50_first_tile_byte_ms": round(
                statistics.median(firsts), 2),
            "p50_batch_complete_ms": round(
                statistics.median(batch_completes), 2),
            "p50_first_tile_byte_ms_barrier": p50_first_barrier,
            "wire_frames_per_flush": round(
                wire.frames_per_flush() or 0.0, 3),
            "shm_ring_hit_rate": (round(hit_rate, 3)
                                  if hit_rate is not None else None),
            "shm_upload_mb_per_sec": round(rates["ring"], 1),
            "socket_upload_mb_per_sec": round(rates["socket"], 1),
            "wire_streams": wire.streams,
            "wire_ring_negotiated": wire.ring_negotiated,
        }
    finally:
        await client.close()
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass


def _fleet_smoke(exec_ms: float = 150.0, grid: int = 4,
                 tile_edge: int = 128, variants: int = 3) -> dict:
    """Fleet-serving smoke probe: the data-parallel router over N=4
    virtual members vs the same burst through ONE member.

    Each member is a REAL serving stack — its own renderer + its own
    ``DeviceRawCache`` shard over a shared pyramid — plus a calibrated
    virtual device-execute occupancy (``exec_ms`` of lane time per
    render).  On this 2-core CI host the chips' compute parallelism
    cannot exist, so the sleep stands in for the member's device
    service time; what the probe then honestly measures is that the
    ROUTING layer scales — consistent-hash spread, per-member lanes,
    stealing under skew — with zero added serialization, and that the
    HBM tier SHARDS: after a mixed-digest burst each staged plane is
    resident on exactly ONE member (duplicates asserted 0 in tier-1;
    total residency ~= the working set, minus any plane whose every
    render happened to be stolen — stealing never adopts).  The real
    1->8 chip curve is the MULTICHIP record's job
    (``__graft_entry__.fleet_scaling_curve``).
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, LocalMember,
        build_local_members)
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.utils import telemetry

    rng = np.random.default_rng(13)
    exec_s = exec_ms / 1000.0

    class VirtualDeviceMember(LocalMember):
        """A fleet member whose device-execute service time is the
        calibrated occupancy above — the render itself (read, stage,
        HBM cache, render kernel, encode) is entirely real."""

        async def render(self, ctx, adopt_cache=True):
            data = await super().render(ctx, adopt_cache)
            await asyncio.sleep(exec_s)
            return data

    def urls(k_base: int):
        out = []
        for v in range(variants):
            for x in range(grid):
                for y in range(grid):
                    w = 20000 + (k_base + v) * 700
                    out.append({
                        "imageId": "1", "theZ": "0", "theT": "0",
                        "tile": f"0,{x},{y},{tile_edge},{tile_edge}",
                        "format": "png", "m": "c",
                        "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
                    })
        return out

    async def run_fleet(tmp: str, n_members: int) -> dict:
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        members = build_local_members(config, services, n_members)
        members = [VirtualDeviceMember(
            m.name, m.handler, m.services,
            down_cooldown_s=m.down_cooldown_s,
            byte_cache_prechecked=m.byte_cache_prechecked)
            for m in members]
        router = FleetRouter(members, lane_width=2,
                             steal_min_backlog=2)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            admission=AdmissionController(512, renderer=router),
            base_services=services)
        before = telemetry.FLEET.totals()
        try:
            ctxs = [ImageRegionCtx.from_params(p) for p in urls(16)]
            # Warm the compile (shared in-process jit cache) outside
            # the window; the plane reads/staging stay in it.
            await handler.render_image_region(
                ImageRegionCtx.from_params(urls(900)[0]))
            t0 = time.perf_counter()
            out = await asyncio.gather(
                *(handler.render_image_region(c) for c in ctxs))
            wall = time.perf_counter() - t0
            assert all(out)
            after = telemetry.FLEET.totals()
            report = router.shard_report()
            return {
                "tps": len(ctxs) / wall,
                "shard": report,
                "routed": after["routed"] - before["routed"],
                "stolen": after["stolen"] - before["stolen"],
            }
        finally:
            await router.close()
            services.pixels_service.close()

    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        single = asyncio.run(run_fleet(tmp, 1))
        fleet = asyncio.run(run_fleet(tmp, 4))
    working_set = grid * grid
    return {
        "fleet_members": 4,
        "fleet_virtual_exec_ms": exec_ms,
        "fleet_tiles_per_sec": round(fleet["tps"], 2),
        "fleet_single_member_tiles_per_sec": round(single["tps"], 2),
        "fleet_speedup": round(fleet["tps"] / single["tps"], 2),
        "fleet_working_set_planes": working_set,
        # Sharded, not duplicated: every plane of the working set
        # resident on exactly one member after the mixed-digest burst.
        "fleet_resident_planes": fleet["shard"]["resident_digests"],
        "fleet_duplicate_staged_planes":
            fleet["shard"]["duplicate_digests"],
        "fleet_member_planes": fleet["shard"]["members"],
        "fleet_routed_total": fleet["routed"],
        "fleet_stolen_total": fleet["stolen"],
    }


def bench_smoke(duration_s: float = 1.5):
    """Hot-path regression gate at smoke scale: CPU, small shapes, <60 s.

    The FULL app — routes, ctx parsing, byte caches, single-flight
    dedup, two-stage batcher pipeline, device plane cache — over a
    small synthetic pyramid (2-channel 512^2, 256^2 png tiles, so
    compiles stay in the seconds on the host platform).  Prints ONE
    JSON line mirroring the service-level keys; wired into tier-1
    (tests/test_bench_smoke.py) so a cache or pipeline regression fails
    tests instead of waiting for the next BENCH round.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.services.cache import CacheConfig

    t_start = time.perf_counter()
    # The gate below judges THIS window's ledger: the top-K table is
    # process-global, and a stale expensive request from whatever this
    # interpreter ran earlier (tier-1 shares it) must not stand in for
    # the smoke run's attribution.
    from omero_ms_image_region_tpu.utils import telemetry
    telemetry.COST_TOPK.reset()
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, 512, 512).reshape(
            2, 1, 512, 512)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        config = AppConfig(
            data_dir=tmp,
            caches=CacheConfig.enabled_all(),
            batcher=BatcherConfig(enabled=True, linger_ms=2.0),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        tps, p50, extras = asyncio.run(_service_run(
            config, concurrency=4, duration_s=duration_s, grid=2,
            tile_edge=256, channels=2, fmt="png"))
    # Wire-transport probes (protocol v3): split posture over a unix
    # socket — first-byte vs batch barrier, frames per vectored flush,
    # and the shm-ring vs socket upload A/B.
    wire = _wire_smoke()
    # Fleet-serving probes: N=4 virtual members vs one member over the
    # same mixed-digest burst — routing-layer scaling + HBM sharding
    # (gated in tests/test_bench_smoke.py).
    fleet = _fleet_smoke()
    # Cost-ledger liveness: the attribution layer must have recorded
    # WHERE the smoke window's time went, request by request — a
    # refactor that silently drops the ledger fails the gate here.
    top = telemetry.COST_TOPK.snapshot()
    cost_keys = sorted(top[0]["cost"].keys()) if top else []
    assert {"device_ms", "queue_ms", "total_ms",
            "wire_bytes"} <= set(cost_keys), \
        f"cost ledger missing fields: {cost_keys}"
    out = {
        "metric": "smoke_hotpath_tiles_per_sec",
        "value": round(tps, 2),
        "unit": "tiles/s",
        "p50_ms": _opt_round(p50, 2),
        "dedup_hit_rate": extras.get("dedup_hit_rate"),
        "warm_repeat_cached": extras.get("warm_repeat_cached"),
        "overlap_efficiency": extras.get("overlap_efficiency"),
        "planecache_hits": extras.get("planecache_hits"),
        "planecache_misses": extras.get("planecache_misses"),
        "cost_ledger_keys": cost_keys,
        # Per-feature hot-path tax (ns/op): trace span record, cost
        # ledger flush, deadline check, admission admit+release, disk
        # write-behind enqueue.  Gated in tests/test_bench_smoke.py so
        # the feature layers stay pay-for-what-you-use.
        "overhead_ns_per_op": (_overheads := _overhead_table()),
        # The perf sentinel's per-request tax, named at top level for
        # the record diff (same number as overhead_ns_per_op.sentinel;
        # the <100µs/op budget gate lives in tests/test_bench_smoke.py).
        "sentinel_overhead_ns_per_op": _overheads.get("sentinel"),
        # Wire v3 probes (split posture, streaming + coalescing + shm
        # ring live) — gated in tests/test_bench_smoke.py.
        **wire,
        # Fleet probes (virtual members; see _fleet_smoke) — gated in
        # tests/test_bench_smoke.py.
        **fleet,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps(out))
    return out


def _jain_index(shares) -> float:
    """Jain's fairness index over per-session service shares:
    (sum x)^2 / (n * sum x^2) — 1.0 = perfectly even, 1/n = one
    session took everything."""
    xs = [max(0.0, float(x)) for x in shares]
    n = len(xs)
    if n == 0:
        return 1.0
    total = sum(xs)
    if total <= 0:
        return 1.0
    return (total * total) / (n * sum(x * x for x in xs))


def _p99(samples_ms) -> float:
    ordered = sorted(samples_ms)
    return ordered[int(0.99 * (len(ordered) - 1))]


def bench_sessions_smoke(viewers: int = 6, tiles_per_viewer: int = 32,
                         warmup_tiles: int = 6, grid: int = 8,
                         tile_edge: int = 64, exec_ms: float = 20.0,
                         bulk_exec_ms: float = 120.0,
                         bulk_concurrency: int = 6):
    """Multi-user serving gate (``bench.py --smoke --sessions``,
    tier-1 via tests/test_bench_smoke.py): "millions of users" as a
    TESTED scenario at smoke scale.

    Three deterministic legs over one fleet stack (2 members, virtual
    device occupancy per the `_fleet_smoke` idiom — ``exec_ms`` of
    lane time per interactive tile, ``bulk_exec_ms`` per bulk render):

    * **baseline** — N panning viewer sessions, no bulk traffic: the
      no-bulk per-session p99 floor.
    * **qos on** — the same viewers plus ONE hostile bulk client
      (full-plane renders, ``bulk_concurrency`` in flight, open-loop)
      with per-session token buckets and the weighted two-class
      dequeue live.  The gate: worst-session interactive p99 within
      2x the baseline, Jain's fairness index over per-session device
      time >= 0.8, and the hostile's overrun shed 503 with the
      ``"fairness"`` reason.
    * **qos off** — the identical hostile scenario with buckets off
      and FIFO dequeue: the A/B leg that PROVES the mechanism (both
      gates regress to failure — one bulk client convoys the fleet).

    A fourth leg replays a deterministic single-session pan trace with
    the predictive viewport prefetcher live (fleet-aware: predictions
    stage into the owning member's HBM shard) and reports the
    predictive hit rate + duplicate-staged count.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, LocalMember,
        build_local_members)
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController, SessionTokenBuckets)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.errors import OverloadedError
    from omero_ms_image_region_tpu.utils import telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(23)
    exec_s = exec_ms / 1000.0
    bulk_exec_s = bulk_exec_ms / 1000.0

    from omero_ms_image_region_tpu.server.pressure import is_bulk

    class VirtualDeviceMember(LocalMember):
        """Calibrated virtual device occupancy per QoS class: the
        render itself (read, stage, HBM cache, kernel, encode) is
        entirely real; the sleep models the device service time a
        2-core CI host cannot exhibit."""

        async def render(self, ctx, adopt_cache=True):
            data = await super().render(ctx, adopt_cache)
            await asyncio.sleep(bulk_exec_s if is_bulk(ctx)
                                else exec_s)
            return data

    def tile_params(x, y, w):
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,{x},{y},{tile_edge},{tile_edge}",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
        }

    def bulk_params(w):
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000",
        }

    def build_stack(tmp, qos_on: bool, prefetch: bool = False):
        from omero_ms_image_region_tpu.server.config import (
            SessionsConfig)
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=prefetch),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        if prefetch:
            # The viewport model only builds with the session tier on
            # (anonymous traffic would share one trajectory, so
            # build_services gates it); the prefetch leg replays a
            # keyed session.  Traffic legs stay sessions-off at the
            # member layer — THIS stack's own FleetImageHandler
            # carries the buckets under test, and default member
            # buckets would meter the hostile even in the qos-off
            # A/B leg.
            config.sessions = SessionsConfig(enabled=True)
        services = build_services(config)
        members = [VirtualDeviceMember(
            m.name, m.handler, m.services,
            down_cooldown_s=m.down_cooldown_s,
            byte_cache_prechecked=m.byte_cache_prechecked)
            for m in build_local_members(config, services, 2)]
        router = FleetRouter(members, lane_width=2,
                             steal_min_backlog=0,
                             qos_weight=4 if qos_on else 0)
        buckets = None
        if qos_on:
            # Sized so the meter separates the CLASSES, not the load:
            # a panning viewer (cost 1, ~30-50 serial tiles/s) never
            # touches its budget, while one full-plane render costs
            # the ENTIRE burst — the hostile is held to ~1 bulk/s, so
            # the mesh lane's two device lanes are never both bulk-
            # occupied and interactive head-of-line blocking is
            # bounded by a single in-flight bulk render.
            buckets = SessionTokenBuckets(
                refill_per_s=100.0, burst=100.0, bulk_cost=100.0)
        handler = FleetImageHandler(
            router,
            admission=AdmissionController(4096, renderer=router,
                                          session_buckets=buckets),
            base_services=services)
        if prefetch and services.prefetcher is not None:
            # The production combined-fleet wiring (server.app): one
            # shared prefetcher, predictions staged into the OWNING
            # member's shard.
            services.prefetcher.cache_for_route = \
                router.cache_for_route
            for member in members[1:]:
                member.services.prefetcher = services.prefetcher
        return config, services, members, router, handler

    async def run_traffic_leg(tmp, qos_on: bool,
                              hostile: bool) -> dict:
        _, services, members, router, handler = build_stack(
            tmp, qos_on)
        try:
            # Warm both compile shapes outside every measured window.
            await handler.render_image_region(
                ImageRegionCtx.from_params(tile_params(0, 0, 61000)))
            await handler.render_image_region(
                ImageRegionCtx.from_params(bulk_params(61000)))

            measuring = asyncio.Event()
            done = asyncio.Event()
            latencies = {v: [] for v in range(viewers)}
            served_ms = {f"viewer-{v}": 0.0 for v in range(viewers)}
            served_ms["bulk-hog"] = 0.0
            # Per-session measuring window [t_first, t_last]: shares
            # are judged as device time per wall-second of EACH
            # session's own window, so a starved viewer (same tile
            # count, longer wall clock) drags the fairness index —
            # equal closed-loop totals cannot mask unfairness.
            windows = {}
            bulk_served = bulk_shed = 0

            async def viewer(v: int):
                # Deterministic pan trace: each session marches along
                # its own row, distinct windows per step (no
                # byte-cache or dedup shortcuts).
                steps = warmup_tiles + tiles_per_viewer
                for step in range(steps):
                    x = step % grid
                    y = (v + step // grid) % grid
                    ctx = ImageRegionCtx.from_params(
                        tile_params(x, y,
                                    22000 + v * 2500 + step * 60))
                    ctx.omero_session_key = f"viewer-{v}"
                    t0 = time.perf_counter()
                    if step == warmup_tiles:
                        measuring.set()
                        windows[f"viewer-{v}"] = [t0, t0]
                    out = await handler.render_image_region(ctx)
                    assert out
                    if step >= warmup_tiles:
                        t1 = time.perf_counter()
                        latencies[v].append((t1 - t0) * 1000.0)
                        served_ms[f"viewer-{v}"] += exec_ms
                        windows[f"viewer-{v}"][1] = t1

            async def bulk_client():
                nonlocal bulk_served, bulk_shed
                seq = 0

                async def one():
                    nonlocal bulk_served, bulk_shed, seq
                    seq += 1
                    ctx = ImageRegionCtx.from_params(
                        bulk_params(30000 + seq * 40))
                    ctx.omero_session_key = "bulk-hog"
                    if measuring.is_set():
                        window = windows.setdefault(
                            "bulk-hog", [time.perf_counter()] * 2)
                        window[1] = time.perf_counter()
                    try:
                        await handler.render_image_region(ctx)
                        if measuring.is_set():
                            bulk_served += 1
                            served_ms["bulk-hog"] += bulk_exec_ms
                            if "bulk-hog" in windows:
                                windows["bulk-hog"][1] = \
                                    time.perf_counter()
                    except OverloadedError:
                        if measuring.is_set():
                            bulk_shed += 1
                        # Hostile: ignores the 1 s Retry-After, but a
                        # floor keeps the gate about QoS, not about
                        # the 2-core CI loop drowning in shed churn
                        # (~120 attempts/s across the 6 streams is
                        # still a hammering client).
                        await asyncio.sleep(0.05)

                pending = set()
                while not done.is_set():
                    while len(pending) < bulk_concurrency:
                        pending.add(asyncio.create_task(one()))
                    finished, pending = await asyncio.wait(
                        pending, timeout=0.02,
                        return_when=asyncio.FIRST_COMPLETED)
                for task in pending:
                    task.cancel()
                await asyncio.gather(*pending,
                                     return_exceptions=True)

            tasks = [asyncio.create_task(viewer(v))
                     for v in range(viewers)]
            hog = (asyncio.create_task(bulk_client()) if hostile
                   else None)
            await asyncio.gather(*tasks)
            done.set()
            if hog is not None:
                await hog
            def rate(key):
                t0, t1 = windows.get(key, (0.0, 0.0))
                return served_ms[key] / max(t1 - t0, 1e-6)

            shares = [rate(f"viewer-{v}") for v in range(viewers)]
            if hostile:
                # The hog's window spans its whole measured activity
                # (sheds included): the rate the fleet actually
                # granted it, not just its completions.
                shares.append(rate("bulk-hog")
                              if "bulk-hog" in windows else 0.0)
            return {
                "p99_ms": max(_p99(latencies[v])
                              for v in range(viewers)),
                "jain": _jain_index(shares),
                "bulk_served": bulk_served,
                "bulk_shed": bulk_shed,
            }
        finally:
            await router.close()
            services.pixels_service.close()

    async def run_prefetch_leg(tmp) -> dict:
        _, services, members, router, handler = build_stack(
            tmp, qos_on=True, prefetch=True)
        prefetcher = services.prefetcher
        try:
            # Deterministic single-session pan: two rows, left to
            # right, velocity (1, 0) — the viewport model should
            # stage each next tile before its request arrives.
            for row in range(2):
                for x in range(grid):
                    ctx = ImageRegionCtx.from_params(
                        tile_params(x, row, 45000 + row * 300 + x))
                    ctx.omero_session_key = "panner"
                    out = await handler.render_image_region(ctx)
                    assert out
                    # Idle device lanes: speculative staging runs
                    # between pan steps, as in a real viewer cadence.
                    await asyncio.to_thread(prefetcher.flush, 2.0)
            report = router.shard_report()
            return {
                "staged": prefetcher.staged,
                "hits": prefetcher.hits,
                "hit_rate": prefetcher.hit_rate(),
                "duplicates": report["duplicate_digests"],
            }
        finally:
            await router.close()
            services.pixels_service.close()

    shed_before = telemetry.RESILIENCE.shed.get("fairness", 0)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        baseline = asyncio.run(run_traffic_leg(tmp, qos_on=True,
                                               hostile=False))
        qos_on = asyncio.run(run_traffic_leg(tmp, qos_on=True,
                                             hostile=True))
        qos_off = asyncio.run(run_traffic_leg(tmp, qos_on=False,
                                              hostile=True))
        prefetch = asyncio.run(run_prefetch_leg(tmp))
    fairness_sheds = (telemetry.RESILIENCE.shed.get("fairness", 0)
                      - shed_before)
    out = {
        "metric": "sessions_smoke",
        "sessions_viewers": viewers,
        "sessions_tiles_per_viewer": tiles_per_viewer,
        "sessions_virtual_exec_ms": exec_ms,
        "sessions_bulk_exec_ms": bulk_exec_ms,
        # The headline pair the gate judges: hostile-bulk p99 with the
        # QoS tier live vs the no-bulk floor.
        "sessions_baseline_p99_ms": _opt_round(baseline["p99_ms"], 1),
        "sessions_interactive_p99_ms": _opt_round(qos_on["p99_ms"], 1),
        "sessions_qos_off_p99_ms": _opt_round(qos_off["p99_ms"], 1),
        "sessions_fairness_index": _opt_round(qos_on["jain"], 3),
        "sessions_fairness_index_off": _opt_round(qos_off["jain"], 3),
        "sessions_bulk_served": qos_on["bulk_served"],
        "sessions_bulk_shed": qos_on["bulk_shed"],
        "sessions_fairness_sheds": fairness_sheds,
        # Predictive prefetch over the deterministic pan trace.
        "prefetch_staged_planes": prefetch["staged"],
        "prefetch_hits": prefetch["hits"],
        "prefetch_hit_rate": _opt_round(prefetch["hit_rate"], 3),
        "prefetch_duplicate_staged_planes": prefetch["duplicates"],
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps(out))
    return out


def bench_overload_smoke(burst: int = 160, exec_ms: float = 40.0,
                         members: int = 2, lane_width: int = 2):
    """Overload-brownout gate at smoke scale (tier-1 via
    tests/test_bench_smoke.py): a ~10x-capacity burst through a real
    fleet handler with the PRESSURE GOVERNOR live must

    * engage brownout ladder steps IN CONFIGURED ORDER (read back
      from the flight recorder's ``pressure.step`` events);
    * keep ZERO 5xx-without-shed (every request either serves or
      sheds 503; nothing errors bare) with a bounded p99;
    * release every step IN REVERSE with hysteresis once the burst
      ends — engage/release exactly once per step, no flapping.

    The members carry a calibrated virtual device occupancy
    (``exec_ms`` of lane time per render, the `_fleet_smoke` idiom)
    so the burst actually QUEUES on this CPU host; the governor's
    queue signal, the ladder walk and the shed/serve accounting are
    all the production code paths.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, LocalMember,
        build_local_members)
    from omero_ms_image_region_tpu.server import pressure
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.errors import OverloadedError
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.utils import telemetry

    t_start = time.perf_counter()
    grid, tile_edge = 4, 64
    exec_s = exec_ms / 1000.0
    rng = np.random.default_rng(17)

    class VirtualDeviceMember(LocalMember):
        async def render(self, ctx, adopt_cache=True):
            data = await super().render(ctx, adopt_cache)
            await asyncio.sleep(exec_s)
            return data

    def urls():
        out = []
        variants = -(-burst // (grid * grid))
        for v in range(variants):
            for x in range(grid):
                for y in range(grid):
                    w = 21000 + v * 650
                    out.append({
                        "imageId": "1", "theZ": "0", "theT": "0",
                        "tile": f"0,{x},{y},{tile_edge},{tile_edge}",
                        "format": "png", "m": "c",
                        "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
                    })
        return out[:burst]

    async def run(tmp: str) -> dict:
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        members = [VirtualDeviceMember(
            m.name, m.handler, m.services,
            down_cooldown_s=m.down_cooldown_s,
            byte_cache_prechecked=m.byte_cache_prechecked)
            for m in build_local_members(config, services, members_n)]
        router = FleetRouter(members, lane_width=lane_width,
                             steal_min_backlog=0)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            admission=AdmissionController(4 * burst, renderer=router),
            base_services=services)
        pcfg = AppConfig.from_dict({"pressure": {
            "enabled": True, "interval-s": 0.02,
            "queue-high": 4 * members_n * lane_width,
            "queue-low": members_n * lane_width,
            "critical-factor": 1.5,
            "step-hold-ticks": 2, "release-hold-ticks": 2,
        }}).pressure
        governor = pressure.PressureGovernor(
            pcfg,
            pressure.build_actuators(pcfg, services=services),
            {"queue": lambda: float(router.queue_depth())})
        pressure.install(governor)
        # The gate reads the ladder walk back from the flight ring;
        # start it clean (and big enough that burst noise cannot
        # push the pressure.step events off the tape).
        telemetry.FLIGHT.reset()
        telemetry.FLIGHT.configure(4096)

        async def governor_loop():
            while True:
                await asyncio.sleep(pcfg.interval_s)
                governor.tick()

        gov_task = asyncio.create_task(governor_loop())
        ctxs = [ImageRegionCtx.from_params(p) for p in urls()]
        # One warm render outside the window (shared jit compile).
        await handler.render_image_region(ctxs[0])
        latencies: list = []
        sheds = unshed = 0

        async def one(ctx):
            nonlocal sheds, unshed
            t0 = time.perf_counter()
            try:
                out = await handler.render_image_region(ctx)
                assert out
                latencies.append(time.perf_counter() - t0)
            except OverloadedError:
                sheds += 1           # shed = 503 + Retry-After: legal
            except Exception:
                unshed += 1          # bare failure: the gate breaker

        try:
            # Ramp through the ELEVATED band first: the continuous
            # prefetch budget must scale down (x0.5) strictly before
            # the binary pause_prefetch step engages — the PR 10
            # budget-before-pause gate.  The pre-wave is sized inside
            # the band (>= queue-high, < critical), held until the
            # governor publishes a scaled budget.
            pre = min(pcfg.queue_high + 4, len(ctxs))
            tasks = [asyncio.create_task(one(c))
                     for c in ctxs[:pre]]
            for _ in range(12):
                await asyncio.sleep(pcfg.interval_s)
                if governor.prefetch_budget() < 1.0:
                    break
            tasks += [asyncio.create_task(one(c))
                      for c in ctxs[pre:]]
            await asyncio.gather(*tasks)
            # Burst over: keep ticking until the ladder fully
            # releases (bounded — hysteresis means a few quiet ticks
            # per step).
            for _ in range(400):
                if not governor.engaged_steps():
                    break
                await asyncio.sleep(pcfg.interval_s)
            released = not governor.engaged_steps()
        finally:
            gov_task.cancel()
            pressure.uninstall()
            await router.close()
            services.pixels_service.close()

        steps = [e for e in telemetry.FLIGHT.snapshot()
                 if e["kind"] == "pressure.step"]
        engages = [e["step"] for e in steps
                   if e["action"] == "engage"]
        releases = [e["step"] for e in steps
                    if e["action"] == "release"]
        ladder = list(pcfg.ladder)
        order_ok = engages == ladder[:len(engages)]
        reverse_ok = releases == list(reversed(engages))[
            :len(releases)]
        flapping = (len(engages) != len(set(engages))
                    or len(releases) != len(set(releases)))
        # The continuous prefetch-budget trajectory (prefetch.budget
        # flight events): the first move must be a SCALE-DOWN in
        # (0, 1) — the level cut the budget before the binary pause
        # floored it — and the last must be the full restore.
        budgets = [e["scale"] for e in telemetry.FLIGHT.snapshot()
                   if e["kind"] == "prefetch.budget"]
        scaled_before_pause = bool(budgets) and 0.0 < budgets[0] < 1.0
        budget_restored = (bool(budgets) and 0.0 in budgets
                           and budgets[-1] == 1.0)
        ordered = sorted(latencies)
        p99 = (ordered[int(0.99 * (len(ordered) - 1))] * 1000.0
               if ordered else None)
        return {
            "served": len(latencies), "sheds": sheds,
            "unshed_failures": unshed,
            "steps_engaged": engages, "steps_released": releases,
            "ladder_order_ok": bool(order_ok),
            "release_reverse_ok": bool(reverse_ok),
            "released_all": bool(released),
            "flapping": bool(flapping),
            "budget_trajectory": budgets,
            "budget_scaled_before_pause": bool(scaled_before_pause),
            "budget_restored": bool(budget_restored),
            "p99_ms": _opt_round(p99, 1),
        }

    members_n = members
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        doc = asyncio.run(run(tmp))
    out = {
        "metric": "overload_smoke",
        "burst": burst,
        "virtual_exec_ms": exec_ms,
        **{f"overload_{k}": v for k, v in doc.items()},
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps(out))
    return out


# The committed synthetic shape-mask fixtures (tests/data/masks):
# mask-class load-model arrivals render these through the real mask
# endpoint during the capacity sweep.
_MASK_FIXTURE_IDS = (9001, 9002, 9003)


def _copy_mask_fixtures(data_dir: str) -> int:
    """Copy the committed mask fixtures into a bench data tree
    (LocalMetadataService reads ``<data_dir>/masks/<id>.{json,bin}``).
    Returns fixtures copied; 0 if the fixture tree is absent."""
    import os
    import shutil
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tests", "data", "masks")
    if not os.path.isdir(src):
        return 0
    dst = os.path.join(data_dir, "masks")
    os.makedirs(dst, exist_ok=True)
    n = 0
    for name in os.listdir(src):
        if name.endswith((".json", ".bin")):
            shutil.copy(os.path.join(src, name),
                        os.path.join(dst, name))
            n += name.endswith(".json")
    return n


def bench_capacity_smoke(exec_ms: float = 60.0, grid: int = 4,
                         tile_edge: int = 64,
                         fleet_sizes=(1, 2, 4), lane_width: int = 2,
                         slo_ms: float = 360.0,
                         shed_limit: float = 0.05,
                         window_s: float = 1.0,
                         load_factors=(0.45, 0.9, 1.5, 2.25),
                         viewers: int = 64,
                         mask_fraction: float = 0.1,
                         pyramid_fraction: float = 0.02,
                         animation_fraction: float = 0.03):
    """Capacity-knee measurement (``bench.py --smoke --capacity``,
    tier-1 via tests/test_bench_smoke.py): the latency-vs-OFFERED-load
    curve of a real in-process fleet under an OPEN-loop arrival
    process, per fleet size.

    Every other bench leg is closed-loop (workers that wait), which
    structurally cannot see queueing collapse — when the service slows
    the offered load slows with it.  Here the ``services.loadmodel``
    generator replays a seeded viewer population (heavy-tailed think
    times and session lengths, per-session pan trajectories)
    time-compressed to each target offered rate, and arrivals fire ON
    SCHEDULE regardless of completions:

    * per fleet size m1/m2/m4 (virtual device occupancy per the
      ``_fleet_smoke`` idiom — ``exec_ms`` of lane time per render),
      sweep offered load across ``load_factors`` x the size's nominal
      capacity and extract the CAPACITY KNEE: the highest offered
      load whose p99 still meets ``slo_ms`` and whose shed rate stays
      under ``shed_limit``;
    * the knee must SCALE with fleet size (the figure the autoscaler's
      floor/ceiling sizing reads — deploy/DEPLOY.md "Capacity &
      autoscaling");
    * **open-loop honesty A/B**: the first past-knee point's arrival
      list replays CLOSED-loop on the same stack — the closed p99
      must come out LOWER (flattering), which is the regression test
      that keeps future bench legs from quietly reverting to
      closed-loop arrivals and reporting a collapse-free curve.

    Emits ONE JSON line (the ``CAPACITY_r*.json`` record family)
    judged direction-aware by ``scripts/bench_gate.py --capacity``
    (knee regresses DOWN, ``_ms`` keys UP).
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, LocalMember,
        build_local_members)
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.services.loadmodel import (
        Arrival, LoadModel, find_knee, run_closed_loop,
        run_open_loop)
    from omero_ms_image_region_tpu.utils import telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(29)
    exec_s = exec_ms / 1000.0
    telemetry.LOADMODEL.reset()

    class VirtualDeviceMember(LocalMember):
        """Calibrated virtual device occupancy (the `_fleet_smoke`
        idiom): the render is entirely real; the sleep models the
        device service time a small CI host cannot exhibit — which
        makes the measured knee a property of the QUEUEING STRUCTURE
        (lanes x members x service time), not of CI core count."""

        async def render(self, ctx, adopt_cache=True):
            data = await super().render(ctx, adopt_cache)
            await asyncio.sleep(exec_s)
            return data

    # The simulated population comes from the validated `loadmodel:`
    # config block (operators tune think/session tails there; a
    # driver round can point this at a real config).  The sweep pins
    # the STRUCTURAL knobs: seeded small population time-compressed
    # per offered rate, FLAT arrivals (diurnal 0 — the knee wants a
    # stationary offered rate; the diurnal ramp is the elasticity
    # drill's input), no bulk (bulk pins to m0 and would muddy the
    # per-size comparison).  Mask-class arrivals DO run — against the
    # committed synthetic fixtures under tests/data/masks — so the
    # measured knee carries the real served mix's mask tax.
    lm_config = AppConfig.from_dict({"loadmodel": {
        "seed": 31, "viewers": viewers, "diurnal-amplitude": 0.0,
        "bulk-fraction": 0.0, "mask-fraction": float(mask_fraction),
        "pyramid-fraction": float(pyramid_fraction),
        "animation-fraction": float(animation_fraction),
        "zoom-fraction": 0.0}}).loadmodel
    model = LoadModel.from_config(lm_config, duration_s=60.0,
                                  grid=grid)
    natural_events = model.events()

    def params_for(arrival):
        sid = int(arrival.session.rsplit("-", 1)[1])
        w = 21000 + (sid * 131 + arrival.step * 37) % 18000
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,{arrival.x},{arrival.y},{tile_edge},"
                    f"{tile_edge}",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
        }

    def nominal_tps(n_members: int) -> float:
        return n_members * lane_width * 1000.0 / exec_ms

    async def run_size(tmp: str, n_members: int) -> tuple:
        from omero_ms_image_region_tpu.server.ctx import ShapeMaskCtx
        from omero_ms_image_region_tpu.server.handler import (
            ShapeMaskHandler)
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        members = [VirtualDeviceMember(
            m.name, m.handler, m.services,
            down_cooldown_s=m.down_cooldown_s,
            byte_cache_prechecked=m.byte_cache_prechecked)
            for m in build_local_members(config, services, n_members)]
        router = FleetRouter(members, lane_width=lane_width,
                             steal_min_backlog=0)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            admission=AdmissionController(4096, renderer=router),
            base_services=services)
        mask_handler = ShapeMaskHandler(services)
        # The PR 20 workload classes ride the measured mix: animation
        # strips compose the SAME fleet handler (each frame shares the
        # plain tile identity), pyramid arrivals exercise the submit
        # path (idempotent dedup — the build itself is background bulk
        # work, not request service time).
        from omero_ms_image_region_tpu.server.handler import (
            WorkloadsHandler)
        from omero_ms_image_region_tpu.server.jobs import (
            PyramidJobManager)
        workloads = WorkloadsHandler(handler, services, max_frames=8)
        pyramid_jobs = PyramidJobManager(
            pixels_service=services.pixels_service)

        async def submit(arrival):
            if arrival.cls == "pyramid":
                job = pyramid_jobs.submit(
                    services.pixels_service.image_dir(1), image_id=1)
                assert job.job_id
                return
            if arrival.cls == "animation":
                fparams = params_for(arrival)
                frame_ctxs = []
                for i in range(2):
                    fp = dict(fparams)
                    fp["theZ"] = str(i)
                    fctx = ImageRegionCtx.from_params(fp)
                    fctx.omero_session_key = arrival.session
                    frame_ctxs.append(fctx)
                n = 0
                async for record in workloads \
                        .render_animation_stream(frame_ctxs):
                    assert record[:4] == b"FRME"
                    n += 1
                assert n == len(frame_ctxs)
                return
            if arrival.cls == "mask":
                # Mask-class arrivals serve the committed synthetic
                # fixtures (tests/data/masks, copied into the bench
                # data tree) — the real mask endpoint, request-color
                # rotated so the explicit-color cache rule is in the
                # measured mix too.
                sid = _MASK_FIXTURE_IDS[
                    arrival.step % len(_MASK_FIXTURE_IDS)]
                ctx = ShapeMaskCtx(
                    shape_id=sid,
                    color=("FF8800" if arrival.step % 2 else None),
                    omero_session_key=arrival.session)
                out = await mask_handler.render_shape_mask(ctx)
                assert out
                return
            ctx = ImageRegionCtx.from_params(params_for(arrival))
            ctx.omero_session_key = arrival.session
            out = await handler.render_image_region(ctx)
            assert out

        try:
            # Warm EVERY class lane outside the measured windows —
            # first-use costs (jit compile per shape, codec and
            # metadata loads) otherwise land as a p99 outlier in the
            # first sweep point, whose p99 is the max of only ~16
            # arrivals.  Masks cycle all (fixture, color) combos the
            # submit() rotation can produce.
            warm = [Arrival(t=0.0, session="warm-0", cls="image",
                            step=0),
                    Arrival(t=0.0, session="warm-0", cls="animation",
                            step=0)]
            warm += [Arrival(t=0.0, session="warm-0", cls="mask",
                             step=s)
                     for s in range(2 * len(_MASK_FIXTURE_IDS))]
            for a in warm:
                await submit(a)
            points = []
            past_knee_arrivals = None
            for factor in load_factors:
                offered = factor * nominal_tps(n_members)
                # Steady-state slice of the simulated day, rescaled
                # to this offered rate (LoadModel.window — the
                # compressed day's thin edges must not under-offer).
                sched = model.window(offered, window_s,
                                     natural_events)
                report = await run_open_loop(
                    submit, sched,
                    offered_tps=len(sched) / window_s)
                assert not report.errors, \
                    f"open-loop leg failed bare: {report.errors[:3]}"
                points.append(report.as_point())
            knee, p99_at_knee, censored = find_knee(
                points, slo_ms, shed_limit)
            ab = None
            if n_members == 1 and knee is not None:
                # Open-loop honesty A/B on the SAME stack: replay the
                # first past-knee point's arrival list closed-loop —
                # workers that wait self-throttle to the service rate,
                # so the flattering p99 must come out LOWER than the
                # open-loop p99 the sweep just measured.
                past = next((p for p in points
                             if p["offered_tps"] > knee), None)
                if past is not None:
                    past_knee_arrivals = model.window(
                        past["offered_tps"], window_s,
                        natural_events)
                    closed = await run_closed_loop(
                        submit, past_knee_arrivals,
                        concurrency=lane_width * n_members)
                    ab = {
                        "offered_tps": past["offered_tps"],
                        "openloop_p99_ms": past["p99_ms"],
                        "closedloop_p99_ms": _opt_round(
                            closed.p99_ms(), 1),
                    }
            return points, knee, p99_at_knee, censored, ab
        finally:
            await router.close()
            services.pixels_service.close()

    curve = {}
    knees = {}
    p99s = {}
    censored_any = False
    honesty = None
    with tempfile.TemporaryDirectory() as tmp:
        # [C=2, Z=2]: two channels for the rendering-window params,
        # two z-planes so animation-class arrivals have a real scrub
        # axis (the strip renders theZ=0 and theZ=1).
        planes = synthetic_wsi_tiles(rng, 4, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 2, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        if mask_fraction > 0 and not _copy_mask_fixtures(tmp):
            raise RuntimeError(
                "mask fixtures missing under tests/data/masks — "
                "run with mask_fraction=0 or restore the fixtures")
        for n in fleet_sizes:
            points, knee, p99_at_knee, censored, ab = asyncio.run(
                run_size(tmp, n))
            curve[f"m{n}"] = points
            knees[f"m{n}"] = knee
            p99s[f"m{n}"] = p99_at_knee
            censored_any = censored_any or censored
            if ab is not None:
                honesty = ab
    widest = f"m{max(fleet_sizes)}"
    knee_1 = knees.get(f"m{min(fleet_sizes)}")
    knee_w = knees.get(widest)
    out = {
        "metric": "capacity_smoke",
        "capacity_slo_ms": slo_ms,
        "capacity_shed_limit": shed_limit,
        "capacity_virtual_exec_ms": exec_ms,
        "capacity_window_s": window_s,
        "capacity_viewers": viewers,
        "capacity_fleet_sizes": list(fleet_sizes),
        "capacity_curve": curve,
        **{f"capacity_knee_offered_tps_{k}": _opt_round(v, 1)
           for k, v in knees.items()},
        # The headline pair the gate judges: the widest fleet's knee
        # (regresses DOWN) and its p99 at the knee (regresses UP).
        "capacity_knee_offered_tps": _opt_round(knee_w, 1),
        "p99_at_knee_ms": _opt_round(p99s.get(widest), 1),
        "capacity_knee_censored": bool(censored_any),
        "capacity_scaling_efficiency": _opt_round(
            (knee_w / (knee_1 * max(fleet_sizes) / min(fleet_sizes)))
            if knee_w and knee_1 else None, 3),
        # The open-loop honesty A/B (m1): closed must flatter.
        "openloop_p99_past_knee_ms": (honesty or {}).get(
            "openloop_p99_ms"),
        "closedloop_p99_past_knee_ms": (honesty or {}).get(
            "closedloop_p99_ms"),
        "capacity_ab_offered_tps": (honesty or {}).get("offered_tps"),
        # Mask-class arrivals in the measured mix (the committed
        # tests/data/masks fixtures through the real mask endpoint):
        # offered vs completed per the LOADMODEL accumulator — a
        # mask error surfaces as completed < offered, never silently.
        "capacity_mask_fraction": float(mask_fraction),
        "capacity_mask_offered":
            telemetry.LOADMODEL.offered.get("mask", 0),
        "capacity_mask_completed":
            telemetry.LOADMODEL.completed.get("mask", 0),
        # PR 20 workload classes in the measured mix: same
        # offered-vs-completed honesty as masks.
        "capacity_pyramid_fraction": float(pyramid_fraction),
        "capacity_pyramid_offered":
            telemetry.LOADMODEL.offered.get("pyramid", 0),
        "capacity_pyramid_completed":
            telemetry.LOADMODEL.completed.get("pyramid", 0),
        "capacity_animation_fraction": float(animation_fraction),
        "capacity_animation_offered":
            telemetry.LOADMODEL.offered.get("animation", 0),
        "capacity_animation_completed":
            telemetry.LOADMODEL.completed.get("animation", 0),
        # Open-loop integrity: arrivals the generator fired behind
        # its own schedule (counted, never hidden).
        "loadmodel_late_fires": telemetry.LOADMODEL.late,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps(out))
    return out


def bench_workloads_smoke(edge: int = 128, mask_rounds: int = 4,
                          frames: int = 8):
    """Device-workloads drill (``bench.py --smoke --workloads``,
    tier-1 via tests/test_bench_smoke.py): the PR 20 plane end to end
    on a real in-process stack.

    Legs:

    * **mask parity + timing** — every committed mask fixture renders
      through the ENDPOINT twice: device-batched (the BatchingRenderer
      ``("mask", ...)`` group path) and host rasterizer.  The bytes
      must be IDENTICAL (the refimpl-golden contract); both sides are
      timed.
    * **overlay** — the composite endpoint (region render + device
      mask blend) against the refimpl ``overlay_masks_batch`` formula.
    * **pyramid** — a background-class build over the device
      downsample with atomic per-level commits; the committed group
      must open through the NGFF reader.
    * **animation** — a z-strip streamed through the workloads
      handler: ordered ``FRME`` records, first-frame latency, and a
      mid-stream close cancelling the remaining frames.

    Emits ONE JSON line (the ``WORKLOADS_r*.json`` record family)
    judged direction-aware by ``scripts/bench_gate.py`` (``_ms`` keys
    regress UP, counts DOWN).
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu import codecs
    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.ngff import (NgffZarrSource,
                                                   find_ngff)
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.ops import maskops
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (AppConfig,
                                                         RawCacheConfig)
    from omero_ms_image_region_tpu.server.ctx import (ImageRegionCtx,
                                                      ShapeMaskCtx)
    from omero_ms_image_region_tpu.server.handler import (
        ImageRegionHandler, ShapeMaskHandler, WorkloadsHandler)
    from omero_ms_image_region_tpu.server.jobs import PyramidJobManager
    from omero_ms_image_region_tpu.utils import telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(47)
    telemetry.WORKLOADS.reset()

    out = {"metric": "workloads_smoke"}

    async def run(tmp: str) -> None:
        config = AppConfig(
            data_dir=tmp,
            raw_cache=RawCacheConfig(enabled=True, prefetch=False))
        services = build_services(config)
        image_handler = ImageRegionHandler(services)
        workloads = WorkloadsHandler(image_handler, services,
                                     max_frames=max(frames, 8))
        device_masks = ShapeMaskHandler(services, device_masks=True)
        host_masks = ShapeMaskHandler(services, device_masks=False)
        try:
            # ---- leg 1: endpoint mask parity + timing (fresh ctx
            # objects defeat the byte cache; fixture colors rotate so
            # both the stored-fill and explicit-color paths run).
            def mask_ctxs():
                # Stored-fill colors only (explicit colors byte-cache,
                # which would let the second pass serve the first
                # pass's bytes); flips vary so the device flip lanes
                # are in the measured mix.
                return [ShapeMaskCtx(
                    shape_id=_MASK_FIXTURE_IDS[
                        i % len(_MASK_FIXTURE_IDS)],
                    flip_horizontal=bool(i % 2),
                    flip_vertical=bool(i % 3 == 0))
                    for i in range(mask_rounds
                                   * len(_MASK_FIXTURE_IDS))]

            # Warm every flip lane first so the timed passes measure
            # steady-state dispatch, not the one-off device compiles.
            for fh, fv in ((False, False), (True, False),
                           (False, True), (True, True)):
                warm = await device_masks.render_shape_mask(
                    ShapeMaskCtx(shape_id=_MASK_FIXTURE_IDS[0],
                                 flip_horizontal=fh,
                                 flip_vertical=fv))
                assert warm
            t0 = time.perf_counter()
            device_pngs = await asyncio.gather(
                *(device_masks.render_shape_mask(c)
                  for c in mask_ctxs()))
            device_ms = (time.perf_counter() - t0) * 1000.0
            t0 = time.perf_counter()
            host_pngs = await asyncio.gather(
                *(host_masks.render_shape_mask(c)
                  for c in mask_ctxs()))
            host_ms = (time.perf_counter() - t0) * 1000.0
            assert device_pngs == host_pngs, \
                "device mask bytes diverged from host rasterizer"
            out["mask_renders"] = len(device_pngs)
            out["mask_device_ms"] = round(device_ms, 1)
            out["mask_host_ms"] = round(host_ms, 1)
            out["mask_parity_ok"] = True

            # ---- leg 2: overlay composite vs the refimpl formula.
            oparams = {"imageId": "1", "theZ": "0", "theT": "0",
                       "region": "0,0,64,64", "format": "png",
                       "m": "c", "c": "1|0:30000$FF0000"}
            octx = ImageRegionCtx.from_params(oparams)
            t0 = time.perf_counter()
            overlay_png = await workloads.render_overlay(
                octx, [_MASK_FIXTURE_IDS[0], _MASK_FIXTURE_IDS[1]])
            overlay_ms = (time.perf_counter() - t0) * 1000.0
            base_png = await image_handler.render_image_region(
                ImageRegionCtx.from_params(oparams))
            base = codecs.decode_to_rgba(base_png)
            ref = base
            for sid in (_MASK_FIXTURE_IDS[0], _MASK_FIXTURE_IDS[1]):
                mask = await services.metadata.get_mask(sid, None)
                grid, _ = maskops.rasterize_mask(mask)
                fill = np.array([mask.resolved_fill_color(None)],
                                dtype=np.uint8)
                ref = maskops.overlay_masks_batch(
                    ref[None], grid[None], fill)[0]
            ref_png = codecs.encode_rgba(ref, "png")
            assert overlay_png == ref_png, \
                "overlay composite diverged from refimpl golden"
            out["overlay_device_ms"] = round(overlay_ms, 1)
            out["overlay_parity_ok"] = True

            # ---- leg 3: pyramid build through the job manager.
            jobs = PyramidJobManager(
                pixels_service=services.pixels_service,
                chunk=(64, 64), min_level_size=32)
            job = jobs.submit(os.path.join(tmp, "2"), image_id=2)
            t0 = time.perf_counter()
            await asyncio.to_thread(jobs.run_job_sync, job)
            out["pyramid_build_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 1)
            out["pyramid_levels"] = job.levels_done
            ngff_root = find_ngff(os.path.join(tmp, "2"))
            assert ngff_root is not None, "pyramid group not committed"
            reader = NgffZarrSource(ngff_root)
            out["pyramid_readable_levels"] = \
                reader.resolution_levels()
            reader.close()

            # ---- leg 4: animation strip, ordered + first-frame ms,
            # then a mid-stream close (the disconnect path) that must
            # cancel the remaining frames.
            def strip_ctxs(n):
                ctxs = []
                for i in range(n):
                    p = {"imageId": "1", "theZ": str(i % 2),
                         "theT": "0", "region": "0,0,64,64",
                         "format": "png", "m": "c",
                         "c": f"1|0:{30000 + i}$FF0000"}
                    ctxs.append(ImageRegionCtx.from_params(p))
                return ctxs

            t0 = time.perf_counter()
            first_ms = None
            n_served = 0
            async for record in workloads.render_animation_stream(
                    strip_ctxs(frames)):
                if first_ms is None:
                    first_ms = (time.perf_counter() - t0) * 1000.0
                assert record[:4] == b"FRME"
                n_served += 1
            total_ms = (time.perf_counter() - t0) * 1000.0
            assert n_served == frames
            out["anim_frames"] = n_served
            out["anim_first_frame_ms"] = round(first_ms, 1)
            out["anim_total_ms"] = round(total_ms, 1)

            # The disconnect drill wants later frames STILL IN FLIGHT
            # when the stream closes; tiny CPU renders settle together
            # under the batcher, so a staggered-latency wrapper keeps
            # the tail pending deterministically.
            class _StaggeredHandler:
                def __init__(self, inner):
                    self.inner = inner
                    self.calls = 0

                async def render_image_region(self, ctx):
                    self.calls += 1
                    await asyncio.sleep(0.02 * self.calls)
                    return await self.inner.render_image_region(ctx)

            slow = WorkloadsHandler(
                _StaggeredHandler(image_handler), services,
                max_frames=max(frames, 8))
            cancelled_before = telemetry.WORKLOADS.stream_cancels
            agen = slow.render_animation_stream(strip_ctxs(frames))
            assert (await agen.__anext__())[:4] == b"FRME"
            await agen.aclose()
            out["anim_cancel_ok"] = (
                telemetry.WORKLOADS.stream_cancels
                == cancelled_before + 1)
        finally:
            close = services.renderer.close()
            if asyncio.iscoroutine(close):
                await close
            services.pixels_service.close()

    with tempfile.TemporaryDirectory() as tmp:
        # [C=1, Z=2, H, W]: two z-planes so the animation strip has a
        # real scrub axis; image "2" (the pyramid job target) keeps
        # one plane.
        planes = synthetic_wsi_tiles(rng, 2, 1, edge, edge).reshape(
            1, 2, edge, edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        build_pyramid(planes[:, :1], os.path.join(tmp, "2"),
                      n_levels=1)
        if not _copy_mask_fixtures(tmp):
            raise RuntimeError(
                "mask fixtures missing under tests/data/masks")
        asyncio.run(run(tmp))

    out["elapsed_s"] = round(time.perf_counter() - t_start, 1)
    print(json.dumps(out))
    return out


def bench_hotkey_smoke(exec_ms: float = 30.0, grid: int = 4,
                       tile_edge: int = 32, n_members: int = 2,
                       lane_width: int = 2, window_s: float = 1.2,
                       load_factor: float = 0.95,
                       viewers: int = 48, skew: float = 2.2,
                       image_population: int = 12,
                       threshold: float = 6.0, decay_s: float = 0.35,
                       emit: bool = True):
    """Hot-plane replication drill (``bench.py --smoke --hotkey``,
    tier-1 via tests/test_bench_smoke.py): survive the viral image.

    Three legs on the same virtual-occupancy fleet (work stealing OFF,
    so every measured delta is the replication tier's and nothing
    else's):

    * **uniform** — the zipf-0 mix (every image rank equally likely):
      the baseline throughput a balanced population gets;
    * **storm, replication disabled** — a zipf-``skew`` population
      (``services.loadmodel`` ``skew``/``image_population`` knobs;
      rank 0 is the viral plane, distinct render identities over ONE
      ``plane_route_key``) with the hot-key tier OFF: the ring pins
      every hot read to one member and its queue eats the storm;
    * **storm, replication enabled** — the same arrival schedule with
      the tier ON: the heat tracker promotes the viral route to an
      R=2 replica set drawn from the ring chain, reads least-queued
      balance across it, and throughput must come back toward the
      uniform mix (the gate: storm >= 0.7x uniform AND the disabled
      A/B measures LESS than the replicated leg).

    The enabled leg also drives the full lifecycle from live state:
    promotion + digest-deduped replica staging (``duplicate_staged``
    must be 0 and ``shard_report`` must classify the hot plane as
    ``replicated_digests``, never ``duplicate_digests``), one
    autoscaler tick at the fleet ceiling while replica pressure holds
    (the ``blocked:ceiling`` decision record must CARRY the
    replica-pressure signal), then heat decay past the demote
    fraction with cool traffic sweeping the route back to R=1.

    Emits ONE JSON line (the ``HOTKEY_r*.json`` record family) judged
    direction-aware by ``scripts/bench_gate.py --hotkey``.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, LocalMember,
        build_local_members)
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.autoscaler import Autoscaler
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, AutoscalerConfig, BatcherConfig, HotkeyConfig,
        RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.services.loadmodel import (
        LoadModel, run_open_loop)
    from omero_ms_image_region_tpu.utils import decisions, telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(37)
    exec_s = exec_ms / 1000.0

    class VirtualDeviceMember(LocalMember):
        """Calibrated virtual device occupancy (the ``_fleet_smoke``
        idiom): the measured deltas are properties of the queueing
        structure, not of CI core count."""

        async def render(self, ctx, adopt_cache=True):
            data = await super().render(ctx, adopt_cache)
            await asyncio.sleep(exec_s)
            return data

    def make_model(s: float) -> "LoadModel":
        lm_config = AppConfig.from_dict({"loadmodel": {
            "seed": 53, "viewers": viewers, "diurnal-amplitude": 0.0,
            "bulk-fraction": 0.0, "mask-fraction": 0.0,
            "zoom-fraction": 0.0, "skew": float(s),
            "image-population": int(image_population)}}).loadmodel
        return LoadModel.from_config(lm_config, duration_s=60.0,
                                     grid=grid)

    def params_for(arrival):
        # The session's popularity RANK addresses the tile lattice:
        # rank 0 is THE viral tile — one plane_route_key — while the
        # channel window varies per (session, step), so the storm is
        # distinct render identities over one source plane (the
        # byte cache cannot flatten it; the plane tier must).
        sid = int(arrival.session.rsplit("-", 1)[1])
        tx = arrival.image % grid
        ty = (arrival.image // grid) % grid
        w = 21000 + (sid * 131 + arrival.step * 37) % 18000
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,{tx},{ty},{tile_edge},{tile_edge}",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
        }

    nominal_tps = n_members * lane_width * 1000.0 / exec_ms
    offered = load_factor * nominal_tps

    async def run_leg(tmp: str, s: float, hot_enabled: bool) -> tuple:
        telemetry.LOADMODEL.reset()
        telemetry.HOTKEY.reset()
        model = make_model(s)
        events = model.events()
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        members = [VirtualDeviceMember(
            m.name, m.handler, m.services,
            down_cooldown_s=m.down_cooldown_s,
            byte_cache_prechecked=m.byte_cache_prechecked)
            for m in build_local_members(config, services, n_members)]
        router = FleetRouter(
            members, lane_width=lane_width, steal_min_backlog=0,
            hotkey=HotkeyConfig(
                enabled=hot_enabled, threshold=threshold,
                decay_s=decay_s, max_replicas=2,
                demote_fraction=0.5, scale_factor=1.5))
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            admission=AdmissionController(4096, renderer=router),
            base_services=services)

        async def submit(arrival):
            ctx = ImageRegionCtx.from_params(params_for(arrival))
            ctx.omero_session_key = arrival.session
            out = await handler.render_image_region(ctx)
            assert out

        try:
            # One warm render outside the measured window (shared jit
            # compile across stacks of one process).
            await submit(events[0])
            sched = model.window(offered, window_s, events)
            report = await run_open_loop(
                submit, sched, offered_tps=len(sched) / window_s)
            assert not report.errors, \
                f"hotkey leg failed bare: {report.errors[:3]}"
            tps = report.served / report.window_s
            extra: dict = {}
            if hot_enabled and s > 0:
                # Live lifecycle state, read BEFORE decay: peak
                # pressure, replica sets, shard classification.
                extra["pressure"] = router.replica_pressure()
                extra["hot_routes"] = router.hot_route_count()
                extra["shard"] = router.shard_report()
                # One autoscaler tick at the fleet ceiling while the
                # pressure holds: the want-up it forces is refused as
                # blocked:ceiling, and THAT decision record must carry
                # the replica-pressure signal (the acceptance line).
                scaler = Autoscaler(AutoscalerConfig(
                    enabled=True, floor=1, ceiling=n_members,
                    hold_ticks=1, cooldown_s=0.0), router)
                extra["tick"] = scaler.tick()
                # Heat decay past the demote fraction, then cool
                # traffic drives the sweep on the LIVE dispatch path.
                await asyncio.sleep(max(4.0 * decay_s, 1.0))
                cool = [a for a in sched if a.image != 0][:4] \
                    or sched[:2]
                for a in cool:
                    await submit(a)
                extra["hot_after"] = router.hot_route_count()
                extra["totals"] = telemetry.HOTKEY.totals()
            return tps, extra
        finally:
            await router.close()
            services.pixels_service.close()

    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        uniform_tps, _ = asyncio.run(run_leg(tmp, 0.0, True))
        disabled_tps, _ = asyncio.run(run_leg(tmp, skew, False))
        decisions.LEDGER.reset()
        storm_tps, storm = asyncio.run(run_leg(tmp, skew, True))

    totals = storm.get("totals", {})
    shard = storm.get("shard", {})
    ledger = decisions.LEDGER.snapshot()
    autoscaler_signal = any(
        r.get("kind") == "autoscaler"
        and float((r.get("detail") or {}).get("signals", {})
                  .get("replica_pressure", 0.0) or 0.0) > 0.0
        for r in ledger)
    ledger_promotions = sum(
        1 for r in ledger if r.get("kind") == "hotkey"
        and r.get("verdict") == "promoted")
    out = {
        "metric": "hotkey_smoke",
        "hotkey_fleet_size": n_members,
        "hotkey_virtual_exec_ms": exec_ms,
        "hotkey_window_s": window_s,
        "hotkey_offered_tps": round(offered, 1),
        "hotkey_skew": float(skew),
        "hotkey_image_population": int(image_population),
        # The headline pair the gate judges: the storm's throughput
        # retention vs the uniform mix (regresses DOWN), and the
        # replication gain over the disabled A/B (regresses DOWN,
        # must stay > 1 — disabled measuring MORE means the tier is
        # dead weight).
        "hotkey_uniform_tps": round(uniform_tps, 1),
        "hotkey_storm_tps": round(storm_tps, 1),
        "hotkey_storm_ratio": round(storm_tps / uniform_tps, 3),
        "hotkey_disabled_tps": round(disabled_tps, 1),
        "hotkey_replication_gain": round(
            storm_tps / max(disabled_tps, 1e-9), 3),
        "hotkey_promotions": int(totals.get("promoted", 0)),
        "hotkey_demotions": int(totals.get("demoted", 0)),
        "hotkey_replica_staged": int(totals.get("staged", 0)),
        "hotkey_duplicate_staged": int(
            totals.get("duplicate_staged", 0)),
        "hotkey_balanced_reads": int(totals.get("balanced", 0)),
        "hotkey_peak_replica_pressure": round(
            float(storm.get("pressure", 0.0)), 2),
        "hotkey_hot_routes_peak": int(storm.get("hot_routes", 0)),
        "hotkey_hot_routes_after_decay": int(
            storm.get("hot_after", 0)),
        "hotkey_demoted_after_decay": bool(
            totals.get("demoted", 0) >= 1
            and storm.get("hot_after", 1) == 0),
        "hotkey_shard_duplicates": int(
            shard.get("duplicate_digests", 0)),
        "hotkey_shard_replicated": int(
            shard.get("replicated_digests", 0)),
        "hotkey_autoscaler_signal": bool(autoscaler_signal),
        "hotkey_ledger_promotions": int(ledger_promotions),
        "loadmodel_late_fires": telemetry.LOADMODEL.late,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    if emit:
        print(json.dumps(out))
    return out


def bench_sentinel_smoke(emit: bool = True):
    """Induced-drift sentinel drill (``bench.py --smoke --sentinel``,
    tier-1 via tests/test_bench_smoke.py): the full confirm → capture
    → recover cycle, deterministically, on a virtual clock.

    A REAL 2-member fleet (the ``_fleet_smoke`` virtual-occupancy
    members) serves a small burst each phase so the forensic
    artifacts a bundle snapshots — flight ring, top-K cost ledgers,
    request exemplars — hold live content; each member runs its OWN
    ``SentinelEngine`` fed a deterministic per-request latency
    (window jitter included, so the sketches are non-degenerate):

    * **warmup** — both members at ~10 ms until their baselines
      learn;
    * **step** — member m1's latency steps to 4x while m0 holds: m1
      must confirm EXACTLY ONE drift after ``confirm_ticks``
      breaching windows, capture EXACTLY ONE complete bundle
      (manifest listing profile + flight + costs + sketch_diff +
      exemplars) and write ONE ``kind=sentinel`` ledger record,
      while m0 stays quiet;
    * **recover** — m1 returns to baseline and ``recover_ticks``
      clean windows must clear the verdict.

    Both members' summaries are ingested into ``telemetry.SENTINEL``
    exactly as the gossip path does, so the asserted merged view is
    the /debug/sentinel shape.  Emits ONE JSON line.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter, build_local_members)
    from omero_ms_image_region_tpu.server.admission import (
        AdmissionController)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.sentinel import SentinelEngine
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.utils import decisions, telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(41)
    grid, tile_edge = 2, 32
    route = "render_image_region"
    base_ms, step_ms = 10.0, 40.0
    min_samples, warmup, confirm, recover = 16, 2, 2, 2

    clk = [0.0]

    def stub_profile(directory: str, ms: float) -> dict:
        # The drill stands in for telemetry.capture_profile (a real
        # jax.profiler capture needs a device window and wall time);
        # the app path keeps the real single-flight capture.
        sub = os.path.join(directory, "profile")
        os.makedirs(sub, exist_ok=True)
        with open(os.path.join(sub, "capture.stub"), "w") as f:
            f.write("drill\n")
        return {"dir": sub, "ms": 0.0, "requested_ms": ms,
                "files": 1, "bytes": 6}

    def make_engine(member: str, bundle_dir: str) -> SentinelEngine:
        return SentinelEngine(
            member=member, tick_interval_s=5.0,
            confirm_ticks=confirm, recover_ticks=recover,
            min_samples=min_samples, warmup_ticks=warmup,
            drift_ratio=1.5, baseline_alpha=0.2,
            bundle_dir=bundle_dir, profile_ms=50.0,
            # Real watermark SHAPE, drill-scaled values: the latency
            # floor sits under the induced step (so the breach is
            # above it) and the throughput mark is tiny (this drill
            # induces a latency drift, not a starvation).
            watermarks={"bench": {
                "p50_service_tile_ms_ex_rtt": {"value": 5.0},
                "service_tiles_per_sec": {"value": 0.001},
            }},
            clock=lambda: clk[0],
            profile_fn=stub_profile)

    def feed(engine: SentinelEngine, center_ms: float) -> None:
        # One window's worth of deterministic observations: a fixed
        # sawtooth around the center so quantiles interpolate over
        # several sketch buckets instead of collapsing into one.
        for i in range(max(min_samples, 24)):
            engine.observe(route, 64 * 1024,
                           center_ms * (1.0 + 0.04 * (i % 5)))

    async def serve_burst(handler, n: int = 4) -> None:
        # Live fleet traffic so the bundle's flight/cost/exemplar
        # snapshots hold real content (durations the ENGINES judge
        # stay the deterministic feed above).
        for i in range(n):
            ctx = ImageRegionCtx.from_params({
                "imageId": "1", "theZ": "0", "theT": "0",
                "tile": f"0,{i % grid},{(i // grid) % grid},"
                        f"{tile_edge},{tile_edge}",
                "format": "png", "m": "c", "c": "1|0:39000$FF0000",
            })
            out = await handler.render_image_region(ctx)
            assert out

    async def run_drill(tmp: str, bundle_dir: str) -> dict:
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        members = build_local_members(config, services, 2)
        router = FleetRouter(members, lane_width=2,
                             steal_min_backlog=0)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            admission=AdmissionController(256, renderer=router),
            base_services=services)
        engines = {
            "m0": make_engine("m0", ""),
            "m1": make_engine("m1", bundle_dir),
        }

        def tick_all() -> dict:
            clk[0] += 5.0
            summaries = {}
            for name, eng in engines.items():
                summaries[name] = eng.tick()
                # The gossip ingest path, verbatim: per-member
                # summaries join the fleet merge.
                telemetry.SENTINEL.ingest(name, summaries[name])
            return summaries

        try:
            # Warmup: both members learn "normal".
            for _ in range(warmup + 1):
                await serve_burst(handler)
                for eng in engines.values():
                    feed(eng, base_ms)
                tick_all()
            assert engines["m1"].verdict == "ok"

            # Latency step on m1 only: confirm_ticks breaching
            # windows -> ONE confirmed drift + ONE bundle.
            for _ in range(confirm):
                await serve_burst(handler)
                feed(engines["m0"], base_ms)
                feed(engines["m1"], step_ms)
                summaries = tick_all()
            drift_summary = summaries["m1"]
            merged_at_drift = telemetry.SENTINEL.merged()

            # Recovery: clean windows clear the verdict.
            for _ in range(recover):
                await serve_burst(handler)
                for eng in engines.values():
                    feed(eng, base_ms)
                summaries = tick_all()
            return {"drift": drift_summary,
                    "merged": merged_at_drift,
                    "final": summaries}
        finally:
            await router.close()
            services.pixels_service.close()

    telemetry.SENTINEL.reset()
    decisions.LEDGER.reset()
    with tempfile.TemporaryDirectory() as tmp, \
            tempfile.TemporaryDirectory() as bundle_dir:
        planes = synthetic_wsi_tiles(rng, 1, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            1, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        phases = asyncio.run(run_drill(tmp, bundle_dir))

        # -- exactly one confirmed drift, on m1, never m0 ------------
        drift = phases["drift"]
        assert drift["verdict"] == "drifting", drift
        assert drift["drifting"], drift
        sentinel_records = [
            r for r in decisions.LEDGER.snapshot()
            if r.get("kind") == "sentinel"]
        drift_records = [r for r in sentinel_records
                         if r.get("verdict") == "drift"]
        assert len(drift_records) == 1, sentinel_records
        assert drift_records[0].get("member") == "m1", drift_records

        # -- exactly one COMPLETE bundle ------------------------------
        bundles = sorted(
            n for n in os.listdir(bundle_dir)
            if n.startswith("sentinel-"))
        assert len(bundles) == 1, bundles
        bundle_path = os.path.join(bundle_dir, bundles[0])
        with open(os.path.join(bundle_path, "manifest.json")) as f:
            manifest = json.load(f)
        files = manifest["files"]
        missing = [k for k in ("profile", "flight", "costs",
                               "sketch_diff", "exemplars")
                   if not files.get(k)]
        assert not missing, f"incomplete bundle: missing {missing}"
        for fname in files.values():
            assert os.path.exists(os.path.join(bundle_path, fname))
        with open(os.path.join(bundle_path, files["flight"])) as f:
            flight_doc = json.load(f)
        assert flight_doc.get("events"), "flight dump empty"

        # -- the merged fleet view saw both members + the drift -------
        merged = phases["merged"]
        assert set(merged["members"]) >= {"m0", "m1"}, merged
        assert merged["verdict"] == "drifting", merged
        assert merged["drifting_members"] == ["m1"], merged

        # -- recovery clears the verdict ------------------------------
        final = phases["final"]
        assert final["m1"]["verdict"] == "ok", final["m1"]
        recovered_records = [r for r in decisions.LEDGER.snapshot()
                             if r.get("kind") == "sentinel"
                             and r.get("verdict") == "recovered"]
        assert len(recovered_records) == 1, recovered_records

    out = {
        "metric": "sentinel_smoke",
        "sentinel_drift_confirms": len(drift_records),
        "sentinel_drifting_member": "m1",
        "sentinel_bundles": len(bundles),
        "sentinel_bundle_files": sorted(files),
        "sentinel_recovered": True,
        "sentinel_merged_members": sorted(merged["members"]),
        "sentinel_drift_keys": list(drift["drifting"]),
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }
    if emit:
        print(json.dumps(out))
    return out


def bench_federation_smoke(grid: int = 3, tile_edge: int = 32,
                           burst: int = 24, emit: bool = True):
    """Multi-PROCESS federated fleet smoke (``bench.py --smoke
    --federation``): this process runs host A of a federated combined
    fleet (one local device-pinned member) and SPAWNS a real sidecar
    process as host B's member, behind one agreed manifest.

    Measured (the MULTICHIP record family grew these keys; rounds
    that predate them skip on null in ``bench_gate --multichip``):

    * **agreement** — the manifest digest agrees and the spawned
      process's OWN ring math assigns every golden probe key to the
      same owner this process computes (``fed_manifest_agreed``);
    * **process scaling** — a closed-loop distinct-tile burst through
      1 process vs 2 (``fed_tiles_per_sec_p1/p2``,
      ``fed_process_scaling_efficiency``);
    * **cross-host warm handoff** — draining the LOCAL member ships
      its HBM shard bytes over the ``shard_transfer`` wire op, and
      the remote process answers the digests resident
      (``fed_drain_prestaged`` / ``fed_remote_resident``);
    * **stitched control-plane forensics** — the gossip round and the
      drain run inside ONE trace, producing a two-process waterfall
      whose ``fed.hop`` spans are causally ordered and whose remote
      stage grafts sit INSIDE their wire exchange's window after
      per-host clock anchoring (``fed_trace_stitched``); an
      autoscaler ticks against the live router until its ledger
      verdicts carry MEASURED outcomes, and the local + remote
      decision rings merge into one host-attributed timeline
      (``decision_records`` / ``fed_decision_hosts``) — with a
      renderer-span delta of ZERO across all forensics reads
      (``forensics_render_delta``).
    """
    import asyncio
    import os
    import tempfile

    import yaml

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel import federation
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.sidecar import (
        SidecarClient, spawn_sidecar)
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)

    t_start = time.perf_counter()
    rng = np.random.default_rng(53)

    def params_for(i: int, leg: str):
        x, y = i % grid, (i // grid) % grid
        w = 20000 + 700 * i + (0 if leg == "p1" else 11)
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,{x},{y},{tile_edge},{tile_edge}",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000",
        }

    async def run(tmp: str, sock: str) -> dict:
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        manifest = federation.FleetManifest(
            [federation.MemberSpec("a0", "hostA"),
             federation.MemberSpec("b0", "hostB", sock)],
            version=1, ring_seed="bench-fed")
        federation.install(manifest, self_host="hostA")
        members = federation.build_federated_members(
            config, services, manifest, SidecarClient, "hostA")
        router = FleetRouter(members, lane_width=2,
                             steal_min_backlog=0,
                             ring_seed=manifest.ring_seed,
                             wire_handoff=True)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            base_services=services)
        coord = federation.FederationCoordinator(manifest, "hostA",
                                                 router)
        out: dict = {}
        try:
            verdicts = await coord.agree(strict=True)
            out["fed_manifest_agreed"] = all(
                v == "agreed" for v in verdicts.values())

            async def measure(leg: str) -> float:
                ctxs = [ImageRegionCtx.from_params(
                    params_for(i, leg)) for i in range(burst)]
                t0 = time.perf_counter()
                done = await asyncio.gather(
                    *(handler.render_image_region(c) for c in ctxs))
                assert all(done)
                return burst / (time.perf_counter() - t0)

            # p1: host B parked (draining — no routes land there),
            # p2: both processes serve.
            await measure("warm")          # shared compile warm-up
            router.members["b0"].draining = True
            p1 = await measure("p1")
            router.members["b0"].draining = False
            p2 = await measure("p2")
            out["fed_tiles_per_sec_p1"] = round(p1, 2)
            out["fed_tiles_per_sec_p2"] = round(p2, 2)
            out["fed_process_scaling_efficiency"] = round(
                p2 / (2.0 * p1), 3)

            # Cross-host warm handoff: the LOCAL member's HBM shard
            # ships over shard_transfer when it drains; the remote
            # process must answer the digests resident.  The gossip
            # round and the drain run inside ONE trace so the
            # cross-host control plane leaves a stitched waterfall.
            from omero_ms_image_region_tpu.utils import (
                decisions, telemetry)
            local = router.members["a0"]
            digests = sorted(local.resident_digests())
            with telemetry.trace_scope("bench-fed-forensics") as trace:
                await coord.gossip_once()
                doc = await router.drain_member("a0",
                                                settle_timeout_s=5.0)
            spans = trace.export_spans()
            telemetry.TRACES.finish("bench-fed-forensics")
            out["fed_drain_planes"] = doc["planes"]
            out["fed_drain_prestaged"] = doc["prestaged"]
            resident = 0
            if digests:
                import json as _json
                status, body = await members[1].client.call(
                    "plane_probe", {}, extra={"digests": digests})
                if status == 200 and body:
                    resident = sum(
                        bool(r) for r in _json.loads(
                            bytes(body).decode()).get("resident", ()))
            out["fed_remote_resident"] = resident
            router.undrain_member("a0")

            # --- stitched two-process waterfall: >=1 fed.hop span,
            # host B's clock anchored, spans causally ordered, and
            # every remote stage graft INSIDE its wire exchange's
            # [send, recv] window (the clock-anchoring contract).
            hops = sorted((s for s in spans if s["name"] == "fed.hop"),
                          key=lambda s: s["start_ms"])
            anchored = federation.host_clock_offset("hostB") is not None
            eps = 0.5    # float rounding on exported ms offsets
            # Causal: no hop starts before the trace began (a
            # mis-anchored clock would fling a graft negative) and
            # none has negative extent.
            ordered = bool(hops) and all(
                s["start_ms"] >= -eps and s["dur_ms"] >= 0.0
                for s in hops)
            wrappers = [s for s in hops
                        if s.get("kind") == "shard_transfer"]
            grafts = [s for s in hops if s.get("kind") == "stage"]
            contained = all(any(
                w["start_ms"] - eps <= g["start_ms"]
                and (g["start_ms"] + g["dur_ms"]
                     <= w["start_ms"] + w["dur_ms"] + eps)
                for w in wrappers) for g in grafts)
            out["fed_hop_spans"] = len(hops)
            out["fed_hop_grafts"] = len(grafts)
            out["fed_trace_stitched"] = int(
                bool(hops) and anchored and ordered and contained)

            # --- decision ledger: an autoscaler ticks against the
            # live router (floor == active members, so the quiet
            # queue wants "down" and the floor refuses it — one
            # "blocked" verdict) until the outcome horizon attaches
            # the MEASURED queue/member deltas; then the local and
            # remote rings merge into one host-attributed timeline,
            # with a renderer-span delta of ZERO for all of it.
            from omero_ms_image_region_tpu.server.autoscaler import (
                Autoscaler)
            from omero_ms_image_region_tpu.server.config import (
                AutoscalerConfig)
            from omero_ms_image_region_tpu.utils.stopwatch import (
                REGISTRY as span_reg)

            def _renders() -> int:
                snap = span_reg.snapshot()
                return sum(snap.get(n, {}).get("count", 0) for n in
                           ("Renderer.renderAsPackedInt",
                            "Renderer.renderAsPackedInt.cpu",
                            "Renderer.renderAsPackedInt.batch"))

            renders_before = _renders()
            fake_now = [0.0]
            scaler = Autoscaler(
                AutoscalerConfig(enabled=True, floor=2,
                                 hold_ticks=1, cooldown_s=0.0),
                router, clock=lambda: fake_now[0])
            horizon = decisions.LEDGER.outcome_horizon_ticks
            for _ in range(horizon + 2):
                fake_now[0] += 1.0
                scaler.tick()
            local_ring = decisions.LEDGER.snapshot()
            remote_ring = []
            import json as _json
            status, body = await members[1].client.call(
                "decisions", {})
            if status == 200 and body:
                remote_ring = list(_json.loads(
                    bytes(body).decode()).get("ring") or ())
            merged = ([dict(r, host=r.get("host") or "hostA")
                       for r in local_ring]
                      + [dict(r, host=r.get("host") or "hostB")
                         for r in remote_ring])
            merged.sort(key=lambda r: r.get("ts", 0.0))
            out["decision_records"] = sum(
                1 for r in merged if r["kind"] == "autoscaler"
                and "outcome" in r)
            out["fed_decision_hosts"] = len(
                {r["host"] for r in merged})
            out["forensics_render_delta"] = _renders() - renders_before
            assert out["fed_trace_stitched"] == 1, \
                "cross-host waterfall failed to stitch: " \
                f"hops={len(hops)} anchored={anchored} " \
                f"ordered={ordered} contained={contained}"
            assert out["decision_records"] >= 1, \
                "no autoscaler decision carried a measured outcome"
            assert out["fed_decision_hosts"] >= 2, \
                "merged decision timeline is missing a host"
            assert out["forensics_render_delta"] == 0, \
                "forensics reads performed render work"
            return out
        finally:
            await router.close()
            for member in members:
                if getattr(member, "remote", False):
                    await member.client.close()
            federation.uninstall()
            services.pixels_service.close()

    out = {"metric": "federation_smoke"}
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        sock = os.path.join(tmp, "fed-b0.sock")
        sidecar_cfg = {
            "data-dir": tmp,
            "batcher": {"enabled": False},
            "raw-cache": {"enabled": True, "prefetch": False,
                          "digest-dedup": True},
            "renderer": {"cpu-fallback-max-px": 0},
            "federation": {
                "enabled": True, "host": "hostB", "shard-epoch": 1,
                "ring-seed": "bench-fed",
                "members": [
                    {"name": "a0", "host": "hostA"},
                    {"name": "b0", "host": "hostB", "address": sock},
                ]},
        }
        cfg_path = os.path.join(tmp, "sidecar.yaml")
        with open(cfg_path, "w") as f:
            yaml.safe_dump(sidecar_cfg, f)
        proc = spawn_sidecar(cfg_path, sock)
        try:
            out.update(asyncio.run(run(tmp, sock)))
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except Exception:
                proc.kill()
    out["elapsed_s"] = round(time.perf_counter() - t_start, 1)
    if emit:
        print(json.dumps(out))
    return out


def bench_partition_smoke(grid: int = 3, tile_edge: int = 32,
                          emit: bool = True):
    """Netsplit chaos drill (``bench.py --smoke --partition``): a
    3-host federated fleet (this process = host A's router + local
    member; two REAL spawned sidecar processes = hosts B and C, each
    running quorum tracking and its own gossip loop) driven through a
    full partition -> fence -> heal -> rejoin cycle UNDER SUSTAINED
    LOAD, with a two-phase epoch roll committed mid-partition.

    The drill cuts every link to host C at the sidecar wire layer
    (``utils.faultinject.PARTITIONS`` locally + the ``partition``
    control op remotely — that op is partition-exempt so the drill
    can always heal what it broke) and gates, on one record:

    * **majority availability** — the A+B majority serves the whole
      load loop with ZERO failures that are not counted shed
      (``part_majority_5xx`` == 0; breaker fail-fasts count as shed);
    * **minority fencing** — C loses quorum within the suspect
      window (``part_fence_ms``), REFUSES state-changing ops
      gracefully while still answering (``part_minority_refusals``
      from byte_put/prestage probes), and restores within
      ``part_restore_ms`` of heal;
    * **mid-partition epoch roll** — the coordinator rolls the fleet
      to epoch 2 while C is dark: strict-majority acks commit it
      (``part_roll_committed``/``part_roll_acks``), and the healed
      minority converges to the committed epoch through gossip
      anti-entropy with NO operator action (``part_rejoin_epoch``);
    * **no split-brain** — after heal every host agrees on the
      epoch-2 digest AND assigns every golden probe key with its OWN
      ring math (``part_postheal_agree``); C's byte tier accepts and
      returns byte-identical content again (``part_byte_agree``); and
      C's decision ledger holds the kind=``quorum`` fenced/restored
      pair (``part_quorum_ledger``).

    Judged by ``scripts/bench_gate.py --partition`` on the PARTITION
    record family.
    """
    import asyncio
    import os
    import tempfile

    import yaml

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.parallel import federation
    from omero_ms_image_region_tpu.parallel.fleet import (
        FleetImageHandler, FleetRouter)
    from omero_ms_image_region_tpu.server.app import build_services
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, RawCacheConfig, RendererConfig)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.errors import OverloadedError
    from omero_ms_image_region_tpu.server.sidecar import (
        SidecarClient, spawn_sidecar)
    from omero_ms_image_region_tpu.server.singleflight import (
        SingleFlight)
    from omero_ms_image_region_tpu.utils import faultinject

    t_start = time.perf_counter()
    rng = np.random.default_rng(59)
    suspect_s = 1.2

    def params_for(i: int):
        x, y = i % grid, (i // grid) % grid
        w = 21000 + 600 * i
        return {
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,{x},{y},{tile_edge},{tile_edge}",
            "format": "png", "m": "c",
            "c": f"1|0:{w}$FF0000",
        }

    async def _poll(client: SidecarClient, timeout_s: float, pred):
        """Poll host C's partition-exempt control op until ``pred``
        accepts the reply doc; returns (doc, waited_ms)."""
        t0 = time.perf_counter()
        doc = None
        while time.perf_counter() - t0 < timeout_s:
            status, body = await client.call(
                "partition", {}, extra={"action": "show"})
            if status == 200 and body:
                doc = json.loads(bytes(body).decode())
                if pred(doc):
                    return doc, (time.perf_counter() - t0) * 1000.0
            await asyncio.sleep(0.06)
        return doc, (time.perf_counter() - t0) * 1000.0

    async def run(tmp: str, sock_b: str, sock_c: str) -> dict:
        config = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        services = build_services(config)
        specs = [federation.MemberSpec("a0", "hostA"),
                 federation.MemberSpec("b0", "hostB", sock_b),
                 federation.MemberSpec("c0", "hostC", sock_c)]
        manifest = federation.FleetManifest(
            list(specs), version=1, ring_seed="bench-part")
        federation.install(manifest, self_host="hostA")
        federation.install_quorum(federation.QuorumTracker(
            manifest, "hostA", suspect_after_s=suspect_s))
        members = federation.build_federated_members(
            config, services, manifest, SidecarClient, "hostA")
        router = FleetRouter(members, lane_width=2,
                             steal_min_backlog=0,
                             ring_seed=manifest.ring_seed,
                             wire_handoff=True)
        federation.set_roll_hook(router.apply_manifest)
        handler = FleetImageHandler(
            router, single_flight=SingleFlight(),
            base_services=services)
        coord = federation.FederationCoordinator(
            manifest, "hostA", router, gossip_interval_s=0.25)
        # Control channel to C: a raw client with no peer_host stamp
        # is partition-exempt by construction — the drill's scalpel
        # must keep working while the fleet's own links are dark.
        ctl_c = SidecarClient(sock_c, wire=config.wire)
        ctl_b = SidecarClient(sock_b, wire=config.wire)
        load = {"n": 0, "shed": 0, "hard": 0}
        stop_load = asyncio.Event()

        async def load_loop() -> None:
            i = 0
            while not stop_load.is_set():
                ctxs = [ImageRegionCtx.from_params(params_for(j))
                        for j in range(i % 5, i % 5 + 4)]
                done = await asyncio.gather(
                    *(handler.render_image_region(c) for c in ctxs),
                    return_exceptions=True)
                for r in done:
                    load["n"] += 1
                    if isinstance(r, OverloadedError):
                        load["shed"] += 1
                    elif isinstance(r, BaseException):
                        load["hard"] += 1
                i += 1
                await asyncio.sleep(0.02)

        out: dict = {}
        gossip_task = None
        load_task = None
        try:
            verdicts = await coord.agree(strict=True)
            out["part_manifest_agreed"] = int(all(
                v == "agreed" for v in verdicts.values()))
            gossip_task = asyncio.create_task(coord.run())
            # Warm-up: compile every process's render program before
            # the clock-sensitive phases (first-compile stalls would
            # smear the fence/restore latencies).
            warm = [ImageRegionCtx.from_params(params_for(i))
                    for i in range(grid * grid)]
            await asyncio.gather(
                *(handler.render_image_region(c) for c in warm))
            load_task = asyncio.create_task(load_loop())
            await asyncio.sleep(0.4)

            # --- partition: cut every link to/from host C.  A's
            # outbound edge is process-local; B's and C's outbound
            # edges go over the exempt control op.
            faultinject.PARTITIONS.add("hostA", "hostC")
            await ctl_b.call("partition", {}, extra={
                "action": "add", "src": "hostB", "dst": "hostC"})
            await ctl_c.call("partition", {}, extra={
                "action": "add", "src": "hostC", "dst": "hostA"})
            await ctl_c.call("partition", {}, extra={
                "action": "add", "src": "hostC", "dst": "hostB"})
            doc, waited = await _poll(
                ctl_c, timeout_s=suspect_s * 6 + 5.0,
                pred=lambda d: (d.get("quorum") or {}).get("fenced"))
            assert doc and (doc.get("quorum") or {}).get("fenced"), \
                f"host C never fenced: {doc}"
            out["part_fence_ms"] = round(waited, 1)

            # --- fenced refusals: state-changing ops answer
            # gracefully (200 + fenced flag), and each one counts.
            payload = b"partition-drill-bytes"
            import hashlib as _hashlib
            digest = _hashlib.blake2b(
                payload, digest_size=16).hexdigest()
            status, body = await ctl_c.call(
                "byte_put", {}, body=payload,
                extra={"key": "bench:part:byte", "digest": digest})
            assert status == 200, f"fenced byte_put errored: {body}"
            assert json.loads(bytes(body).decode()).get("fenced"), \
                "fenced minority accepted byte-tier write authority"
            status, body = await ctl_c.call(
                "prestage", {}, extra={"entries": []})
            assert status == 200 and json.loads(
                bytes(body).decode()).get("fenced"), \
                "fenced minority accepted inbound shard staging"
            refusals = ((doc.get("quorum") or {}).get("refusals")
                        or {})
            status, body = await ctl_c.call(
                "partition", {}, extra={"action": "show"})
            if status == 200 and body:
                refusals = (json.loads(bytes(body).decode())
                            .get("quorum") or {}).get("refusals") or {}
            out["part_minority_refusals"] = int(
                sum(refusals.values()))

            # --- mid-partition epoch roll: strict majority (A + B)
            # acks; dark C is "unreachable" and must not block it.
            rolled = federation.FleetManifest(
                list(specs), version=2, ring_seed="bench-part-v2")
            roll = await coord.roll_epoch(rolled)
            out["part_roll_committed"] = int(bool(roll["committed"]))
            out["part_roll_acks"] = roll["acks"]
            assert roll["committed"], f"majority roll aborted: {roll}"
            await asyncio.sleep(0.5)       # roll rides under load

            # --- heal: clear every rule, then watch C restore and
            # converge to the committed epoch via anti-entropy.
            faultinject.PARTITIONS.clear()
            await ctl_b.call("partition", {},
                             extra={"action": "clear"})
            await ctl_c.call("partition", {},
                             extra={"action": "clear"})
            doc, waited = await _poll(
                ctl_c, timeout_s=suspect_s * 6 + 5.0,
                pred=lambda d: not (d.get("quorum")
                                    or {}).get("fenced", True))
            assert doc and not (doc.get("quorum") or {}).get(
                "fenced", True), f"host C never restored: {doc}"
            out["part_restore_ms"] = round(waited, 1)
            doc, _ = await _poll(
                ctl_c, timeout_s=10.0,
                pred=lambda d: d.get("epoch") == 2)
            out["part_rejoin_epoch"] = int(doc.get("epoch") or 0) \
                if doc else 0
            assert out["part_rejoin_epoch"] == 2, \
                f"healed minority never converged to epoch 2: {doc}"

            # --- post-heal agreement: every host answers the epoch-2
            # digest AND its own ring math assigns the golden probe
            # keys identically (the split-brain gate).  The breaker on
            # A's c0 link may still be half-open — give it a few
            # rounds to prove the link again.
            agree_deadline = time.perf_counter() + 8.0
            agreed = {}
            while time.perf_counter() < agree_deadline:
                agreed = await coord.agree(strict=False)
                if agreed and all(v == "agreed"
                                  for v in agreed.values()):
                    break
                await asyncio.sleep(0.25)
            out["part_postheal_agree"] = int(bool(agreed) and all(
                v == "agreed" for v in agreed.values()))
            assert out["part_postheal_agree"] == 1, \
                f"post-heal agreement incomplete: {agreed}"

            # --- byte-tier rejoin: the restored C accepts write
            # authority again and answers the bytes back verbatim.
            status, body = await ctl_c.call(
                "byte_put", {}, body=payload,
                extra={"key": "bench:part:byte", "digest": digest})
            stored = (status == 200 and json.loads(
                bytes(body).decode()).get("stored"))
            status, body = await ctl_c.call(
                "byte_fetch", {}, extra={"key": "bench:part:byte"})
            out["part_byte_agree"] = int(
                bool(stored) and status == 200
                and bytes(body) == payload)

            # --- C's own ledger holds the fence/restore pair.
            ledger = 0
            status, body = await ctl_c.call("decisions", {})
            if status == 200 and body:
                ring = json.loads(
                    bytes(body).decode()).get("ring") or ()
                ledger = sum(1 for r in ring
                             if r.get("kind") == "quorum")
            out["part_quorum_ledger"] = ledger

            stop_load.set()
            await load_task
            load_task = None
            out["part_load_requests"] = load["n"]
            out["part_majority_shed"] = load["shed"]
            out["part_majority_5xx"] = load["hard"]
            assert load["n"] > 0, "load loop never ran"
            assert load["hard"] == 0, \
                f"majority side failed {load['hard']} requests " \
                f"without shedding (of {load['n']})"
            return out
        finally:
            stop_load.set()
            for task in (load_task, gossip_task):
                if task is not None:
                    task.cancel()
                    try:
                        await task
                    except (asyncio.CancelledError, Exception):
                        pass
            faultinject.PARTITIONS.clear()
            await ctl_c.close()
            await ctl_b.close()
            await router.close()
            for member in members:
                if getattr(member, "remote", False):
                    await member.client.close()
            federation.uninstall()
            services.pixels_service.close()

    out = {"metric": "partition_smoke"}
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, grid * tile_edge,
                                     grid * tile_edge).reshape(
            2, 1, grid * tile_edge, grid * tile_edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        sock_b = os.path.join(tmp, "part-b0.sock")
        sock_c = os.path.join(tmp, "part-c0.sock")
        members_doc = [
            {"name": "a0", "host": "hostA"},
            {"name": "b0", "host": "hostB", "address": sock_b},
            {"name": "c0", "host": "hostC", "address": sock_c},
        ]
        procs = []
        try:
            for host, sock in (("hostB", sock_b), ("hostC", sock_c)):
                sidecar_cfg = {
                    "data-dir": tmp,
                    "batcher": {"enabled": False},
                    "raw-cache": {"enabled": True, "prefetch": False,
                                  "digest-dedup": True},
                    "renderer": {"cpu-fallback-max-px": 0},
                    "image-region-cache": {"enabled": True},
                    "federation": {
                        "enabled": True, "host": host,
                        "shard-epoch": 1, "ring-seed": "bench-part",
                        "quorum": True,
                        "suspect-after-s": suspect_s,
                        "gossip-interval-s": 0.3,
                        "members": members_doc,
                    },
                }
                cfg_path = os.path.join(
                    tmp, f"sidecar-{host}.yaml")
                with open(cfg_path, "w") as f:
                    yaml.safe_dump(sidecar_cfg, f)
                procs.append(spawn_sidecar(cfg_path, sock))
            out.update(asyncio.run(run(tmp, sock_b, sock_c)))
        finally:
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=15)
                except Exception:
                    proc.kill()
    out["elapsed_s"] = round(time.perf_counter() - t_start, 1)
    if emit:
        print(json.dumps(out))
    return out


def bench_restart_smoke():
    """Warm-restart gate at smoke scale: render, "kill", restart with
    persistence on, and prove the first previously-seen tile serves
    from the disk byte tier + a deserialized executable — no pixel
    read, no device dispatch, no XLA compile.

    In-process restart semantics: the second life builds a completely
    fresh service stack (new memory caches, new HBM cache, new
    executable registry) over the SAME persistence directory — what a
    process restart drops is exactly what a fresh stack starts
    without.  (The one thing an in-process "kill" cannot drop is
    XLA's jit cache; the compile assertion therefore ALSO checks that
    the second life's registry really deserialized its programs from
    disk, which is the mechanism a real restart rides.)

    Reported keys (one JSON line, like the other smoke gates):

    * ``restart_time_to_first_tile_ms`` — boot-to-first-200 on the
      repeat working set;
    * ``restart_warm_hit_rate`` — fraction of the repeat working set
      served with ZERO new device dispatches (acceptance: >= 0.9);
    * ``restart_first_tile_identical`` — rehydrated bytes ==
      pre-restart bytes, and == the jax-free refimpl render of the
      same request (golden check);
    * ``rehydrate_*`` — what the boot rehydrator replayed.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, PersistenceConfig, RawCacheConfig,
        RendererConfig)
    from omero_ms_image_region_tpu.services.cache import CacheConfig
    from omero_ms_image_region_tpu.utils import telemetry

    t_start = time.perf_counter()
    rng = np.random.default_rng(11)
    grid, edge, channels = 2, 256, 2
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(rng, 2, 1, 512, 512).reshape(
            2, 1, 512, 512)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        warm_dir = os.path.join(tmp, "warm-state")

        def mkconfig():
            return AppConfig(
                data_dir=tmp,
                # sync disk writes: the gate must judge durability, not
                # race the write-behind queue.
                caches=CacheConfig.enabled_all(disk_sync_writes=True),
                batcher=BatcherConfig(enabled=True, linger_ms=2.0),
                raw_cache=RawCacheConfig(enabled=True, prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0),
                persistence=PersistenceConfig(
                    enabled=True, dir=warm_dir,
                    snapshot_interval_s=0))   # snapshot explicitly

        def url(i):
            x, y = i % grid, (i // grid) % grid
            chans = ",".join(f"{c + 1}|0:{60000 - 5000 * c}$FF0000"
                             for c in range(channels))
            return (f"/webgateway/render_image_region/1/0/0"
                    f"?tile=0,{x},{y},{edge},{edge}"
                    f"&format=png&m=c&c={chans}")

        out = asyncio.run(_restart_run(mkconfig, url, grid * grid))

        # Golden check via the jax-free refimpl path: the rehydrated
        # bytes must equal what the reference renderer produces for
        # the identical request — a poisoned or stale disk entry
        # cannot pass this.
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        from omero_ms_image_region_tpu.server.degraded import (
            DegradedCpuHandler)
        chans = ",".join(f"{c + 1}|0:{60000 - 5000 * c}$FF0000"
                         for c in range(channels))
        ctx = ImageRegionCtx.from_params({
            "imageId": "1", "theZ": "0", "theT": "0",
            "tile": f"0,0,0,{edge},{edge}", "format": "png",
            "m": "c", "c": chans}, None)
        golden = asyncio.run(
            DegradedCpuHandler(mkconfig()).render_image_region(ctx))
        out["restart_first_tile_identical"] = bool(
            out.pop("_first_body") == golden
            and out["restart_bytes_identical"])

    out.update({
        "metric": "restart_smoke",
        "unit": "invariants",
        "rehydrate_executables_loaded":
            telemetry.PERSIST.rehydrate_executables_loaded,
        "rehydrate_planes_restaged":
            telemetry.PERSIST.rehydrate_planes_restaged,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    })
    print(json.dumps(out))
    return out


async def _restart_run(mkconfig, url, working_set: int):
    import asyncio
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import (SERVICES_KEY,
                                                      create_app)
    from omero_ms_image_region_tpu.utils import telemetry

    # ---- life 1: render the working set, persist, "die".
    app = create_app(mkconfig())
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        bodies = []
        for i in range(working_set):
            r = await client.get(url(i))
            body = await r.read()
            assert r.status == 200, f"life-1 render failed: {r.status}"
            bodies.append(body)
        services = app[SERVICES_KEY]
        exec_cache = services.renderer.exec_cache
        if exec_cache is not None:
            # The background executable captures must land before the
            # "crash" — a real deployment has its whole life for this;
            # the smoke has seconds.
            await asyncio.to_thread(exec_cache.drain, 30.0)
        snapshot_path = await asyncio.to_thread(
            services.warmstate.snapshot_now)
        assert snapshot_path and os.path.exists(snapshot_path)
    finally:
        await client.close()

    # ---- life 2: fresh stack over the same persistence dir.
    compiles_before = telemetry.COMPILE.events
    t_boot = time.perf_counter()
    app = create_app(mkconfig())
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        # The rehydrator is background + best-effort; the gate waits
        # for it so the assertions below judge the REHYDRATED state.
        for _ in range(200):
            if (not telemetry.PERSIST.rehydrate_running
                    and telemetry.PERSIST.rehydrate_items_total):
                break
            await asyncio.sleep(0.05)
        renderer = app[SERVICES_KEY].renderer
        first_ms = None
        identical = True
        warm_hits = 0
        for i in range(working_set):
            d0 = renderer.batches_dispatched
            t0 = time.perf_counter()
            r = await client.get(url(i))
            body = await r.read()
            if first_ms is None:
                first_ms = (time.perf_counter() - t_boot) * 1000.0
            assert r.status == 200, f"restart render failed: {r.status}"
            if body != bodies[i]:
                identical = False
            if renderer.batches_dispatched == d0:
                warm_hits += 1
        return {
            "value": working_set,
            "restart_time_to_first_tile_ms": round(first_ms, 1),
            "restart_warm_hit_rate": round(warm_hits / working_set, 3),
            "restart_bytes_identical": identical,
            "restart_compile_events": (telemetry.COMPILE.events
                                       - compiles_before),
            "_first_body": bodies[0],
        }
    finally:
        await client.close()


def bench_offload_smoke(grid: int = 3, edge: int = 128,
                        variants: int = 2):
    """Repeat-viewer offload gate (``bench.py --smoke --offload``):
    the edge ladder end to end over a REAL 2-sidecar remote fleet —
    cold render, warm-local byte hit, warm-peer byte fetch (the owner
    drains; its successor serves the owner's bytes over
    ``byte_probe``/``byte_fetch`` instead of re-rendering), and
    If-None-Match -> 304 revalidation.

    Reported keys (one JSON line, like the other smoke gates):

    * ``origin_offload_ratio`` — fraction of the repeat-viewer mix
      served with ZERO device render work (acceptance: >= 0.8);
    * ``p50_304_ms`` — revalidation latency (acceptance: at least 10x
      below ``p50_service_tile_ms``, the cold render p50 measured in
      the same run);
    * ``peer_hit_rate`` — fraction of the re-routed working set served
      from the draining owner's byte tier, byte-identical to the
      origin render.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, FleetConfig, RawCacheConfig,
        RendererConfig, SidecarConfig)
    from omero_ms_image_region_tpu.services.cache import CacheConfig

    t_start = time.perf_counter()
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(
            rng, 2, 1, grid * edge, grid * edge).reshape(
            2, 1, grid * edge, grid * edge)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        socks = [os.path.join(tmp, f"m{i}.sock") for i in range(2)]

        def member_cfg():
            # Each sidecar owns its OWN byte-cache chain (memory LRU
            # per process-alike stack): the peer tier is real, not an
            # artifact of a shared cache.
            return AppConfig(
                data_dir=tmp,
                caches=CacheConfig.enabled_all(),
                batcher=BatcherConfig(enabled=False),
                raw_cache=RawCacheConfig(enabled=True, prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0))

        frontend_cfg = AppConfig(
            data_dir=tmp,
            sidecar=SidecarConfig(role="frontend"),
            fleet=FleetConfig(enabled=True, sockets=tuple(socks)))

        params = []
        for v in range(variants):
            w = 30000 + v * 900
            for x in range(grid):
                for y in range(grid):
                    params.append({
                        "imageId": "1", "theZ": "0", "theT": "0",
                        "tile": f"0,{x},{y},{edge},{edge}",
                        "format": "png", "m": "c",
                        "c": f"1|0:{w}$FF0000,2|0:{w - 700}$00FF00",
                    })

        def url_of(p):
            q = "&".join(f"{k}={p[k]}" for k in
                         ("tile", "format", "m", "c"))
            return (f"/webgateway/render_image_region/"
                    f"{p['imageId']}/{p['theZ']}/{p['theT']}?{q}")

        out = asyncio.run(_offload_run(member_cfg, frontend_cfg,
                                       socks, params, url_of))

    out.update({
        "metric": "offload_smoke",
        "unit": "invariants",
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    })
    print(json.dumps(out))
    return out


async def _offload_run(member_cfg, frontend_cfg, socks, params,
                       url_of):
    import asyncio
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import (FLEET_ROUTER_KEY,
                                                      create_app)
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.sidecar import run_sidecar
    from omero_ms_image_region_tpu.utils import telemetry
    from omero_ms_image_region_tpu.utils.stopwatch import \
        REGISTRY as SPANS

    def render_spans() -> int:
        snap = SPANS.snapshot()
        return (snap.get("Renderer.renderAsPackedInt",
                         {}).get("count", 0)
                + snap.get("Renderer.renderAsPackedInt.cpu",
                           {}).get("count", 0))

    sidecars = [asyncio.create_task(run_sidecar(member_cfg(), sock))
                for sock in socks]
    for sock in socks:
        for _ in range(400):
            for task in sidecars:
                if task.done():
                    task.result()     # surface an early death
            if os.path.exists(sock):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"sidecar socket {sock} missing")

    app = create_app(frontend_cfg)
    client = TestClient(TestServer(app))
    await client.start_server()
    router = app[FLEET_ROUTER_KEY]
    try:
        urls = [url_of(p) for p in params]
        ctxs = [ImageRegionCtx.from_params(dict(p), None)
                for p in params]

        # ---- cold: every tile renders once on its ring owner.
        bodies, etags, cold_ms = {}, {}, []
        for u in urls:
            t0 = time.perf_counter()
            r = await client.get(u)
            body = await r.read()
            cold_ms.append((time.perf_counter() - t0) * 1000.0)
            assert r.status == 200, f"cold render failed: {r.status}"
            etags[u] = r.headers.get("ETag")
            assert etags[u], "200 missing its ETag"
            bodies[u] = body
        renders_cold = render_spans()
        assert renders_cold > 0, "cold leg rendered nothing"

        warm_total = 0
        # ---- warm-local: straight repeats hit the owner's byte tier.
        for u in urls:
            r = await client.get(u)
            body = await r.read()
            assert r.status == 200 and body == bodies[u]
            warm_total += 1

        # ---- 304: revalidation with the cold leg's ETags.
        t304 = []
        for u in urls:
            t0 = time.perf_counter()
            r = await client.get(
                u, headers={"If-None-Match": etags[u]})
            await r.read()
            t304.append((time.perf_counter() - t0) * 1000.0)
            assert r.status == 304, f"expected 304, got {r.status}"
            assert r.headers.get("ETag") == etags[u]
            warm_total += 1

        # ---- warm-peer: drain one member; its shard re-routes to
        # the survivor, which must serve the DRAINING owner's bytes
        # over byte_probe/byte_fetch — zero re-renders.
        owners = {u: router.owner_of(ctx)
                  for u, ctx in zip(urls, ctxs)}
        victim = next(name for name in router.order
                      if any(o == name for o in owners.values()))
        owned = [u for u in urls if owners[u] == victim]
        await router.drain_member(victim, prestage=False,
                                  settle_timeout_s=5.0)
        fetches_before = telemetry.HTTPCACHE.peer_fetches
        for u in owned:
            r = await client.get(u)
            body = await r.read()
            assert r.status == 200, f"peer leg failed: {r.status}"
            assert body == bodies[u], \
                "peer bytes differ from the origin render"
            warm_total += 1
        peer_fetches = telemetry.HTTPCACHE.peer_fetches \
            - fetches_before
        router.undrain_member(victim)

        renders_warm = render_spans() - renders_cold
        offload = 1.0 - renders_warm / max(1, warm_total)
        return {
            "value": round(offload, 3),
            "origin_offload_ratio": round(offload, 3),
            "p50_service_tile_ms": round(
                float(np.median(cold_ms)), 2),
            "p50_304_ms": round(float(np.median(t304)), 3),
            "peer_hit_rate": round(
                peer_fetches / max(1, len(owned)), 3),
            "peer_working_set": len(owned),
            "warm_requests": warm_total,
            "warm_renders": renders_warm,
            "n_304": len(t304),
        }
    finally:
        await client.close()
        for task in sidecars:
            task.cancel()
        await asyncio.gather(*sidecars, return_exceptions=True)


def bench_chaos_smoke(duration_s: float = 1.5, seed: int = 1234,
                      artifacts_dir: str = None):
    """Robustness gate at smoke scale: the full frontend -> sidecar ->
    batcher chain under SEEDED fault injection (wire drops/truncations/
    delays, transient device errors, a freezing device lane), with
    deadlines + admission control + breaker armed.

    The invariants (tests/test_chaos_smoke.py wires this into tier-1):

    * **zero 5xx-without-shed** — every response is 200, 503 (shed,
      with ``Retry-After``) or 504 (deadline); a bare 500 means a
      fault leaked through the tolerance layer as a raw failure;
    * **bounded p99** — chaos-window latency stays under the request
      deadline plus scheduling slack (the deadline actually cuts
      tails, rather than work queueing toward a timeout);
    * the chaos actually happened (injected-fault counters are
      nonzero — a chaos run that injected nothing proves nothing) and
      the service still made progress (some 200s);
    * ``plane_put`` was never auto-retried;
    * the FORENSIC chain fired: the flight-recorder ring is non-empty
      after the chaos window, and the induced availability-SLO breach
      (the sidecar is killed at the end and requests shed) produced a
      black-box dump plus slow-request waterfalls.

    ``artifacts_dir`` keeps the dump/waterfall files after the run
    (tests round-trip them through scripts/trace_report.py); None
    spools them inside the run's tempdir.  Prints ONE JSON line, like
    the other smoke gate.
    """
    import asyncio
    import os
    import tempfile

    from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.server.config import (
        AppConfig, BatcherConfig, FaultToleranceConfig, RawCacheConfig,
        RendererConfig, SidecarConfig, SloConfig, TelemetryConfig)
    from omero_ms_image_region_tpu.utils import telemetry
    from omero_ms_image_region_tpu.utils.faultinject import (
        FaultInjectionConfig)

    DEADLINE_MS = 5000.0
    t_start = time.perf_counter()
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as tmp:
        art = artifacts_dir or os.path.join(tmp, "artifacts")
        planes = synthetic_wsi_tiles(rng, 2, 1, 512, 512).reshape(
            2, 1, 512, 512)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        sock = os.path.join(tmp, "chaos.sock")
        sidecar_cfg = AppConfig(
            data_dir=tmp,
            batcher=BatcherConfig(enabled=True, linger_ms=2.0),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        frontend_cfg = AppConfig(
            data_dir=tmp,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            # Forensics under chaos: every request over 1 ms dumps its
            # waterfall, and an availability SLO tight enough that the
            # induced outage below must breach it (short windows keep
            # the smoke run fast; the burn math is scale-free).
            telemetry=TelemetryConfig(
                slow_request_ms=1.0,
                slow_request_dir=os.path.join(art, "slow"),
                flight_recorder_dir=os.path.join(art, "flight")),
            slo=SloConfig(availability_target=0.999,
                          fast_window_s=5.0, slow_window_s=10.0,
                          breach_burn_rate=5.0),
            fault_tolerance=FaultToleranceConfig(
                request_deadline_ms=DEADLINE_MS,
                retry_base_backoff_ms=10.0,
                retry_max_backoff_ms=100.0,
                # One injected connection death fails EVERY multiplexed
                # in-flight call at once, so consecutive-failure bursts
                # run 4-5 deep per fault; 8 keeps the breaker for real
                # outages rather than single chaos events.
                breaker_failure_threshold=8,
                breaker_reset_s=0.25,
                admission_max_queue=64))
        chaos = FaultInjectionConfig(
            seed=seed,
            wire_drop_rate=0.04,
            wire_truncate_rate=0.02,
            wire_delay_rate=0.05, wire_delay_ms=30.0,
            device_error_rate=0.08,
            freeze_rate=0.05, freeze_ms=100.0)
        retries_before = dict(telemetry.RESILIENCE.retries)
        try:
            out = asyncio.run(_chaos_run(sidecar_cfg, frontend_cfg,
                                         sock, chaos, duration_s,
                                         DEADLINE_MS))
        finally:
            # The chaos SLO posture must not leak into whatever this
            # process runs next (tier-1 shares the interpreter).
            telemetry.SLO.reset()
        # Diff against the pre-run counters: the gate must judge THIS
        # window, not retries other tests in the process accumulated.
        retried_ops = {
            op for op, n in telemetry.RESILIENCE.retries.items()
            if n > retries_before.get(op, 0)}
        slow_dir = os.path.join(art, "slow")
        out.update({
            "metric": "chaos_smoke",
            "unit": "invariants",
            "deadline_ms": DEADLINE_MS,
            "plane_put_retried": "plane_put" in retried_ops,
            "retried_ops": sorted(retried_ops),
            "slow_dumps": (len(os.listdir(slow_dir))
                           if os.path.isdir(slow_dir) else 0),
            "elapsed_s": round(time.perf_counter() - t_start, 1),
        })
    print(json.dumps(out))
    return out


async def _chaos_run(sidecar_cfg, frontend_cfg, sock, chaos,
                     duration_s, deadline_ms):
    import asyncio
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.server.sidecar import run_sidecar
    from omero_ms_image_region_tpu.utils import faultinject

    sidecar_task = asyncio.create_task(run_sidecar(sidecar_cfg, sock))
    for _ in range(600):
        if sidecar_task.done():
            raise AssertionError(
                f"chaos sidecar died at startup: "
                f"{sidecar_task.exception()!r}")
        if os.path.exists(sock):
            break
        await asyncio.sleep(0.05)
    app = create_app(frontend_cfg)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        grid, channels, edge = 2, 2, 256

        def url(i, k):
            x, y = i % grid, (i // grid) % grid
            w = 20000 + (k % 5000) * 9
            chans = ",".join(f"{c + 1}|0:{w - 1000 * c}$FF0000"
                             for c in range(channels))
            return (f"/webgateway/render_image_region/1/0/0"
                    f"?tile=0,{x},{y},{edge},{edge}"
                    f"&format=png&m=c&c={chans}")

        # Warm FIRST (compiles, byte-cache-miss path) with no chaos, so
        # the p99 bound below measures the tolerance layer, not XLA's
        # first-compile.
        resps = await asyncio.gather(
            *(client.get(url(i, i)) for i in range(grid * grid)))
        assert all(r.status == 200 for r in resps), \
            [r.status for r in resps]

        faultinject.install(chaos)
        statuses: list = []
        latencies_ms: list = []
        missing_retry_after = 0
        seq = 0
        t_stop = time.perf_counter() + duration_s

        async def worker(i: int) -> None:
            nonlocal seq, missing_retry_after
            while time.perf_counter() < t_stop:
                seq += 1
                t0 = time.perf_counter()
                r = await client.get(url(i, 16 + seq))
                await r.read()
                statuses.append(r.status)
                latencies_ms.append(
                    (time.perf_counter() - t0) * 1000.0)
                if r.status == 503 and "Retry-After" not in r.headers:
                    missing_retry_after += 1

        await asyncio.gather(*(worker(i) for i in range(4)))
        ok = sum(1 for s in statuses if s == 200)
        shed = sum(1 for s in statuses if s == 503)
        deadline_hit = sum(1 for s in statuses if s == 504)
        bare_5xx = sum(1 for s in statuses
                       if s >= 500 and s not in (503, 504))
        lat = sorted(latencies_ms)
        p99 = lat[max(0, int(len(lat) * 0.99) - 1)] if lat else 0.0
        inj = faultinject.active()
        injected = inj.snapshot() if inj is not None else {}
        # The black box must have been recording through the window
        # (batch formation, retries, breaker transitions) — a chaos
        # run whose flight ring is empty proves the recorder is dead.
        from omero_ms_image_region_tpu.utils import telemetry
        flight_events = len(telemetry.FLIGHT)

        # Induced SLO breach: kill the device backend and keep asking.
        # Every request now sheds (503 after the retry ladder, then
        # breaker-fast), availability burns through the tight budget in
        # both windows, and the breach transition must dump the flight
        # recorder — the acceptance-criteria forensic chain, end to
        # end, deterministic (no chaos dice involved).
        faultinject.uninstall()
        sidecar_task.cancel()
        try:
            await sidecar_task
        except (asyncio.CancelledError, Exception):
            pass
        try:
            os.unlink(sock)
        except OSError:
            pass
        outage_statuses = []
        for i in range(12):
            r = await client.get(url(i, 9000 + i))
            await r.read()
            outage_statuses.append(r.status)
        slo_breached = telemetry.SLO.any_breached()
        flight_dir = frontend_cfg.telemetry.flight_recorder_dir
        dumps = (sorted(os.listdir(flight_dir))
                 if os.path.isdir(flight_dir) else [])
        dump_events = 0
        if dumps:
            with open(os.path.join(flight_dir, dumps[-1])) as f:
                dump_events = len(json.load(f).get("events", ()))
        return {
            "injected": injected,
            "value": len(statuses),
            "ok": ok, "shed": shed, "deadline_hit": deadline_hit,
            "bare_5xx": bare_5xx,
            "missing_retry_after": missing_retry_after,
            "p99_ms": round(p99, 1),
            "zero_bare_5xx": bare_5xx == 0,
            "p99_bounded": p99 <= deadline_ms + 2000.0,
            "flight_events": flight_events,
            "outage_sheds": sum(1 for s in outage_statuses
                                if s in (503, 504)),
            "slo_breached": slo_breached,
            "flight_dumps": len(dumps),
            "flight_dump": (os.path.join(flight_dir, dumps[-1])
                            if dumps else None),
            "flight_dump_events": dump_events,
        }
    finally:
        await client.close()
        faultinject.uninstall()
        sidecar_task.cancel()
        try:
            await sidecar_task
        except (asyncio.CancelledError, Exception):
            pass


# -------------------------------------------------------------- config 1

def bench_config1(rng):
    """1-ch uint8 256^2 linear tile: single-tile renders/sec.

    Measures the path a DEFAULT deployment actually serves: 256^2 is at
    the tiny-render threshold (``RendererConfig.cpu_fallback_max_px``),
    so requests take the host reference kernel — the measured winner at
    this size on any deployment (device dispatch+fetch overhead exceeds
    the ~2 ms of host math).  The CPU comparator is the same kernel, so
    the served number equals the reference within noise by construction.
    """
    from omero_ms_image_region_tpu.refimpl import render_ref
    from omero_ms_image_region_tpu.server.config import RendererConfig

    rdef, s = _settings_for(1, ptype="uint8", window=(0.0, 255.0),
                            model="greyscale")
    raw = rng.integers(0, 255, size=(1, 256, 256)).astype(np.float32)

    assert 256 * 256 <= RendererConfig().cpu_fallback_max_px, \
        "default config no longer serves 256^2 via the CPU fallback"
    # Served path and comparator are the same kernel by construction;
    # one timing feeds both keys.
    t_served = _timed(lambda: render_ref(raw, rdef), repeats=10)
    return 1.0 / t_served, 1.0 / t_served


# -------------------------------------------------------------- config 2

def bench_config2(rng):
    """3-ch uint16 full planes (2048^2) -> JPEG bytes, streamed.

    ``render_image`` traffic is a stream of plane requests; the device
    pipeline (dispatch all, prefix-fetch + entropy-code in arrival
    order) hides the per-dispatch round trip exactly as the flagship
    tile path does.  A CPU comparator (reference renderer + PIL) runs on
    identical planes.
    """
    import jax

    from omero_ms_image_region_tpu.flagship import (
        batched_args, synthetic_wsi_tiles,
    )
    from omero_ms_image_region_tpu.ops.jpegenc import (
        SparseWireFetcher, default_sparse_cap, encode_sparse_buffers,
        quant_tables, render_to_jpeg_sparse,
    )
    from omero_ms_image_region_tpu.refimpl import render_ref

    import concurrent.futures as cf

    n_planes = 6
    rdef, s = _settings_for(3)
    planes = synthetic_wsi_tiles(rng, n_planes, 3, 2048, 2048)
    dev = [jax.device_put(p[None]) for p in planes]
    jax.block_until_ready(dev)
    args = batched_args(s, np.zeros((1, 3, 1, 1), np.float32))[1:]
    qy, qc = (t.astype(np.int32) for t in quant_tables(85))
    cap = default_sparse_cap(2048, 2048)
    fetcher = SparseWireFetcher(2048, 2048, cap)

    def stream(pool):
        # Dispatch every plane up-front (device pipelines), then hand each
        # finished wire buffer to the pool: plane k's entropy encode (C++,
        # GIL released) overlaps plane k+1's prefix fetch.
        handles = [
            fetcher.start(render_to_jpeg_sparse(p, *args, qy, qc, cap=cap))
            for p in dev
        ]
        futs = [
            pool.submit(encode_sparse_buffers,
                        fetcher.finish(h), 2048, 2048, 85, cap)
            for h in handles
        ]
        for f in futs:
            assert f.result()[0][:2] == b"\xff\xd8"

    with cf.ThreadPoolExecutor(max_workers=4) as pool:
        planes_per_sec = n_planes / _timed(lambda: stream(pool), repeats=3)

    # CPU comparator: reference render + PIL JPEG on one identical plane.
    def cpu_plane():
        _cpu_jpeg(render_ref(planes[0].astype(np.float32), rdef))

    cpu_planes_per_sec = 1.0 / _timed(cpu_plane, repeats=3)
    return planes_per_sec, cpu_planes_per_sec


# -------------------------------------------------------------- config 4

def bench_config4(rng):
    """intmax Z-projection over 32-plane 3-ch 512^2 stacks -> JPEG.

    Projection + render + JPEG front end fuse into one device dispatch
    per request; a stream of projection requests pipelines (dispatch all,
    prefix-fetch + encode in arrival order) so the link round trip is
    paid once, not per request.
    """
    import jax
    import jax.numpy as jnp

    from omero_ms_image_region_tpu.flagship import (
        batched_args, synthetic_wsi_tiles,
    )
    from omero_ms_image_region_tpu.models.rendering import Projection
    from omero_ms_image_region_tpu.ops.jpegenc import (
        SparseWireFetcher, default_sparse_cap, encode_sparse_buffers,
        quant_tables, render_to_jpeg_sparse,
    )
    from omero_ms_image_region_tpu.ops.projection import project_stack

    n_req = 6
    rdef, s = _settings_for(3)
    stacks = [jax.device_put(synthetic_wsi_tiles(rng, 3, 32, 512, 512))
              for _ in range(n_req)]          # [C=3, Z=32, H, W] each
    jax.block_until_ready(stacks)
    args = batched_args(s, np.zeros((1, 3, 1, 1), np.float32))[1:]
    qy, qc = (np.asarray(t, np.int32) for t in quant_tables(85))
    cap = default_sparse_cap(512, 512)
    fetcher = SparseWireFetcher(512, 512, cap)

    @jax.jit
    def project_render(stacks_):
        planes = jax.vmap(
            lambda st: project_stack(st, Projection.MAXIMUM_INTENSITY,
                                     0, 31, 1, 65535.0)
        )(stacks_.astype(jnp.float32))
        return render_to_jpeg_sparse(planes[None], *args, qy, qc, cap=cap)

    def stream():
        handles = [fetcher.start(project_render(st)) for st in stacks]
        for h in handles:
            jpegs = encode_sparse_buffers(
                fetcher.finish(h), 512, 512, 85, cap)
            assert jpegs[0][:2] == b"\xff\xd8"

    tpu_rate = n_req / _timed(stream, repeats=3)

    # CPU comparator: reference projection + render + PIL JPEG on one
    # identical stack.
    from omero_ms_image_region_tpu.refimpl import project_ref, render_ref

    host_stack = np.asarray(stacks[0], np.float32)   # [C, Z, H, W]

    def cpu_projection():
        planes = np.stack([
            project_ref(host_stack[c], Projection.MAXIMUM_INTENSITY,
                        0, 31, 1, 65535.0)
            for c in range(3)
        ])
        _cpu_jpeg(render_ref(planes, rdef))

    cpu_rate = 1.0 / _timed(cpu_projection, repeats=3)
    return tpu_rate, cpu_rate


# -------------------------------------------------------------- config 5

def bench_config4_stream(rng):
    """WSI-scale streamed Z-projection, 32-plane 1024^2 uint16 stack.

    Cold: banded host-side folds (``project_region_banded`` with
    ``placement="host"`` — the serving default for host sources: a
    projection is a reduction, so only the finished plane crosses the
    link), projections/s end to end; fresh bytes per rep.  Warm: the
    same banded fold over DEVICE-resident planes (the HBM raw-cache
    serving case — interactive re-projection after the stack is
    staged), with a per-rep on-device XOR so content differs every rep.
    """
    import jax.numpy as jnp

    from omero_ms_image_region_tpu.models.rendering import Projection
    from omero_ms_image_region_tpu.ops.projection import (
        project_region_banded)

    base = rng.integers(0, 60000, size=(32, 1024, 1024)).astype(np.uint16)

    def run_cold(stack):
        # placement="host" (the serving default for host sources): the
        # fold is a reduction, so only the projected plane crosses the
        # link — the old device-fold cold path uploaded all 64 MB.
        out = project_region_banded(
            lambda z, y0, h: stack[z, y0:y0 + h],
            Projection.MAXIMUM_INTENSITY, 32, 0, 31, 1, 65535.0,
            plane_shape=(1024, 1024), band_rows=256, z_chunk=8,
            placement="host")
        np.asarray(out.ravel()[:1])    # force the fold chain to land

    run_cold(base)                     # compile folds + stitch
    cold_times = []
    for rep in (1, 2):
        fresh = base ^ np.uint16(rep)
        t0 = time.perf_counter()
        run_cold(fresh)
        cold_times.append(time.perf_counter() - t0)

    staged = jnp.asarray(base)         # one upload; stays in HBM
    staged.block_until_ready()

    def run_warm(rep):
        stack = staged ^ jnp.uint16(rep)   # fresh content, zero upload
        # Device-resident source: one sliced [z, band, W] chunk per
        # fold dispatch (per-plane slicing would cost a dispatch per
        # plane — ~150 round trips through the tunnel).
        out = project_region_banded(
            None, Projection.MAXIMUM_INTENSITY, 32, 0, 31, 1, 65535.0,
            plane_shape=(1024, 1024), band_rows=512, z_chunk=32,
            get_chunk=lambda zs, y0, h:
                stack[zs[0]:zs[-1] + 1, y0:y0 + h],
            placement="device")
        np.asarray(out.ravel()[:1])

    run_warm(0)                        # compile the device-slice path
    warm_times = []
    for rep in (1, 2):
        t0 = time.perf_counter()
        run_warm(rep + 1)
        warm_times.append(time.perf_counter() - t0)
    return 1.0 / min(cold_times), 1.0 / min(warm_times)


def bench_config5(rng):
    """Batched mask rasterize + alpha overlay over rendered tiles."""
    from omero_ms_image_region_tpu.models.mask import Mask
    from omero_ms_image_region_tpu.ops.maskops import (
        overlay_masks_batch, unpack_mask_bits,
    )

    B, H, W = 16, 512, 512
    masks = [
        Mask(shape_id=i, width=W, height=H,
             bytes_=np.packbits(
                 rng.integers(0, 2, size=H * W).astype(np.uint8)).tobytes())
        for i in range(B)
    ]
    base = rng.integers(0, 255, size=(B, H, W, 4)).astype(np.uint8)
    fills = rng.integers(0, 255, size=(B, 4)).astype(np.uint8)

    def run():
        grids = np.stack([unpack_mask_bits(m.bytes_, W, H) for m in masks])
        overlay_masks_batch(base, grids, fills)

    def run_cpu():
        # Reference flavor: one mask at a time, PIL rasterize +
        # alpha_composite (the way the Java service's BufferedImage +
        # IndexColorModel path would overlay, ShapeMaskRequestHandler
        # .java:185-203) — the comparator BASELINE.json config 5 needs.
        from PIL import Image
        for m, tile, fill in zip(masks, base, fills):
            grid = unpack_mask_bits(m.bytes_, W, H)
            over = np.empty((H, W, 4), np.uint8)
            over[..., 0] = fill[0]
            over[..., 1] = fill[1]
            over[..., 2] = fill[2]
            over[..., 3] = grid * fill[3]
            Image.alpha_composite(Image.fromarray(tile, "RGBA"),
                                  Image.fromarray(over, "RGBA"))

    return B / _timed(run, repeats=3), B / _timed(run_cpu, repeats=3)


def main():
    # --smoke: the CPU-fast hot-path gate (also a tier-1 test); no
    # device, no multi-minute windows, one JSON line.  --smoke --chaos
    # runs the same scale under seeded fault injection instead (the
    # robustness gate: zero bare 5xx, bounded p99); --smoke --restart
    # runs the cold-restart scenario (render, kill, restart with
    # persistence on — the warm-state gate).
    # --smoke --overload runs the brownout-ladder scenario (a 10x
    # burst must engage ladder steps in configured order, keep zero
    # 5xx-without-shed with bounded p99, and release with hysteresis).
    # --smoke --sessions runs the multi-user serving scenario (N
    # panning viewers + one hostile bulk client: per-session p99,
    # Jain's fairness index, predictive prefetch hit rate).
    # --smoke --offload runs the repeat-viewer offload scenario
    # (cold -> warm-local -> warm-peer -> 304 over a 2-sidecar fleet:
    # origin offload ratio, 304 latency, peer byte-fetch hit rate).
    # --smoke --capacity runs the open-loop capacity sweep (the
    # services.loadmodel arrival process against m1/m2/m4 fleets:
    # latency-vs-offered-load curve, capacity knee per size, and the
    # closed-vs-open honesty A/B) — the CAPACITY record family.
    # --smoke --hotkey runs the hot-plane replication drill (zipf
    # storm vs uniform mix, replication-disabled A/B, promotion →
    # staging → balanced reads → decay demotion) — the HOTKEY family.
    # --smoke --partition runs the netsplit chaos drill (3-process
    # fleet under load: partition → fence → heal → rejoin, plus a
    # mid-partition epoch roll) — the PARTITION record family.
    # --smoke --workloads runs the device-workloads drill (batched
    # device mask parity + timing, overlay vs refimpl golden, pyramid
    # job build, animation stream first-frame/cancel) — the WORKLOADS
    # record family.
    if "--smoke" in sys.argv[1:]:
        if "--chaos" in sys.argv[1:]:
            bench_chaos_smoke()
        elif "--restart" in sys.argv[1:]:
            bench_restart_smoke()
        elif "--overload" in sys.argv[1:]:
            bench_overload_smoke()
        elif "--sessions" in sys.argv[1:]:
            bench_sessions_smoke()
        elif "--offload" in sys.argv[1:]:
            bench_offload_smoke()
        elif "--capacity" in sys.argv[1:]:
            bench_capacity_smoke()
        elif "--workloads" in sys.argv[1:]:
            # Device workloads: batched mask parity + timing, overlay
            # vs refimpl golden, crash-safe pyramid build, animation
            # streaming — the WORKLOADS record family.
            bench_workloads_smoke()
        elif "--hotkey" in sys.argv[1:]:
            # Hot-plane replication: zipf storm vs uniform mix on a
            # 2-member fleet, replication-disabled A/B, promotion →
            # staging → balanced reads → decay demotion lifecycle —
            # the HOTKEY record family.
            bench_hotkey_smoke()
        elif "--federation" in sys.argv[1:]:
            # Multi-process federated fleet: manifest agreement
            # against a REAL spawned sidecar process, 1-vs-2-process
            # scaling, cross-host warm shard handoff over the wire —
            # the MULTICHIP family's multi-process keys.
            bench_federation_smoke()
        elif "--partition" in sys.argv[1:]:
            # Netsplit chaos drill: a 3-process fleet under sustained
            # load through partition -> fence -> heal -> rejoin with
            # a mid-partition two-phase epoch roll — the PARTITION
            # record family.
            bench_partition_smoke()
        elif "--sentinel" in sys.argv[1:]:
            # Induced-drift sentinel drill: deterministic latency
            # step on a virtual clock through a 2-member fleet ->
            # one confirmed drift -> one complete incident bundle ->
            # recovery clears the verdict.
            bench_sentinel_smoke()
        else:
            bench_smoke()
        return
    # Fresh entropy per run: the tunnel relay memoizes content-identical
    # transfers and dispatches, so a fixed seed would let repeat bench
    # runs serve cached uploads/replies and overstate the link.  The
    # content class (synthetic_wsi_tiles) is statistically identical
    # run to run, so vs_baseline stays comparable.
    import os as _os
    # Persistent compilation cache: repeat bench runs (and the driver's
    # end-of-round run) skip the 20-40 s first compiles per program.
    try:
        import jax
        jax.config.update(
            "jax_compilation_cache_dir",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          ".jax_cache"))
    except Exception:
        pass
    rng = np.random.default_rng(
        int.from_bytes(_os.urandom(8), "little"))

    # A dropped relay connection mid-compile surfaces as a transient
    # JaxRuntimeError and would otherwise zero out the whole round's
    # record; each section gets one retry on that class of failure.
    from omero_ms_image_region_tpu.utils.transient import retry_transient

    flag = retry_transient(lambda: bench_flagship(rng), "bench_flagship",
                           backoff_s=15.0)
    _WATERFALL_SPANS = (
        "batcher.queueWait", "batcher.groupTiles", "batcher.stage",
        "wire.fetch", "wire.fetch2", "jfif.encodeBatch",
        "Renderer.renderAsPackedInt.batch")
    try:
        # Fixed sampling policy: ALWAYS two windows, best-of-2 per
        # engine, regardless of where the first window lands.  The
        # tunnel's multi-second congestion windows can crater one
        # section while the rest of the run measures a healthy link;
        # best-of-2 rides that out.  Sampling the same way on every
        # run keeps the statistic comparable (a retry only-when-low
        # would be a one-sided filter that inflates the estimate).
        # EVERY window's tiles/s is reported (service_windows_*), so
        # the round-over-round trend carries its own spread.
        from omero_ms_image_region_tpu.utils.stopwatch import (
            REGISTRY as _SPAN_REG)
        _SPAN_REG.reset()
        windows = [bench_service_level(rng)[1]]
        try:
            windows.append(bench_service_level(rng)[1])
        except Exception:
            pass
        service_windows = {
            e: [round(w[e][0], 1) for w in windows if e in w]
            for e in ("sparse", "huffman")}
        service_engines = {e: max(v) for e, v in service_windows.items()
                           if v}
        service_tps = (max(service_engines.values())
                       if service_engines else None)
        # p50 request latency from the window that carried the headline
        # (closed-loop, 16-way concurrency — the number a user feels).
        service_p50_ms = None
        service_hot_path = {}
        if service_engines:
            best_eng = max(service_engines, key=service_engines.get)
            best_i = max(range(len(windows)),
                         key=lambda i: windows[i].get(best_eng,
                                                      (0.0, None))[0])
            service_p50_ms = windows[best_i][best_eng][1]
            # Dedup / plane-cache / pipeline-overlap probes from the
            # headline window (so the next BENCH round can falsify the
            # hot-path win mechanically).
            service_hot_path = windows[best_i][best_eng][2] or {}
        # The stage waterfall across the service windows: where a tile's
        # wall time goes between the HTTP socket and the JPEG bytes.
        service_waterfall = {
            k: v for k, v in _SPAN_REG.snapshot().items()
            if k in _WATERFALL_SPANS}
        # Link context for the service number: the huffman engine ships
        # ~90 KB/tile, so service tiles/s is bounded by fetch_rate/0.09
        # on congested windows — reporting the adjacent rate makes a
        # weather-bound result readable as such.
        try:
            from omero_ms_image_region_tpu.utils.linkprobe import \
                measure_fetch_mb_s
            service_fetch_mb_s = measure_fetch_mb_s(nbytes=2 << 20,
                                                    repeats=2)
        except Exception:
            service_fetch_mb_s = None
    except Exception:
        # App stack unavailable; library numbers stand.
        service_tps, service_engines = None, {}
        service_windows, service_waterfall = {}, {}
        service_p50_ms = None
        service_fetch_mb_s = None
        service_hot_path = {}
    c1_tpu, c1_cpu = retry_transient(
        lambda: bench_config1(rng), "bench_config1", backoff_s=15.0)
    c2_planes, c2_cpu = retry_transient(
        lambda: bench_config2(rng), "bench_config2", backoff_s=15.0)
    c4_projections, c4_cpu = retry_transient(
        lambda: bench_config4(rng), "bench_config4", backoff_s=15.0)
    c4_stream, c4_stream_warm = retry_transient(
        lambda: bench_config4_stream(rng), "bench_config4_stream",
        backoff_s=15.0)
    c5_masks, c5_cpu = retry_transient(
        lambda: bench_config5(rng), "bench_config5", backoff_s=15.0)

    print(json.dumps({
        "metric": "jpeg_tiles_per_sec_1024sq_4ch_u16",
        "value": round(flag["tiles_per_sec"], 2),
        "unit": "tiles/s",
        "vs_baseline": round(flag["tiles_per_sec"] / flag["cpu_tps"], 2),
        "jpeg_engine": flag["engine"],
        "sparse_tiles_per_sec": round(flag["sparse_tiles_per_sec"], 2),
        "huffman_tiles_per_sec": round(flag["huffman_tiles_per_sec"], 2),
        "cold_tiles_per_sec": round(flag["cold_tiles_per_sec"], 2),
        # RAW-bytes/s over the adjacent raw upload rate: ~1.0 = wire-
        # bound plain staging; >1.0 = the packed wire (io.staging)
        # is carrying the same planes in fewer bytes than raw.
        "cold_overlap_efficiency": round(
            flag["cold_overlap_efficiency"], 2),
        "p50_batch_ms": round(flag["p50_batch_ms"], 2),
        "p50_tile_ms": round(flag["p50_tile_ms"], 2),
        "p50_tile_ms_ex_rtt": round(flag["p50_tile_ms_ex_rtt"], 2),
        "p50_tile_ms_sparse": round(flag["p50_tile_ms_sparse"], 2),
        "p50_tile_ms_huffman": round(flag["p50_tile_ms_huffman"], 2),
        "tunnel_rtt_floor_ms": round(flag["rtt_floor_ms"], 2),
        "cpu_ref_tiles_per_sec": round(flag["cpu_tps"], 2),
        "raw_upload_mb_per_sec": round(flag["upload_mb_s"], 1),
        # None when every probe rep was swallowed by congestion noise.
        "sparse_exec_ms_batch": _opt_round(
            flag["sparse_exec_ms_batch"], 1),
        "huffman_exec_ms_batch": _opt_round(
            flag["huffman_exec_ms_batch"], 1),
        "device_ceiling_tiles_per_sec": _opt_round(
            flag["device_ceiling_tps"], 1),
        "device_ceiling_vs_baseline": _opt_round(
            flag["device_ceiling_tps"]
            and flag["device_ceiling_tps"] / flag["cpu_tps"], 2),
        # Config-3 pan through the FULL HTTP stack (16-way concurrency).
        "service_tiles_per_sec": _opt_round(service_tps, 1),
        "service_vs_baseline": _opt_round(
            service_tps and service_tps / flag["cpu_tps"], 2),
        "service_sparse_tiles_per_sec": _opt_round(
            service_engines.get("sparse"), 1),
        "service_huffman_tiles_per_sec": _opt_round(
            service_engines.get("huffman"), 1),
        # Every sampled window per engine (the spread behind the
        # best-of headline — congestion weather made visible).
        "service_windows_tiles_per_sec": service_windows,
        # Closed-loop p50 request latency at service concurrency (16
        # clients, batched — includes queue + group amortization), raw
        # and with the tunnel's RTT floor subtracted.  Recorded every
        # run so a serving-stack latency regression shows in the trend.
        "p50_service_tile_ms": _opt_round(service_p50_ms, 2),
        "p50_service_tile_ms_ex_rtt": _opt_round(
            service_p50_ms and max(
                0.0, service_p50_ms - flag["rtt_floor_ms"]), 2),
        # First BODY byte at the client (the progressive-wire
        # headline): with streaming + first-tile-out this lands a
        # batch-tail before request completion; watermark-gated in
        # scripts/bench_gate.py (direction: _ms regresses upward).
        "p50_first_tile_byte_ms": service_hot_path.get(
            "p50_first_tile_byte_ms"),
        # BASELINE.md's <50 ms target is INTERACTIVE tile latency
        # (single in-flight tile); pinned as a boolean so the r3-style
        # 68 ms regression class cannot pass silently.
        "p50_ex_rtt_target_met": bool(
            flag["p50_tile_ms_ex_rtt"] < 50.0),
        # Hot-path probes from the headline window: single-flight
        # coalescing of a concurrent-identical burst, byte-cache warm
        # repeat (no device span), content-digest staging skips, and
        # device-execute coverage of the wall clock (1.0 = the device
        # never idled behind the fetch/stage half).
        "service_dedup_hit_rate": service_hot_path.get(
            "dedup_hit_rate"),
        "service_warm_repeat_cached": service_hot_path.get(
            "warm_repeat_cached"),
        "service_overlap_efficiency": service_hot_path.get(
            "overlap_efficiency"),
        "service_planecache_hits": service_hot_path.get(
            "planecache_hits"),
        "service_planecache_misses": service_hot_path.get(
            "planecache_misses"),
        # Stage waterfall over the service windows (span -> count,
        # mean, p50 ms): queue wait, device batch, wire fetch (+second
        # fetches), host entropy/framing.
        "service_waterfall": service_waterfall,
        # Wire-transport accounting across the run (frames per
        # vectored flush, shm-ring hit rate): populated when the
        # serving posture actually crosses the sidecar wire; the
        # combined-mode windows report null rather than a fake 1.0.
        "wire_frames_per_flush": _opt_round(
            telemetry_wire_frames_per_flush(), 3),
        "shm_ring_hit_rate": _opt_round(
            telemetry_wire_ring_hit_rate(), 3),
        # Device->host rate adjacent to the service windows: on
        # congested links service tiles/s ~= this / 0.09 MB-per-tile
        # (huffman wire), i.e. the wire, not the stack, is the bound.
        "service_window_fetch_mb_per_sec": _opt_round(
            service_fetch_mb_s, 1),
        "batch": 8,
        "config1_tile256_u8_per_sec": round(c1_tpu, 2),
        "config1_cpu_ref_per_sec": round(c1_cpu, 2),
        "config2_fullplane_2048_3ch_per_sec": round(c2_planes, 2),
        "config2_cpu_ref_per_sec": round(c2_cpu, 2),
        "config4_zproj32_3ch_512_per_sec": round(c4_projections, 2),
        "config4_stream_zproj32_1024_per_sec": round(c4_stream, 2),
        "config4_stream_zproj32_1024_warm_per_sec": round(
            c4_stream_warm, 2),
        "config4_cpu_ref_per_sec": round(c4_cpu, 2),
        "config5_mask_overlay_512_per_sec": round(c5_masks, 2),
        "config5_cpu_ref_per_sec": round(c5_cpu, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
