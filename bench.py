"""Benchmark harness: the 5 BASELINE.md configs, TPU vs CPU reference.

The reference publishes no numbers (BASELINE.md), so the baseline is our own
faithful CPU implementation of the Java ``Renderer`` semantics
(``omero_ms_image_region_tpu.refimpl``) run on the same workload.

Headline metric (BASELINE.json): tiles/sec on 4-channel uint16 1024x1024
tiles (config 3, batched deep-zoom pan).  ``vs_baseline`` = TPU tiles/sec
divided by CPU-reference tiles/sec on identical tiles.  The other four
configs report as extras in the same JSON line.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np


def _settings_for(C, ptype="uint16", window=(100.0, 40000.0), model="rgb"):
    from omero_ms_image_region_tpu.flagship import FLAGSHIP_COLORS
    from omero_ms_image_region_tpu.models.pixels import Pixels
    from omero_ms_image_region_tpu.models.rendering import (
        RenderingModel, default_rendering_def,
    )
    from omero_ms_image_region_tpu.ops.render import pack_settings

    pixels = Pixels(image_id=1, pixels_type=ptype, size_x=8192, size_y=8192,
                    size_c=C)
    rdef = default_rendering_def(pixels)
    rdef.model = (RenderingModel.RGB if model == "rgb"
                  else RenderingModel.GREYSCALE)
    for i, cb in enumerate(rdef.channel_bindings):
        cb.active = True
        cb.red, cb.green, cb.blue = FLAGSHIP_COLORS[i % len(FLAGSHIP_COLORS)]
        cb.input_start, cb.input_end = window
    return rdef, pack_settings(rdef)


def _timed(fn, *args, repeats=3, warmup=True):
    """Best-of-N wall time for fn(*args) with one warm-up call."""
    if warmup:
        fn(*args)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn(*args)
        times.append(time.perf_counter() - t0)
    return min(times)


# ----------------------------------------------------------- config 3 (HEAD)

def bench_flagship(rng):
    """4-ch uint16 1024^2 batched pan: tiles/sec TPU vs CPU ref + p50."""
    from omero_ms_image_region_tpu.flagship import (
        batched_args, flagship_settings,
    )
    from omero_ms_image_region_tpu.ops.render import (
        render_tile_batch_packed, unpack_rgba,
    )
    from omero_ms_image_region_tpu.refimpl import render_ref

    rdef, settings = flagship_settings()
    B, C, H, W = 8, 4, 1024, 1024
    n_batches = 4
    raw_batches = [
        rng.integers(0, 65535, size=(B, C, H, W)).astype(np.float32)
        for _ in range(n_batches)
    ]
    args_suffix = batched_args(settings, raw_batches[0])[1:]
    np.asarray(render_tile_batch_packed(raw_batches[0], *args_suffix))

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [render_tile_batch_packed(raw, *args_suffix)
                for raw in raw_batches]
        for o in outs:
            unpack_rgba(np.asarray(o))  # sync + fetch + host RGBA view
        times.append(time.perf_counter() - t0)
    tiles_per_sec = (B * n_batches) / min(times)

    lat = []
    for raw in raw_batches * 2:
        t0 = time.perf_counter()
        np.asarray(render_tile_batch_packed(raw, *args_suffix))
        lat.append((time.perf_counter() - t0) * 1000.0)
    p50_batch_ms = statistics.median(lat)

    # CPU reference on identical tiles (>=1 tile, capped wall time).
    n, t0 = 0, time.perf_counter()
    while True:
        render_ref(raw_batches[0][n % B], rdef)
        n += 1
        dt = time.perf_counter() - t0
        if dt > 15.0 or n >= 32:
            break
    cpu_tps = n / dt
    return tiles_per_sec, p50_batch_ms, cpu_tps


# -------------------------------------------------------------- config 1

def bench_config1(rng):
    """1-ch uint8 256^2 linear tile: single-tile renders/sec, both paths."""
    from omero_ms_image_region_tpu.ops.render import render_tile_packed
    from omero_ms_image_region_tpu.refimpl import render_ref

    rdef, s = _settings_for(1, ptype="uint8", window=(0.0, 255.0),
                            model="greyscale")
    raw = rng.integers(0, 255, size=(1, 256, 256)).astype(np.float32)

    def tpu():
        np.asarray(render_tile_packed(
            raw, s["window_start"], s["window_end"], s["family"],
            s["coefficient"], s["reverse"], s["cd_start"], s["cd_end"],
            s["tables"]))

    t_tpu = _timed(tpu, repeats=20)
    t_cpu = _timed(lambda: render_ref(raw, rdef), repeats=5)
    return 1.0 / t_tpu, 1.0 / t_cpu


# -------------------------------------------------------------- config 2

def bench_config2(rng):
    """3-ch uint16 full plane (2048^2) window+color composite."""
    from omero_ms_image_region_tpu.ops.render import render_tile_packed

    _, s = _settings_for(3)
    raw = rng.integers(0, 65535, size=(3, 2048, 2048)).astype(np.float32)

    def tpu():
        np.asarray(render_tile_packed(
            raw, s["window_start"], s["window_end"], s["family"],
            s["coefficient"], s["reverse"], s["cd_start"], s["cd_end"],
            s["tables"]))

    return 1.0 / _timed(tpu, repeats=5)


# -------------------------------------------------------------- config 4

def bench_config4(rng):
    """intmax Z-projection over a 32-plane 3-ch 512^2 stack + render."""
    from omero_ms_image_region_tpu.models.rendering import Projection
    from omero_ms_image_region_tpu.ops.projection import project_stack
    from omero_ms_image_region_tpu.ops.render import render_tile_packed

    _, s = _settings_for(3)
    stacks = rng.integers(0, 65535, size=(3, 32, 512, 512)).astype(
        np.float32)

    def run():
        planes = [project_stack(stacks[c], Projection.MAXIMUM_INTENSITY,
                                0, 31, 1, 65535.0) for c in range(3)]
        raw = np.stack([np.asarray(p) for p in planes])
        np.asarray(render_tile_packed(
            raw, s["window_start"], s["window_end"], s["family"],
            s["coefficient"], s["reverse"], s["cd_start"], s["cd_end"],
            s["tables"]))

    return 1.0 / _timed(run, repeats=5)


# -------------------------------------------------------------- config 5

def bench_config5(rng):
    """Batched mask rasterize + alpha overlay over rendered tiles."""
    from omero_ms_image_region_tpu.models.mask import Mask
    from omero_ms_image_region_tpu.ops.maskops import (
        overlay_masks_batch, unpack_mask_bits,
    )

    B, H, W = 16, 512, 512
    masks = [
        Mask(shape_id=i, width=W, height=H,
             bytes_=np.packbits(
                 rng.integers(0, 2, size=H * W).astype(np.uint8)).tobytes())
        for i in range(B)
    ]
    base = rng.integers(0, 255, size=(B, H, W, 4)).astype(np.uint8)
    fills = rng.integers(0, 255, size=(B, 4)).astype(np.uint8)

    def run():
        grids = np.stack([unpack_mask_bits(m.bytes_, W, H) for m in masks])
        overlay_masks_batch(base, grids, fills)

    return B / _timed(run, repeats=3)


def main():
    rng = np.random.default_rng(7)

    tiles_per_sec, p50_batch_ms, cpu_tps = bench_flagship(rng)
    c1_tpu, c1_cpu = bench_config1(rng)
    c2_planes = bench_config2(rng)
    c4_projections = bench_config4(rng)
    c5_masks = bench_config5(rng)

    print(json.dumps({
        "metric": "render_tiles_per_sec_1024sq_4ch_u16",
        "value": round(tiles_per_sec, 2),
        "unit": "tiles/s",
        "vs_baseline": round(tiles_per_sec / cpu_tps, 2),
        "p50_batch_ms": round(p50_batch_ms, 2),
        "cpu_ref_tiles_per_sec": round(cpu_tps, 2),
        "batch": 8,
        "config1_tile256_u8_per_sec": round(c1_tpu, 2),
        "config1_cpu_ref_per_sec": round(c1_cpu, 2),
        "config2_fullplane_2048_3ch_per_sec": round(c2_planes, 2),
        "config4_zproj32_3ch_512_per_sec": round(c4_projections, 2),
        "config5_mask_overlay_512_per_sec": round(c5_masks, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
