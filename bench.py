"""Benchmark harness: the 5 BASELINE.md configs, TPU vs CPU reference.

The reference publishes no numbers (BASELINE.md), so the baseline is our own
faithful CPU implementation of the Java ``Renderer`` semantics
(``omero_ms_image_region_tpu.refimpl``) run on the same workload.

Headline metric (BASELINE.json): tiles/sec on 4-channel uint16 1024x1024
tiles (config 3, batched deep-zoom pan).  ``vs_baseline`` = TPU tiles/sec
divided by CPU-reference tiles/sec on identical tiles.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np


def bench_tpu(raw_batches, settings, repeats=3):
    """End-to-end device tiles/sec: host->HBM, render, RGBA->host."""
    from omero_ms_image_region_tpu.flagship import batched_args
    from omero_ms_image_region_tpu.ops.render import (
        render_tile_batch_packed, unpack_rgba,
    )

    args_suffix = batched_args(settings, raw_batches[0])[1:]
    # Warm-up / compile.
    out = render_tile_batch_packed(raw_batches[0], *args_suffix)
    np.asarray(out)

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = [render_tile_batch_packed(raw, *args_suffix)
                for raw in raw_batches]
        for o in outs:
            unpack_rgba(np.asarray(o))  # sync + fetch + host RGBA view
        times.append(time.perf_counter() - t0)
    total_tiles = sum(r.shape[0] for r in raw_batches)
    best = min(times)
    # p50 per-batch dispatch latency.
    lat = []
    for raw in raw_batches * 2:
        t0 = time.perf_counter()
        np.asarray(render_tile_batch_packed(raw, *args_suffix))
        lat.append((time.perf_counter() - t0) * 1000.0)
    return total_tiles / best, statistics.median(lat)


def bench_cpu_ref(raw, rdef, max_seconds=20.0):
    """CPU-reference tiles/sec on identical tiles (>=1 rendered)."""
    from omero_ms_image_region_tpu.refimpl import render_ref

    n, t0 = 0, time.perf_counter()
    while True:
        render_ref(raw[n % raw.shape[0]], rdef)
        n += 1
        dt = time.perf_counter() - t0
        if dt > max_seconds or n >= 32:
            return n / dt


def main():
    from omero_ms_image_region_tpu.flagship import flagship_settings

    rdef, settings = flagship_settings()
    rng = np.random.default_rng(7)
    B, C, H, W = 8, 4, 1024, 1024
    n_batches = 4
    raw_batches = [
        rng.integers(0, 65535, size=(B, C, H, W)).astype(np.float32)
        for _ in range(n_batches)
    ]

    tiles_per_sec, p50_ms = bench_tpu(raw_batches, settings)
    cpu_tps = bench_cpu_ref(raw_batches[0], rdef)

    print(json.dumps({
        "metric": "render_tiles_per_sec_1024sq_4ch_u16",
        "value": round(tiles_per_sec, 2),
        "unit": "tiles/s",
        "vs_baseline": round(tiles_per_sec / cpu_tps, 2),
        "p50_batch_ms": round(p50_ms, 2),
        "cpu_ref_tiles_per_sec": round(cpu_tps, 2),
        "batch": B,
    }))


if __name__ == "__main__":
    sys.exit(main())
