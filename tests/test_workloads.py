"""Device workloads plane (PR 20): batched device mask rasterization
pinned byte-identical to the host path, the overlay composite against
the refimpl golden, crash-safe pyramid jobs (kill/resume byte
stability, serving-path pickup, bulk-shed deferral), ordered animation
streaming with cancel-on-disconnect, z/t scrub prediction, and the
explain plane's answers for every new route."""

import asyncio
import os
import shutil

import numpy as np
import pytest

from omero_ms_image_region_tpu import codecs
from omero_ms_image_region_tpu.io.ngff import NgffZarrSource, find_ngff
from omero_ms_image_region_tpu.io.service import PixelsService
from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.ops.lut import LutProvider
from omero_ms_image_region_tpu.server.batcher import BatchingRenderer
from omero_ms_image_region_tpu.server.ctx import (
    BadRequestError, ImageRegionCtx, ShapeMaskCtx,
)
from omero_ms_image_region_tpu.server.handler import (
    ImageRegionHandler, ImageRegionServices, NotFoundError, Renderer,
    ShapeMaskHandler, WorkloadsHandler, frame_record,
)
from omero_ms_image_region_tpu.server.jobs import PyramidJobManager
from omero_ms_image_region_tpu.services.cache import CacheConfig, Caches
from omero_ms_image_region_tpu.services.metadata import (
    CanReadMemo, LocalMetadataService,
)
from omero_ms_image_region_tpu.utils import telemetry

IMG = 7
W = H = 64
Z = 4
MASK_IDS = (9001, 9002, 9003)
_FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "data", "masks")


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("workloads")
    rng = np.random.default_rng(20)
    planes = rng.integers(0, 60000, size=(2, Z, H, W)).astype(np.uint16)
    build_pyramid(planes, str(root / str(IMG)), chunk=(32, 32),
                  n_levels=2)
    os.makedirs(root / "masks", exist_ok=True)
    for name in os.listdir(_FIXTURES):
        shutil.copy(os.path.join(_FIXTURES, name),
                    root / "masks" / name)
    return str(root)


def _services(data_dir, renderer=None, pixels=None):
    return ImageRegionServices(
        pixels_service=pixels or PixelsService(data_dir),
        metadata=LocalMetadataService(data_dir),
        caches=Caches.from_config(CacheConfig.enabled_all()),
        can_read_memo=CanReadMemo(),
        renderer=renderer or Renderer(),
        lut_provider=LutProvider(),
        cpu_fallback_max_px=0,
    )


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _ctx(**params):
    base = {"imageId": str(IMG), "theZ": "0", "theT": "0",
            "format": "png"}
    base.update(params)
    return ImageRegionCtx.from_params(base)


def _mask_ctx(shape_id, **params):
    base = {"shapeId": str(shape_id)}
    base.update(params)
    return ShapeMaskCtx.from_params(base)


# ------------------------------------------------- mask byte identity

class TestMaskParity:
    def test_device_bytes_identical_to_host(self, data_dir):
        """Every committed fixture, every flip combination: the
        batched device rasterizer serves the EXACT bytes the host
        path serves (default stored fill — the uncached branch, so
        both passes really render)."""
        host_services = _services(data_dir)
        host = ShapeMaskHandler(host_services, device_masks=False)

        async def main():
            device_services = _services(data_dir,
                                        renderer=BatchingRenderer())
            handler = ShapeMaskHandler(device_services,
                                       device_masks=True)
            before = dict(telemetry.WORKLOADS.requests)
            try:
                for sid in MASK_IDS:
                    for fh in (False, True):
                        for fv in (False, True):
                            ctx = _mask_ctx(
                                sid,
                                flip=("hv" if fh and fv else
                                      "h" if fh else
                                      "v" if fv else None))
                            dev = await handler.render_shape_mask(ctx)
                            hst = await host.render_shape_mask(ctx)
                            assert dev == hst, (sid, fh, fv)
            finally:
                await device_services.renderer.close()
            delta = (telemetry.WORKLOADS.requests.get("mask_device", 0)
                     - before.get("mask_device", 0))
            assert delta == len(MASK_IDS) * 4

        run(main())

    def test_concurrent_masks_coalesce_and_match(self, data_dir):
        """Same-geometry masks submitted together ride one batched
        dispatch; each comes back as ITS OWN bytes."""
        host_services = _services(data_dir)
        host = ShapeMaskHandler(host_services, device_masks=False)

        async def main():
            device_services = _services(data_dir,
                                        renderer=BatchingRenderer(
                                            linger_ms=5.0))
            handler = ShapeMaskHandler(device_services,
                                       device_masks=True)
            try:
                ctxs = [_mask_ctx(sid) for sid in MASK_IDS]
                dev = await asyncio.gather(
                    *[handler.render_shape_mask(c) for c in ctxs])
                hst = [await host.render_shape_mask(c) for c in ctxs]
                assert dev == hst
                assert len(set(dev)) == len(MASK_IDS)
            finally:
                await device_services.renderer.close()

        run(main())

    def test_plain_renderer_falls_back_to_host(self, data_dir):
        """device_masks=True with a renderer that has no batched mask
        path (plain Renderer) silently serves the host rasterizer —
        no error, counted as a host render."""
        services = _services(data_dir)
        handler = ShapeMaskHandler(services, device_masks=True)
        before = telemetry.WORKLOADS.requests.get("mask_host", 0)
        png = run(handler.render_shape_mask(_mask_ctx(9001)))
        rgba = codecs.decode_to_rgba(png)
        assert rgba.shape == (H, W, 4)
        assert telemetry.WORKLOADS.requests.get("mask_host", 0) == \
            before + 1


# ------------------------------------------------- overlay composites

class TestOverlay:
    def _refimpl(self, services, image_handler, ctx, shape_ids,
                 color=None):
        """The golden: host rasterize + the exact
        ``overlay_masks_batch`` integer blend + the shared PNG tail."""
        from omero_ms_image_region_tpu.ops.maskops import (
            overlay_masks_batch, rasterize_mask,
        )
        from omero_ms_image_region_tpu.utils.color import \
            split_html_color

        async def main():
            base_png = await image_handler.render_image_region(ctx)
            base = codecs.decode_to_rgba(base_png)
            override = (split_html_color(color)
                        if color is not None else None)
            out = base
            for sid in shape_ids:
                mask = await services.metadata.get_mask(sid, None)
                grid, _ = rasterize_mask(mask, override,
                                         ctx.flip_horizontal,
                                         ctx.flip_vertical)
                fill = np.array([mask.resolved_fill_color(override)],
                                dtype=np.uint8)
                out = overlay_masks_batch(out[None], grid[None],
                                          fill)[0]
            return codecs.encode_rgba(out, "png")

        return run(main())

    def test_overlay_matches_refimpl_golden(self, data_dir):
        services = _services(data_dir)
        image_handler = ImageRegionHandler(services)
        workloads = WorkloadsHandler(image_handler, services)
        ctx = _ctx(region=f"0,0,{W},{H}")
        got = run(workloads.render_overlay(ctx, list(MASK_IDS)))
        want = self._refimpl(services, image_handler, ctx,
                             list(MASK_IDS))
        assert got == want

    def test_overlay_color_override_matches_refimpl(self, data_dir):
        services = _services(data_dir)
        image_handler = ImageRegionHandler(services)
        workloads = WorkloadsHandler(image_handler, services)
        ctx = _ctx(region=f"0,0,{W},{H}")
        got = run(workloads.render_overlay(ctx, [9001],
                                           color="00FF00"))
        want = self._refimpl(services, image_handler, ctx, [9001],
                             color="00FF00")
        assert got == want
        # And the override genuinely changes the composite.
        plain = run(workloads.render_overlay(ctx, [9001]))
        assert got != plain

    def test_overlay_validation(self, data_dir):
        services = _services(data_dir)
        workloads = WorkloadsHandler(ImageRegionHandler(services),
                                     services)
        ctx = _ctx(region=f"0,0,{W},{H}")
        with pytest.raises(BadRequestError):
            run(workloads.render_overlay(ctx, []))
        with pytest.raises(NotFoundError):
            run(workloads.render_overlay(ctx, [4242]))
        with pytest.raises(BadRequestError):
            run(workloads.render_overlay(ctx, [9001],
                                         color="not-a-color"))
        # Region geometry must match the mask's plane.
        small = _ctx(region="0,0,32,32")
        with pytest.raises(BadRequestError):
            run(workloads.render_overlay(small, [9001]))


# ------------------------------------------------ downsample parity

class TestDownsampleParity:
    @pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32"])
    def test_device_downsample_matches_host(self, dtype):
        """``ops.pyramid.downsample2_batch`` vs the store writers'
        host mean-pool: identical output for every storage dtype the
        pyramid path writes."""
        from omero_ms_image_region_tpu.io.store import _downsample2
        from omero_ms_image_region_tpu.ops.pyramid import \
            downsample2_batch

        rng = np.random.default_rng(4)
        planes = rng.integers(0, 255, size=(1, 2, 3, 64, 48)).astype(
            dtype)
        dev = downsample2_batch(planes)
        host = np.empty_like(dev)
        for t in range(1):
            for c in range(2):
                for z in range(3):
                    host[t, c, z] = _downsample2(
                        planes[t, c, z]).astype(dtype)
        np.testing.assert_array_equal(dev, host)


# ---------------------------------------------------- pyramid jobs

def _tree_bytes(root):
    out = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            p = os.path.join(dirpath, fn)
            with open(p, "rb") as f:
                out[os.path.relpath(p, root)] = f.read()
    return out


class TestPyramidJobs:
    def _planes(self):
        rng = np.random.default_rng(9)
        return rng.integers(0, 60000, size=(1, 1, 64, 64)).astype(
            np.uint16)

    def test_submit_missing_source_raises(self, tmp_path):
        jobs = PyramidJobManager()
        with pytest.raises(FileNotFoundError):
            jobs.submit(str(tmp_path / "nope"))

    def test_sync_build_commits_readable_levels(self, tmp_path):
        build_pyramid(self._planes(), str(tmp_path / "img"),
                      chunk=(32, 32), n_levels=1)
        jobs = PyramidJobManager(chunk=(32, 32), min_level_size=16)
        job = jobs.submit(str(tmp_path / "img"))
        jobs.run_job_sync(job)
        assert job.state == "done"
        assert job.levels_done == job.levels_total == 3  # 64, 32, 16
        root = find_ngff(str(tmp_path / "img"))
        assert root is not None
        reader = NgffZarrSource(root)
        try:
            assert reader.resolution_levels() == 3
        finally:
            reader.close()
        # Idempotent re-submit: a fresh build over a committed pyramid
        # resumes and leaves the bytes untouched.
        before = _tree_bytes(job.dest)
        job2 = PyramidJobManager(chunk=(32, 32),
                                 min_level_size=16).submit(
            str(tmp_path / "img"))
        PyramidJobManager(chunk=(32, 32),
                          min_level_size=16).run_job_sync(job2)
        assert job2.resumed is True
        assert _tree_bytes(job2.dest) == before

    def test_kill_resume_is_byte_stable(self, tmp_path):
        """A build killed mid-level resumes to EXACTLY the bytes an
        uninterrupted build writes — committed levels are skipped, tmp
        debris is cleared, the group markers land last."""
        planes = self._planes()
        build_pyramid(planes, str(tmp_path / "a"), chunk=(32, 32),
                      n_levels=1)
        build_pyramid(planes, str(tmp_path / "b"), chunk=(32, 32),
                      n_levels=1)

        ref_mgr = PyramidJobManager(chunk=(32, 32), min_level_size=16)
        ref = ref_mgr.submit(str(tmp_path / "a"))
        ref_mgr.run_job_sync(ref)

        # "Kill" after level 0: run the real prepare + first level
        # step, then abandon — no group markers, plus tmp debris the
        # next run must sweep.
        killed = PyramidJobManager(chunk=(32, 32), min_level_size=16)
        job = killed.submit(str(tmp_path / "b"))
        cur, n_levels = killed._prepare(job)
        killed._level_step(job, cur, 0, n_levels)
        debris = os.path.join(job.dest, ".lvl-1.tmp")
        os.makedirs(debris, exist_ok=True)
        with open(os.path.join(debris, "junk"), "w") as f:
            f.write("killed mid-write")
        assert find_ngff(str(tmp_path / "b")) is None  # invisible

        resumed_mgr = PyramidJobManager(chunk=(32, 32),
                                        min_level_size=16)
        job2 = resumed_mgr.submit(str(tmp_path / "b"))
        resumed_mgr.run_job_sync(job2)
        assert job2.resumed is True
        assert not os.path.exists(debris)
        assert _tree_bytes(job2.dest) == _tree_bytes(ref.dest)

    def test_serving_path_picks_up_committed_pyramid(self, tmp_path):
        """A TIFF-backed image gains NGFF levels through the job; the
        NORMAL serving path (PixelsService sniff + handler render)
        serves them with no special reader."""
        from omero_ms_image_region_tpu.io.tiffwrite import \
            write_ome_tiff

        planes = self._planes()
        img_dir = str(tmp_path / "8")
        os.makedirs(img_dir)
        write_ome_tiff(planes, os.path.join(img_dir, "img.ome.tiff"),
                       tile=(32, 32), n_levels=1)
        pixels = PixelsService(str(tmp_path))
        src = pixels.get_pixel_source(8)
        assert len(src.resolution_descriptions()) == 1

        jobs = PyramidJobManager(pixels_service=pixels,
                                 chunk=(32, 32), min_level_size=16)
        job = jobs.submit_image(8)
        jobs.run_job_sync(job)
        assert job.state == "done"

        # _commit invalidated the cached handle: the next open
        # re-sniffs and prefers the committed NGFF group.
        src = pixels.get_pixel_source(8)
        assert isinstance(src, NgffZarrSource)
        assert src.resolution_levels() == 3

        services = _services(str(tmp_path), pixels=pixels)
        handler = ImageRegionHandler(services)
        tile = run(handler.render_image_region(
            ImageRegionCtx.from_params({
                "imageId": "8", "theZ": "0", "theT": "0",
                "format": "png", "tile": "1,0,0,32,32"})))
        assert codecs.decode_to_rgba(tile).shape == (32, 32, 4)
        pixels.close()

    def test_bulk_shed_defers_then_resumes(self, tmp_path,
                                           monkeypatch):
        """While the pressure ladder's shed_bulk step is engaged the
        job parks in ``deferred`` between levels; release lets it
        finish.  Bulk never starves interactive."""
        from omero_ms_image_region_tpu.server import pressure

        class FakeGov:
            shedding = True

            def bulk_shed_active(self):
                return self.shedding

        gov = FakeGov()
        monkeypatch.setattr(pressure, "active", lambda: gov)
        build_pyramid(self._planes(), str(tmp_path / "img"),
                      chunk=(32, 32), n_levels=1)
        jobs = PyramidJobManager(chunk=(32, 32), min_level_size=16,
                                 defer_poll_s=0.01)
        job = jobs.submit(str(tmp_path / "img"))

        async def main():
            task = asyncio.ensure_future(jobs._execute(job))
            for _ in range(500):
                if job.state == "deferred":
                    break
                await asyncio.sleep(0.01)
            assert job.state == "deferred"
            gov.shedding = False
            await asyncio.wait_for(task, 30)

        run(main())
        assert job.state == "done"
        assert telemetry.WORKLOADS.jobs.get("deferred", 0) >= 1

    def test_cancel_mid_build(self, tmp_path):
        build_pyramid(self._planes(), str(tmp_path / "img"),
                      chunk=(32, 32), n_levels=1)
        jobs = PyramidJobManager(chunk=(32, 32), min_level_size=16)
        job = jobs.submit(str(tmp_path / "img"))
        assert jobs.cancel(job.job_id) is True
        run(jobs._execute(job))
        assert job.state == "cancelled"
        # Never committed: the serving path still sees no pyramid.
        assert find_ngff(str(tmp_path / "img")) is None

    def test_sidecar_answers_after_restart(self, tmp_path):
        """``job_for_source`` reads the on-disk sidecar when the
        in-memory ledger is gone — a restarted frontend still explains
        a previous process's build."""
        build_pyramid(self._planes(), str(tmp_path / "img"),
                      chunk=(32, 32), n_levels=1)
        jobs = PyramidJobManager(chunk=(32, 32), min_level_size=16)
        job = jobs.submit(str(tmp_path / "img"))
        jobs.run_job_sync(job)
        doc = jobs.job_for_source(str(tmp_path / "img"))
        assert doc["state"] == "done"
        fresh = PyramidJobManager()
        doc2 = fresh.job_for_source(str(tmp_path / "img"))
        assert doc2 is not None and doc2["jobId"] == job.job_id

    def test_duplicate_submit_dedups(self, tmp_path):
        build_pyramid(self._planes(), str(tmp_path / "img"),
                      chunk=(32, 32), n_levels=1)
        jobs = PyramidJobManager()
        a = jobs.submit(str(tmp_path / "img"))
        b = jobs.submit(str(tmp_path / "img"))
        assert a is b


# ----------------------------------------------- animation streaming

class _StaggeredHandler:
    """Wraps the real image handler with a per-call growing delay so a
    mid-stream close deterministically finds later frames pending."""

    def __init__(self, inner, step_s=0.05):
        self.inner = inner
        self.step_s = step_s
        self.calls = 0

    async def render_image_region(self, ctx):
        self.calls += 1
        await asyncio.sleep(self.step_s * self.calls)
        return await self.inner.render_image_region(ctx)


class TestAnimationStream:
    def _frame_ctxs(self, n):
        return [_ctx(theZ=str(z)) for z in range(n)]

    def test_frame_record_framing(self):
        rec = frame_record(b"abc")
        assert rec[:4] == b"FRME"
        assert int.from_bytes(rec[4:8], "big") == 3
        assert rec[8:] == b"abc"

    def test_frames_stream_in_order_byte_identical(self, data_dir):
        services = _services(data_dir)
        image_handler = ImageRegionHandler(services)
        workloads = WorkloadsHandler(image_handler, services,
                                     max_frames=8)
        ctxs = self._frame_ctxs(Z)

        async def main():
            frames = []
            async for rec in workloads.render_animation_stream(ctxs):
                assert rec[:4] == b"FRME"
                n = int.from_bytes(rec[4:8], "big")
                assert len(rec) == 8 + n
                frames.append(rec[8:])
            return frames

        frames = run(main())
        assert len(frames) == Z
        # Frame i is EXACTLY the plain route's bytes for plane z=i —
        # order preserved, identity shared.
        for i, body in enumerate(frames):
            direct = run(image_handler.render_image_region(
                _ctx(theZ=str(i))))
            assert body == direct
        assert len({bytes(f) for f in frames}) == Z

    def test_frame_cap_and_empty_rejected(self, data_dir):
        services = _services(data_dir)
        workloads = WorkloadsHandler(ImageRegionHandler(services),
                                     services, max_frames=2)

        async def drain(ctxs):
            async for _ in workloads.render_animation_stream(ctxs):
                pass

        with pytest.raises(BadRequestError):
            run(drain(self._frame_ctxs(3)))
        with pytest.raises(BadRequestError):
            run(drain([]))

    def test_disconnect_cancels_remaining_frames(self, data_dir):
        """Closing the generator after the first frame (the client
        vanished) cancels every not-yet-settled render task and counts
        one cancelled stream."""
        services = _services(data_dir)
        stag = _StaggeredHandler(ImageRegionHandler(services))
        workloads = WorkloadsHandler(stag, services, max_frames=8)
        before = telemetry.WORKLOADS.stream_cancels

        async def main():
            agen = workloads.render_animation_stream(
                self._frame_ctxs(Z))
            first = await agen.__anext__()
            assert first[:4] == b"FRME"
            await agen.aclose()

        run(main())
        assert telemetry.WORKLOADS.stream_cancels == before + 1
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "animation.cancelled" in kinds


# ------------------------------------------------- scrub prediction

class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestScrubPrediction:
    def _tracker(self):
        from omero_ms_image_region_tpu.services.viewport import \
            ViewportTracker
        return ViewportTracker(clock=_Clock())

    def test_scrub_velocity_median_of_plane_deltas(self):
        tracker = self._tracker()
        for t in (0, 1, 2, 3):
            tracker.observe("s", 1, 0, t, 0, 5, 5)
        assert tracker.scrub_velocity("s") == (0, 1)
        # z-scrub the other way.
        for z in (6, 4, 2):
            tracker.observe("s2", 1, z, 0, 0, 5, 5)
        assert tracker.scrub_velocity("s2") == (-2, 0)

    def test_pan_does_not_vote_as_scrub(self):
        tracker = self._tracker()
        for x in range(4):
            tracker.observe("s", 1, 0, 0, 0, x, 0)
        assert tracker.scrub_velocity("s") is None

    def test_predict_extends_scrub_to_future_planes(self):
        tracker = self._tracker()
        for t in (0, 1, 2):
            tracker.observe("s", 1, 0, t, 0, 5, 5)
        preds = tracker.predict("s", lookahead=2)
        planes = [(p.z, p.t, p.x, p.y) for p in preds]
        assert (0, 3, 5, 5) in planes
        assert (0, 4, 5, 5) in planes
        # Sliders clamp at zero: a backwards scrub never predicts a
        # negative plane.
        for t in (2, 1, 0):
            tracker.observe("back", 1, 0, t, 0, 5, 5)
        assert all(p.t >= 0 and p.z >= 0
                   for p in tracker.predict("back", lookahead=4))


# ------------------------------------------------------ explain plane

class TestExplainWorkloadRoutes:
    def _config(self):
        from omero_ms_image_region_tpu.server.config import AppConfig
        return AppConfig.from_dict({})

    def _explain(self, path, **kw):
        from omero_ms_image_region_tpu.server.explain import explain
        return run(explain(path, self._config(), **kw))

    def test_classify_covers_every_render_route(self):
        from omero_ms_image_region_tpu.server.explain import (
            classify_render_path, parse_render_path,
        )
        cases = {
            "/webgateway/render_image_region/1/0/0/?tile=0,0,0":
                "image",
            "/webgateway/render_shape_mask/9001/?color=FF0000":
                "mask",
            "/webgateway/render_overlay/1/0/0/?shapes=9001": "overlay",
            "/webgateway/render_animation/1/0/0/?axis=z&frames=3":
                "animation",
        }
        for path, want in cases.items():
            kind, params = classify_render_path(path)
            assert kind == want, path
        with pytest.raises(BadRequestError):
            classify_render_path("/webgateway/render_overlay/x")
        # The image-only parser keeps its pinned contract.
        with pytest.raises(BadRequestError):
            parse_render_path("/webgateway/render_shape_mask/1")

    def test_mask_explain_identity_and_posture(self):
        doc = self._explain(
            "/webgateway/render_shape_mask/9001/?color=FF0000&flip=h")
        assert doc["kind"] == "mask"
        assert doc["qos"] == "interactive"
        assert doc["device_batched"] is True
        assert doc["identity"].endswith(":f10")
        assert doc["dry_run"] is True

    def test_overlay_explain_shares_base_identity(self):
        doc = self._explain(
            "/webgateway/render_overlay/1/0/0/"
            "?shapes=9001,9002&color=FF0000")
        assert doc["kind"] == "overlay"
        assert doc["shapes"] == [9001, 9002]
        assert doc["identity"].startswith(doc["base_identity"])
        assert ":ov:9001,9002:FF0000" in doc["identity"]
        assert doc["plane_route_key"]

    def test_animation_explain_per_frame_identities(self):
        doc = self._explain(
            "/webgateway/render_animation/1/0/2/?axis=t&frames=3")
        assert doc["kind"] == "animation"
        assert doc["frames"] == 3 and doc["axis"] == "t"
        assert len(doc["identities"]) == 3
        assert len(set(doc["identities"])) == 3
        assert len(doc["plane_route_keys"]) == 3
        assert doc["streamed"] is True
        from omero_ms_image_region_tpu.server.explain import explain
        with pytest.raises(BadRequestError):
            run(explain("/webgateway/render_animation/1/0/0/"
                        "?frames=100000", self._config()))

    def test_explain_reports_pyramid_job_state(self, tmp_path):
        rng = np.random.default_rng(9)
        planes = rng.integers(0, 60000, size=(1, 1, 64, 64)).astype(
            np.uint16)
        build_pyramid(planes, str(tmp_path / "1"), chunk=(32, 32),
                      n_levels=1)
        pixels = PixelsService(str(tmp_path))
        jobs = PyramidJobManager(pixels_service=pixels,
                                 chunk=(32, 32), min_level_size=16)
        job = jobs.submit_image(1)
        jobs.run_job_sync(job)
        doc = self._explain(
            "/webgateway/render_overlay/1/0/0/?shapes=9001",
            jobs=jobs)
        assert doc["pyramid_job"]["state"] == "done"
        assert doc["pyramid_job"]["jobId"] == job.job_id
        pixels.close()


# -------------------------------------------------- telemetry plane

class TestWorkloadTelemetry:
    def _lint_module(self):
        import importlib.util
        scripts = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts")
        spec = importlib.util.spec_from_file_location(
            "metrics_lint", os.path.join(scripts, "metrics_lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_families_lint_clean_and_reset(self):
        """The workload families expose under the closed
        kind/action label keys, lint clean against the committed
        budget, and reset() returns them to emit-when-live silence."""
        telemetry.reset()
        telemetry.WORKLOADS.count_request("mask_device")
        telemetry.WORKLOADS.count_job("submitted")
        telemetry.WORKLOADS.job_started()
        telemetry.WORKLOADS.count_level_committed()
        telemetry.WORKLOADS.count_stream()
        telemetry.WORKLOADS.count_frames(3)
        telemetry.WORKLOADS.count_stream_cancelled()
        telemetry.WORKLOADS.observe_first_frame_ms(12.5)
        text = telemetry.finalize_exposition(
            telemetry.session_metric_lines())
        assert ('imageregion_workload_requests_total'
                '{kind="mask_device"} 1') in text
        assert ('imageregion_pyramid_jobs_total'
                '{action="submitted"} 1') in text
        assert "imageregion_pyramid_jobs_active 1" in text
        assert "imageregion_pyramid_levels_committed_total 1" in text
        assert "imageregion_animation_streams_total 1" in text
        assert "imageregion_animation_frames_total 3" in text
        assert "imageregion_animation_cancelled_total 1" in text
        assert "imageregion_animation_first_frame_ms 12.5" in text
        lint = self._lint_module()
        assert lint.lint_exposition(text, lint.load_budget()) == []
        telemetry.reset()
        after = telemetry.finalize_exposition(
            telemetry.session_metric_lines())
        assert "imageregion_workload_" not in after
        assert "imageregion_animation_" not in after
