"""Quantization kernel vs the CPU reference implementation and hand-computed
golden values."""

import numpy as np
import pytest

from omero_ms_image_region_tpu.models.rendering import Family
from omero_ms_image_region_tpu.ops.quantum import (
    FAMILY_EXPONENTIAL,
    FAMILY_LINEAR,
    FAMILY_LOGARITHMIC,
    FAMILY_POLYNOMIAL,
    quantize,
)
from omero_ms_image_region_tpu.refimpl import quantize_ref


def _run_quantize(raw, ws, we, family, k):
    C = raw.shape[0]
    return np.asarray(
        quantize(
            raw.astype(np.float32),
            np.full(C, ws, np.float32),
            np.full(C, we, np.float32),
            np.full(C, family, np.int32),
            np.full(C, k, np.float32),
        )
    )


def test_linear_golden():
    raw = np.array([[[0, 100, 200, 255, 300]]], dtype=np.float32)
    q = _run_quantize(raw, 0, 255, FAMILY_LINEAR, 1.0)
    assert q.tolist() == [[[0, 100, 200, 255, 255]]]


def test_linear_window_scales():
    raw = np.array([[[1000, 2000, 3000]]], dtype=np.float32)
    q = _run_quantize(raw, 1000, 3000, FAMILY_LINEAR, 1.0)
    assert q.tolist() == [[[0, 128, 255]]]


def test_below_window_clamps_to_zero():
    raw = np.array([[[-50, 0, 10]]], dtype=np.float32)
    q = _run_quantize(raw, 10, 20, FAMILY_LINEAR, 1.0)
    assert q.tolist() == [[[0, 0, 0]]]


def test_degenerate_window_is_step_function():
    raw = np.array([[[5, 10, 15]]], dtype=np.float32)
    q = _run_quantize(raw, 10, 10, FAMILY_LINEAR, 1.0)
    assert q.tolist() == [[[0, 255, 255]]]


@pytest.mark.parametrize(
    "family,jfam,k",
    [
        (Family.LINEAR, FAMILY_LINEAR, 1.0),
        (Family.POLYNOMIAL, FAMILY_POLYNOMIAL, 2.0),
        (Family.POLYNOMIAL, FAMILY_POLYNOMIAL, 0.5),
        (Family.LOGARITHMIC, FAMILY_LOGARITHMIC, 1.0),
        (Family.EXPONENTIAL, FAMILY_EXPONENTIAL, 1.0),
    ],
)
def test_matches_cpu_reference(family, jfam, k):
    rng = np.random.default_rng(42)
    raw = rng.uniform(0, 65535, size=(1, 16, 16)).astype(np.float32)
    ws, we = 256.0, 60000.0
    got = _run_quantize(raw, ws, we, jfam, k)[0]
    want = quantize_ref(raw[0], ws, we, family, k)
    # float32 vs float64 rounding can differ by 1 at bin edges
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_signed_window_linear():
    raw = np.array([[[-32768, 0, 32767]]], dtype=np.float32)
    q = _run_quantize(raw, -32768, 32767, FAMILY_LINEAR, 1.0)
    assert q[0, 0, 0] == 0
    assert q[0, 0, 2] == 255
    assert abs(int(q[0, 0, 1]) - 128) <= 1


def test_exponential_monotone_no_overflow():
    raw = np.linspace(0, 65535, 64, dtype=np.float32)[None, None, :]
    q = _run_quantize(raw, 0, 65535, FAMILY_EXPONENTIAL, 1.0)[0, 0]
    assert np.all(np.diff(q) >= 0)
    assert np.isfinite(q).all()
    assert q[0] == 0 and q[-1] == 255


def test_mixed_families_one_call():
    raw = np.tile(np.linspace(0, 255, 8, dtype=np.float32), (4, 1))[
        :, None, :
    ]
    q = np.asarray(
        quantize(
            raw,
            np.zeros(4, np.float32),
            np.full(4, 255, np.float32),
            np.array(
                [
                    FAMILY_LINEAR,
                    FAMILY_POLYNOMIAL,
                    FAMILY_LOGARITHMIC,
                    FAMILY_EXPONENTIAL,
                ],
                np.int32,
            ),
            np.ones(4, np.float32),
        )
    )
    for c, fam in enumerate(
        [Family.LINEAR, Family.POLYNOMIAL, Family.LOGARITHMIC,
         Family.EXPONENTIAL]
    ):
        want = quantize_ref(raw[c], 0.0, 255.0, fam, 1.0)
        assert np.abs(q[c].astype(int) - want.astype(int)).max() <= 1
