"""Fault tolerance across the sidecar wire: op-aware retry, circuit
breaking, deadline propagation, degraded-mode CPU fallback, admission
shedding, and supervised crash recovery — the frontend -> sidecar ->
batcher chain failing the way the runbook says it fails
(deploy/DEPLOY.md)."""

import asyncio
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.models.mask import Mask
from omero_ms_image_region_tpu.server.app import (SERVICES_KEY,
                                                  create_app)
from omero_ms_image_region_tpu.server.config import (
    AppConfig, FaultToleranceConfig, SidecarConfig)
from omero_ms_image_region_tpu.server.errors import (
    DeadlineExceededError, OverloadedError)
from omero_ms_image_region_tpu.server.sidecar import (
    SidecarClient, _pack, _read_frame, run_sidecar)
from omero_ms_image_region_tpu.services.metadata import write_mask
from omero_ms_image_region_tpu.utils.transient import (CircuitBreaker,
                                                       RetryPolicy,
                                                       deadline_scope)

IMG, MASK = 3, 9
H = W = 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(21)
    planes = rng.integers(0, 60000, size=(2, 2, H, W)).astype(np.uint16)
    build_pyramid(planes, str(tmp_path / str(IMG)), chunk=(32, 32),
                  n_levels=1)
    bits = np.zeros(H * W, np.uint8)
    bits[:512] = 1
    write_mask(str(tmp_path), Mask(shape_id=MASK, width=W, height=H,
                                   bytes_=np.packbits(bits).tobytes()))
    return str(tmp_path)


URL = (f"/webgateway/render_image_region/{IMG}/0/0"
       f"?c=1|0:60000$FF0000&m=g&format=png")


async def _wait_socket(sock, task):
    for _ in range(200):
        if task.done():
            raise AssertionError(
                f"sidecar died at startup: {task.exception()!r}")
        if os.path.exists(sock):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("sidecar socket never appeared")


# ------------------------------------------------------- op-aware retry

def test_idempotent_ops_retry_plane_put_does_not(tmp_path):
    """A connection that dies under a request is retried transparently
    for idempotent ops — and NEVER for plane_put (the acceptance
    criterion: a state-changing upload the dead peer may or may not
    have executed must surface, not silently re-run)."""
    sock = str(tmp_path / "fake.sock")

    async def scenario():
        received = []

        async def on_conn(reader, writer):
            try:
                while True:
                    header, _body = await _read_frame(reader)
                    received.append(header["op"])
                    if received.count(header["op"]) == 1:
                        # First sight of this op: die under it.
                        writer.close()
                        return
                    writer.write(_pack({"id": header["id"],
                                        "status": 200}, b"ok"))
                    await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass

        server = await asyncio.start_unix_server(on_conn, path=sock)
        client = SidecarClient(
            sock, retry=RetryPolicy(max_attempts=3,
                                    base_backoff_s=0.005, jitter=0.0))
        try:
            status, payload = await client.call("image", {})
            assert status == 200 and bytes(payload) == b"ok"
            assert received.count("image") == 2      # one retry
            with pytest.raises(ConnectionError):
                await client.call("plane_put", {}, body=b"\x00",
                                  extra={"digest": "d",
                                         "dtype": "uint8",
                                         "shape": [1]})
            assert received.count("plane_put") == 1  # NO auto-retry
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    asyncio.run(scenario())


def test_byte_tier_ops_retry_contract(tmp_path):
    """The fleet-global byte tier's wire ops inherit the op-aware
    retry contract: ``byte_probe``/``byte_fetch`` are pure reads and
    retry through a dropped connection; ``byte_put`` — the peer
    write-back — is NEVER blind-retried (the plane_put contract,
    extended: a state-changing store the dead peer may or may not
    have executed must surface, not silently re-run)."""
    sock = str(tmp_path / "fake-bytes.sock")

    async def scenario():
        received = []

        async def on_conn(reader, writer):
            try:
                while True:
                    header, _body = await _read_frame(reader)
                    received.append(header["op"])
                    if received.count(header["op"]) == 1:
                        writer.close()   # die under the first sight
                        return
                    writer.write(_pack({"id": header["id"],
                                        "status": 200}, b"ok"))
                    await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass

        server = await asyncio.start_unix_server(on_conn, path=sock)
        client = SidecarClient(
            sock, retry=RetryPolicy(max_attempts=3,
                                    base_backoff_s=0.005, jitter=0.0))
        try:
            status, payload = await client.call(
                "byte_fetch", {}, extra={"key": "k"})
            assert status == 200 and bytes(payload) == b"ok"
            assert received.count("byte_fetch") == 2    # one retry
            status, payload = await client.call(
                "byte_probe", {}, extra={"keys": ["k"]})
            assert status == 200
            assert received.count("byte_probe") == 2    # one retry
            with pytest.raises(ConnectionError):
                await client.call("byte_put", {}, body=b"\x00",
                                  extra={"key": "k", "digest": "d"})
            assert received.count("byte_put") == 1      # NO auto-retry
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    asyncio.run(scenario())


# ------------------------------------------------------- circuit breaker

def test_breaker_fails_fast_and_recovers(tmp_path):
    """Consecutive connection failures open the breaker (calls fail
    fast with OverloadedError instead of paying the connect path);
    after the reset window a half-open trial against a now-live
    sidecar closes it again."""
    sock = str(tmp_path / "dead.sock")   # nothing listening

    async def scenario():
        client = SidecarClient(
            sock, breaker=CircuitBreaker(2, reset_after_s=0.2),
            retry=None)
        try:
            for _ in range(2):
                with pytest.raises(ConnectionError):
                    await client.call("ping", {})
            with pytest.raises(OverloadedError) as ei:
                await client.call("ping", {})
            assert ei.value.retry_after_s > 0
            assert client.breaker.state_name == "open"

            # Bring a live answerer up; after the reset window the
            # half-open trial succeeds and the breaker closes.
            async def on_conn(reader, writer):
                try:
                    while True:
                        header, _ = await _read_frame(reader)
                        writer.write(_pack({"id": header["id"],
                                            "status": 200}, b"{}"))
                        await writer.drain()
                except (asyncio.IncompleteReadError,
                        ConnectionResetError):
                    pass

            server = await asyncio.start_unix_server(on_conn, path=sock)
            await asyncio.sleep(0.25)
            status, _ = await client.call("ping", {})
            assert status == 200
            assert client.breaker.state_name == "closed"
            server.close()
            await server.wait_closed()
        finally:
            await client.close()

    asyncio.run(scenario())


# -------------------------------------------------- deadline propagation

def test_deadline_rides_wire_and_spent_budget_is_504(data_dir,
                                                     tmp_path):
    """The remaining budget crosses the wire as deadline_ms; a request
    arriving with nothing left answers 504 WITHOUT rendering, and a
    client-side spent budget never even sends."""
    sock = str(tmp_path / "render.sock")

    async def scenario():
        cfg = AppConfig(data_dir=data_dir)
        task = asyncio.create_task(run_sidecar(cfg, sock))
        client = SidecarClient(sock)
        try:
            await _wait_socket(sock, task)
            # Server side: explicit spent budget -> 504, no render.
            status, err = await client.call(
                "ping", {}, extra={"deadline_ms": 0})
            assert status == 504 and "deadline" in str(err)
            # Generous budget flows through to a 200.
            with deadline_scope(30000.0):
                status, _ = await client.call("ping", {})
            assert status == 200
            # Client side: a spent budget raises before sending.
            with deadline_scope(0.0001):
                await asyncio.sleep(0.001)
                with pytest.raises(DeadlineExceededError):
                    await client.call("ping", {})
            return True
        finally:
            await client.close()
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    assert asyncio.run(scenario())


def test_request_deadline_maps_to_http_504(data_dir, tmp_path):
    """fault-tolerance.request-deadline-ms opens the budget at the
    HTTP frontend; an impossible budget surfaces as 504 + JSON error
    (never a 500, never a hang)."""
    sock = str(tmp_path / "render.sock")

    async def scenario():
        cfg = AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            fault_tolerance=FaultToleranceConfig(
                request_deadline_ms=0.0001))
        sidecar_task = asyncio.create_task(
            run_sidecar(AppConfig(data_dir=data_dir), sock))
        app = create_app(cfg)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await _wait_socket(sock, sidecar_task)
            r = await client.get(URL)
            assert r.status == 504
            doc = await r.json()
            assert "deadline" in doc["error"]
            return True
        finally:
            await client.close()
            sidecar_task.cancel()
            try:
                await sidecar_task
            except (asyncio.CancelledError, Exception):
                pass

    assert asyncio.run(scenario())


# ------------------------------------------------------- degraded mode

def test_degraded_mode_serves_tiles_while_sidecar_down(data_dir,
                                                       tmp_path):
    """With degraded-mode on and NO sidecar listening, tiles and masks
    still serve — on the frontend's CPU reference path — and /readyz
    stays 200 (the LB must keep routing) while reporting the
    degradation; /metrics counts the fallback renders."""
    sock = str(tmp_path / "never.sock")
    mask_url = f"/webgateway/render_shape_mask/{MASK}?color=00FF00"

    def frontend_cfg():
        return AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            fault_tolerance=FaultToleranceConfig(
                degraded_mode=True, retry_max_attempts=1))

    async def degraded():
        app = create_app(frontend_cfg())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            png = await r.read()
            assert r.status == 200 and png[:4] == b"\x89PNG"
            rm = await client.get(mask_url)
            assert rm.status == 200
            mask_png = await rm.read()
            # Projections are refused in degraded mode: shed, not a
            # frontend-CPU-minutes render.
            rp = await client.get(
                f"/webgateway/render_image_region/{IMG}/0/0"
                f"?c=1|0:60000$FF0000&m=g&p=intmax|0:1&format=png")
            assert rp.status == 503
            assert "Retry-After" in rp.headers
            rz = await client.get("/readyz")
            assert rz.status == 200
            doc = await rz.json()
            assert doc["checks"]["degraded-mode"] == "active"
            assert doc["checks"]["sidecar"] == "unreachable"
            m = await (await client.get("/metrics")).text()
            line = [ln for ln in m.splitlines() if ln.startswith(
                "imageregion_degraded_renders_total")]
            assert line and int(line[0].rsplit(" ", 1)[1]) >= 2
            return png, mask_png
        finally:
            await client.close()

    png, mask_png = asyncio.run(degraded())

    # The degraded bytes ARE the combined app's bytes: 64^2 tiles take
    # the same refimpl CPU path there, so the fallback is bit-exact.
    async def combined():
        app = create_app(AppConfig(data_dir=data_dir))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            rm = await client.get(mask_url)
            return await r.read(), await rm.read()
        finally:
            await client.close()

    assert (png, mask_png) == asyncio.run(combined())


def test_without_degraded_mode_sidecar_outage_is_503(data_dir,
                                                     tmp_path):
    """Degraded mode off (the default): a dead sidecar surfaces as
    503 + Retry-After — an availability failure the client should
    retry, never a bare 500 — and /readyz goes unready."""
    sock = str(tmp_path / "never.sock")

    async def scenario():
        cfg = AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            fault_tolerance=FaultToleranceConfig(retry_max_attempts=1))
        app = create_app(cfg)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            assert r.status == 503
            assert "Retry-After" in r.headers
            body = await r.read()
            assert b"Traceback" not in body
            rz = await client.get("/readyz")
            assert rz.status == 503
            assert (await rz.json())["checks"]["sidecar"] == \
                "unreachable"
            return True
        finally:
            await client.close()

    assert asyncio.run(scenario())


# ------------------------------------------------------ admission shed

def test_admission_shed_is_503_with_retry_after(data_dir):
    """A full admission queue sheds at the HTTP surface with 503 +
    Retry-After + JSON error body; freeing the queue admits again."""

    async def scenario():
        cfg = AppConfig(
            data_dir=data_dir,
            fault_tolerance=FaultToleranceConfig(admission_max_queue=1))
        app = create_app(cfg)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            admission = app[SERVICES_KEY].admission
            assert admission is not None
            admission.inflight = 1          # pin the queue full
            r = await client.get(URL)
            assert r.status == 503
            assert "Retry-After" in r.headers
            assert "error" in await r.json()
            admission.inflight = 0
            r2 = await client.get(URL)
            assert r2.status == 200
            m = await (await client.get("/metrics")).text()
            assert 'imageregion_shed_total{reason="queue-full"}' in m
            return True
        finally:
            await client.close()

    assert asyncio.run(scenario())


# ------------------------------------------------- startup probe detail

def test_spawn_sidecar_surfaces_boot_crash_exit_code(tmp_path,
                                                     monkeypatch):
    """A sidecar that crashes during boot (here: unreadable config)
    fails the spawn IMMEDIATELY with the child's exit code — it must
    never masquerade as the 3-minute 'socket never appeared'
    timeout."""
    from omero_ms_image_region_tpu.server.sidecar import spawn_sidecar

    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match=r"exited with \d+ during "
                                           r"startup"):
        spawn_sidecar(str(tmp_path / "does-not-exist.yaml"),
                      str(tmp_path / "never.sock"))
    # Well under the 180 s socket timeout: the probe read the child's
    # death, it did not wait it out.
    assert time.monotonic() - t0 < 120.0


# --------------------------------------------- supervised crash recovery

def test_supervised_sidecar_recovers_from_mid_request_crash(
        data_dir, tmp_path, monkeypatch):
    """The acceptance drill, with REAL processes: a seeded fault kills
    the sidecar MID-request (die-after-requests); the in-flight caller
    sees a connection failure, and the supervisor restarts the device
    process so later requests succeed WITHOUT operator action."""
    import yaml

    from omero_ms_image_region_tpu.server.sidecar import (
        SidecarSupervisor)

    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    sock = str(tmp_path / "render.sock")
    cfg_path = tmp_path / "sidecar.yaml"
    cfg_path.write_text(yaml.safe_dump({
        "data-dir": data_dir,
        "fault-injection": {"seed": 1, "die-after-requests": 2},
    }))

    sup = SidecarSupervisor.for_config(str(cfg_path), sock,
                                       max_backoff_s=2.0)
    sup.start()
    try:
        async def drive():
            client = SidecarClient(sock, breaker=None)
            try:
                status, _ = await client.call("ping", {})
                assert status == 200
                # Request #2 kills the sidecar process mid-call.
                with pytest.raises(ConnectionError):
                    await client.call("ping", {})
                # Recovery without operator action: keep asking until
                # the supervisor's respawn answers.
                deadline = time.monotonic() + 240.0
                while time.monotonic() < deadline:
                    try:
                        status, _ = await client.call("ping", {})
                        if status == 200:
                            return True
                    except (ConnectionError, OSError):
                        pass
                    await asyncio.sleep(1.0)
                return False
            finally:
                await client.close()

        assert asyncio.run(drive()), "sidecar never came back"
        # The monitor thread counts a restart only once its startup
        # probe returns — which can trail the first successful ping by
        # a poll interval; wait for the bookkeeping, not just the
        # serving.
        deadline = time.monotonic() + 30.0
        while sup.restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sup.restarts >= 1
    finally:
        sup.stop()
    assert sup.proc.poll() is not None   # stop() really stopped it
