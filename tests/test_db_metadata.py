"""DbMetadataService against a seeded OMERO-schema subset (sqlite).

The service's SQL is written for asyncpg/PostgreSQL; the adapter here
translates only the placeholder style ($N -> ?) so the very same
statements execute against sqlite — an e2e check of the queries, the
group-permission ACL bits, and the session resolution, without a live
OMERO database (this image ships no Postgres driver or server;
``PostgresMetadataService.connect`` stays gated on asyncpg).
"""

import asyncio
import re
import sqlite3

import numpy as np
import pytest

from omero_ms_image_region_tpu.services.db_metadata import (
    DbMetadataService, GROUP_READ, USER_READ, WORLD_READ,
)

# Canonical OMERO permission longs (ome.model.internal.Permissions).
PRIVATE = -120        # rw----
GROUP_RO = -56        # rwr---
PUBLIC_RO = -52       # rwr-r-


class SqliteDb:
    """fetchrow/fetch over sqlite with $N -> ? placeholder translation."""

    def __init__(self, conn: sqlite3.Connection):
        conn.row_factory = sqlite3.Row
        self.conn = conn

    @staticmethod
    def _translate(sql: str) -> str:
        return re.sub(r"\$\d+", "?", sql)

    async def fetchrow(self, sql: str, *args):
        cur = self.conn.execute(self._translate(sql), args)
        row = cur.fetchone()
        return None if row is None else dict(row)

    async def fetch(self, sql: str, *args):
        cur = self.conn.execute(self._translate(sql), args)
        return [dict(r) for r in cur.fetchall()]


SCHEMA = """
CREATE TABLE experimentergroup (
    id INTEGER PRIMARY KEY, name TEXT, permissions INTEGER);
CREATE TABLE experimenter (id INTEGER PRIMARY KEY, omename TEXT);
CREATE TABLE groupexperimentermap (child INTEGER, parent INTEGER);
CREATE TABLE session (
    id INTEGER PRIMARY KEY, uuid TEXT, owner INTEGER, closed TEXT);
CREATE TABLE image (
    id INTEGER PRIMARY KEY, owner_id INTEGER, group_id INTEGER,
    fileset INTEGER);
CREATE TABLE fileset (id INTEGER PRIMARY KEY, templateprefix TEXT);
CREATE TABLE filesetentry (
    id INTEGER PRIMARY KEY, fileset INTEGER, originalfile INTEGER,
    clientpath TEXT);
CREATE TABLE originalfile (
    id INTEGER PRIMARY KEY, path TEXT, name TEXT, mimetype TEXT);
CREATE TABLE pixelstype (id INTEGER PRIMARY KEY, value TEXT);
CREATE TABLE pixels (
    id INTEGER PRIMARY KEY, image INTEGER, sizex INTEGER, sizey INTEGER,
    sizez INTEGER, sizec INTEGER, sizet INTEGER, pixelstype INTEGER);
CREATE TABLE roi (id INTEGER PRIMARY KEY, image INTEGER);
CREATE TABLE shape (
    id INTEGER PRIMARY KEY, roi INTEGER, owner_id INTEGER,
    group_id INTEGER, width INTEGER, height INTEGER, bytes BLOB,
    fillcolor INTEGER);
"""

MASK_BITS = np.packbits(
    np.tile([1, 0], 16 * 8 // 2).astype(np.uint8)).tobytes()


@pytest.fixture()
def db():
    conn = sqlite3.connect(":memory:")
    conn.executescript(SCHEMA)
    conn.executemany(
        "INSERT INTO experimentergroup VALUES (?, ?, ?)",
        [(0, "system", PRIVATE),
         (10, "lab-private", PRIVATE),
         (11, "lab-shared", GROUP_RO),
         (12, "atlas-public", PUBLIC_RO)])
    conn.executemany(
        "INSERT INTO experimenter VALUES (?, ?)",
        [(100, "owner"), (101, "labmate"), (102, "outsider"),
         (103, "root")])
    conn.executemany(
        "INSERT INTO groupexperimentermap VALUES (?, ?)",
        [(100, 10), (100, 11), (100, 12),
         (101, 10), (101, 11),
         (102, 12),
         (103, 0)])
    conn.executemany(
        "INSERT INTO session VALUES (?, ?, ?, ?)",
        [(1, "sess-owner", 100, None),
         (2, "sess-labmate", 101, None),
         (3, "sess-outsider", 102, None),
         (4, "sess-root", 103, None),
         (5, "sess-closed", 100, "2026-01-01 00:00:00")])
    conn.executemany(
        "INSERT INTO image VALUES (?, ?, ?, ?)",
        [(1, 100, 10, None),   # private image
         (2, 100, 11, None),   # group-readable image
         (3, 100, 12, None),   # world-readable image
         (4, 100, 12, 900),    # fileset-backed (ManagedRepository)
         (5, 100, 12, None)])  # pre-FS (legacy Pixels file)
    conn.execute("INSERT INTO fileset VALUES (900, 'demo_2/2026-07/31/')")
    conn.executemany(
        "INSERT INTO filesetentry VALUES (?, ?, ?, ?)",
        [(1, 900, 800, "a.fake"), (2, 900, 801, "img.ome.tiff")])
    conn.executemany(
        "INSERT INTO originalfile VALUES (?, ?, ?, ?)",
        [(800, "demo_2/2026-07/31/", "a.fake", "application/x-fake"),
         (801, "demo_2/2026-07/31/", "img.ome.tiff", "image/tiff")])
    conn.execute("INSERT INTO pixelstype VALUES (1, 'uint16')")
    conn.execute("INSERT INTO pixelstype VALUES (2, 'uint8')")
    conn.executemany(
        "INSERT INTO pixels VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        [(50, 1, 4096, 4096, 16, 4, 1, 1),
         (51, 2, 512, 256, 1, 3, 1, 2),
         (52, 4, 96, 64, 1, 2, 1, 1),
         (53, 5, 48, 32, 2, 1, 1, 1)])
    conn.execute("INSERT INTO roi VALUES (7, 2)")
    # mask on the group-readable image; fillcolor = RGBA 0x00FF00FF
    conn.execute(
        "INSERT INTO shape VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        (70, 7, 100, 11, 16, 8, MASK_BITS, 0x00FF00FF))
    # mask with no fillcolor in the private group
    conn.execute(
        "INSERT INTO shape VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        (71, 7, 100, 10, 16, 8, MASK_BITS, None))
    conn.commit()
    return SqliteDb(conn)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestPermissionBits:
    def test_documented_longs_decode(self):
        assert PRIVATE & USER_READ and not PRIVATE & GROUP_READ
        assert GROUP_RO & GROUP_READ and not GROUP_RO & WORLD_READ
        assert PUBLIC_RO & WORLD_READ


class TestCanRead:
    @pytest.mark.parametrize("image_id,session,expect", [
        (1, "sess-owner", True),      # owner reads own private image
        (1, "sess-labmate", False),   # member, but group is rw----
        (1, "sess-outsider", False),
        (1, "sess-root", True),       # admin reads everything
        (1, None, False),
        (2, "sess-owner", True),
        (2, "sess-labmate", True),    # member of rwr--- group
        (2, "sess-outsider", False),  # non-member, no world read
        (2, None, False),
        (3, "sess-outsider", True),   # member of public group
        (3, None, True),              # anonymous world read
    ])
    def test_image_acl(self, db, image_id, session, expect):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", image_id, session)) is expect

    def test_closed_session_is_anonymous(self, db):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", 1, "sess-closed")) is False
        assert run(svc.can_read("Image", 3, "sess-closed")) is True

    def test_unknown_object_is_unreadable(self, db):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", 999, "sess-root")) is False


class TestPixels:
    def test_resolves_geometry_and_type(self, db):
        svc = DbMetadataService(db)
        px = run(svc.get_pixels_description(1, "sess-owner"))
        assert (px.size_x, px.size_y, px.size_z, px.size_c, px.size_t) \
            == (4096, 4096, 16, 4, 1)
        assert px.pixels_type == "uint16"
        assert px.type.np_dtype == np.dtype("uint16")

    def test_acl_gates_pixels(self, db):
        svc = DbMetadataService(db)
        assert run(svc.get_pixels_description(1, "sess-labmate")) is None
        assert run(svc.get_pixels_description(2, "sess-labmate")) \
            is not None


class TestMask:
    def test_mask_with_fillcolor(self, db):
        svc = DbMetadataService(db)
        mask = run(svc.get_mask(70, "sess-labmate"))
        assert (mask.width, mask.height) == (16, 8)
        assert mask.bytes_ == MASK_BITS
        assert mask.fill_color == (0, 255, 0, 255)

    def test_mask_without_fillcolor(self, db):
        svc = DbMetadataService(db)
        mask = run(svc.get_mask(71, "sess-owner"))
        assert mask.fill_color is None

    def test_mask_acl(self, db):
        svc = DbMetadataService(db)
        assert run(svc.get_mask(71, "sess-labmate")) is None  # rw---- group
        assert run(svc.get_mask(70, "sess-outsider")) is None


class TestHandlerIntegration:
    def test_image_handler_serves_via_db_metadata(self, db, tmp_path):
        """The HTTP handler stack runs unchanged on the DB backend."""
        from omero_ms_image_region_tpu.io.store import build_pyramid

        rng = np.random.default_rng(3)
        planes = rng.integers(0, 60000, (3, 1, 64, 64)).astype(np.uint16)
        build_pyramid(planes, str(tmp_path / "2"), n_levels=1)

        from omero_ms_image_region_tpu.io.service import PixelsService
        from omero_ms_image_region_tpu.ops.lut import LutProvider
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        from omero_ms_image_region_tpu.server.handler import (
            ImageRegionHandler, ImageRegionServices, NotFoundError, Renderer)
        from omero_ms_image_region_tpu.services.cache import (
            CacheConfig, Caches)
        from omero_ms_image_region_tpu.services.metadata import CanReadMemo

        services = ImageRegionServices(
            pixels_service=PixelsService(str(tmp_path)),
            metadata=DbMetadataService(db),
            caches=Caches.from_config(CacheConfig()),
            can_read_memo=CanReadMemo(),
            renderer=Renderer(),
            lut_provider=LutProvider(),
        )
        handler = ImageRegionHandler(services)
        ctx = ImageRegionCtx.from_params(
            {"imageId": "2", "theZ": "0", "theT": "0",
             "tile": "0,0,0,32,32", "m": "c", "c": "1|0:60000$FF0000"},
            "sess-labmate")
        body = run(handler.render_image_region(ctx))
        assert body[:2] == b"\xff\xd8"

        denied = ImageRegionCtx.from_params(
            {"imageId": "2", "theZ": "0", "theT": "0",
             "tile": "0,0,0,32,32", "m": "c", "c": "1|0:60000$FF0000"},
            "sess-outsider")
        with pytest.raises(NotFoundError):
            run(handler.render_image_region(denied))


class TestBinaryRepoResolution:
    """Image -> repository path resolution (the file-path resolver bean,
    ``beanRefContext.xml:13-16``; ``config.yaml:18-20`` omero.data.dir)."""

    def test_fileset_image_resolves_managed_repo_paths(self, db):
        svc = DbMetadataService(db)
        paths = run(svc.resolve_image_paths(4))
        assert paths == [
            "ManagedRepository/demo_2/2026-07/31/a.fake",
            "ManagedRepository/demo_2/2026-07/31/img.ome.tiff",
        ]

    def test_prefs_image_falls_back_to_pixels_file(self, db):
        svc = DbMetadataService(db)
        assert run(svc.resolve_image_paths(5)) == ["Pixels/53"]

    def test_unknown_image_resolves_nothing(self, db):
        svc = DbMetadataService(db)
        assert run(svc.resolve_image_paths(999)) == []

    @staticmethod
    def _services(db, tmp_path, repo_root):
        from omero_ms_image_region_tpu.io.service import PixelsService
        from omero_ms_image_region_tpu.ops.lut import LutProvider
        from omero_ms_image_region_tpu.server.handler import (
            ImageRegionServices, Renderer)
        from omero_ms_image_region_tpu.services.cache import (
            CacheConfig, Caches)
        from omero_ms_image_region_tpu.services.metadata import CanReadMemo

        return ImageRegionServices(
            pixels_service=PixelsService(str(tmp_path / "data"),
                                         repo_root=str(repo_root)),
            metadata=DbMetadataService(db),
            caches=Caches.from_config(CacheConfig()),
            can_read_memo=CanReadMemo(),
            renderer=Renderer(),
            lut_provider=LutProvider(),
        )

    def test_e2e_serves_from_managed_repository(self, db, tmp_path):
        """A fileset image renders straight out of a mounted repository
        tree, with zero re-arrangement into the data_dir layout."""
        from omero_ms_image_region_tpu.io.tiffwrite import write_ome_tiff
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        from omero_ms_image_region_tpu.server.handler import (
            ImageRegionHandler)

        rng = np.random.default_rng(9)
        planes = rng.integers(0, 60000, (2, 1, 64, 96)).astype(np.uint16)
        repo = tmp_path / "OMERO"
        d = repo / "ManagedRepository" / "demo_2" / "2026-07" / "31"
        d.mkdir(parents=True)
        write_ome_tiff(planes, str(d / "img.ome.tiff"), tile=(32, 32),
                       n_levels=1)
        (d / "a.fake").write_bytes(b"not pixel data")

        handler = ImageRegionHandler(self._services(db, tmp_path, repo))
        ctx = ImageRegionCtx.from_params(
            {"imageId": "4", "theZ": "0", "theT": "0",
             "region": "0,0,96,64", "m": "g", "c": "1|0:60000$FFFFFF",
             "format": "png"},
            "sess-owner")
        body = run(handler.render_image_region(ctx))
        assert body[:8] == b"\x89PNG\r\n\x1a\n"
        from PIL import Image as PILImage
        import io as _io
        img = np.asarray(PILImage.open(_io.BytesIO(body)).convert("L"))
        want = np.round(
            planes[0, 0].astype(np.float64) / 60000 * 255
        ).clip(0, 255).astype(np.uint8)
        assert np.abs(img.astype(int) - want.astype(int)).max() <= 1

    def test_e2e_serves_prefs_romio_file(self, db, tmp_path):
        """A pre-FS image serves from the legacy big-endian
        Pixels/<pixels_id> file."""
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        from omero_ms_image_region_tpu.server.handler import (
            ImageRegionHandler)

        rng = np.random.default_rng(10)
        planes = rng.integers(0, 60000, (2, 32, 48)).astype(np.uint16)
        repo = tmp_path / "OMERO"
        (repo / "Pixels").mkdir(parents=True)
        # ROMIO layout: big-endian planes, z fastest.
        (repo / "Pixels" / "53").write_bytes(
            planes.astype(">u2").tobytes())

        handler = ImageRegionHandler(self._services(db, tmp_path, repo))
        ctx = ImageRegionCtx.from_params(
            {"imageId": "5", "theZ": "1", "theT": "0",
             "region": "8,4,24,16", "m": "g", "c": "1|0:60000$FFFFFF",
             "format": "png"},
            "sess-owner")
        body = run(handler.render_image_region(ctx))
        from PIL import Image as PILImage
        import io as _io
        img = np.asarray(PILImage.open(_io.BytesIO(body)).convert("L"))
        want = np.round(
            planes[1, 4:20, 8:32].astype(np.float64) / 60000 * 255
        ).clip(0, 255).astype(np.uint8)
        assert np.abs(img.astype(int) - want.astype(int)).max() <= 1

    def test_local_layout_still_wins(self, db, tmp_path):
        """An image present in data_dir never consults the repository."""
        from omero_ms_image_region_tpu.io.store import build_pyramid

        rng = np.random.default_rng(11)
        planes = rng.integers(0, 60000, (2, 1, 32, 32)).astype(np.uint16)
        build_pyramid(planes, str(tmp_path / "data" / "4"), n_levels=1)
        repo = tmp_path / "OMERO"
        repo.mkdir()
        svc = self._services(db, tmp_path, repo)
        src = svc.pixels_service.get_pixel_source(4)
        from omero_ms_image_region_tpu.io.store import ChunkedPyramidStore
        assert isinstance(src, ChunkedPyramidStore)


def test_romio_dir_fanout_paths():
    """ids >= 1000 nest into Dir-### groups
    (ome.io.nio.AbstractFileSystemService)."""
    from omero_ms_image_region_tpu.services.db_metadata import (
        _romio_rel_path)
    assert _romio_rel_path(53) == "Pixels/53"
    assert _romio_rel_path(999) == "Pixels/999"
    assert _romio_rel_path(1234) == "Pixels/Dir-001/1234"
    assert _romio_rel_path(1234567) == "Pixels/Dir-001/Dir-234/1234567"
    assert _romio_rel_path(1000) == "Pixels/Dir-001/1000"


def test_vendor_named_repo_file_resolves(tmp_path, db):
    """A fileset whose file is named .svs (an Aperio TIFF) serves from
    the repository — TIFF-based vendor names must not be filtered out
    by suffix."""
    import numpy as np

    from omero_ms_image_region_tpu.io.ometiff import OmeTiffSource
    from omero_ms_image_region_tpu.io.service import PixelsService
    from omero_ms_image_region_tpu.io.tiffwrite import write_ome_tiff

    rng = np.random.default_rng(12)
    planes = rng.integers(0, 60000, (1, 1, 32, 32)).astype(np.uint16)
    repo = tmp_path / "OMERO"
    d = repo / "ManagedRepository" / "lab"
    d.mkdir(parents=True)
    write_ome_tiff(planes, str(d / "slide.svs"), tile=(32, 32),
                   n_levels=1)
    svc = PixelsService(str(tmp_path / "data"), repo_root=str(repo))
    src = svc.get_pixel_source(
        42, candidates=["ManagedRepository/lab/slide.svs"])
    assert isinstance(src, OmeTiffSource)
    svc.close()
