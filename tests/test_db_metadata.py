"""DbMetadataService against a seeded OMERO-schema subset (sqlite).

The service's SQL is written for asyncpg/PostgreSQL; the adapter here
translates only the placeholder style ($N -> ?) so the very same
statements execute against sqlite — an e2e check of the queries, the
group-permission ACL bits, and the session resolution, without a live
OMERO database (this image ships no Postgres driver or server;
``PostgresMetadataService.connect`` stays gated on asyncpg).
"""

import asyncio
import re
import sqlite3

import numpy as np
import pytest

from omero_ms_image_region_tpu.services.db_metadata import (
    DbMetadataService, GROUP_READ, USER_READ, WORLD_READ,
)

# Canonical OMERO permission longs (ome.model.internal.Permissions).
PRIVATE = -120        # rw----
GROUP_RO = -56        # rwr---
PUBLIC_RO = -52       # rwr-r-


class SqliteDb:
    """fetchrow/fetch over sqlite with $N -> ? placeholder translation."""

    def __init__(self, conn: sqlite3.Connection):
        conn.row_factory = sqlite3.Row
        self.conn = conn

    @staticmethod
    def _translate(sql: str) -> str:
        return re.sub(r"\$\d+", "?", sql)

    async def fetchrow(self, sql: str, *args):
        cur = self.conn.execute(self._translate(sql), args)
        row = cur.fetchone()
        return None if row is None else dict(row)

    async def fetch(self, sql: str, *args):
        cur = self.conn.execute(self._translate(sql), args)
        return [dict(r) for r in cur.fetchall()]


SCHEMA = """
CREATE TABLE experimentergroup (
    id INTEGER PRIMARY KEY, name TEXT, permissions INTEGER);
CREATE TABLE experimenter (id INTEGER PRIMARY KEY, omename TEXT);
CREATE TABLE groupexperimentermap (child INTEGER, parent INTEGER);
CREATE TABLE session (
    id INTEGER PRIMARY KEY, uuid TEXT, owner INTEGER, closed TEXT);
CREATE TABLE image (
    id INTEGER PRIMARY KEY, owner_id INTEGER, group_id INTEGER);
CREATE TABLE pixelstype (id INTEGER PRIMARY KEY, value TEXT);
CREATE TABLE pixels (
    id INTEGER PRIMARY KEY, image INTEGER, sizex INTEGER, sizey INTEGER,
    sizez INTEGER, sizec INTEGER, sizet INTEGER, pixelstype INTEGER);
CREATE TABLE roi (id INTEGER PRIMARY KEY, image INTEGER);
CREATE TABLE shape (
    id INTEGER PRIMARY KEY, roi INTEGER, owner_id INTEGER,
    group_id INTEGER, width INTEGER, height INTEGER, bytes BLOB,
    fillcolor INTEGER);
"""

MASK_BITS = np.packbits(
    np.tile([1, 0], 16 * 8 // 2).astype(np.uint8)).tobytes()


@pytest.fixture()
def db():
    conn = sqlite3.connect(":memory:")
    conn.executescript(SCHEMA)
    conn.executemany(
        "INSERT INTO experimentergroup VALUES (?, ?, ?)",
        [(0, "system", PRIVATE),
         (10, "lab-private", PRIVATE),
         (11, "lab-shared", GROUP_RO),
         (12, "atlas-public", PUBLIC_RO)])
    conn.executemany(
        "INSERT INTO experimenter VALUES (?, ?)",
        [(100, "owner"), (101, "labmate"), (102, "outsider"),
         (103, "root")])
    conn.executemany(
        "INSERT INTO groupexperimentermap VALUES (?, ?)",
        [(100, 10), (100, 11), (100, 12),
         (101, 10), (101, 11),
         (102, 12),
         (103, 0)])
    conn.executemany(
        "INSERT INTO session VALUES (?, ?, ?, ?)",
        [(1, "sess-owner", 100, None),
         (2, "sess-labmate", 101, None),
         (3, "sess-outsider", 102, None),
         (4, "sess-root", 103, None),
         (5, "sess-closed", 100, "2026-01-01 00:00:00")])
    conn.executemany(
        "INSERT INTO image VALUES (?, ?, ?)",
        [(1, 100, 10),     # private image
         (2, 100, 11),     # group-readable image
         (3, 100, 12)])    # world-readable image
    conn.execute("INSERT INTO pixelstype VALUES (1, 'uint16')")
    conn.execute("INSERT INTO pixelstype VALUES (2, 'uint8')")
    conn.executemany(
        "INSERT INTO pixels VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        [(50, 1, 4096, 4096, 16, 4, 1, 1),
         (51, 2, 512, 256, 1, 3, 1, 2)])
    conn.execute("INSERT INTO roi VALUES (7, 2)")
    # mask on the group-readable image; fillcolor = RGBA 0x00FF00FF
    conn.execute(
        "INSERT INTO shape VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        (70, 7, 100, 11, 16, 8, MASK_BITS, 0x00FF00FF))
    # mask with no fillcolor in the private group
    conn.execute(
        "INSERT INTO shape VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
        (71, 7, 100, 10, 16, 8, MASK_BITS, None))
    conn.commit()
    return SqliteDb(conn)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestPermissionBits:
    def test_documented_longs_decode(self):
        assert PRIVATE & USER_READ and not PRIVATE & GROUP_READ
        assert GROUP_RO & GROUP_READ and not GROUP_RO & WORLD_READ
        assert PUBLIC_RO & WORLD_READ


class TestCanRead:
    @pytest.mark.parametrize("image_id,session,expect", [
        (1, "sess-owner", True),      # owner reads own private image
        (1, "sess-labmate", False),   # member, but group is rw----
        (1, "sess-outsider", False),
        (1, "sess-root", True),       # admin reads everything
        (1, None, False),
        (2, "sess-owner", True),
        (2, "sess-labmate", True),    # member of rwr--- group
        (2, "sess-outsider", False),  # non-member, no world read
        (2, None, False),
        (3, "sess-outsider", True),   # member of public group
        (3, None, True),              # anonymous world read
    ])
    def test_image_acl(self, db, image_id, session, expect):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", image_id, session)) is expect

    def test_closed_session_is_anonymous(self, db):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", 1, "sess-closed")) is False
        assert run(svc.can_read("Image", 3, "sess-closed")) is True

    def test_unknown_object_is_unreadable(self, db):
        svc = DbMetadataService(db)
        assert run(svc.can_read("Image", 999, "sess-root")) is False


class TestPixels:
    def test_resolves_geometry_and_type(self, db):
        svc = DbMetadataService(db)
        px = run(svc.get_pixels_description(1, "sess-owner"))
        assert (px.size_x, px.size_y, px.size_z, px.size_c, px.size_t) \
            == (4096, 4096, 16, 4, 1)
        assert px.pixels_type == "uint16"
        assert px.type.np_dtype == np.dtype("uint16")

    def test_acl_gates_pixels(self, db):
        svc = DbMetadataService(db)
        assert run(svc.get_pixels_description(1, "sess-labmate")) is None
        assert run(svc.get_pixels_description(2, "sess-labmate")) \
            is not None


class TestMask:
    def test_mask_with_fillcolor(self, db):
        svc = DbMetadataService(db)
        mask = run(svc.get_mask(70, "sess-labmate"))
        assert (mask.width, mask.height) == (16, 8)
        assert mask.bytes_ == MASK_BITS
        assert mask.fill_color == (0, 255, 0, 255)

    def test_mask_without_fillcolor(self, db):
        svc = DbMetadataService(db)
        mask = run(svc.get_mask(71, "sess-owner"))
        assert mask.fill_color is None

    def test_mask_acl(self, db):
        svc = DbMetadataService(db)
        assert run(svc.get_mask(71, "sess-labmate")) is None  # rw---- group
        assert run(svc.get_mask(70, "sess-outsider")) is None


class TestHandlerIntegration:
    def test_image_handler_serves_via_db_metadata(self, db, tmp_path):
        """The HTTP handler stack runs unchanged on the DB backend."""
        from omero_ms_image_region_tpu.io.store import build_pyramid

        rng = np.random.default_rng(3)
        planes = rng.integers(0, 60000, (3, 1, 64, 64)).astype(np.uint16)
        build_pyramid(planes, str(tmp_path / "2"), n_levels=1)

        from omero_ms_image_region_tpu.io.service import PixelsService
        from omero_ms_image_region_tpu.ops.lut import LutProvider
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        from omero_ms_image_region_tpu.server.handler import (
            ImageRegionHandler, ImageRegionServices, NotFoundError, Renderer)
        from omero_ms_image_region_tpu.services.cache import (
            CacheConfig, Caches)
        from omero_ms_image_region_tpu.services.metadata import CanReadMemo

        services = ImageRegionServices(
            pixels_service=PixelsService(str(tmp_path)),
            metadata=DbMetadataService(db),
            caches=Caches.from_config(CacheConfig()),
            can_read_memo=CanReadMemo(),
            renderer=Renderer(),
            lut_provider=LutProvider(),
        )
        handler = ImageRegionHandler(services)
        ctx = ImageRegionCtx.from_params(
            {"imageId": "2", "theZ": "0", "theT": "0",
             "tile": "0,0,0,32,32", "m": "c", "c": "1|0:60000$FF0000"},
            "sess-labmate")
        body = run(handler.render_image_region(ctx))
        assert body[:2] == b"\xff\xd8"

        denied = ImageRegionCtx.from_params(
            {"imageId": "2", "theZ": "0", "theT": "0",
             "tile": "0,0,0,32,32", "m": "c", "c": "1|0:60000$FF0000"},
            "sess-outsider")
        with pytest.raises(NotFoundError):
            run(handler.render_image_region(denied))
