"""scripts/metrics_lint.py — the committed cardinality budget.

Three contracts:

* the committed budget (conf/metrics_budget.json) is CONSISTENT with
  the live METRIC_TYPES registry (no stale families, every label
  bounded, products within budget);
* a REAL exposition — request + provenance + robustness + fleet
  families, exemplars included — lints clean against it;
* a smuggled label (new key on an existing family, or a family that
  never registered) FAILS, mechanically.
"""

import importlib.util
import os

import pytest

from omero_ms_image_region_tpu.utils import provenance, telemetry

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(scope="module")
def lint():
    return _load_script("metrics_lint")


@pytest.fixture(scope="module")
def budget(lint):
    return lint.load_budget()


class TestRegistryBudget:
    def test_committed_budget_is_clean(self, lint, budget):
        assert lint.lint_registry(budget) == []

    def test_unbounded_label_fails(self, lint, budget):
        import copy
        bad = copy.deepcopy(budget)
        bad["families"]["imageregion_provenance_total"]["labels"] \
            .append("session")
        findings = lint.lint_registry(bad)
        assert any("session" in f for f in findings)

    def test_stale_family_fails(self, lint, budget):
        import copy
        bad = copy.deepcopy(budget)
        bad["families"]["imageregion_made_up_total"] = {"labels": []}
        findings = lint.lint_registry(bad)
        assert any("imageregion_made_up_total" in f for f in findings)

    def test_product_over_budget_fails(self, lint, budget):
        import copy
        bad = copy.deepcopy(budget)
        bad["families"]["imageregion_provenance_total"][
            "max_series"] = 2
        findings = lint.lint_registry(bad)
        assert any("label product" in f for f in findings)


class TestExpositionBudget:
    def _exposition(self) -> str:
        # Exercise the labeled families the budget is really about:
        # request histogram WITH an exemplar, provenance counters,
        # fleet + robustness labels.
        telemetry.REQUEST_HIST.observe(
            "render_image_region", 41.0,
            exemplar=("a1b2c3d4e5f60718", "render_cold"))
        telemetry.count_request("render_image_region", 200)
        telemetry.PROVENANCE.count(
            {"tier": "render_cold", "member": "m1", "stolen": 1})
        telemetry.PROVENANCE.count({"tier": "byte_cache"})
        telemetry.FLEET.count_routed("m0")
        telemetry.HOTKEY.count_promoted()
        telemetry.HOTKEY.count_balanced("m0")
        telemetry.PRESSURE.set_signal("hbm_frac", 0.5)
        telemetry.QOS.count_shed("bulk")
        telemetry.RESILIENCE.count_retry("image")
        return telemetry.finalize_exposition(
            telemetry.request_metric_lines(exemplars=True)
            + telemetry.robustness_metric_lines()
            + telemetry.fleet_metric_lines())

    def test_real_exposition_is_clean(self, lint, budget):
        assert lint.lint_exposition(self._exposition(), budget) == []

    def test_smuggled_label_key_fails(self, lint, budget):
        text = self._exposition() + (
            '\nimageregion_provenance_total{tier="peer",'
            'image="12345"} 1\n')
        findings = lint.lint_exposition(text, budget)
        assert any("image" in f and "provenance" in f
                   for f in findings)

    def test_unregistered_family_fails(self, lint, budget):
        text = self._exposition() + "\nimageregion_rogue_total 1\n"
        findings = lint.lint_exposition(text, budget)
        assert any("imageregion_rogue_total" in f for f in findings)

    def test_label_on_labelfree_family_fails(self, lint, budget):
        # A family the budget does NOT list gets labels=[] — any
        # label on it is the smuggle the check exists for.
        text = self._exposition() + (
            '\nimageregion_httpcache_304_total{member="m0"} 1\n')
        findings = lint.lint_exposition(text, budget)
        assert any("imageregion_httpcache_304_total" in f
                   for f in findings)

    def test_exemplar_tail_tolerated(self, lint, budget):
        text = self._exposition()
        assert " # {" in text, "exemplar did not reach exposition"
        assert lint.lint_exposition(text, budget) == []

    def test_tier_vocabulary_is_closed(self):
        # A drifted tier string never reaches the label set.
        telemetry.PROVENANCE.count({"tier": "made-up-tier",
                                    "member": "m9"})
        lines = telemetry.PROVENANCE.metric_lines()
        assert any('tier="render_cold"' in ln for ln in lines)
        assert not any("made-up" in ln for ln in lines)
        for tier in provenance.TIERS:
            assert provenance.assemble(
                type("C", (), {"tile": None, "region": None,
                               "projection": None})(), 200
            )["tier"] in provenance.TIERS
