"""JPEG 2000 decoder vs the openjpeg oracle (via PIL), plus the
TIFF 33003/33005 (Aperio) integration and fuzz.

Closes the last Bio-Formats format gap named in round-3's review: SVS
and vendor WSI pyramids that store JPEG 2000 tiles.
"""

import io
import os
import struct

import numpy as np
import pytest
from PIL import Image

from omero_ms_image_region_tpu.io.jp2k import (Jp2kError, decode_jp2k,
                                               decode_tiff_jp2k)
from omero_ms_image_region_tpu.io.ometiff import OmeTiffSource
from omero_ms_image_region_tpu.io.tiff import TiffFile
from omero_ms_image_region_tpu.server.region import RegionDef


def _enc(img, **kw):
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG2000", **kw)
    return buf.getvalue()


def _oracle(data):
    ref = np.asarray(Image.open(io.BytesIO(data)))
    return ref[:, :, None] if ref.ndim == 2 else ref


from vendor_tiff import smooth_rgb as _smooth_rgb  # noqa: E402
from vendor_tiff import write_jp2k_tiff as _write_jp2k_tiff  # noqa: E402


# --------------------------------------------------------- codestreams

class TestLossless:
    """5/3 reversible streams must decode EXACTLY."""

    @pytest.mark.parametrize("size", [(4, 4), (16, 16), (17, 13),
                                      (64, 64), (33, 70)])
    def test_gray_exact(self, size):
        rng = np.random.default_rng(hash(size) % 1000)
        a = rng.integers(0, 256, size, dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False))
        np.testing.assert_array_equal(got[:, :, 0], a)

    def test_rgb_rct_exact(self):
        rng = np.random.default_rng(3)
        a = rng.integers(0, 256, (48, 80, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False))
        np.testing.assert_array_equal(got, a)

    def test_quality_layers_exact(self):
        rng = np.random.default_rng(4)
        a = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False,
                               quality_layers=[40, 20, 0]))
        np.testing.assert_array_equal(got, a)

    def test_tiled_exact(self):
        rng = np.random.default_rng(5)
        a = rng.integers(0, 256, (48, 80, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False,
                               tile_size=(32, 32)))
        np.testing.assert_array_equal(got, a)

    def test_explicit_precincts_exact(self):
        rng = np.random.default_rng(6)
        a = rng.integers(0, 256, (48, 80, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False,
                               precinct_size=(64, 64)))
        np.testing.assert_array_equal(got, a)

    def test_small_codeblocks_exact(self):
        rng = np.random.default_rng(7)
        a = rng.integers(0, 256, (48, 80), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False,
                               codeblock_size=(16, 16)))
        np.testing.assert_array_equal(got[:, :, 0], a)

    def test_raw_j2k_codestream(self, tmp_path):
        rng = np.random.default_rng(8)
        a = rng.integers(0, 256, (32, 32), dtype=np.uint8)
        path = str(tmp_path / "x.j2k")
        Image.fromarray(a).save(path, irreversible=False)
        data = open(path, "rb").read()
        assert data[:2] == b"\xff\x4f"     # SOC, no JP2 wrapper
        np.testing.assert_array_equal(
            decode_jp2k(data)[:, :, 0], a)


class TestLossy:
    """9/7 irreversible streams must match openjpeg's own decode
    within float rounding."""

    def test_gray(self):
        yy, xx = np.mgrid[0:64, 0:96]
        a = (xx * 255 // 95).astype(np.uint8)
        data = _enc(a, irreversible=True)
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1

    def test_rgb_ict(self):
        data = _enc(_smooth_rgb(64, 96), irreversible=True)
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1

    def test_noise(self):
        rng = np.random.default_rng(9)
        a = rng.integers(0, 256, (40, 56, 3), dtype=np.uint8)
        data = _enc(a, irreversible=True)
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1

    def test_rate_truncated(self):
        data = _enc(_smooth_rgb(64, 96), irreversible=True,
                    quality_layers=[30])
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1

    def test_tiles(self):
        data = _enc(_smooth_rgb(64, 96), irreversible=True,
                    tile_size=(32, 32))
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1


class TestProgressionOrders:
    @pytest.mark.parametrize("order", ["LRCP", "RLCP", "RPCL",
                                       "PCRL", "CPRL"])
    def test_orders_decode_exactly(self, order):
        rng = np.random.default_rng(10)
        a = rng.integers(0, 256, (48, 64, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False,
                               progression=order))
        np.testing.assert_array_equal(got, a)


class Test16Bit:
    def test_uint16_lossless(self):
        rng = np.random.default_rng(11)
        a = rng.integers(0, 65535, (32, 40), dtype=np.uint16)
        # PIL writes 16-bit via mode I;16
        got = decode_jp2k(_enc(a, irreversible=False))
        assert got.dtype == np.uint16
        np.testing.assert_array_equal(got[:, :, 0], a)


# --------------------------------------------------------------- fuzz

class TestFuzz:
    def test_truncations_fail_cleanly_or_degrade(self):
        rng = np.random.default_rng(12)
        a = rng.integers(0, 256, (32, 32), dtype=np.uint8)
        data = _enc(a, irreversible=False)
        for cut in (1, 2, 10, 40, len(data) // 2, len(data) - 4):
            try:
                out = decode_jp2k(data[:cut])
            except (Jp2kError, ValueError):
                continue
            # JPEG 2000 is progressive: a truncated-but-parseable
            # stream legitimately decodes to a degraded image.
            assert out.shape == (32, 32, 1)

    def test_garbage_fails_cleanly(self):
        rng = np.random.default_rng(13)
        for n in (0, 2, 16, 256):
            blob = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            with pytest.raises((Jp2kError, ValueError)):
                decode_jp2k(b"\xff\x4f\xff\x51" + blob)

    def test_not_jp2k_rejected(self):
        with pytest.raises(Jp2kError, match="not a JPEG 2000"):
            decode_jp2k(b"II*\x00plainly-not")


# ------------------------------------------------------- TIFF (Aperio)

def test_tiff_33005_rgb(tmp_path):
    arr = _smooth_rgb(100, 150)
    path = str(tmp_path / "a.tif")
    _write_jp2k_tiff(path, arr, 33005, tile=64)
    src = OmeTiffSource(path)
    assert src.size_c == 3
    for c in range(3):
        got = src.get_region(0, c, 0, RegionDef(10, 20, 80, 60), 0)
        # Lossless tiles: exact except replicated-edge padding crops.
        np.testing.assert_array_equal(got, arr[20:80, 10:90, c])
    src.close()


def test_tiff_33003_ycbcr(tmp_path):
    arr = _smooth_rgb(64, 96)
    path = str(tmp_path / "y.tif")
    _write_jp2k_tiff(path, arr, 33003, tile=64, ycc=True)
    tf = TiffFile(path)
    got = tf.read_segment(tf.ifds[0], 0, 0)   # first 64x64 tile
    # YCbCr round trip (forward f32 + decode int) costs a little.
    assert np.abs(got.astype(int)
                  - arr[:64, :64].astype(int)).max() <= 3
    tf.close()


def test_tiff_jp2k_e2e(tmp_path):
    """33005 tiles serve through the HTTP app."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.server.config import AppConfig

    arr = _smooth_rgb(128, 128)
    d = tmp_path / "1"
    os.makedirs(d)
    _write_jp2k_tiff(str(d / "wsi.tif"), arr, 33005, tile=64)
    config = AppConfig(data_dir=str(tmp_path))

    async def fetch():
        app = create_app(config)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(
                "/webgateway/render_image_region/1/0/0"
                "?region=0,0,128,128"
                "&c=1|0:255$FF0000,2|0:255$00FF00,3|0:255$0000FF&m=c"
                "&format=png")
            assert r.status == 200
            return await r.read()
        finally:
            await client.close()

    body = asyncio.run(fetch())
    png = np.asarray(Image.open(io.BytesIO(body)).convert("RGB"))
    assert np.abs(png.astype(int) - arr.astype(int)).max() <= 1


class TestMCT:
    """Streams with the multiple-component transform ON (openjpeg CLI
    default for RGB; PIL defaults mct=0, so these set it explicitly)."""

    def test_rct_lossless_exact(self):
        rng = np.random.default_rng(14)
        a = rng.integers(0, 256, (40, 64, 3), dtype=np.uint8)
        got = decode_jp2k(_enc(a, irreversible=False, mct=1))
        np.testing.assert_array_equal(got, a)

    def test_ict_lossy(self):
        data = _enc(_smooth_rgb(64, 96), irreversible=True, mct=1)
        d = np.abs(decode_jp2k(data).astype(int) - _oracle(data))
        assert d.max() <= 1


class TestNativeT1:
    def test_python_fallback_stays_exact(self, monkeypatch):
        """The pure-Python Tier-1 remains a correct fallback when no
        toolchain builds the native library."""
        import omero_ms_image_region_tpu.io.jp2k as jp2k_mod

        monkeypatch.setattr(
            jp2k_mod, "_t1",
            lambda *a: jp2k_mod._t1_decode(*a[:7], half_at_zero=a[7]))
        rng = np.random.default_rng(15)
        a = rng.integers(0, 256, (32, 48, 3), dtype=np.uint8)
        got = jp2k_mod.decode_jp2k(_enc(a, irreversible=False))
        np.testing.assert_array_equal(got, a)

    def test_native_matches_python_per_block(self):
        native = pytest.importorskip("omero_ms_image_region_tpu.native")
        try:
            native._load_jp2kt1()
        except ImportError:
            pytest.skip("no toolchain")
        import omero_ms_image_region_tpu.io.jp2k as jp2k_mod

        # Collect real code-block payloads by decoding through a spy.
        seen = []
        orig = jp2k_mod._t1_decode

        def spy(data, w, h, npasses, msbs, orient, segsym,
                half_at_zero=False):
            out = orig(data, w, h, npasses, msbs, orient, segsym,
                       half_at_zero)
            seen.append(((data, w, h, npasses, msbs, orient, segsym,
                          half_at_zero), out))
            return out

        rng = np.random.default_rng(16)
        a = rng.integers(0, 256, (48, 48), dtype=np.uint8)
        data = _enc(a, irreversible=True, codeblock_size=(16, 16))
        old = jp2k_mod._t1
        jp2k_mod._t1 = lambda *args: spy(*args[:7],
                                         half_at_zero=args[7])
        try:
            jp2k_mod.decode_jp2k(data)
        finally:
            jp2k_mod._t1 = old
        assert seen
        for (args, want) in seen:
            got = native.jp2k_t1_decode(*args)
            np.testing.assert_array_equal(got, want)


class TestHostileHeaders:
    """Corrupt headers must not drive allocations or tile loops."""

    def _siz_stream(self, xsiz, ysiz, xtsiz, ytsiz):
        siz = struct.pack(">HIIIIIIIIH", 0, xsiz, ysiz, 0, 0,
                          xtsiz, ytsiz, 0, 0, 1) + bytes([7, 1, 1])
        return (b"\xff\x4f" + b"\xff\x51"
                + struct.pack(">H", 2 + len(siz)) + siz)

    def test_huge_image_area_rejected(self):
        with pytest.raises(Jp2kError, match="sample cap"):
            decode_jp2k(self._siz_stream(100000, 100000,
                                         100000, 100000))

    def test_huge_tile_grid_rejected(self):
        with pytest.raises(Jp2kError, match="tile cap|tile"):
            decode_jp2k(self._siz_stream(10000, 10000, 1, 1))

    def test_tile_part_local_cod_rejected(self):
        from omero_ms_image_region_tpu.io.jp2k import _find_codestream

        rng = np.random.default_rng(17)
        a = rng.integers(0, 256, (16, 16), dtype=np.uint8)
        data = _find_codestream(_enc(a, irreversible=False))
        # Splice a COD marker right after a SOT header (before SOD).
        sot = data.index(b"\xff\x90")
        sod = data.index(b"\xff\x93", sot)
        cod = (b"\xff\x52" + struct.pack(">H", 12)
               + bytes([0, 0, 0, 1, 0, 1, 4, 4, 0, 1]))
        spliced = data[:sod] + cod + data[sod:]
        # Fix Psot (tile-part length) so the splice stays in bounds.
        isot, psot = struct.unpack(">HI", spliced[sot + 4:sot + 10])
        spliced = (spliced[:sot + 6]
                   + struct.pack(">I", psot + len(cod))
                   + spliced[sot + 10:])
        with pytest.raises(Jp2kError, match="tile-part-local"):
            decode_jp2k(spliced)


def test_subsampled_components_upsample(monkeypatch):
    """4:2:0-style subsampled chroma (Aperio 33003) replicates up to
    the full grid instead of being rejected.  No encoder here can
    write subsampled J2K, so the stream is synthesized by decoding a
    full-res stream and shrinking the chroma components' registration
    in SIZ is out of reach — instead exercise the interleave path
    directly via the decoder internals."""
    import omero_ms_image_region_tpu.io.jp2k as jp2k_mod

    rng = np.random.default_rng(18)
    a = rng.integers(0, 256, (32, 32, 3), dtype=np.uint8)
    data = _enc(a, irreversible=False)
    dec = jp2k_mod._Decoder(jp2k_mod._find_codestream(data))
    # Pretend components 1/2 are 2x2-subsampled: halve their decoded
    # planes; the interleave must replicate them back to full size.
    orig = jp2k_mod._Decoder._decode_tile

    def shrunk(self, t):
        # The real codestream is full-resolution: decode it with the
        # pristine grids, then present components 1/2 as if the stream
        # had been 2x2-subsampled.
        for c in self.comps:
            c.dx = c.dy = 1
        try:
            planes = orig(self, t)
        finally:
            self.comps[1].dx = self.comps[1].dy = 2
            self.comps[2].dx = self.comps[2].dy = 2
        if planes is None:
            return None
        return [planes[0], planes[1][::2, ::2], planes[2][::2, ::2]]

    monkeypatch.setattr(jp2k_mod._Decoder, "_decode_tile", shrunk)
    # Subsampled grids the outer loop pastes into.
    dec.comps[1].dx = dec.comps[1].dy = 2
    dec.comps[2].dx = dec.comps[2].dy = 2
    out = dec.decode()
    assert out.shape == (32, 32, 3)
    np.testing.assert_array_equal(out[:, :, 0], a[:, :, 0])
    np.testing.assert_array_equal(out[::2, ::2, 1], a[::2, ::2, 1])
    assert (out[1::2, ::2, 1] == out[::2, ::2, 1]).all()  # replicated


def test_hostile_component_count_rejected():
    siz = struct.pack(">HIIIIIIIIH", 0, 1000, 1000, 0, 0,
                      1000, 1000, 0, 0, 100)
    siz += bytes([7, 1, 1]) * 100
    blob = (b"\xff\x4f" + b"\xff\x51"
            + struct.pack(">H", 2 + len(siz)) + siz)
    with pytest.raises(Jp2kError, match="component cap|64-component"):
        decode_jp2k(blob)


class TestErrorContract:
    """Residual malformed-header shapes must surface as Jp2kError (a
    ValueError), never IndexError/struct.error/AttributeError."""

    def test_qcd_even_body(self):
        # Style-1 QCD whose body length parses to a struct error.
        blob = (b"\xff\x4f"
                + b"\xff\x51" + struct.pack(">H", 41)
                + struct.pack(">HIIIIIIIIH", 0, 8, 8, 0, 0, 8, 8, 0,
                              0, 1) + bytes([7, 1, 1])
                + b"\xff\x5c" + struct.pack(">H", 4) + bytes([1, 0]))
        with pytest.raises(Jp2kError):
            decode_jp2k(blob + b"\xff\xd9")

    def test_truncated_jp2_box(self):
        sig = b"\x00\x00\x00\x0cjP  \r\n\x87\n"
        blob = sig + struct.pack(">I", 1) + b"jp2c" + b"\x00\x00"
        with pytest.raises((Jp2kError, ValueError)):
            decode_jp2k(blob)

    def test_sot_without_siz(self):
        blob = (b"\xff\x4f"
                + b"\xff\x90" + struct.pack(">H", 10)
                + struct.pack(">HIBB", 0, 14, 0, 1)
                + b"\xff\x93" + b"\xff\xd9")
        with pytest.raises((Jp2kError, ValueError)):
            decode_jp2k(blob)

    def test_deep_components_rejected(self):
        siz = struct.pack(">HIIIIIIIIH", 0, 8, 8, 0, 0, 8, 8, 0, 0,
                          1) + bytes([37, 1, 1])   # 38-bit depth
        blob = (b"\xff\x4f" + b"\xff\x51"
                + struct.pack(">H", 2 + len(siz)) + siz + b"\xff\xd9")
        with pytest.raises(Jp2kError, match="32-bit max"):
            decode_jp2k(blob)
