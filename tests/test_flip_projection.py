"""Flip and Z-projection ops vs the reference-semantics CPU implementation.

Flip geometries mirror ImageRegionRequestHandlerTest.java:107-200 (exhaustive
h/v/both incl. 1xN, Nx1, 1x1 and error cases).
"""

import numpy as np
import pytest

from omero_ms_image_region_tpu.models.rendering import Projection
from omero_ms_image_region_tpu.ops.flip import flip_image
from omero_ms_image_region_tpu.ops.projection import (
    check_projection_bounds,
    project_stack,
)
from omero_ms_image_region_tpu.refimpl import flip_ref, project_ref


@pytest.mark.parametrize("h,w", [(4, 6), (1, 5), (5, 1), (1, 1), (3, 3)])
@pytest.mark.parametrize(
    "fh,fv", [(True, False), (False, True), (True, True), (False, False)]
)
def test_flip_matches_reference(h, w, fh, fv):
    src = np.arange(h * w * 4, dtype=np.uint8).reshape(h, w, 4)
    got = np.asarray(flip_image(src, fh, fv))
    want = flip_ref(src, fh, fv)
    np.testing.assert_array_equal(got, want)


def test_flip_horizontal_golden():
    src = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int32)
    got = np.asarray(flip_image(src, True, False))
    np.testing.assert_array_equal(got, [[3, 2, 1], [6, 5, 4]])


def test_flip_vertical_golden():
    src = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int32)
    got = np.asarray(flip_image(src, False, True))
    np.testing.assert_array_equal(got, [[4, 5, 6], [1, 2, 3]])


def test_flip_null_raises():
    with pytest.raises(ValueError, match="null"):
        flip_image(None, True, False)


def test_flip_zero_size_raises():
    with pytest.raises(ValueError, match="0 size"):
        flip_image(np.zeros((0, 4)), True, False)


def test_flip_noop_returns_same():
    src = np.ones((2, 2))
    assert flip_image(src, False, False) is src


# ---------------------------------------------------------------- projection

def _stack(Z=8, H=4, W=4, seed=0, lo=0, hi=65535):
    rng = np.random.default_rng(seed)
    return rng.uniform(lo, hi, size=(Z, H, W)).astype(np.float32)


@pytest.mark.parametrize(
    "alg",
    [Projection.MAXIMUM_INTENSITY, Projection.MEAN_INTENSITY,
     Projection.SUM_INTENSITY],
)
@pytest.mark.parametrize("start,end,step", [(0, 7, 1), (2, 5, 1), (0, 7, 2),
                                            (3, 3, 1)])
def test_projection_matches_reference(alg, start, end, step):
    stack = _stack()
    got = np.asarray(
        project_stack(stack, alg, start, end, step, type_max=65535.0)
    )
    want = project_ref(stack, alg, start, end, step, type_max=65535.0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=0.51)


def test_max_is_inclusive_mean_exclusive_of_end():
    # Plane values = z index; start=0, end=3.
    stack = np.stack([np.full((2, 2), z, np.float32) for z in range(5)])
    mx = np.asarray(
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert mx[0, 0] == 3  # inclusive of end plane
    mean = np.asarray(
        project_stack(stack, Projection.MEAN_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert mean[0, 0] == pytest.approx(1.0)  # planes 0,1,2 only


def test_max_clamps_negative_to_zero():
    # Reference accumulator starts at 0 (ProjectionService.java:183).
    stack = np.full((3, 2, 2), -7.0, np.float32)
    mx = np.asarray(
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 2, 1, 65535.0)
    )
    assert (mx == 0).all()


def test_sum_clamps_to_type_max():
    stack = np.full((4, 2, 2), 60000.0, np.float32)
    s = np.asarray(
        project_stack(stack, Projection.SUM_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert (s == 65535.0).all()


def test_project_stack_validates_z_interval():
    stack = _stack(Z=4)
    with pytest.raises(ValueError, match="negative"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, -1, 2, 1, 65535.0)
    with pytest.raises(ValueError, match=">= 4"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 4, 1, 65535.0)
    with pytest.raises(ValueError, match="stepping"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 2, 0, 65535.0)


def test_projection_bounds_checks():
    with pytest.raises(ValueError, match="negative"):
        check_projection_bounds(-1, 3, 1, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match=">= 8"):
        check_projection_bounds(0, 8, 1, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match="stepping"):
        check_projection_bounds(0, 3, 0, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match="timepoint must be"):
        check_projection_bounds(0, 3, 1, 0, 5, 8, 3, 1)
    with pytest.raises(ValueError, match="channel index"):
        check_projection_bounds(0, 3, 1, 7, 0, 8, 3, 1)
