"""Flip and Z-projection ops vs the reference-semantics CPU implementation.

Flip geometries mirror ImageRegionRequestHandlerTest.java:107-200 (exhaustive
h/v/both incl. 1xN, Nx1, 1x1 and error cases).
"""

import numpy as np
import pytest

from omero_ms_image_region_tpu.models.rendering import Projection
from omero_ms_image_region_tpu.ops.flip import flip_image
from omero_ms_image_region_tpu.ops.projection import (
    check_projection_bounds,
    project_stack,
)
from omero_ms_image_region_tpu.refimpl import flip_ref, project_ref


@pytest.mark.parametrize("h,w", [(4, 6), (1, 5), (5, 1), (1, 1), (3, 3)])
@pytest.mark.parametrize(
    "fh,fv", [(True, False), (False, True), (True, True), (False, False)]
)
def test_flip_matches_reference(h, w, fh, fv):
    src = np.arange(h * w * 4, dtype=np.uint8).reshape(h, w, 4)
    got = np.asarray(flip_image(src, fh, fv))
    want = flip_ref(src, fh, fv)
    np.testing.assert_array_equal(got, want)


def test_flip_horizontal_golden():
    src = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int32)
    got = np.asarray(flip_image(src, True, False))
    np.testing.assert_array_equal(got, [[3, 2, 1], [6, 5, 4]])


def test_flip_vertical_golden():
    src = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int32)
    got = np.asarray(flip_image(src, False, True))
    np.testing.assert_array_equal(got, [[4, 5, 6], [1, 2, 3]])


def test_flip_null_raises():
    with pytest.raises(ValueError, match="null"):
        flip_image(None, True, False)


def test_flip_zero_size_raises():
    with pytest.raises(ValueError, match="0 size"):
        flip_image(np.zeros((0, 4)), True, False)


def test_flip_noop_returns_same():
    src = np.ones((2, 2))
    assert flip_image(src, False, False) is src


# ---------------------------------------------------------------- projection

def _stack(Z=8, H=4, W=4, seed=0, lo=0, hi=65535):
    rng = np.random.default_rng(seed)
    return rng.uniform(lo, hi, size=(Z, H, W)).astype(np.float32)


@pytest.mark.parametrize(
    "alg",
    [Projection.MAXIMUM_INTENSITY, Projection.MEAN_INTENSITY,
     Projection.SUM_INTENSITY],
)
@pytest.mark.parametrize("start,end,step", [(0, 7, 1), (2, 5, 1), (0, 7, 2),
                                            (3, 3, 1)])
def test_projection_matches_reference(alg, start, end, step):
    stack = _stack()
    got = np.asarray(
        project_stack(stack, alg, start, end, step, type_max=65535.0)
    )
    want = project_ref(stack, alg, start, end, step, type_max=65535.0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=0.51)


def test_max_is_inclusive_mean_exclusive_of_end():
    # Plane values = z index; start=0, end=3.
    stack = np.stack([np.full((2, 2), z, np.float32) for z in range(5)])
    mx = np.asarray(
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert mx[0, 0] == 3  # inclusive of end plane
    mean = np.asarray(
        project_stack(stack, Projection.MEAN_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert mean[0, 0] == pytest.approx(1.0)  # planes 0,1,2 only


def test_max_clamps_negative_to_zero():
    # Reference accumulator starts at 0 (ProjectionService.java:183).
    stack = np.full((3, 2, 2), -7.0, np.float32)
    mx = np.asarray(
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 2, 1, 65535.0)
    )
    assert (mx == 0).all()


def test_sum_clamps_to_type_max():
    stack = np.full((4, 2, 2), 60000.0, np.float32)
    s = np.asarray(
        project_stack(stack, Projection.SUM_INTENSITY, 0, 3, 1, 65535.0)
    )
    assert (s == 65535.0).all()


def test_project_stack_validates_z_interval():
    stack = _stack(Z=4)
    with pytest.raises(ValueError, match="negative"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, -1, 2, 1, 65535.0)
    with pytest.raises(ValueError, match=">= 4"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 4, 1, 65535.0)
    with pytest.raises(ValueError, match="stepping"):
        project_stack(stack, Projection.MAXIMUM_INTENSITY, 0, 2, 0, 65535.0)


def test_projection_bounds_checks():
    with pytest.raises(ValueError, match="negative"):
        check_projection_bounds(-1, 3, 1, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match=">= 8"):
        check_projection_bounds(0, 8, 1, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match="stepping"):
        check_projection_bounds(0, 3, 0, 0, 0, 8, 3, 1)
    with pytest.raises(ValueError, match="timepoint must be"):
        check_projection_bounds(0, 3, 1, 0, 5, 8, 3, 1)
    with pytest.raises(ValueError, match="channel index"):
        check_projection_bounds(0, 3, 1, 7, 0, 8, 3, 1)


# ------------------------------------------------ streaming projection

class TestProjectPlanes:
    """project_planes (WSI-scale streaming) vs project_stack parity and
    bounded reads — VERDICT item: ProjectionService.java:72,176-291."""

    @pytest.mark.parametrize("alg", [Projection.MAXIMUM_INTENSITY,
                                     Projection.MEAN_INTENSITY,
                                     Projection.SUM_INTENSITY])
    @pytest.mark.parametrize("start,end,step", [
        (0, 7, 1), (2, 5, 1), (1, 6, 2), (3, 3, 1), (0, 0, 1),
    ])
    def test_matches_full_stack_kernel(self, alg, start, end, step):
        from omero_ms_image_region_tpu.ops.projection import (
            project_planes, project_stack)
        rng = np.random.default_rng(4)
        stack = rng.integers(0, 60000, size=(8, 17, 23)).astype(np.float32)
        expected = np.asarray(project_stack(
            stack, alg, start, end, step, type_max=65535.0))
        got = np.asarray(project_planes(
            lambda z: stack[z], alg, 8, start, end, step,
            type_max=65535.0))
        np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_reads_only_window_planes(self):
        """Only planes inside the Z window are read — the whole point
        of streaming vs the reference's full-stack getStack."""
        from omero_ms_image_region_tpu.ops.projection import (
            project_planes)
        reads = []

        def get_plane(z):
            reads.append(z)
            return np.full((4, 4), z, np.float32)

        project_planes(get_plane, Projection.MAXIMUM_INTENSITY,
                       32, 10, 13, 1, 65535.0)
        assert reads == [10, 11, 12, 13]
        reads.clear()
        project_planes(get_plane, Projection.MEAN_INTENSITY,
                       32, 10, 13, 1, 65535.0)
        assert reads == [10, 11, 12]            # exclusive end

    def test_wsi_scale_bounded(self):
        """32-Z 4096^2 projection completes with one plane resident at
        a time (planes generated lazily; a full stack would be 2 GB)."""
        from omero_ms_image_region_tpu.ops.projection import (
            project_planes)
        H = W = 4096
        live = {"now": 0, "peak": 0}

        class Plane(np.ndarray):
            def __del__(self):
                live["now"] -= 1

        def get_plane(z):
            live["now"] += 1
            live["peak"] = max(live["peak"], live["now"])
            base = np.full((H, W), 100 * z, np.uint16)
            return base.view(Plane)

        out = np.asarray(project_planes(
            get_plane, Projection.MAXIMUM_INTENSITY, 32, 0, 31, 1,
            65535.0))
        assert out.shape == (H, W)
        assert out[0, 0] == 3100.0              # max over z: 100*31
        # Streaming keeps at most a couple of host planes alive, never
        # anything like the 32-plane stack.
        assert live["peak"] <= 4, live["peak"]

    def test_handler_projection_streams(self, tmp_path):
        """The serving projection path reads per-plane regions (never
        get_stack) and serves correct results end to end."""
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu import codecs
        from omero_ms_image_region_tpu.io.store import build_pyramid
        from omero_ms_image_region_tpu.io.service import PixelsService
        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.server.config import AppConfig

        rng = np.random.default_rng(5)
        planes = rng.integers(0, 60000, size=(1, 6, 64, 64)).astype(
            np.uint16)
        build_pyramid(planes, str(tmp_path / "1"), chunk=(32, 32),
                      n_levels=1)
        calls = {"get_stack": 0}
        orig = PixelsService.get_pixel_source

        def spying(self, image_id, candidates=None, pixels=None):
            src = orig(self, image_id, candidates, pixels)
            real = src.get_stack

            def counted(c, t):
                calls["get_stack"] += 1
                return real(c, t)
            src.get_stack = counted
            return src

        PixelsService.get_pixel_source = spying
        try:
            config = AppConfig(data_dir=str(tmp_path))

            async def fetch():
                app = create_app(config)
                client = TestClient(TestServer(app))
                await client.start_server()
                try:
                    r = await client.get(
                        "/webgateway/render_image_region/1/0/0"
                        "?c=1|0:60000$FF0000&m=g&p=intmax|1:4"
                        "&format=png")
                    assert r.status == 200
                    return await r.read()
                finally:
                    await client.close()

            body = asyncio.run(fetch())
        finally:
            PixelsService.get_pixel_source = orig
        assert calls["get_stack"] == 0
        rgba = codecs.decode_to_rgba(body)
        expected = planes[0, 1:5].astype(np.float32).max(axis=0)
        expected = np.clip(expected / 60000.0 * 255.0, 0, 255)
        np.testing.assert_allclose(
            rgba[..., 0].astype(np.float32), np.round(expected),
            atol=1.0)

    def test_empty_window_with_shape_reads_nothing(self):
        from omero_ms_image_region_tpu.ops.projection import (
            project_planes)
        reads = []

        def get_plane(z):
            reads.append(z)
            return np.zeros((4, 4), np.float32)

        out = np.asarray(project_planes(
            get_plane, Projection.MEAN_INTENSITY, 32, 3, 3, 1, 65535.0,
            shape=(4, 4)))
        assert reads == []
        np.testing.assert_array_equal(out, np.zeros((4, 4)))


class TestProjectRegionBanded:
    """Spatially-banded streaming projection: band-sized peak memory,
    exact parity with the full-stack kernel."""

    @pytest.mark.parametrize("placement", ["host", "device"])
    @pytest.mark.parametrize("alg", [
        Projection.MAXIMUM_INTENSITY, Projection.MEAN_INTENSITY,
        Projection.SUM_INTENSITY])
    @pytest.mark.parametrize("start,end,stepping", [
        (0, 7, 1), (2, 6, 2), (1, 1, 1), (3, 3, 1)])
    def test_parity_with_project_stack(self, alg, start, end, stepping,
                                       placement):
        """Both fold placements (host numpy, device jnp) match the
        full-stack kernel bit-for-bit in semantics."""
        from omero_ms_image_region_tpu.ops.projection import (
            project_region_banded, project_stack)

        rng = np.random.default_rng(44)
        # H=75 not divisible by band_rows=32: exercises the overlapped
        # last band.
        stack = rng.integers(0, 60000, size=(8, 75, 40)).astype(
            np.uint16)
        want = np.asarray(project_stack(
            stack.astype(np.float32), alg, start, end, stepping,
            65535.0))
        got = np.asarray(project_region_banded(
            lambda z, y0, h: stack[z, y0:y0 + h],
            alg, 8, start, end, stepping, 65535.0,
            plane_shape=(75, 40), band_rows=32, z_chunk=3,
            placement=placement))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-3)

    def test_auto_placement_folds_host_sources_on_host(self):
        """A numpy source must not upload the stack: auto placement
        folds host-side and ships one plane."""
        import jax.numpy as jnp

        from omero_ms_image_region_tpu.ops import projection as proj

        rng = np.random.default_rng(47)
        stack = rng.integers(0, 60000, size=(6, 64, 48)).astype(
            np.uint16)
        uploads = []
        orig = jnp.asarray

        def spy(x, *a, **k):
            if isinstance(x, np.ndarray) and x.ndim >= 2:
                uploads.append(x.shape)
            return orig(x, *a, **k)

        proj.jnp.asarray = spy
        try:
            got = np.asarray(proj.project_region_banded(
                lambda z, y0, h: stack[z, y0:y0 + h],
                Projection.MAXIMUM_INTENSITY, 6, 0, 5, 1, 65535.0,
                plane_shape=(64, 48), band_rows=32, z_chunk=4))
        finally:
            proj.jnp.asarray = orig
        # Exactly ONE device transfer: the finished projected plane.
        assert uploads == [(64, 48)]
        np.testing.assert_array_equal(
            got, stack.astype(np.float32).max(axis=0))

    def test_project_planes_host_placement_parity(self):
        from omero_ms_image_region_tpu.ops.projection import (
            project_planes, project_stack)

        rng = np.random.default_rng(48)
        stack = rng.integers(0, 60000, size=(5, 40, 40)).astype(
            np.uint16)
        for alg in (Projection.MAXIMUM_INTENSITY,
                    Projection.MEAN_INTENSITY,
                    Projection.SUM_INTENSITY):
            want = np.asarray(project_stack(
                stack.astype(np.float32), alg, 1, 4, 1, 65535.0))
            got = np.asarray(project_planes(
                lambda z: stack[z], alg, 5, 1, 4, 1, 65535.0,
                placement="host"))
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-3)

    def test_reads_are_band_bounded(self):
        from omero_ms_image_region_tpu.ops.projection import (
            project_region_banded)

        rng = np.random.default_rng(45)
        stack = rng.integers(0, 60000, size=(32, 128, 64)).astype(
            np.uint16)
        max_read_rows = []

        def get_band(z, y0, h):
            max_read_rows.append(h)
            return stack[z, y0:y0 + h]

        got = np.asarray(project_region_banded(
            get_band, Projection.MAXIMUM_INTENSITY, 32, 0, 31, 1,
            65535.0, plane_shape=(128, 64), band_rows=16, z_chunk=8))
        # Every read is at most one band tall — never a full plane.
        assert max(max_read_rows) <= 16
        assert len(max_read_rows) == 8 * 32   # 8 bands x 32 planes
        np.testing.assert_array_equal(
            got, stack.astype(np.float32).max(axis=0))

    def test_handler_uses_banding_above_threshold(self, monkeypatch):
        """A plane past the banding threshold projects through
        band-bounded reads end to end (asserted peak-read bound)."""
        import omero_ms_image_region_tpu.server.handler as handler_mod
        from omero_ms_image_region_tpu.io.memory import (
            InMemoryPixelSource)

        rng = np.random.default_rng(46)
        planes = rng.integers(0, 60000, size=(1, 6, 96, 80)).astype(
            np.uint16)
        src = InMemoryPixelSource(planes)
        read_rows = []
        orig = src.get_region

        def spy(z, c, t, region, level=0):
            read_rows.append(region.height)
            return orig(z, c, t, region, level)

        src.get_region = spy
        # 96x80 u16 = 15 KB: force the banded branch + small bands.
        monkeypatch.setattr(handler_mod,
                            "_PROJECTION_BAND_THRESHOLD_BYTES", 1024)
        monkeypatch.setattr(handler_mod, "_PROJECTION_BAND_BYTES",
                            32 * 80 * 4)

        from omero_ms_image_region_tpu.ops.lut import LutProvider
        from omero_ms_image_region_tpu.services.cache import (
            CacheConfig, Caches)
        from omero_ms_image_region_tpu.services.metadata import (
            CanReadMemo)

        class SrcPixelsService:
            repo_root = None

            def exists(self, image_id):
                return True

            def is_open(self, image_id):
                return True

            def get_pixel_source(self, image_id, candidates=None,
                                 pixels=None):
                return src

        class Meta:
            async def get_pixels_description(self, image_id, key):
                from omero_ms_image_region_tpu.models.pixels import (
                    Pixels)
                return Pixels(image_id=image_id, pixels_type="uint16",
                              size_x=80, size_y=96, size_z=6, size_c=1,
                              size_t=1)

            async def can_read(self, t, i, k):
                return True

        services = handler_mod.ImageRegionServices(
            pixels_service=SrcPixelsService(),
            metadata=Meta(),
            caches=Caches.from_config(CacheConfig()),
            can_read_memo=CanReadMemo(),
            renderer=handler_mod.Renderer(),
            lut_provider=LutProvider(),
        )
        handler = handler_mod.ImageRegionHandler(services)
        import asyncio
        from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
        ctx = ImageRegionCtx.from_params(
            {"imageId": "1", "theZ": "0", "theT": "0",
             "region": "0,0,80,96", "m": "g", "c": "1|0:60000$FFFFFF",
             "p": "intmax", "format": "png"}, None)
        body = asyncio.new_event_loop().run_until_complete(
            handler.render_image_region(ctx))
        assert body[:8] == b"\x89PNG\r\n\x1a\n"
        assert read_rows and max(read_rows) <= 64
        from PIL import Image as PILImage
        import io as _io
        img = np.asarray(PILImage.open(_io.BytesIO(body)).convert("L"))
        want = np.round(np.clip(
            planes[0].astype(np.float32).max(axis=0)
            / 60000.0 * 255.0, 0, 255))
        np.testing.assert_allclose(img.astype(np.float32), want, atol=1)
