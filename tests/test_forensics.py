"""Attribution-and-forensics layer: per-request cost ledger, flight
recorder, SLO burn-rate engine, on-demand profiling, the cancelled
queue-wait split, and the bench regression gate."""

import asyncio
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from omero_ms_image_region_tpu.utils import telemetry

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# ----------------------------------------------------------- cost ledger

class TestCostLedger:
    def test_trace_accumulates_costs(self):
        trace = telemetry.Trace("t1")
        trace.add_cost("device_ms", 2.0)
        trace.add_cost("device_ms", 3.0)
        assert trace.export_costs() == {"device_ms": 5.0}

    def test_add_cost_lands_on_every_context_trace(self):
        """A group render under group_trace attributes pro-rata to
        every member's ledger."""
        telemetry.TRACES.start("a")
        telemetry.TRACES.start("b")
        with telemetry.group_trace(("a", "b")):
            telemetry.add_cost("device_ms", 4.0)
        for tid in ("a", "b"):
            trace = telemetry.TRACES.finish(tid)
            assert trace.export_costs()["device_ms"] == 4.0

    def test_merge_costs_drops_malformed_fields(self):
        telemetry.TRACES.start("w")
        telemetry.merge_costs("w", {"device_ms": "3.5",
                                    "staged_bytes": None})
        costs = telemetry.TRACES.finish("w").export_costs()
        assert costs == {"device_ms": 3.5}

    def test_assemble_ledger_classes(self):
        trace = telemetry.Trace("t2", "r")
        trace.add_span("cache.hit", trace.t0, 0.5)
        ledger, cache_class = telemetry.assemble_ledger(trace, 10.0, 99)
        assert cache_class == "byte-cache"
        assert ledger["wire_bytes"] == 99
        assert ledger["total_ms"] == 10.0
        trace2 = telemetry.Trace("t3", "r")
        trace2.add_span("dedup.coalesced", trace2.t0, 0.5)
        assert telemetry.assemble_ledger(trace2, 1.0, 1)[1] == "coalesced"
        assert telemetry.assemble_ledger(
            telemetry.Trace("t4", "r"), 1.0, 1)[1] == "render"

    def test_topk_is_bounded_and_sorted(self):
        topk = telemetry.CostTopK(k=3)
        for ms in (5.0, 1.0, 9.0, 7.0, 3.0):
            topk.offer({"total_ms": ms})
        snap = topk.snapshot()
        assert [d["total_ms"] for d in snap] == [9.0, 7.0, 5.0]
        assert topk.observed == 5

    def test_cost_histograms_feed_per_route(self):
        telemetry.observe_request_cost("r", {
            "device_ms": 2.0, "staged_bytes": 2048, "wire_bytes": 1024,
            "queue_ms": 1.0})
        lines = telemetry.cost_metric_lines()
        text = "\n".join(lines)
        assert 'imageregion_request_cost_device_ms_count{route="r"} 1' \
            in text
        # Byte fields convert to KB for the log-scale buckets.
        assert 'imageregion_request_cost_staged_kb_sum{route="r"} 2' \
            in text
        assert 'imageregion_request_cost_wire_kb_sum{route="r"} 1' \
            in text


# ------------------------------------------------------- flight recorder

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = telemetry.FlightRecorder(maxlen=16)
        for i in range(100):
            rec.record("e", i=i)
        assert len(rec) == 16
        assert rec.events_total == 100
        assert rec.snapshot()[-1]["i"] == 99

    def test_configure_preserves_events(self):
        rec = telemetry.FlightRecorder(maxlen=32)
        rec.record("a")
        rec.configure(64)
        assert [e["kind"] for e in rec.snapshot()] == ["a"]

    def test_dump_roundtrips_through_trace_report(self, tmp_path):
        rec = telemetry.FlightRecorder()
        rec.record("admission.shed", reason="queue-full", inflight=64)
        rec.record("breaker.open", op="image")
        path = rec.dump(str(tmp_path), "test")
        assert path and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert doc["flight_recorder"] is True
        assert doc["reason"] == "test"
        assert [e["kind"] for e in doc["events"]] == [
            "admission.shed", "breaker.open"]
        mod = _load_script("trace_report")
        out = mod.render_doc(doc)
        assert "flight recorder" in out
        assert "admission.shed" in out and "reason=queue-full" in out

    def test_trace_report_renders_robustness_timeline(self, tmp_path):
        """Pressure transitions, ladder steps, watchdog fires and
        drain phases are marked on the flight timeline and rolled
        into a self-preservation summary — a post-incident dump tells
        the whole degrade-by-choice story."""
        rec = telemetry.FlightRecorder()
        rec.record("pressure.level", level="elevated", prev="ok",
                   queue=52.0)
        rec.record("pressure.step", step="pause_prefetch",
                   action="engage", engaged=1)
        rec.record("watchdog.fire", action="requeue-group",
                   target="lane:2x256x256", age_s=0.42, tiles=3)
        rec.record("drain.phase", member="m1", phase="drained",
                   settled=True, planes=12, prestaged=12)
        rec.record("pressure.step", step="pause_prefetch",
                   action="release", engaged=0)
        path = rec.dump(str(tmp_path), "incident")
        with open(path) as f:
            doc = json.load(f)
        mod = _load_script("trace_report")
        out = mod.render_doc(doc)
        assert "pressure.level" in out
        assert "watchdog.fire" in out and "action=requeue-group" in out
        assert "drain.phase" in out and "phase=drained" in out
        assert "self-preservation:" in out
        assert "pressure.step:engage:pause_prefetch=1" in out
        assert "watchdog.fire:requeue-group=1" in out
        assert "drain:drained=1" in out

    def test_trace_report_renders_autoscale_events(self, tmp_path):
        """Autoscaler transitions and refusals join the
        self-preservation footer: a post-incident dump says when the
        fleet grew/shrank and why a wanted move was refused."""
        rec = telemetry.FlightRecorder()
        rec.record("autoscale.down", member="m2", active=2, queue=0)
        rec.record("autoscale.up", member="m2", active=3, queue=31)
        rec.record("autoscale.blocked", reason="cooldown",
                   want="down")
        rec.record("autoscale.blocked", reason="floor", want="down")
        path = rec.dump(str(tmp_path), "elastic")
        with open(path) as f:
            doc = json.load(f)
        mod = _load_script("trace_report")
        out = mod.render_doc(doc)
        assert "self-preservation:" in out
        assert "autoscale.down:m2=1" in out
        assert "autoscale.up:m2=1" in out
        assert "autoscale.blocked:cooldown=1" in out
        assert "autoscale.blocked:floor=1" in out

    def test_trace_report_renders_quorum_epoch_events(self, tmp_path):
        """PR 18's partition-tolerance events (quorum fence/restore,
        two-phase epoch propose/commit) are marked on the flight
        timeline and rolled into the self-preservation footer — a
        netsplit post-mortem reads when each island fenced, with what
        reachability, and which epoch the majority rolled."""
        rec = telemetry.FlightRecorder()
        rec.record("quorum.fence", host="hostC", reachable=1, hosts=3)
        rec.record("epoch.propose", epoch=2, digest="201e036bb714",
                   by="hostA")
        rec.record("epoch.commit", epoch=2, digest="201e036bb714",
                   by="hostA")
        rec.record("quorum.restore", host="hostC", reachable=3,
                   hosts=3)
        path = rec.dump(str(tmp_path), "netsplit")
        with open(path) as f:
            doc = json.load(f)
        mod = _load_script("trace_report")
        out = mod.render_doc(doc)
        assert "quorum.fence" in out and "host=hostC" in out
        assert "epoch.commit" in out and "epoch=2" in out
        assert "self-preservation:" in out
        assert "quorum.fence:1/3=1" in out
        assert "quorum.restore:3/3=1" in out
        assert "epoch.propose:v2=1" in out
        assert "epoch.commit:v2=1" in out

    def test_trace_report_renders_session_serving_events(
            self, tmp_path):
        """PR 10's session-serving events (fairness sheds, viewport
        predictions, prefetch budget moves) are marked on the flight
        timeline and rolled into their own summary footer."""
        rec = telemetry.FlightRecorder()
        rec.record("qos.shed", reason="fairness", cls="bulk",
                   session="abc123", cost=4.0)
        rec.record("prefetch.predict", n=2, session="abc123",
                   x=3, y=1)
        rec.record("prefetch.budget", scale=0.5, prev=1.0,
                   level="elevated", paused=False)
        rec.record("prefetch.budget", scale=0.0, prev=0.5,
                   level="critical", paused=True)
        path = rec.dump(str(tmp_path), "incident")
        with open(path) as f:
            doc = json.load(f)
        mod = _load_script("trace_report")
        out = mod.render_doc(doc)
        assert "qos.shed" in out and "reason=fairness" in out
        assert "prefetch.predict" in out
        assert "prefetch.budget" in out and "scale=0.5" in out
        assert "session-serving:" in out
        assert "qos.shed:bulk=1" in out
        assert "prefetch.budget:0.0=1" in out
        assert "prefetch.predict=1" in out

    def test_same_second_dumps_do_not_collide(self, tmp_path):
        rec = telemetry.FlightRecorder()
        rec.record("e")
        a = rec.dump(str(tmp_path), "manual")
        b = rec.dump(str(tmp_path), "manual")
        assert a != b
        assert len(os.listdir(tmp_path)) == 2

    def test_spool_prunes_oldest(self, tmp_path):
        rec = telemetry.FlightRecorder()
        rec.record("e")
        for _ in range(rec.MAX_DUMPS + 5):
            rec.dump(str(tmp_path), "x")
        assert len(os.listdir(tmp_path)) == rec.MAX_DUMPS

    def test_shape_estimate_claim_is_one_shot(self):
        assert telemetry.SHAPE_COSTS.claim_estimate("B1x1x8x8")
        assert not telemetry.SHAPE_COSTS.claim_estimate("B1x1x8x8")
        telemetry.SHAPE_COSTS.reset()
        assert telemetry.SHAPE_COSTS.claim_estimate("B1x1x8x8")

    def test_dump_never_raises(self):
        rec = telemetry.FlightRecorder()
        rec.record("e")
        # An unwritable spool directory yields None, not an exception.
        assert rec.dump("/proc/definitely/not/writable", "x") is None


# ------------------------------------------------------------ SLO engine

class TestSloEngine:
    def _engine(self, clock, **kw):
        eng = telemetry.SloEngine()
        kw.setdefault("availability_target", 0.99)
        kw.setdefault("fast_window_s", 10.0)
        kw.setdefault("slow_window_s", 30.0)
        kw.setdefault("breach_burn_rate", 10.0)
        eng.configure(clock=lambda: clock[0], **kw)
        return eng

    def test_burn_rate_math(self):
        clock = [1000.0]
        eng = self._engine(clock)
        for _ in range(98):
            eng.record(200, 1.0)
        for _ in range(2):
            eng.record(503, 1.0)
        # 2% errors against a 1% budget = burn rate 2.0 both windows.
        fast, slow = eng.burn_rates()["availability"]
        assert fast == pytest.approx(2.0)
        assert slow == pytest.approx(2.0)
        assert not eng.any_breached()

    def test_breach_fires_once_per_episode(self):
        clock = [1000.0]
        fired = []
        eng = self._engine(clock)
        eng.on_breach = lambda obj, fast, slow: fired.append(obj)
        for _ in range(10):
            eng.record(503, 1.0)
        assert eng.any_breached()
        assert fired == ["availability"]
        # Still breached: no second callback while the episode holds.
        eng.record(503, 1.0)
        assert fired == ["availability"]
        # Recovery (errors age out of both windows) re-arms the hook.
        clock[0] += 60.0
        for _ in range(50):
            eng.record(200, 1.0)
        assert not eng.any_breached()
        for _ in range(50):
            eng.record(503, 1.0)
        assert fired == ["availability", "availability"]

    def test_latency_objective(self):
        clock = [5000.0]
        eng = self._engine(clock, availability_target=0.0,
                           latency_ms=100.0, latency_target=0.9)
        for _ in range(8):
            eng.record(200, 10.0)
        for _ in range(2):
            eng.record(200, 500.0)
        # 20% slow against a 10% budget = burn 2.0; errors excluded.
        eng.record(503, 9999.0)
        fast, _slow = eng.burn_rates()["latency"]
        assert fast == pytest.approx(2.0)

    def test_both_objectives_breaching_fire_both_hooks(self):
        """One record can transition BOTH objectives at once (a window
        boundary dropping good buckets moves every denominator); each
        breach owns its own flight-recorder dump."""
        clock = [1000.0]
        fired = []
        eng = self._engine(clock, availability_target=0.9,
                           latency_ms=10.0, latency_target=0.9)
        eng.on_breach = lambda obj, fast, slow: fired.append(obj)
        # Pin the burn computation over threshold for both objectives
        # so the one record() transitions them together.
        eng._burn_rates_locked = lambda: {
            "availability": (99.0, 99.0), "latency": (99.0, 99.0)}
        eng.record(200, 1.0)
        assert sorted(fired) == ["availability", "latency"]
        assert eng.breaches_total == 2

    def test_disabled_is_free_and_silent(self):
        eng = telemetry.SloEngine()
        eng.record(500, 1.0)
        assert eng.burn_rates() == {}
        assert eng.metric_lines() == []
        assert eng.summary() == "disabled"

    def test_metric_lines_and_summary(self):
        clock = [1000.0]
        eng = self._engine(clock)
        for _ in range(10):
            eng.record(503, 1.0)
        text = "\n".join(eng.metric_lines())
        assert 'imageregion_slo_burn_rate{slo="availability",' \
               'window="fast"}' in text
        assert 'imageregion_slo_breach{slo="availability"} 1' in text
        assert eng.summary().startswith("BREACH availability burn")


# ------------------------------------------------- cancelled queue waits

class TestCancelledQueueWaits:
    def test_cancelled_waits_use_separate_series(self):
        """Deadline- and fault-cancelled pendings must not enter the
        dispatched-wait series or its high-water gauge (the BENCH_r05
        mean-vs-p50 skew)."""
        import time as _time

        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer, _Pending)
        from omero_ms_image_region_tpu.utils.stopwatch import REGISTRY

        REGISTRY.reset()
        renderer = BatchingRenderer()
        loop = asyncio.new_event_loop()
        try:
            pend = _Pending(raw=None, settings={}, h=1, w=1,
                            future=loop.create_future())
            pend.t_enqueue = _time.perf_counter() - 2.0  # waited ~2 s
            renderer._record_queue_waits([pend], _time.perf_counter(),
                                         cancelled=True)
            snap = REGISTRY.snapshot()
            assert "batcher.queueWait" not in snap
            assert snap["batcher.queueWait.cancelled"]["count"] == 1
            assert snap["batcher.queueWait.cancelled"]["mean_ms"] \
                >= 1900.0
            assert renderer.queue_wait_max_ms == 0.0
        finally:
            loop.close()
        REGISTRY.reset()

    def test_expired_pending_cancelled_not_rendered(self):
        """A pending whose budget died in the queue gets its 504 at
        dispatch pop and records a CANCELLED wait, not a dispatched
        one."""
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)
        from omero_ms_image_region_tpu.utils.stopwatch import REGISTRY
        from omero_ms_image_region_tpu.utils.transient import (
            DeadlineExceededError, deadline_scope)

        from test_batcher import _settings

        REGISTRY.reset()
        rng = np.random.default_rng(3)
        settings = _settings()
        raw = rng.integers(0, 60000, size=(3, 8, 8)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(linger_ms=5.0)
            try:
                with deadline_scope(0.01):   # spent before dispatch
                    with pytest.raises(DeadlineExceededError):
                        await batcher.render(raw, settings)
            finally:
                await batcher.close()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(main())
        finally:
            loop.close()
        snap = REGISTRY.snapshot()
        assert snap["batcher.queueWait.cancelled"]["count"] == 1
        assert "batcher.queueWait" not in snap
        assert telemetry.RESILIENCE.deadline_cancelled == 1
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "batch.deadline-cancelled" in kinds
        REGISTRY.reset()


# ------------------------------------------------------------ bench gate

class TestBenchGate:
    def _gate(self):
        return _load_script("bench_gate")

    def _write(self, tmp_path, name, doc):
        path = tmp_path / name
        path.write_text(json.dumps(doc) + "\n")
        return str(path)

    def test_regression_fails(self, tmp_path, capsys):
        gate = self._gate()
        old = self._write(tmp_path, "BENCH_r01.json",
                          {"service_tiles_per_sec": 100.0})
        new = self._write(tmp_path, "BENCH_r02.json",
                          {"service_tiles_per_sec": 89.0})
        assert gate.main([old, new]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["verdict"] == "fail"
        assert verdict["keys"][0]["verdict"] == "regression"

    def test_exact_ten_percent_pair_fails(self, tmp_path):
        """The acceptance pair: a synthetic dead-on 10% drop."""
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": 100.0})
        new = self._write(tmp_path, "b.json",
                          {"service_tiles_per_sec": 90.0})
        assert gate.main([old, new]) == 1

    def test_within_threshold_passes(self, tmp_path):
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": 100.0})
        new = self._write(tmp_path, "b.json",
                          {"service_tiles_per_sec": 91.0})
        assert gate.main([old, new]) == 0
        # Improvements obviously pass too.
        better = self._write(tmp_path, "c.json",
                             {"service_tiles_per_sec": 140.0})
        assert gate.main([old, better]) == 0

    def test_null_value_skips_unless_strict(self, tmp_path):
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": None})
        new = self._write(tmp_path, "b.json",
                          {"service_tiles_per_sec": 50.0})
        assert gate.main([old, new]) == 0
        assert gate.main(["--strict", old, new]) == 1

    def test_dir_mode_picks_newest_pair(self, tmp_path, capsys):
        gate = self._gate()
        self._write(tmp_path, "BENCH_r01.json",
                    {"service_tiles_per_sec": 500.0})
        self._write(tmp_path, "BENCH_r04.json",
                    {"service_tiles_per_sec": 100.0})
        self._write(tmp_path, "BENCH_r05.json",
                    {"service_tiles_per_sec": 50.0})
        assert gate.main(["--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["old"] == "BENCH_r04.json"
        assert verdict["new"] == "BENCH_r05.json"

    def test_custom_keys(self, tmp_path):
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"x": 10.0, "service_tiles_per_sec": 1.0})
        new = self._write(tmp_path, "b.json",
                          {"x": 5.0, "service_tiles_per_sec": 1.0})
        assert gate.main(["--key", "x", old, new]) == 1
        assert gate.main([old, new]) == 0

    def test_sessions_keys_gated_direction_aware(self, tmp_path,
                                                 capsys):
        """--sessions judges SESSIONS_r*.json on the multi-user
        serving keys, direction-aware by name: the per-session p99
        regresses UP (a ``_ms`` key), the fairness index and the
        predictive hit rate regress DOWN."""
        gate = self._gate()
        good = {"sessions_interactive_p99_ms": 120.0,
                "sessions_fairness_index": 0.95,
                "prefetch_hit_rate": 0.9}
        self._write(tmp_path, "SESSIONS_r01.json", good)
        # p99 UP 50% = regression even though the other keys held.
        self._write(tmp_path, "SESSIONS_r02.json",
                    {**good, "sessions_interactive_p99_ms": 180.0})
        assert gate.main(["--sessions", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["sessions_interactive_p99_ms"] == "regression"
        assert by_key["sessions_fairness_index"] == "pass"
        # Fairness index DOWN past threshold = regression.
        self._write(tmp_path, "SESSIONS_r03.json",
                    {**good, "sessions_fairness_index": 0.7})
        assert gate.main(["--sessions", "--dir", str(tmp_path)]) == 1
        # Holding every key passes; records predating the sessions
        # bench skip on null instead of failing.
        self._write(tmp_path, "SESSIONS_r04.json", good)
        self._write(tmp_path, "SESSIONS_r05.json",
                    {**good, "sessions_interactive_p99_ms": 115.0})
        assert gate.main(["--sessions", "--dir", str(tmp_path)]) == 0
        capsys.readouterr()

    def test_offload_keys_gated_direction_aware(self, tmp_path,
                                                capsys):
        """--offload judges OFFLOAD_r*.json on the repeat-viewer
        offload keys, direction-aware by name: the offload ratio and
        peer hit rate regress DOWN (less traffic absorbed off the
        origin), the 304 latency is a ``_ms`` key and regresses UP."""
        gate = self._gate()
        good = {"origin_offload_ratio": 1.0, "peer_hit_rate": 1.0,
                "p50_304_ms": 1.6}
        self._write(tmp_path, "OFFLOAD_r01.json", good)
        # Offload ratio DOWN 20% = regression.
        self._write(tmp_path, "OFFLOAD_r02.json",
                    {**good, "origin_offload_ratio": 0.8})
        assert gate.main(["--offload", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["origin_offload_ratio"] == "regression"
        assert by_key["p50_304_ms"] == "pass"
        # 304 latency UP 10x = regression even with the ratios flat.
        self._write(tmp_path, "OFFLOAD_r03.json",
                    {**good, "p50_304_ms": 16.0})
        assert gate.main(["--offload", "--dir", str(tmp_path)]) == 1
        capsys.readouterr()
        # Holding (or improving) every key passes.
        self._write(tmp_path, "OFFLOAD_r04.json", good)
        self._write(tmp_path, "OFFLOAD_r05.json",
                    {**good, "p50_304_ms": 1.2})
        assert gate.main(["--offload", "--dir", str(tmp_path)]) == 0
        # BENCH records in the same dir are ignored under --offload.
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["new"] == "OFFLOAD_r05.json"

    def test_capacity_keys_gated_direction_aware(self, tmp_path,
                                                 capsys):
        """--capacity judges CAPACITY_r*.json (bench --smoke
        --capacity, the open-loop offered-load sweep) direction-aware
        by name: the knee and the scaling efficiency regress DOWN
        (less capacity before the SLO breaks), the p99 AT the knee is
        a ``_ms`` key and regresses UP."""
        gate = self._gate()
        good = {"capacity_knee_offered_tps": 120.0,
                "p99_at_knee_ms": 80.0,
                "capacity_scaling_efficiency": 0.5}
        self._write(tmp_path, "CAPACITY_r01.json", good)
        # Knee DOWN 25% = regression (the service hits collapse at
        # lower offered load) even with the p99 flat.
        self._write(tmp_path, "CAPACITY_r02.json",
                    {**good, "capacity_knee_offered_tps": 90.0})
        assert gate.main(["--capacity", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["capacity_knee_offered_tps"] == "regression"
        assert by_key["p99_at_knee_ms"] == "pass"
        # p99-at-knee UP 50% = regression even with the knee flat.
        self._write(tmp_path, "CAPACITY_r03.json",
                    {**good, "p99_at_knee_ms": 120.0})
        assert gate.main(["--capacity", "--dir", str(tmp_path)]) == 1
        capsys.readouterr()
        # Holding or improving every key passes; --watermark covers
        # the family (the newest round judged against the best knee
        # ever measured — r01's 120, not r03's).
        self._write(tmp_path, "CAPACITY_r04.json",
                    {**good, "capacity_knee_offered_tps": 130.0})
        assert gate.main(["--capacity", "--dir", str(tmp_path)]) == 0
        capsys.readouterr()
        assert gate.main(["--capacity", "--watermark", "--dir",
                          str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["mode"] == "watermark"
        by_key = {v["key"]: v for v in verdict["keys"]}
        assert by_key["capacity_knee_offered_tps"][
            "watermark_record"] == "CAPACITY_r01.json"
        # A new round under the best-ever knee by >10% fails the
        # watermark even if it passes pairwise against a sagged r04.
        self._write(tmp_path, "CAPACITY_r05.json",
                    {**good, "capacity_knee_offered_tps": 100.0})
        assert gate.main(["--capacity", "--watermark", "--dir",
                          str(tmp_path)]) == 1
        capsys.readouterr()

    def test_hotkey_keys_gated_direction_aware(self, tmp_path,
                                               capsys):
        """--hotkey judges HOTKEY_r*.json (bench --smoke --hotkey,
        the viral-image storm) direction-aware by name: the storm
        throughput ratio, the replication gain and the absolute storm
        throughput all regress DOWN.  ``hotkey_duplicate_staged`` is a
        correctness rider judged on the new record alone — any value
        above zero is an outright regression regardless of trend."""
        gate = self._gate()
        good = {"hotkey_storm_ratio": 0.95,
                "hotkey_replication_gain": 1.6,
                "hotkey_storm_tps": 100.0,
                "hotkey_duplicate_staged": 0}
        self._write(tmp_path, "HOTKEY_r01.json", good)
        # Storm ratio DOWN 30% = regression (the hot member melts
        # again) even with the raw throughput flat.
        self._write(tmp_path, "HOTKEY_r02.json",
                    {**good, "hotkey_storm_ratio": 0.65})
        assert gate.main(["--hotkey", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["hotkey_storm_ratio"] == "regression"
        assert by_key["hotkey_replication_gain"] == "pass"
        assert by_key["hotkey_duplicate_staged"] == "pass"
        # Replication gain collapsing toward 1.0 = regression (the
        # A/B says replication no longer buys anything).
        self._write(tmp_path, "HOTKEY_r03.json",
                    {**good, "hotkey_replication_gain": 1.05})
        assert gate.main(["--hotkey", "--dir", str(tmp_path)]) == 1
        capsys.readouterr()
        # A single duplicate-staged plane fails outright even with
        # every trend key flat or improving.
        self._write(tmp_path, "HOTKEY_r04.json", good)
        self._write(tmp_path, "HOTKEY_r05.json",
                    {**good, "hotkey_storm_tps": 110.0,
                     "hotkey_duplicate_staged": 1})
        assert gate.main(["--hotkey", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["hotkey_duplicate_staged"] == "regression"
        assert by_key["hotkey_storm_tps"] == "pass"
        # Holding every key passes; records predating the hotkey
        # bench skip on null instead of failing.
        self._write(tmp_path, "HOTKEY_r06.json", good)
        self._write(tmp_path, "HOTKEY_r07.json",
                    {**good, "hotkey_storm_tps": 104.0})
        assert gate.main(["--hotkey", "--dir", str(tmp_path)]) == 0
        capsys.readouterr()
        self._write(tmp_path, "HOTKEY_r08.json", {"ok": True})
        assert gate.main(["--hotkey", "--dir", str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["hotkey_storm_ratio"] == "skipped"
        assert by_key["hotkey_duplicate_staged"] == "skipped"
        # --watermark holds the best storm throughput ever measured.
        assert gate.main(["--hotkey", "--watermark", "--dir",
                          str(tmp_path)]) == 0
        capsys.readouterr()
        self._write(tmp_path, "HOTKEY_r09.json",
                    {**good, "hotkey_storm_tps": 80.0})
        assert gate.main(["--hotkey", "--watermark", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v for v in verdict["keys"]}
        assert by_key["hotkey_storm_tps"][
            "watermark_record"] == "HOTKEY_r05.json"
        capsys.readouterr()

    def test_partition_keys_gated_direction_aware(self, tmp_path,
                                                  capsys):
        """--partition judges PARTITION_r*.json (bench --smoke
        --partition, the netsplit chaos drill): fence/restore latency
        are ``_ms`` keys and regress UP; the availability and
        split-brain contracts (majority 5xx-without-shed, roll
        commit, rejoin epoch, post-heal agreement, byte round-trip,
        counted refusals) are correctness riders judged on the new
        record alone."""
        gate = self._gate()
        good = {"part_fence_ms": 1200.0, "part_restore_ms": 1400.0,
                "part_majority_5xx": 0, "part_roll_committed": 1,
                "part_rejoin_epoch": 2, "part_postheal_agree": 1,
                "part_byte_agree": 1, "part_minority_refusals": 2}
        self._write(tmp_path, "PARTITION_r01.json", good)
        # Fence latency UP 3x = regression (the minority served
        # un-fenced — potentially split-brain — for 3x longer).
        self._write(tmp_path, "PARTITION_r02.json",
                    {**good, "part_fence_ms": 3600.0})
        assert gate.main(["--partition", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["part_fence_ms"] == "regression"
        assert by_key["part_restore_ms"] == "pass"
        assert by_key["part_majority_5xx"] == "pass"
        # One majority-side failure that was not counted shed fails
        # outright, with every trend key flat.
        self._write(tmp_path, "PARTITION_r03.json", good)
        self._write(tmp_path, "PARTITION_r04.json",
                    {**good, "part_majority_5xx": 1})
        assert gate.main(["--partition", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["part_majority_5xx"] == "regression"
        assert by_key["part_fence_ms"] == "pass"
        # An aborted roll, a minority that refused nothing, or a
        # post-heal disagreement each fail the same way.
        self._write(tmp_path, "PARTITION_r05.json",
                    {**good, "part_roll_committed": 0,
                     "part_minority_refusals": 0,
                     "part_postheal_agree": 0})
        assert gate.main(["--partition", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["part_roll_committed"] == "regression"
        assert by_key["part_minority_refusals"] == "regression"
        assert by_key["part_postheal_agree"] == "regression"
        # Holding every contract passes — including a one-gossip-tick
        # restore wobble (+29%): fence/restore are tick-quantized, so
        # the family's default bar is 0.50, not the 0.10 that would
        # fail identical code on honest jitter.  Records that predate
        # the family skip on null instead of failing.
        self._write(tmp_path, "PARTITION_r06.json", good)
        self._write(tmp_path, "PARTITION_r07.json",
                    {**good, "part_restore_ms": 1800.0})
        assert gate.main(["--partition", "--dir",
                          str(tmp_path)]) == 0
        capsys.readouterr()
        self._write(tmp_path, "PARTITION_r08.json", {"ok": True})
        assert gate.main(["--partition", "--dir",
                          str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["part_fence_ms"] == "skipped"
        assert by_key["part_majority_5xx"] == "skipped"
        capsys.readouterr()

    def test_workloads_keys_gated_direction_aware(self, tmp_path,
                                                  capsys):
        """--workloads judges WORKLOADS_r*.json (bench --smoke
        --workloads, the device mask/overlay/pyramid/animation drill)
        direction-aware by name: the batched latencies and the
        pyramid build are ``_ms`` keys and regress UP; the parity-mix
        size (``mask_renders``) regresses DOWN — fewer masks
        exercised is a shrunken drill, not a win."""
        gate = self._gate()
        good = {"mask_device_ms": 12.0, "overlay_device_ms": 8.0,
                "pyramid_build_ms": 150.0, "anim_first_frame_ms": 9.0,
                "anim_total_ms": 40.0, "mask_renders": 12}
        self._write(tmp_path, "WORKLOADS_r01.json", good)
        # First-frame latency UP 3x = regression (the stream promise
        # is "first frame fast"), with every other key flat.
        self._write(tmp_path, "WORKLOADS_r02.json",
                    {**good, "anim_first_frame_ms": 27.0})
        assert gate.main(["--workloads", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["anim_first_frame_ms"] == "regression"
        assert by_key["mask_device_ms"] == "pass"
        assert by_key["mask_renders"] == "pass"
        # The parity mix shrinking is judged DOWNWARD: 12 -> 4 masks
        # rendered means the drill stopped proving what it claims.
        self._write(tmp_path, "WORKLOADS_r03.json", good)
        self._write(tmp_path, "WORKLOADS_r04.json",
                    {**good, "mask_renders": 4})
        assert gate.main(["--workloads", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["mask_renders"] == "regression"
        assert by_key["pyramid_build_ms"] == "pass"
        # Smoke-scale batched renders are a few ms, so the family bar
        # is the wide 0.50, not 0.10: a +40% wobble on the overlay
        # latency passes; a faster round obviously passes too.
        self._write(tmp_path, "WORKLOADS_r05.json", good)
        self._write(tmp_path, "WORKLOADS_r06.json",
                    {**good, "overlay_device_ms": 11.2,
                     "anim_total_ms": 30.0})
        assert gate.main(["--workloads", "--dir",
                          str(tmp_path)]) == 0
        capsys.readouterr()
        # Records that predate the workloads bench skip on null.
        self._write(tmp_path, "WORKLOADS_r07.json", {"ok": True})
        assert gate.main(["--workloads", "--dir",
                          str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        by_key = {v["key"]: v["verdict"] for v in verdict["keys"]}
        assert by_key["mask_device_ms"] == "skipped"
        assert by_key["mask_renders"] == "skipped"
        capsys.readouterr()

    def test_multichip_fleet_curve_gated(self, tmp_path, capsys):
        """--multichip judges MULTICHIP_r*.json on the fleet scaling
        keys: ok-true-only rounds (every record predating the curve)
        skip on null, a scaling regression fails, and --watermark
        holds the best-ever curve."""
        gate = self._gate()
        curve = {"fleet_tiles_per_sec_m1": 100.0,
                 "fleet_tiles_per_sec_m4": 360.0,
                 "fleet_tiles_per_sec_m8": 650.0,
                 "fleet_scaling_efficiency": 0.81}
        self._write(tmp_path, "MULTICHIP_r01.json", {"ok": True})
        self._write(tmp_path, "MULTICHIP_r02.json",
                    {"ok": True, **curve})
        # r01 -> r02: the legacy record carries no curve — skip, pass.
        assert gate.main(["--multichip", "--dir",
                          str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        assert all(k["verdict"] == "skipped" for k in verdict["keys"])
        # BENCH records in the same dir are ignored under --multichip.
        self._write(tmp_path, "BENCH_r09.json",
                    {"service_tiles_per_sec": 1.0})
        # A fleet that stopped scaling fails the gate.
        self._write(tmp_path, "MULTICHIP_r03.json", {
            "ok": True, "fleet_tiles_per_sec_m1": 100.0,
            "fleet_tiles_per_sec_m4": 200.0,
            "fleet_tiles_per_sec_m8": 300.0,
            "fleet_scaling_efficiency": 0.37})
        assert gate.main(["--multichip", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["new"] == "MULTICHIP_r03.json"
        assert {k["key"] for k in verdict["keys"]
                if k["verdict"] == "regression"} == {
            "fleet_tiles_per_sec_m8", "fleet_tiles_per_sec_m4",
            "fleet_scaling_efficiency"}
        # Watermark mode: r03 is judged against r02's best-ever marks.
        assert gate.main(["--multichip", "--watermark", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["keys"][0]["watermark_record"] == \
            "MULTICHIP_r02.json"

    def test_multichip_forensics_keys_gated_skip_on_null(
            self, tmp_path, capsys):
        """--multichip also judges the control-plane forensics
        acceptance keys: fed_trace_stitched (the stitched cross-host
        waterfall verdict, 1 or 0) and decision_records (outcome-
        carrying autoscaler records in the merged ledger).  Records
        predating the forensics bench skip on null instead of
        failing; losing the stitch (1 -> 0) fails the gate."""
        gate = self._gate()
        curve = {"fleet_tiles_per_sec_m8": 650.0,
                 "fleet_scaling_efficiency": 0.81}
        self._write(tmp_path, "MULTICHIP_r01.json",
                    {"ok": True, **curve})
        self._write(tmp_path, "MULTICHIP_r02.json",
                    {"ok": True, **curve,
                     "fed_trace_stitched": 1, "decision_records": 3})
        # r01 predates the forensics bench: both new keys skip.
        assert gate.main(["--multichip", "--dir",
                          str(tmp_path)]) == 0
        verdict = json.loads(capsys.readouterr().out)
        by_key = {k["key"]: k["verdict"] for k in verdict["keys"]}
        assert by_key["fed_trace_stitched"] == "skipped"
        assert by_key["decision_records"] == "skipped"
        # A round that lost the stitch regresses 1 -> 0.
        self._write(tmp_path, "MULTICHIP_r03.json",
                    {"ok": True, **curve,
                     "fed_trace_stitched": 0, "decision_records": 3})
        assert gate.main(["--multichip", "--dir",
                          str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        by_key = {k["key"]: k["verdict"] for k in verdict["keys"]}
        assert by_key["fed_trace_stitched"] == "regression"
        assert by_key["decision_records"] == "pass"

    def test_latency_key_gates_in_the_up_direction(self, tmp_path):
        """p50_service_tile_ms_ex_rtt is a DEFAULT key and judged
        lower-is-better: a >=10% latency INCREASE fails even when
        throughput is flat (the regression class a throughput-only
        gate cannot see)."""
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": 100.0,
                           "p50_service_tile_ms_ex_rtt": 100.0})
        worse = self._write(tmp_path, "b.json",
                           {"service_tiles_per_sec": 100.0,
                            "p50_service_tile_ms_ex_rtt": 110.0})
        assert gate.main([old, worse]) == 1
        # A latency DROP (improvement) passes, as does one within
        # threshold.
        better = self._write(tmp_path, "c.json",
                             {"service_tiles_per_sec": 100.0,
                              "p50_service_tile_ms_ex_rtt": 50.0})
        assert gate.main([old, better]) == 0
        near = self._write(tmp_path, "d.json",
                           {"service_tiles_per_sec": 100.0,
                            "p50_service_tile_ms_ex_rtt": 109.0})
        assert gate.main([old, near]) == 0

    def test_latency_key_skips_on_null_like_throughput(self, tmp_path):
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": 100.0,
                           "p50_service_tile_ms_ex_rtt": None})
        new = self._write(tmp_path, "b.json",
                          {"service_tiles_per_sec": 100.0,
                           "p50_service_tile_ms_ex_rtt": 50.0})
        assert gate.main([old, new]) == 0
        assert gate.main(["--strict", old, new]) == 1

    def test_raw_upload_is_a_default_key(self, tmp_path):
        """The r01 -> r05 524 -> 4.8 MB/s upload collapse class gates
        by default now."""
        gate = self._gate()
        old = self._write(tmp_path, "a.json",
                          {"service_tiles_per_sec": 100.0,
                           "raw_upload_mb_per_sec": 500.0})
        new = self._write(tmp_path, "b.json",
                          {"service_tiles_per_sec": 100.0,
                           "raw_upload_mb_per_sec": 5.0})
        assert gate.main([old, new]) == 1

    def test_watermark_catches_compounded_drift(self, tmp_path,
                                                capsys):
        """The r02 -> r05 failure mode in miniature: -10% per round
        passes every PAIRWISE gate but compounds past the watermark
        threshold — the watermark gate fails where pairwise cannot."""
        gate = self._gate()
        rates = [100.0, 91.0, 83.0, 76.0]      # each pair within 10%
        for i, rate in enumerate(rates):
            self._write(tmp_path, f"BENCH_r{i + 1:02d}.json",
                        {"service_tiles_per_sec": rate})
        # Every pairwise gate over the sequence passes...
        paths = sorted(str(p) for p in tmp_path.iterdir())
        for old, new in zip(paths, paths[1:]):
            assert gate.main([old, new]) == 0
        capsys.readouterr()
        # ...but the best-ever watermark (100, set by r01) fails r04.
        assert gate.main(["--watermark", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["mode"] == "watermark"
        row = verdict["keys"][0]
        assert row["verdict"] == "regression"
        assert row["old"] == 100.0
        assert row["watermark_record"] == "BENCH_r01.json"

    def test_watermark_passes_a_recovered_record(self, tmp_path):
        """A new record at (or within threshold of) the best-ever mark
        passes — recovery closes the gate cleanly."""
        gate = self._gate()
        for i, rate in enumerate([100.0, 70.0, 60.0, 96.0]):
            self._write(tmp_path, f"BENCH_r{i + 1:02d}.json",
                        {"service_tiles_per_sec": rate})
        assert gate.main(["--watermark", "--dir", str(tmp_path)]) == 0

    def test_watermark_latency_key_uses_min(self, tmp_path, capsys):
        """Latency watermarks are the BEST (lowest) value ever seen;
        a new record >=10% above that mark fails even if it beats the
        previous round."""
        gate = self._gate()
        lat = [40.0, 90.0, 80.0]   # best-ever 40 set in r01
        for i, v in enumerate(lat):
            self._write(tmp_path, f"BENCH_r{i + 1:02d}.json",
                        {"service_tiles_per_sec": 100.0,
                         "p50_service_tile_ms_ex_rtt": v})
        assert gate.main(["--watermark", "--dir", str(tmp_path)]) == 1
        verdict = json.loads(capsys.readouterr().out)
        rows = {r["key"]: r for r in verdict["keys"]}
        row = rows["p50_service_tile_ms_ex_rtt"]
        assert row["verdict"] == "regression"
        assert row["old"] == 40.0

    def test_watermark_skips_never_recorded_keys(self, tmp_path):
        """A key no historical record ever carried skips (weather
        semantics), and --strict turns that into a failure."""
        gate = self._gate()
        for i in range(2):
            self._write(tmp_path, f"BENCH_r{i + 1:02d}.json",
                        {"service_tiles_per_sec": 100.0})
        assert gate.main(["--watermark", "--dir", str(tmp_path)]) == 0
        assert gate.main(["--watermark", "--strict", "--dir",
                          str(tmp_path)]) == 1

    def test_watermark_reads_driver_envelopes(self, tmp_path):
        """Historical BENCH records are driver envelopes ({parsed} or
        a {tail} whose bench line may have its leading brace sheared
        off by the front-truncated capture); the watermark gate must
        read every round or the mark silently shrinks."""
        gate = self._gate()
        self._write(tmp_path, "BENCH_r01.json",
                    {"parsed": {"metric": "m",
                                "service_tiles_per_sec": 100.0}})
        bench_line = json.dumps({"metric": "m",
                                 "service_tiles_per_sec": 50.0})
        self._write(tmp_path, "BENCH_r02.json",
                    {"parsed": None,
                     "tail": "noise\n" + bench_line[1:] + "\n"})
        assert gate.main(["--watermark", "--dir", str(tmp_path)]) == 1


# -------------------------------------------------------- debug surface

IMG = 7
URL = (f"/webgateway/render_image_region/{IMG}/0/0"
       "?tile=0,0,0,32,32&format=jpeg&m=c&c=1|0:60000$FF0000")


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    from omero_ms_image_region_tpu.io.store import build_pyramid
    root = tmp_path_factory.mktemp("forensicsdata")
    rng = np.random.default_rng(13)
    planes = rng.integers(0, 60000, size=(2, 2, 64, 64)).astype(
        np.uint16)
    build_pyramid(planes, str(root / str(IMG)), chunk=(32, 32),
                  n_levels=1)
    return str(root)


def _device_config(data_dir, tmp_path=None):
    from omero_ms_image_region_tpu.server.config import AppConfig
    cfg = AppConfig(data_dir=data_dir)
    cfg.renderer.cpu_fallback_max_px = 0   # exercise the batched path
    # Barrier settlement so device-cost attribution lands before the
    # request finishes (first-tile-out races it on slow hosts); the
    # streaming path is gated deterministically in test_wire_v3.
    cfg.wire.streaming = False
    if tmp_path is not None:
        cfg.telemetry.profile_dir = str(tmp_path / "profiles")
        cfg.telemetry.flight_recorder_dir = str(tmp_path / "flight")
    return cfg


class TestDebugEndpoints:
    def test_combined_costs_flight_profile(self, data_dir, tmp_path):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app

        async def main():
            app = create_app(_device_config(data_dir, tmp_path))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()

                r = await client.get("/debug/costs")
                costs = await r.json()
                assert r.status == 200
                assert costs["observed"] >= 1
                top = costs["top"][0]
                assert top["route"] == "render_image_region"
                assert top["cost"]["device_ms"] > 0
                assert top["cost"]["wire_bytes"] > 0
                # The shape cost model saw the batched dispatch.
                assert any(s["dispatches"] >= 1
                           for s in costs["shapes"].values())

                r = await client.get("/debug/flightrecorder?dump=1")
                flight = await r.json()
                assert r.status == 200
                kinds = {e["kind"] for e in flight["events"]}
                assert "batch.formed" in kinds
                assert flight["dumped_to"] and os.path.exists(
                    flight["dumped_to"])

                # The acceptance criterion: a capture artifact on the
                # CPU backend.
                r = await client.get("/debug/profile?ms=50")
                prof = await r.json()
                assert r.status == 200, prof
                assert prof["files"], prof
                assert os.path.isdir(prof["dir"])
                assert prof["bytes"] > 0
            finally:
                await client.close()

        asyncio.run(main())

    def test_profile_bad_ms_is_400(self, data_dir):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app

        async def main():
            app = create_app(_device_config(data_dir))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/debug/profile?ms=banana")
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(main())

    def test_proxy_forwards_profile_and_merges_flight(self, data_dir,
                                                      tmp_path):
        """Frontend proxy: /debug/profile rides the sidecar wire (the
        capture runs in the device-owning process) and the frontend's
        /debug/flightrecorder merges the sidecar's ring."""
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.server.config import (
            AppConfig, SidecarConfig)
        from omero_ms_image_region_tpu.server.sidecar import run_sidecar

        sock = str(tmp_path / "f.sock")

        async def main():
            task = asyncio.create_task(
                run_sidecar(_device_config(data_dir, tmp_path), sock))
            for _ in range(200):
                if task.done():
                    raise AssertionError(
                        f"sidecar died: {task.exception()!r}")
                if os.path.exists(sock):
                    break
                await asyncio.sleep(0.05)
            app = create_app(AppConfig(
                data_dir=data_dir,
                sidecar=SidecarConfig(socket=sock, role="frontend")))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                r = await client.get("/debug/profile?ms=50")
                prof = await r.json()
                assert r.status == 200, prof
                assert prof["files"], prof
                r = await client.get("/debug/flightrecorder")
                flight = await r.json()
                assert r.status == 200
                assert flight["sidecar"] is not None
                assert flight["sidecar"]["events_total"] > 0
                # Proxy-side cost ledger: the render above carried its
                # device-side costs over the wire (in-process sidecar
                # shares the trace; either path must yield a ledger).
                r = await client.get("/debug/costs")
                costs = await r.json()
                assert costs["top"], costs
                assert costs["top"][0]["cost"]["device_ms"] > 0
            finally:
                await client.close()
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass

        asyncio.run(main())


# ------------------------------------------------------- reset contract

class TestResetContract:
    def test_reset_clears_every_accumulator(self):
        """Repeated in-process test apps must not leak counts across
        tests: everything reset() owns goes back to zero."""
        telemetry.RESILIENCE.count_shed("queue-full")
        telemetry.RESILIENCE.count_retry("image")
        telemetry.RESILIENCE.observe_attempts("image", 2)
        telemetry.RESILIENCE.count_deadline_cancelled()
        telemetry.READINESS.prewarm_pending = True
        telemetry.FLIGHT.record("e")
        telemetry.SLO.configure(availability_target=0.9)
        telemetry.SLO.record(503, 1.0)
        telemetry.SHAPE_COSTS.observe("B1x1x8x8", 1.0)
        telemetry.COST_TOPK.offer({"total_ms": 5.0})
        telemetry.observe_request_cost("r", {"device_ms": 1.0})
        telemetry.count_request("r", 200)
        telemetry.FLEET.count_routed("m0")
        telemetry.FLEET.count_stolen("m1")
        telemetry.FLEET.count_failed_over("m2")
        telemetry.SESSIONS.set_tracked(5)
        telemetry.SESSIONS.count_observation()
        telemetry.SESSIONS.count_evicted()
        telemetry.PREFETCH.count_predicted()
        telemetry.PREFETCH.count_staged()
        telemetry.PREFETCH.count_hit()
        telemetry.PREFETCH.count_skipped("budget")
        telemetry.PREFETCH.set_budget(0.5)
        telemetry.QOS.count_shed("interactive")
        telemetry.QOS.count_dequeued("bulk")
        telemetry.QOS.count_jump()
        telemetry.HTTPCACHE.count_etag_request()
        telemetry.HTTPCACHE.count_not_modified()
        telemetry.HTTPCACHE.count_head()
        telemetry.HTTPCACHE.count_peer_probe()
        telemetry.HTTPCACHE.count_peer_hit()
        telemetry.HTTPCACHE.count_peer_fetch()
        telemetry.HTTPCACHE.count_peer_fallback()
        telemetry.HTTPCACHE.count_peer_putback()

        telemetry.reset()

        assert telemetry.RESILIENCE.shed == {}
        assert telemetry.RESILIENCE.retries == {}
        assert telemetry.RESILIENCE.deadline_cancelled == 0
        assert telemetry.RESILIENCE.attempts_hist.series("x") == []
        assert telemetry.READINESS.prewarm_pending is False
        assert len(telemetry.FLIGHT) == 0
        assert telemetry.FLIGHT.events_total == 0
        assert telemetry.SLO.enabled is False
        assert telemetry.SLO.metric_lines() == []
        assert telemetry.SHAPE_COSTS.metric_lines() == []
        assert telemetry.COST_TOPK.snapshot() == []
        assert telemetry.cost_metric_lines() == []
        assert telemetry.FLEET.totals() == {
            "routed": 0, "stolen": 0, "failed_over": 0}
        assert telemetry.fleet_metric_lines() == []
        assert telemetry.SESSIONS.tracked == 0
        assert telemetry.SESSIONS.observations == 0
        assert telemetry.SESSIONS.evicted == 0
        assert telemetry.PREFETCH.predicted == 0
        assert telemetry.PREFETCH.staged == 0
        assert telemetry.PREFETCH.hits == 0
        assert telemetry.PREFETCH.skipped == {}
        assert telemetry.PREFETCH.budget_scale == 1.0
        assert telemetry.PREFETCH.hit_rate() is None
        assert telemetry.QOS.shed == {}
        assert telemetry.QOS.dequeued == {}
        assert telemetry.QOS.jumps == 0
        assert telemetry.HTTPCACHE.not_modified == 0
        assert telemetry.HTTPCACHE.etag_requests == 0
        assert telemetry.HTTPCACHE.head == 0
        assert telemetry.HTTPCACHE.peer_probes == 0
        assert telemetry.HTTPCACHE.peer_hits == 0
        assert telemetry.HTTPCACHE.peer_fetches == 0
        assert telemetry.HTTPCACHE.peer_fallbacks == 0
        assert telemetry.HTTPCACHE.peer_putbacks == 0
        assert telemetry.HTTPCACHE.metric_lines() == []
        assert telemetry.request_metric_lines() == [
            "imageregion_flight_events 0",
            "imageregion_flight_events_total 0",
            "imageregion_flight_dumps_total 0",
        ]


# ------------------------------------------- waterfall tail breakdown

class TestWaterfallTailBreakdown:
    def test_span_stats_report_tail_percentiles_and_max(self):
        """The r05 anomaly class made visible: a stage whose mean is
        dominated by a few stragglers exposes p95/p99/max alongside
        the mean and p50 in every stats export."""
        from omero_ms_image_region_tpu.utils.stopwatch import (
            StopWatchRegistry)

        reg = StopWatchRegistry()
        for _ in range(90):
            reg.record("batcher.queueWait", 2.0)
        for _ in range(10):                         # straggler decile
            reg.record("batcher.queueWait", 5000.0)
        s = reg.snapshot()["batcher.queueWait"]
        assert s["count"] == 100
        assert s["p50_ms"] <= 4.0                   # bucket bound of 2ms
        assert s["mean_ms"] > 400.0                 # the mean conflates
        assert s["p95_ms"] >= 4000.0                # the tail is visible
        assert s["p99_ms"] >= 4000.0
        assert s["max_ms"] == 5000.0                # exact high-water
        assert s["p95_ms"] <= s["p99_ms"] <= 2 * s["max_ms"]

    def test_trace_report_renders_stats_tables(self, capsys):
        """scripts/trace_report.py renders a per-stage stats mapping
        (the bench record's service_waterfall export) as a table and
        flags heavy-tail stages."""
        mod = _load_script("trace_report")
        doc = {
            "service_waterfall": {
                "batcher.queueWait": {
                    "count": 672, "total_ms": 1530041.2,
                    "mean_ms": 2276.8, "p50_ms": 2.2,
                    "p95_ms": 16384.0, "p99_ms": 16384.0,
                    "max_ms": 21034.7},
                "wire.fetch": {
                    "count": 102, "total_ms": 218004.3,
                    "mean_ms": 2137.3, "p50_ms": 598.7,
                    "p95_ms": 8192.0, "p99_ms": 8192.0,
                    "max_ms": 9123.0},
            },
        }
        out = mod.render_doc(doc)
        assert "batcher.queueWait" in out
        assert "p95" in out and "p99" in out and "max" in out
        # The 1000x mean-vs-p50 stage is called out; the 3.5x one not.
        assert out.count("heavy tail") == 1
        # Plain {span: stats} mappings (REGISTRY.snapshot()) render too.
        out2 = mod.render_doc(doc["service_waterfall"])
        assert "wire.fetch" in out2
        # Legacy stats without the tail fields still render (dashes).
        legacy = {"x": {"count": 1, "total_ms": 1.0, "mean_ms": 1.0,
                        "p50_ms": 1.0}}
        assert "x" in mod.render_doc(legacy)


# ----------------------------------- cross-host waterfall rendering

class TestFederatedTraceRendering:
    def test_fed_hop_spans_render_kind_at_host_with_footer(self):
        """fed.hop spans render as fed:kind@host and the report gains
        a per-HOST ms footer — the stitched multi-host story the
        Control-plane forensics runbook documents."""
        mod = _load_script("trace_report")
        doc = {
            "trace_id": "t-fed", "route": "region", "status": 200,
            "total_ms": 20.0,
            "spans": [
                {"name": "service.total", "start_ms": 0.0,
                 "dur_ms": 20.0},
                {"name": "fed.hop", "start_ms": 2.0, "dur_ms": 6.0,
                 "host": "hostB", "member": "b0",
                 "kind": "shard_transfer", "bytes": 4096},
                {"name": "fed.hop", "start_ms": 3.0, "dur_ms": 2.0,
                 "host": "hostB", "member": "b0", "kind": "stage"},
                {"name": "fed.hop", "start_ms": 10.0, "dur_ms": 1.0,
                 "host": "hostC", "member": "c0", "kind": "gossip"},
            ],
        }
        out = mod.render_trace(doc)
        assert "fed:shard_transfer@hostB" in out
        assert "fed:stage@hostB" in out
        assert "fed:gossip@hostC" in out
        # kind/host fold into the marker, not the extras suffix.
        assert "'kind'" not in out and "'host'" not in out
        assert "'bytes': 4096" in out
        # Per-host footer sums each host's span time.
        assert "hosts: hostB=8.0ms  hostC=1.0ms" in out
        # The member lane column still works alongside.
        assert "members=b0,c0" in out

    def test_single_host_trace_has_no_hosts_footer(self):
        mod = _load_script("trace_report")
        doc = {"spans": [{"name": "render", "start_ms": 0.0,
                          "dur_ms": 5.0}]}
        assert "hosts:" not in mod.render_trace(doc)

    def test_decision_events_marked_and_summed_in_flight_render(self):
        """decision.<kind> flight events get the ``+`` mark and a
        control-plane footer keyed kind:verdict."""
        mod = _load_script("trace_report")
        doc = {
            "reason": "test", "pid": 1, "ts": 100.0,
            "events": [
                {"ts": 98.0, "kind": "decision.autoscaler",
                 "verdict": "blocked", "seq": 1, "member": "m0"},
                {"ts": 99.0, "kind": "decision.gossip",
                 "verdict": "mismatch", "seq": 2},
                {"ts": 99.5, "kind": "decision.gossip",
                 "verdict": "mismatch", "seq": 3},
                {"ts": 99.9, "kind": "request.shed"},
            ],
        }
        out = mod.render_flight(doc)
        assert "+ decision.autoscaler" in out
        assert ("control-plane: decision.autoscaler:blocked=1  "
                "decision.gossip:mismatch=2") in out
        # Non-decision events keep their unmarked rendering.
        assert "+ request.shed" not in out

    def test_flight_render_without_decisions_has_no_footer(self):
        mod = _load_script("trace_report")
        doc = {"reason": "r", "pid": 1, "ts": 1.0,
               "events": [{"ts": 0.5, "kind": "request.shed"}]}
        assert "control-plane:" not in mod.render_flight(doc)
