"""Cache tiers, metadata/ACL service, session decoding."""

import asyncio
import json
import os

import numpy as np
import pytest

from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.models.mask import Mask
from omero_ms_image_region_tpu.services.cache import (
    CacheConfig, Caches, CacheStack, MemoryLRUCache,
)
from omero_ms_image_region_tpu.services.metadata import (
    CanReadMemo, LocalMetadataService, write_mask,
)
from omero_ms_image_region_tpu.services.sessions import (
    StaticSessionStore, decode_django_session, resolve_session_key,
)


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


class TestMemoryLRU:
    def test_get_set_evict(self):
        cache = MemoryLRUCache(max_bytes=100)
        cache.set_sync("a", b"x" * 60)
        cache.set_sync("b", b"y" * 60)          # evicts a
        assert cache.get_sync("a") is None
        assert cache.get_sync("b") == b"y" * 60

    def test_lru_order(self):
        cache = MemoryLRUCache(max_bytes=100)
        cache.set_sync("a", b"x" * 40)
        cache.set_sync("b", b"y" * 40)
        cache.get_sync("a")                      # a now most-recent
        cache.set_sync("c", b"z" * 40)           # evicts b
        assert cache.get_sync("a") is not None
        assert cache.get_sync("b") is None

    def test_overwrite_accounts_size(self):
        cache = MemoryLRUCache(max_bytes=100)
        cache.set_sync("a", b"x" * 90)
        cache.set_sync("a", b"y" * 10)
        cache.set_sync("b", b"z" * 80)
        assert cache.get_sync("a") == b"y" * 10


class TestCacheStack:
    def test_backfill_upper_tiers(self):
        upper, lower = MemoryLRUCache(), MemoryLRUCache()
        stack = CacheStack([upper, lower])
        lower.set_sync("k", b"v")
        assert run(stack.get("k")) == b"v"
        assert upper.get_sync("k") == b"v"

    def test_disabled_is_a_noop(self):
        tier = MemoryLRUCache()
        stack = CacheStack([tier], enabled=False)
        run(stack.set("k", b"v"))
        assert run(stack.get("k")) is None
        assert tier.get_sync("k") is None

    def test_caches_from_config_flags(self):
        caches = Caches.from_config(CacheConfig(pixels_metadata=True))
        assert caches.image_region.enabled is False
        assert caches.pixels_metadata.enabled is True


class TestLocalMetadata:
    @pytest.fixture
    def data_dir(self, tmp_path):
        planes = np.arange(2 * 1 * 32 * 32, dtype=np.uint16).reshape(
            2, 1, 32, 32)
        build_pyramid(planes, str(tmp_path / "7"), chunk=(16, 16),
                      n_levels=1)
        write_mask(str(tmp_path), Mask(
            shape_id=5, width=8, height=4, bytes_=bytes(4),
            fill_color=(1, 2, 3, 4)))
        return str(tmp_path)

    def test_pixels_description(self, data_dir):
        svc = LocalMetadataService(data_dir)
        pixels = run(svc.get_pixels_description(7, None))
        assert (pixels.size_x, pixels.size_y, pixels.size_c) == (32, 32, 2)
        assert pixels.pixels_type == "uint16"
        assert run(svc.get_pixels_description(404, None)) is None

    def test_mask_round_trip(self, data_dir):
        svc = LocalMetadataService(data_dir)
        mask = run(svc.get_mask(5, None))
        assert (mask.width, mask.height) == (8, 4)
        assert mask.fill_color == (1, 2, 3, 4)
        assert run(svc.get_mask(404, None)) is None

    def test_acl_default_public(self, data_dir):
        svc = LocalMetadataService(data_dir)
        assert run(svc.can_read("Image", 7, None)) is True
        assert run(svc.can_read("Image", 404, None)) is False
        assert run(svc.can_read("Mask", 5, None)) is True
        assert run(svc.can_read("Mask", 404, None)) is False

    def test_acl_session_restricted(self, data_dir):
        with open(os.path.join(data_dir, "7", "acl.json"), "w") as f:
            json.dump({"sessions": ["good-key"]}, f)
        svc = LocalMetadataService(data_dir)
        assert run(svc.can_read("Image", 7, "good-key")) is True
        assert run(svc.can_read("Image", 7, "bad-key")) is False
        assert run(svc.can_read("Image", 7, None)) is False


class TestCanReadMemo:
    def test_memo_and_ttl(self):
        memo = CanReadMemo(ttl_seconds=1000)
        assert memo.get("s", "Image", 1) is None
        memo.put("s", "Image", 1, True)
        assert memo.get("s", "Image", 1) is True
        expired = CanReadMemo(ttl_seconds=-1)
        expired.put("s", "Image", 1, True)
        assert expired.get("s", "Image", 1) is None


class TestSharedCanReadMemo:
    def test_shared_tier_is_visible_across_instances(self):
        """The shared tier plays the Hazelcast distributed-map role: a
        decision memoized by one service instance is seen by another."""
        shared = MemoryLRUCache(max_bytes=1 << 20)
        a = CanReadMemo(ttl_seconds=1000, shared=shared)
        b = CanReadMemo(ttl_seconds=1000, shared=shared)
        run(a.put_async("s", "Image", 9, False))
        assert run(b.get_async("s", "Image", 9)) is False
        assert b.get("s", "Image", 9) is False  # promoted to local tier

    def test_without_shared_tier_stays_local(self):
        a = CanReadMemo(ttl_seconds=1000)
        b = CanReadMemo(ttl_seconds=1000)
        run(a.put_async("s", "Image", 9, True))
        assert run(b.get_async("s", "Image", 9)) is None


class TestPostgresSessionStore:
    def test_reads_django_session_table(self, monkeypatch):
        """Exercises the asyncpg code path with a stub driver."""
        import base64
        import sys
        import types

        payload = base64.b64encode(
            b"hmac:" + __import__("pickle").dumps(
                {"connector": {"omero_session_key": "pgkey"}}))

        class FakePool:
            async def fetchrow(self, query, sid):
                assert "django_session" in query and "$1" in query
                return (payload,) if sid == "sid1" else None

            async def close(self):
                pass

        fake = types.ModuleType("asyncpg")

        async def create_pool(dsn, **kw):
            return FakePool()

        fake.create_pool = create_pool
        monkeypatch.setitem(sys.modules, "asyncpg", fake)

        from omero_ms_image_region_tpu.services.sessions import (
            DjangoPostgresSessionStore,
        )
        store = DjangoPostgresSessionStore("postgresql://x/y")

        async def main():
            hit = await store.get_session_key("sid1")
            miss = await store.get_session_key("other")
            await store.close()
            return hit, miss

        hit, miss = run(main())
        assert hit == "pgkey"
        assert miss is None


class TestSessions:
    def test_static_store(self):
        store = StaticSessionStore({"cookie1": "omero-key-1"})
        assert run(store.get_session_key("cookie1")) == "omero-key-1"
        assert run(store.get_session_key("other")) is None
        assert run(StaticSessionStore(accept_all=True)
                   .get_session_key("x")) == "x"

    def test_resolve_from_cookies(self):
        store = StaticSessionStore({"sid": "key"})
        assert run(resolve_session_key(store, {"sessionid": "sid"})) == "key"
        assert run(resolve_session_key(store, {})) is None
        assert run(resolve_session_key(None, {"sessionid": "sid"})) is None

    def test_decode_django_json_session(self):
        payload = json.dumps(
            {"connector": {"omero_session_key": "abc123"}}).encode()
        assert decode_django_session(payload) == "abc123"
        assert decode_django_session(b"garbage!!") is None
