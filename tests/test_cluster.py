"""Multi-host bootstrap helpers (single-process semantics)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omero_ms_image_region_tpu.parallel import cluster


def test_initialize_standalone_is_noop():
    cluster.initialize()  # no cluster env: must not raise
    assert jax.process_count() >= 1


def test_global_mesh_spans_devices():
    mesh = cluster.global_mesh(chan_parallel=1)
    assert mesh.size == len(jax.devices())
    assert set(mesh.axis_names) == {"data", "chan"}


def test_local_batch_slice_single_process_covers_all():
    mesh = cluster.global_mesh(chan_parallel=1)
    data = mesh.shape["data"]
    sl = cluster.local_batch_slice(mesh, data * 3)
    assert sl == slice(0, data * 3)
    with pytest.raises(ValueError):
        cluster.local_batch_slice(mesh, data * 3 + 1)
