"""Multi-host bootstrap helpers (single-process semantics)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omero_ms_image_region_tpu.parallel import cluster
from omero_ms_image_region_tpu.parallel.mesh import resolve_devices


def test_initialize_standalone_is_noop():
    cluster.initialize()  # no cluster env: must not raise
    assert jax.process_count() >= 1


def test_global_mesh_spans_devices():
    mesh = cluster.global_mesh(chan_parallel=1)
    assert mesh.size == len(jax.devices())
    assert set(mesh.axis_names) == {"data", "chan"}


def test_local_batch_slice_single_process_covers_all():
    mesh = cluster.global_mesh(chan_parallel=1)
    data = mesh.shape["data"]
    sl = cluster.local_batch_slice(mesh, data * 3)
    assert sl == slice(0, data * 3)
    if data == 1:
        # Every batch divides a 1-row data axis; the indivisibility
        # contract needs a wider mesh (covered by the n_devices=8 test).
        pytest.skip("needs a multi-device data axis")
    with pytest.raises(ValueError):
        cluster.local_batch_slice(mesh, data * 3 + 1)


def test_global_mesh_falls_back_to_virtual_host_mesh():
    if len(resolve_devices(8)) < 8:
        pytest.skip("no 8-wide device pool (real or virtual) available")
    mesh = cluster.global_mesh(chan_parallel=2, n_devices=8)
    assert mesh.size == 8
    assert mesh.shape == {"data": 4, "chan": 2}


def test_local_batch_slice_indivisible_raises_on_wide_mesh():
    if len(resolve_devices(8)) < 8:
        pytest.skip("no 8-wide device pool (real or virtual) available")
    mesh = cluster.global_mesh(chan_parallel=1, n_devices=8)
    assert mesh.shape["data"] == 8
    sl = cluster.local_batch_slice(mesh, 16)
    assert sl == slice(0, 16)  # single process owns every row
    with pytest.raises(ValueError):
        cluster.local_batch_slice(mesh, 17)
