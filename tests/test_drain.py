"""Zero-downtime rolling drains (parallel.fleet + /admin/drain).

The headline drill: drain -> restart -> undrain EVERY fleet member in
sequence under a sustained mixed-digest load, with ZERO failed
requests (not even sheds) and the drained member's shard arriving
WARM on its ring successors (pre-staged via the drain manifest's
routing identities — never cold-missed)."""

import asyncio
import os
import tempfile

import numpy as np
import pytest

from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
from omero_ms_image_region_tpu.io.devicecache import DeviceRawCache
from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.parallel.fleet import (
    FleetImageHandler, FleetRouter, build_local_members)
from omero_ms_image_region_tpu.server.admission import (
    AdmissionController)
from omero_ms_image_region_tpu.server.app import build_services
from omero_ms_image_region_tpu.server.config import (AppConfig,
                                                     BatcherConfig,
                                                     RawCacheConfig,
                                                     RendererConfig)
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.server.singleflight import SingleFlight
from omero_ms_image_region_tpu.utils import telemetry

GRID = 4
EDGE = 128


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(scope="module")
def data_dir():
    rng = np.random.default_rng(21)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(
            rng, 2, 1, GRID * EDGE, GRID * EDGE).reshape(
            2, 1, GRID * EDGE, GRID * EDGE)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        yield tmp


def _ctxs(variants=2):
    """Mixed-digest working set: every tile of the grid, each at
    ``variants`` window settings (same plane identity -> same shard
    owner; different settings -> distinct renders)."""
    out = []
    for v in range(variants):
        for x in range(GRID):
            for y in range(GRID):
                w = 30000 + v * 800
                out.append(ImageRegionCtx.from_params({
                    "imageId": "1", "theZ": "0", "theT": "0",
                    "tile": f"0,{x},{y},{EDGE},{EDGE}",
                    "format": "png", "m": "c",
                    "c": f"1|0:{w}$FF0000,2|0:{w - 700}$00FF00",
                }))
    return out


def _fleet(tmp, n=3):
    config = AppConfig(
        data_dir=tmp,
        batcher=BatcherConfig(enabled=False),
        raw_cache=RawCacheConfig(enabled=True, prefetch=False),
        renderer=RendererConfig(cpu_fallback_max_px=0))
    services = build_services(config)
    members = build_local_members(config, services, n)
    router = FleetRouter(members, lane_width=2, steal_min_backlog=0)
    handler = FleetImageHandler(
        router, single_flight=SingleFlight(),
        admission=AdmissionController(512, renderer=router),
        base_services=services)
    return services, members, router, handler


class TestRollingRestartDrill:
    def test_drain_restart_undrain_every_member_zero_failures(
            self, data_dir):
        working = _ctxs()
        errors: list = []
        served = {"n": 0}

        async def drill():
            services, members, router, handler = _fleet(data_dir)
            stop = asyncio.Event()

            async def load():
                """Sustained mixed-digest load for the whole drill;
                ANY failure (even a shed) is a drill failure."""
                i = 0
                while not stop.is_set():
                    ctx = working[i % len(working)]
                    i += 1
                    try:
                        out = await handler.render_image_region(ctx)
                        assert out
                        served["n"] += 1
                    except Exception as e:     # noqa: BLE001
                        errors.append(repr(e))
                    await asyncio.sleep(0)

            loader = asyncio.create_task(load())
            warm_rates = []
            try:
                # Warm the whole working set once so every shard has
                # resident planes to hand over.
                await asyncio.gather(*(
                    handler.render_image_region(c) for c in working))
                for name in list(router.order):
                    member = router.members[name]
                    owned = [c for c in working
                             if router.owner_of(c) == name]
                    shard_digests = set(member.resident_digests())
                    doc = await router.drain_member(
                        name, prestage=True, max_planes=256,
                        settle_timeout_s=10.0)
                    assert doc["settled"] is True
                    # The handed-over shard is RESIDENT on the
                    # surviving members before any request asks.
                    survivors = set()
                    for other in router.order:
                        if other != name:
                            survivors |= router.members[other] \
                                .resident_digests()
                    assert shard_digests <= survivors, \
                        f"{name}: shard not pre-staged warm"
                    # Warm-hit rate on the successors: rendering the
                    # drained member's working set must hit HBM, not
                    # re-read the pixel store.
                    hits_before = sum(
                        router.members[o].services.raw_cache.hits
                        for o in router.order if o != name)
                    await asyncio.gather(*(
                        handler.render_image_region(c)
                        for c in owned))
                    hits_after = sum(
                        router.members[o].services.raw_cache.hits
                        for o in router.order if o != name)
                    if owned:
                        rate = (hits_after - hits_before) / len(owned)
                        warm_rates.append((name, rate))
                        assert rate >= 0.8, \
                            f"{name}: warm-hit {rate:.2f} < 0.8"
                    # "Restart": the member comes back with a COLD
                    # HBM cache (exactly what a process restart
                    # drops), then rejoins the ring.
                    member.services.raw_cache = DeviceRawCache(
                        member.services.raw_cache.max_bytes)
                    router.undrain_member(name)
                    assert name not in router.draining_members()
                    # Pre-stage BACK (the PR 9 follow-on): the drain
                    # manifest replays into the rejoined member, so
                    # its shard is HBM-resident again BEFORE its
                    # first routed request — a rolling restart ends
                    # with a warm fleet, not a cold rejoiner.
                    if shard_digests:
                        task = router.last_undrain_prestage
                        assert task is not None, \
                            f"{name}: no pre-stage-back scheduled"
                        await task
                        back = set(member.resident_digests())
                        assert shard_digests <= back, \
                            f"{name}: rejoined cold " \
                            f"({len(back)}/{len(shard_digests)} " \
                            f"planes back)"
            finally:
                stop.set()
                await loader
                await router.close()
                services.pixels_service.close()
            return warm_rates

        warm_rates = asyncio.run(drill())
        # ZERO 5xx-without-shed — in this drill, zero failures at all.
        assert errors == [], f"load failures during drill: {errors[:5]}"
        assert served["n"] > 0
        assert len(warm_rates) >= 2      # m0 may own 0 of the set
        # Drain accounting: every member drained once, planes were
        # pre-staged, and the phases hit the black box.
        assert telemetry.DRAIN.drains_total == 3
        assert telemetry.DRAIN.prestaged_planes > 0
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "drain.phase" in kinds

    def test_draining_member_takes_no_new_routes(self, data_dir):
        async def scenario():
            services, members, router, handler = _fleet(data_dir)
            try:
                working = _ctxs(variants=1)
                name = router.order[1]
                await router.drain_member(name, prestage=False,
                                          settle_timeout_s=2.0)
                owners = {router.owner_of(c) for c in working}
                assert name not in owners
                router.undrain_member(name)
                owners = {router.owner_of(c) for c in working}
                # Rejoined: its ring arcs flow back (the working set
                # spans every member at this size).
                assert name in owners
            finally:
                await router.close()
                services.pixels_service.close()

        asyncio.run(scenario())


class TestAdminDrainEndpoint:
    def _app_client(self, data_dir):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.server.config import FleetConfig

        config = AppConfig(
            data_dir=data_dir,
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))
        config.fleet = FleetConfig(enabled=True, members=2)
        app = create_app(config)
        return TestClient(TestServer(app))

    def test_fail_readyz_pulls_a_draining_instance_from_rotation(
            self, data_dir):
        """Satellite (PR 9 follow-on): with ``drain.fail-readyz`` on,
        /readyz answers 503 while any member drains — nginx/k8s pull
        the instance during a rolling restart — and recovers to 200
        on undrain.  The default posture stays annotation-only."""
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.server.config import FleetConfig

        async def scenario(fail_readyz):
            config = AppConfig(
                data_dir=data_dir,
                batcher=BatcherConfig(enabled=False),
                raw_cache=RawCacheConfig(enabled=True, prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0))
            config.fleet = FleetConfig(enabled=True, members=2)
            config.drain.fail_readyz = fail_readyz
            client = TestClient(TestServer(create_app(config)))
            await client.start_server()
            try:
                assert (await client.get("/readyz")).status == 200
                r = await client.post("/admin/drain?member=m1")
                assert r.status == 200
                r = await client.get("/readyz")
                draining_status = r.status
                body = await r.json()
                # The annotation is present in BOTH postures.
                assert "m1" in body["checks"].get("drain", "")
                await client.post("/admin/undrain?member=m1")
                assert (await client.get("/readyz")).status == 200
                return draining_status
            finally:
                await client.close()

        assert asyncio.run(scenario(True)) == 503
        assert asyncio.run(scenario(False)) == 200

    def test_drain_undrain_roundtrip_and_last_member_guard(
            self, data_dir):
        async def scenario():
            client = self._app_client(data_dir)
            await client.start_server()
            try:
                r = await client.get("/admin/drain")
                assert r.status == 200
                doc = await r.json()
                assert set(doc["members"]) == {"m0", "m1"}

                r = await client.post("/admin/drain?member=m1")
                assert r.status == 200
                doc = await r.json()
                assert doc["member"] == "m1"
                assert doc["members"]["m1"]["draining"] is True

                # Draining the LAST routable member is refused.
                r = await client.post("/admin/drain?member=m0")
                assert r.status == 409

                # Drain state is on /readyz (annotation) and /metrics.
                r = await client.get("/readyz")
                body = await r.json()
                assert "m1" in body["checks"].get("drain", "")
                r = await client.get("/metrics")
                text = await r.text()
                assert 'imageregion_drain_state{member="m1"} 2' \
                    in text

                r = await client.post("/admin/undrain?member=m1")
                assert r.status == 200
                doc = await r.json()
                assert doc["members"]["m1"]["draining"] is False

                r = await client.post("/admin/drain?member=nope")
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(scenario())
