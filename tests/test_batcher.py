"""Micro-batcher: correctness vs the direct path, coalescing, ragged pads."""

import asyncio

import numpy as np
import pytest

from omero_ms_image_region_tpu.flagship import flagship_settings
from omero_ms_image_region_tpu.models.rendering import (
    RenderingModel, default_rendering_def,
)
from omero_ms_image_region_tpu.models.pixels import Pixels
from omero_ms_image_region_tpu.ops.render import pack_settings
from omero_ms_image_region_tpu.server.batcher import (
    BatchingRenderer, pick_bucket,
)
from omero_ms_image_region_tpu.server.handler import Renderer


def _settings(C=3):
    pixels = Pixels(image_id=1, pixels_type="uint16", size_x=64, size_y=64,
                    size_c=C)
    rdef = default_rendering_def(pixels)
    rdef.model = RenderingModel.RGB
    colors = [(255, 0, 0), (0, 255, 0), (0, 0, 255)]
    for c, cb in enumerate(rdef.channel_bindings):
        cb.red, cb.green, cb.blue = colors[c % 3]
        cb.input_start, cb.input_end = 0.0, 60000.0
    return pack_settings(rdef)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestPickBucket:
    def test_rounds_up(self):
        assert pick_bucket(100, 200) == (256, 256)
        assert pick_bucket(256, 257) == (512, 512)
        assert pick_bucket(1, 1) == (256, 256)

    def test_oversize_passthrough(self):
        assert pick_bucket(5000, 100) == (5000, 100)


class TestBatchingRenderer:
    def test_matches_direct_renderer(self):
        rng = np.random.default_rng(0)
        settings = _settings()
        raw = rng.integers(0, 60000, size=(3, 40, 56)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(linger_ms=0.5)
            try:
                direct = await Renderer().render(raw, settings)
                batched = await batcher.render(raw, settings)
                return direct, batched
            finally:
                await batcher.close()

        direct, batched = run(main())
        assert batched.shape == (40, 56)       # cropped back from 256 pad
        np.testing.assert_array_equal(direct, batched)

    def test_jpeg_group_cobatches_same_mcu_grid(self):
        """Different true sizes sharing one 16-aligned grid batch together;
        each SOF0 carries its own dimensions."""
        import io

        from PIL import Image

        rng = np.random.default_rng(4)
        settings = _settings()
        raw_a = rng.integers(0, 60000, size=(3, 20, 28)).astype(np.float32)
        raw_b = rng.integers(0, 60000, size=(3, 32, 32)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(max_batch=4, linger_ms=20.0)
            try:
                outs = await asyncio.gather(
                    batcher.render_jpeg(raw_a, settings, 85, 28, 20),
                    batcher.render_jpeg(raw_b, settings, 85, 32, 32))
            finally:
                # close() awaits the in-flight group, so the dispatch
                # counter read below cannot race the group tail when
                # first-tile-out settles the waiters early.
                await batcher.close()
            return outs, batcher.batches_dispatched

        (a, b), dispatched = run(main())
        assert dispatched == 1
        assert Image.open(io.BytesIO(a)).size == (28, 20)
        assert Image.open(io.BytesIO(b)).size == (32, 32)

    def test_jpeg_matches_direct_renderer_jpeg(self):
        rng = np.random.default_rng(5)
        settings = _settings()
        raw = rng.integers(0, 60000, size=(3, 48, 48)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(linger_ms=0.5)
            try:
                direct = await Renderer().render_jpeg(
                    raw, settings, 85, 48, 48)
                batched = await batcher.render_jpeg(
                    raw, settings, 85, 48, 48)
                return direct, batched
            finally:
                await batcher.close()

        direct, batched = run(main())
        assert direct == batched  # same kernel, same entropy coder

    def test_concurrent_requests_coalesce(self):
        rng = np.random.default_rng(1)
        settings = _settings()
        raws = [rng.integers(0, 60000, size=(3, 32, 32)).astype(np.float32)
                for _ in range(8)]

        async def main():
            batcher = BatchingRenderer(max_batch=8, linger_ms=20.0)
            try:
                outs = await asyncio.gather(*(
                    batcher.render(r, settings) for r in raws))
                return outs, batcher.batches_dispatched
            finally:
                await batcher.close()

        outs, n_batches = run(main())
        assert n_batches < len(raws)           # actually coalesced
        direct = Renderer()
        for raw, out in zip(raws, outs):
            expected = run(direct.render(raw, settings))
            np.testing.assert_array_equal(out, expected)

    def test_mixed_settings_share_batch(self):
        """Different windows/colors must still produce per-tile results."""
        rng = np.random.default_rng(2)
        raw = rng.integers(0, 60000, size=(3, 16, 16)).astype(np.float32)
        s1, s2 = _settings(), _settings()
        s2["window_start"] = s2["window_start"] + 1000.0
        s2["tables"] = s2["tables"][..., ::-1].copy()    # swap rgb

        async def main():
            batcher = BatchingRenderer(max_batch=4, linger_ms=20.0)
            try:
                return await asyncio.gather(
                    batcher.render(raw, s1), batcher.render(raw, s2))
            finally:
                await batcher.close()

        out1, out2 = run(main())
        exp1 = run(Renderer().render(raw, s1))
        exp2 = run(Renderer().render(raw, s2))
        np.testing.assert_array_equal(out1, exp1)
        np.testing.assert_array_equal(out2, exp2)
        assert not np.array_equal(out1, out2)

    def test_different_channel_counts_do_not_mix(self):
        rng = np.random.default_rng(3)
        raw3 = rng.integers(0, 60000, size=(3, 16, 16)).astype(np.float32)
        raw4 = rng.integers(0, 60000, size=(4, 16, 16)).astype(np.float32)
        _, s4 = flagship_settings(4)

        async def main():
            batcher = BatchingRenderer(linger_ms=5.0)
            try:
                return await asyncio.gather(
                    batcher.render(raw3, _settings(3)),
                    batcher.render(raw4, s4))
            finally:
                await batcher.close()

        out3, out4 = run(main())
        assert out3.shape == out4.shape == (16, 16)

    def test_render_error_propagates(self):
        settings = _settings()
        bad = np.zeros((2, 16, 16), np.float32)   # C mismatch vs settings

        async def main():
            batcher = BatchingRenderer(linger_ms=0.5)
            try:
                with pytest.raises(Exception):
                    await batcher.render(bad, settings)
            finally:
                await batcher.close()

        run(main())


class TestPipelining:
    def test_groups_overlap_up_to_depth(self):
        """With pipeline_depth=2, a second group dispatches while the
        first is still rendering (the loop must not serialize on the
        full render)."""
        import threading

        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        gate = threading.Event()
        concurrent = {"now": 0, "peak": 0}
        lock = threading.Lock()

        class SlowRenderer(BatchingRenderer):
            def _render_group(self, group):
                with lock:
                    concurrent["now"] += 1
                    concurrent["peak"] = max(concurrent["peak"],
                                             concurrent["now"])
                # Both groups must be in flight before either finishes.
                if concurrent["peak"] < 2:
                    gate.wait(timeout=30)
                else:
                    gate.set()
                with lock:
                    concurrent["now"] -= 1
                return super()._render_group(group)

        r = SlowRenderer(max_batch=1, linger_ms=0.0, pipeline_depth=2)
        rng = np.random.default_rng(3)
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        s = pack_settings(flagship_rdef(1))

        async def go():
            tiles = [rng.integers(0, 60000, (1, 16, 16))
                     .astype(np.float32) for _ in range(2)]
            return await asyncio.gather(
                *(r.render(t, s) for t in tiles))

        outs = asyncio.run(go())
        assert concurrent["peak"] == 2
        assert all(o.shape == (16, 16) for o in outs)

    def test_depth_one_serializes(self):
        import threading

        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        concurrent = {"now": 0, "peak": 0}
        lock = threading.Lock()

        class Probe(BatchingRenderer):
            def _render_group(self, group):
                with lock:
                    concurrent["now"] += 1
                    concurrent["peak"] = max(concurrent["peak"],
                                             concurrent["now"])
                try:
                    return super()._render_group(group)
                finally:
                    with lock:
                        concurrent["now"] -= 1

        r = Probe(max_batch=1, linger_ms=0.0, pipeline_depth=1)
        rng = np.random.default_rng(4)
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        s = pack_settings(flagship_rdef(1))

        async def go():
            tiles = [rng.integers(0, 60000, (1, 16, 16))
                     .astype(np.float32) for _ in range(4)]
            return await asyncio.gather(
                *(r.render(t, s) for t in tiles))

        asyncio.run(go())
        assert concurrent["peak"] == 1


class TestTwoStagePipeline:
    def test_stage_span_recorded_and_results_match_direct(self):
        """The fetch/stage half records its own span and the split
        changes no pixels: batched output equals the direct renderer."""
        from omero_ms_image_region_tpu.utils.stopwatch import REGISTRY

        rng = np.random.default_rng(11)
        settings = _settings()
        raw = rng.integers(0, 60000, size=(3, 24, 24)).astype(np.float32)
        before = REGISTRY.snapshot().get("batcher.stage",
                                         {}).get("count", 0)

        async def main():
            batcher = BatchingRenderer(linger_ms=0.5, device_lanes=2)
            try:
                return await batcher.render(raw, settings)
            finally:
                await batcher.close()

        batched = run(main())
        direct = run(Renderer().render(raw, settings))
        np.testing.assert_array_equal(batched, direct)
        after = REGISTRY.snapshot()["batcher.stage"]["count"]
        assert after == before + 1

    def test_device_lanes_bound_execute_concurrency(self):
        """With device_lanes=1 and pipeline_depth=2, two groups overlap
        in fetch/stage but never in device-execute."""
        import threading

        from omero_ms_image_region_tpu.ops import render as render_ops

        concurrent = {"now": 0, "peak": 0, "staged": 0}
        lock = threading.Lock()
        both_staged = threading.Event()
        real = render_ops.render_tile_batch_packed

        class Probe(BatchingRenderer):
            def _stage_group(self, group):
                out = super()._stage_group(group)
                with lock:
                    concurrent["staged"] += 1
                    if concurrent["staged"] >= 2:
                        both_staged.set()
                # Hold every group in the stage->execute handoff until
                # BOTH have staged, so execute concurrency is actually
                # contested.
                both_staged.wait(timeout=30)
                return out

        def counting_kernel(*args, **kw):
            with lock:
                concurrent["now"] += 1
                concurrent["peak"] = max(concurrent["peak"],
                                         concurrent["now"])
            try:
                import time as _t
                _t.sleep(0.05)    # force overlap if the gate leaked
                return real(*args, **kw)
            finally:
                with lock:
                    concurrent["now"] -= 1

        r = Probe(max_batch=1, linger_ms=0.0, pipeline_depth=2,
                  device_lanes=1)
        rng = np.random.default_rng(12)
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        s = pack_settings(flagship_rdef(1))
        import omero_ms_image_region_tpu.server.batcher as batcher_mod
        orig = batcher_mod.render_tile_batch_packed
        batcher_mod.render_tile_batch_packed = counting_kernel
        try:
            async def go():
                tiles = [rng.integers(0, 60000, (1, 16, 16))
                         .astype(np.float32) for _ in range(2)]
                return await asyncio.gather(
                    *(r.render(t, s) for t in tiles))

            outs = asyncio.run(go())
        finally:
            batcher_mod.render_tile_batch_packed = orig
        assert concurrent["staged"] == 2    # stages ran for both groups
        assert concurrent["peak"] == 1      # executes never overlapped
        assert all(o.shape == (16, 16) for o in outs)

    def test_device_lanes_validation(self):
        with pytest.raises(ValueError):
            BatchingRenderer(device_lanes=0)

    def test_queue_wait_max_gauge_tracks_high_water(self):
        rng = np.random.default_rng(13)
        settings = _settings()
        raw = rng.integers(0, 60000, size=(3, 16, 16)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(linger_ms=5.0)
            try:
                await asyncio.gather(*(
                    batcher.render(raw, settings) for _ in range(4)))
                return batcher.queue_wait_max_ms
            finally:
                await batcher.close()

        max_ms = run(main())
        assert max_ms > 0.0
        # The gauge reaches /metrics through device_metric_lines.
        from omero_ms_image_region_tpu.utils import telemetry

        class _Services:
            renderer = None
        svc = _Services()

        async def gauge():
            svc.renderer = BatchingRenderer(linger_ms=0.0)
            try:
                lines = telemetry.device_metric_lines(svc)
                return [ln for ln in lines
                        if "queue_wait_max_ms" in ln]
            finally:
                await svc.renderer.close()

        assert run(gauge())


class TestTransientRetry:
    """One host-local retry of a group whose dispatch died on a
    transient transport error (utils.transient; tunnel relay drops
    surface as JaxRuntimeError INTERNAL/UNAVAILABLE mid-compile)."""

    @staticmethod
    def _transient_error():
        # Name-matched by is_transient_device_error (the real class
        # lives in jax.errors; the classifier is import-light).
        cls = type("JaxRuntimeError", (RuntimeError,), {})
        return cls("INTERNAL: http://127.0.0.1:8083/remote_compile: "
                   "read body: response body closed before all bytes "
                   "were read")

    def test_classifier(self):
        from omero_ms_image_region_tpu.utils.transient import (
            is_transient_device_error,
        )
        assert is_transient_device_error(self._transient_error())
        # Deterministic program/runtime failures must not match.
        cls = type("JaxRuntimeError", (RuntimeError,), {})
        assert not is_transient_device_error(
            cls("RESOURCE_EXHAUSTED: out of memory"))
        assert not is_transient_device_error(
            ValueError("response body closed"))

    def test_retry_once_then_propagate(self):
        from omero_ms_image_region_tpu.utils.transient import (
            retry_transient,
        )
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise self._transient_error()
            return "ok"

        assert retry_transient(flaky, backoff_s=0.0) == "ok"
        assert calls["n"] == 2

        calls["n"] = 0

        def always_broken():
            calls["n"] += 1
            raise self._transient_error()

        with pytest.raises(RuntimeError):
            retry_transient(always_broken, backoff_s=0.0)
        assert calls["n"] == 2   # exactly one retry

    def test_group_render_survives_one_transient_failure(self):
        settings = _settings()
        rng = np.random.default_rng(1)
        raw = rng.integers(0, 60000, size=(3, 16, 16)).astype(np.float32)
        fails = {"left": 1}
        outer = self

        class Flaky(BatchingRenderer):
            def _render_group(self, group):
                if fails["left"]:
                    fails["left"] -= 1
                    raise outer._transient_error()
                return super()._render_group(group)

        async def main():
            batcher = Flaky(linger_ms=0.0)
            try:
                out = await batcher.render(raw, settings)
                assert out.shape == (16, 16)
            finally:
                await batcher.close()

        run(main())

    def test_multihost_gate_disables_retry(self):
        settings = _settings()
        rng = np.random.default_rng(2)
        raw = rng.integers(0, 60000, size=(3, 16, 16)).astype(np.float32)
        outer = self

        class Flaky(BatchingRenderer):
            def __init__(self, **kw):
                super().__init__(**kw)
                self._transient_retry_enabled = False

            def _render_group(self, group):
                raise outer._transient_error()

        async def main():
            batcher = Flaky(linger_ms=0.0)
            try:
                with pytest.raises(RuntimeError):
                    await batcher.render(raw, settings)
            finally:
                await batcher.close()

        run(main())


class TestPrewarm:
    def test_prewarm_compiles_and_serving_matches(self):
        """prewarm_renderer runs the real serving entry points; a
        subsequent batched render of the warmed shape still produces
        correct output (programs warm, semantics untouched)."""
        from omero_ms_image_region_tpu.server.prewarm import (
            prewarm_renderer,
        )

        prewarm_renderer(["3x64"], ("sparse",), max_batch=2,
                         buckets=((64, 64),))

        settings = _settings()
        rng = np.random.default_rng(5)
        raw = rng.integers(0, 60000, size=(3, 64, 64)).astype(np.float32)

        async def main():
            batcher = BatchingRenderer(linger_ms=0.0,
                                       buckets=((64, 64),))
            try:
                direct = await Renderer().render(raw, settings)
                batched = await batcher.render(raw, settings)
                np.testing.assert_array_equal(np.asarray(direct),
                                              np.asarray(batched))
                jpeg = await batcher.render_jpeg(raw, settings, 85,
                                                 64, 64)
                assert jpeg[:2] == b"\xff\xd8"
            finally:
                await batcher.close()

        run(main())

    def test_prewarm_failure_is_nonfatal(self):
        from omero_ms_image_region_tpu.server.prewarm import (
            prewarm_renderer,
        )

        # 8192 channels is out of parse range -> ValueError (caught at
        # config load normally); prewarm_renderer itself must raise for
        # malformed specs (the loader's contract) ...
        import pytest as _pytest
        with _pytest.raises(ValueError):
            prewarm_renderer(["0x64"], ("sparse",), 2, ((64, 64),))
        # ... but a VALID spec whose compile dies is logged, not fatal.
        prewarm_renderer(["3x64"], ("no-such-engine",), 2, ((64, 64),))

    def test_prewarm_skips_cpu_fallback_shapes_and_dtype_specs(self):
        """Shapes the CPU fallback serves are skipped (their device
        program would never be hit); a spec's :dtype suffix warms the
        storage dtype those images actually stage."""
        from omero_ms_image_region_tpu.server.prewarm import (
            prewarm_renderer,
        )

        # 64*64 = 4096 <= threshold -> skipped (returns instantly even
        # with a bogus engine that would fail compile).
        prewarm_renderer(["3x64"], ("no-such-engine",), 2, ((64, 64),),
                         cpu_fallback_max_px=64 * 64)
        # Non-default storage dtype (uint8 sources) compiles fine.
        prewarm_renderer(["3x64:uint8"], ("sparse",), 2, ((64, 64),))
