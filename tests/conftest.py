"""Test configuration: force an 8-device virtual CPU mesh, and probe
multi-process collective capability.

Tests must not depend on TPU availability; the multi-chip sharding tests run
on XLA's host-platform device virtualization, as the driver's
``dryrun_multichip`` does.

The true multi-PROCESS pod tests (``tests/test_multihost.py``) need more
than virtual devices: the backend must execute computations whose shards
span OS processes.  This image's CPU backend does not —
``jax.device_put`` with a cross-process sharding fails with
``INVALID_ARGUMENT: Multiprocess computations aren't implemented on the
CPU backend`` — so those tests have failed since the seed for an
ENVIRONMENT reason, hiding any real regression inside an
expected-failure count.  ``_multihost_supported`` probes the capability
once per session (two tiny worker processes join via
``jax.distributed`` and run one cross-process sharded reduction); when
the probe fails, every test in ``test_multihost.py`` is SKIPPED with
the probe's verdict as the reason.  On an image whose backend gains the
capability (real TPU slices, a newer CPU collectives build), the probe
passes and the tests run — a regression there fails loudly again.
"""

import os
import socket
import subprocess
import sys

import pytest

# Override (not setdefault): the shell may pin JAX_PLATFORMS to the real
# TPU tunnel, which tests must never touch.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()


# ------------------------------------------- multihost capability probe

# Minimal cross-process sharded computation: exactly the operation the
# multihost tests' workers die on when the backend lacks multiprocess
# collectives (device_put with a sharding spanning both processes).
_PROBE_SCRIPT = r"""
import sys
import numpy as np
pid, coord = int(sys.argv[1]), sys.argv[2]
import jax
jax.distributed.initialize(coord, num_processes=2, process_id=pid)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec
devs = np.array(jax.devices())
mesh = Mesh(devs, ("d",))
arr = jax.device_put(jnp.arange(devs.size),
                     NamedSharding(mesh, PartitionSpec("d")))
print(float(jax.jit(lambda a: a.sum())(arr)))
"""

_MULTIHOST_VERDICT = None   # (supported: bool, reason: str), memoized


def _probe_env() -> dict:
    """One virtual device per worker (the probe needs speed, not
    width), platform-neutral like the tests' own workers."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS",
                        "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def _multihost_supported():
    global _MULTIHOST_VERDICT
    if _MULTIHOST_VERDICT is not None:
        return _MULTIHOST_VERDICT
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    env = _probe_env()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SCRIPT, str(pid), coord],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        text=True) for pid in (0, 1)]
    reason = ""
    ok = True
    for pid, proc in enumerate(procs):
        try:
            _out, err = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            ok, reason = False, "capability probe timed out"
            break
        if proc.returncode != 0:
            ok = False
            tail = [ln for ln in err.strip().splitlines() if ln]
            reason = tail[-1][-200:] if tail else \
                f"probe worker {pid} exited {proc.returncode}"
            break
    _MULTIHOST_VERDICT = (ok, reason)
    return _MULTIHOST_VERDICT


def pytest_collection_modifyitems(config, items):
    multihost = [item for item in items
                 if os.path.basename(str(item.fspath))
                 == "test_multihost.py"]
    if not multihost:
        return
    supported, reason = _multihost_supported()
    if supported:
        return
    marker = pytest.mark.skip(
        reason=f"backend lacks multiprocess collectives "
               f"(env-blocked since seed, not a regression): {reason}")
    for item in multihost:
        item.add_marker(marker)
