"""Test configuration: force an 8-device virtual CPU mesh.

Tests must not depend on TPU availability; the multi-chip sharding tests run
on XLA's host-platform device virtualization, as the driver's
``dryrun_multichip`` does.
"""

import os

# Override (not setdefault): the shell may pin JAX_PLATFORMS to the real
# TPU tunnel, which tests must never touch.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
