"""Control-plane flight data: the decision ledger (ring + spool +
closed vocabulary), per-HOST clock anchoring for cross-host trace
stitching, and fleet-level SLO burn aggregation."""

import json
import threading

import pytest

from omero_ms_image_region_tpu.parallel import federation
from omero_ms_image_region_tpu.utils import decisions, telemetry
from omero_ms_image_region_tpu.utils.decisions import DecisionLedger


@pytest.fixture(autouse=True)
def _fresh_state():
    telemetry.reset()
    federation.uninstall()
    yield
    telemetry.reset()
    federation.uninstall()


# ------------------------------------------------------- decision ledger

class TestDecisionLedger:
    def test_record_returns_seq_and_rings(self):
        led = DecisionLedger(ring_size=16)
        s1 = led.record("autoscaler", "up", member="m0",
                        detail={"signals": {"queue_depth": 3}})
        s2 = led.record("gossip", "ok")
        assert (s1, s2) == (1, 2)
        ring = led.snapshot()
        assert [r["seq"] for r in ring] == [1, 2]
        assert ring[0]["member"] == "m0"
        assert ring[0]["detail"]["signals"]["queue_depth"] == 3
        # No member/host/detail -> the keys are absent, not empty.
        assert "member" not in ring[1] and "host" not in ring[1]

    def test_closed_vocabulary_rejects_without_raising(self):
        led = DecisionLedger()
        assert led.record("autoscaler", "sideways") == -1
        assert led.record("weather", "ok") == -1
        assert led.snapshot() == []
        assert led.status()["records_total"] == 0
        # The exposition side is equally closed: nothing counted.
        assert telemetry.DECISIONS.counts == {}

    def test_every_kind_verdict_pair_in_vocab_is_recordable(self):
        led = DecisionLedger(ring_size=1024)
        for kind in decisions.KINDS:
            for verdict in decisions.VERDICTS:
                assert led.record(kind, verdict) > 0

    def test_ring_bound_evicts_oldest(self):
        led = DecisionLedger(ring_size=16)
        for i in range(40):
            led.record("gossip", "ok", detail={"i": i})
        ring = led.snapshot()
        assert len(ring) == 16
        assert ring[0]["detail"]["i"] == 24        # oldest evicted
        assert led.status()["records_total"] == 40  # lifetime survives

    def test_snapshot_limit_and_isolation(self):
        led = DecisionLedger()
        for _ in range(5):
            led.record("drain", "done")
        tail = led.snapshot(limit=2)
        assert [r["seq"] for r in tail] == [4, 5]
        tail[0]["seq"] = 999                       # copies, not views
        assert led.snapshot()[3]["seq"] == 4

    def test_resolve_attaches_outcome_in_ring(self):
        led = DecisionLedger()
        seq = led.record("autoscaler", "down", member="m3")
        assert led.resolve(seq, {"ticks": 3, "queue_depth_delta": -2})
        [rec] = led.snapshot()
        assert rec["outcome"]["queue_depth_delta"] == -2

    def test_resolve_after_eviction_reports_miss(self):
        led = DecisionLedger(ring_size=16)
        seq = led.record("autoscaler", "up")
        for _ in range(20):
            led.record("gossip", "ok")
        assert not led.resolve(seq, {"ticks": 3})

    def test_spool_writes_jsonl_and_outcome_line(self, tmp_path):
        led = DecisionLedger(spool_dir=str(tmp_path))
        seq = led.record("epoch", "installed", detail={"epoch": 4})
        led.resolve(seq, {"ticks": 1})
        lines = [json.loads(l) for l in
                 (tmp_path / "decisions.jsonl").read_text().splitlines()]
        assert lines[0]["kind"] == "epoch"
        assert lines[0]["detail"]["epoch"] == 4
        # The outcome spools as its OWN line keyed by seq, so a
        # post-mortem can join them even after the ring moved on.
        assert lines[1]["outcome_for"] == seq
        assert led.status()["spool_errors"] == 0

    def test_spool_rotates_once_at_bound(self, tmp_path, monkeypatch):
        monkeypatch.setattr(decisions, "_SPOOL_MAX_BYTES", 256)
        led = DecisionLedger(spool_dir=str(tmp_path))
        for i in range(32):
            led.record("gossip", "ok", detail={"pad": "x" * 32, "i": i})
        assert (tmp_path / "decisions.jsonl").exists()
        assert (tmp_path / "decisions.jsonl.1").exists()
        assert not (tmp_path / "decisions.jsonl.2").exists()
        assert (tmp_path / "decisions.jsonl").stat().st_size < 512

    def test_spool_errors_counted_not_raised(self, tmp_path):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("")
        led = DecisionLedger(spool_dir=str(blocker / "sub"))
        assert led.record("drain", "failed") == 1   # still ringed
        assert led.status()["spool_errors"] == 1

    def test_configure_preserves_ring_contents(self):
        led = DecisionLedger(ring_size=64)
        for _ in range(20):
            led.record("gossip", "ok")
        led.configure(ring_size=16, outcome_horizon_ticks=5,
                      host="hostA")
        ring = led.snapshot()
        assert len(ring) == 16                      # tail-truncated
        assert ring[-1]["seq"] == 20                # newest survive
        st = led.status()
        assert st["ring_size"] == 16
        assert st["outcome_horizon_ticks"] == 5
        assert st["host"] == "hostA"

    def test_configure_floors_pathological_values(self):
        led = DecisionLedger()
        led.configure(ring_size=1, outcome_horizon_ticks=0)
        assert led.status()["ring_size"] == 16
        assert led.outcome_horizon_ticks == 1

    def test_host_stamp_rides_every_record(self):
        led = DecisionLedger(host="hostB")
        led.record("manifest", "agreed", member="b0")
        [rec] = led.snapshot()
        assert rec["host"] == "hostB"

    def test_record_counts_metric_and_fires_flight_event(self):
        decisions.record("autoscaler", "blocked", member="m1",
                         detail={"reason": "floor"})
        decisions.record("gossip", "mismatch")
        lines = telemetry.robustness_metric_lines()
        assert ('imageregion_decision_total{kind="autoscaler",'
                'verdict="blocked"} 1') in lines
        events = [e for e in telemetry.FLIGHT.snapshot()
                  if e["kind"].startswith("decision.")]
        assert [e["kind"] for e in events] == [
            "decision.autoscaler", "decision.gossip"]
        assert events[0]["verdict"] == "blocked"
        assert events[0]["member"] == "m1"
        # Empty member must not mask the flight ring's own
        # process-identity stamp.
        assert "member" not in events[1]

    def test_concurrent_records_never_lose_or_dupe_seqs(self):
        led = DecisionLedger(ring_size=4096)

        def burst():
            for _ in range(100):
                led.record("gossip", "ok")

        threads = [threading.Thread(target=burst) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        seqs = [r["seq"] for r in led.snapshot()]
        assert sorted(seqs) == list(range(1, 801))


# --------------------------------------- cross-host clock anchoring

class TestHostClockAnchoring:
    """The manifest_hello clock exchange, pinned in isolation — the
    sidecar hello's midpoint anchoring lifted to per-HOST, with the
    same clamp contract: skew may place a graft oddly WITHIN its
    exchange window, never outside it."""

    def test_midpoint_offset_derivation(self):
        off = federation.record_host_clock("hostB", 10.0, 10.2, 500.0)
        assert off == pytest.approx(10.1 - 500.0)
        assert federation.host_clock_offset("hostB") == off
        clocks = federation.host_clocks()
        assert clocks["hostB"]["rtt_ms"] == pytest.approx(200.0)

    def test_anchor_maps_remote_instant_into_local_window(self):
        # Remote clock runs 1000 s AHEAD: offset maps it back, and an
        # anchor taken mid-exchange lands mid-window.
        federation.record_host_clock("hostB", 10.0, 10.2, 1010.1)
        t = federation.anchor_remote_time("hostB", 1010.15,
                                          (10.0, 10.2))
        assert t == pytest.approx(10.15)

    def test_negative_offset_skew_clamps_into_window(self):
        # Remote clock far BEHIND local (large positive offset): a
        # stale offset flings the mapped anchor past recv — clamped to
        # the window's hi edge, never after it.
        federation.record_host_clock("hostB", 10.0, 10.2, 5.0)
        late = federation.anchor_remote_time("hostB", 9.0,
                                             (10.0, 10.2))
        assert late == 10.2
        # And a skew throwing it BEFORE send clamps to the lo edge.
        early = federation.anchor_remote_time("hostB", 1.0,
                                              (10.0, 10.2))
        assert early == 10.0

    def test_no_offset_degrades_to_none(self):
        # A peer answering hello WITHOUT the anchor field (an older
        # build): record_host_clock declines, anchoring degrades to
        # None, and callers skip the graft instead of erroring.
        assert federation.record_host_clock("hostB", 1.0, 1.1,
                                            None) is None
        assert federation.host_clock_offset("hostB") is None
        assert federation.anchor_remote_time("hostB", 5.0,
                                             (1.0, 1.1)) is None

    def test_garbage_anchor_fields_degrade_to_none(self):
        assert federation.record_host_clock("hostB", 1.0, 1.1,
                                            "soon") is None
        assert federation.record_host_clock("", 1.0, 1.1, 5.0) is None
        federation.record_host_clock("hostB", 1.0, 1.1, 5.0)
        assert federation.anchor_remote_time("hostB", "soon",
                                             (1.0, 1.1)) is None

    def test_reexchange_overwrites_offset(self):
        # Offsets re-derive on every exchange, bounding drift by the
        # gossip interval: the newest exchange wins.
        federation.record_host_clock("hostB", 10.0, 10.2, 500.0)
        federation.record_host_clock("hostB", 20.0, 20.2, 600.0)
        assert federation.host_clock_offset("hostB") == \
            pytest.approx(20.1 - 600.0)

    def test_hello_handler_answers_clock_and_host(self):
        manifest = federation.FleetManifest(
            [federation.MemberSpec(name="a0", host="hostA")],
            version=1, ring_seed="s")
        federation.install(manifest, self_host="hostA")
        resp = federation.handle_manifest_hello(
            {"manifest_version": 1, "digest": manifest.digest()})
        assert resp["host"] == "hostA"
        assert isinstance(resp["clock"], float)

    def test_remote_host_of_gates_on_cross_host(self):
        manifest = federation.FleetManifest(
            [federation.MemberSpec(name="a0", host="hostA"),
             federation.MemberSpec(name="b0", host="hostB",
                                   address="/tmp/b0.sock")],
            version=1, ring_seed="s")
        federation.install(manifest, self_host="hostA")
        assert federation.remote_host_of("b0") == "hostB"
        assert federation.remote_host_of("a0") == ""   # same host
        assert federation.remote_host_of("zz") == ""   # unknown
        federation.uninstall()
        assert federation.remote_host_of("b0") == ""   # no manifest

    def test_uninstall_clears_clocks(self):
        federation.record_host_clock("hostB", 1.0, 1.2, 50.0)
        federation.uninstall()
        assert federation.host_clocks() == {}


# ------------------------------------------------ fleet-level SLO burn

def _export(err=0, ok=10, slow=0, fast=10, age=1.0,
            availability_target=0.999, latency_ms=100.0):
    return {
        "bucket_s": 5.0,
        "availability_target": availability_target,
        "latency_ms": latency_ms,
        "latency_target": 0.99,
        "fast_window_s": 60.0,
        "slow_window_s": 600.0,
        "buckets": [[age, ok, err, fast, slow]],
    }


class TestFleetSloStats:
    def test_ingest_rejects_empty_or_disabled_exports(self):
        fed = telemetry.FleetSloStats()
        assert not fed.ingest("hostB", {})           # disabled engine
        assert not fed.ingest("hostB", {"buckets": []})
        assert not fed.ingest("", _export())
        assert not fed.ingest("hostB", "nope")
        assert fed.hosts == {}

    def test_host_bound_drops_and_counts_overflow(self):
        fed = telemetry.FleetSloStats()
        for i in range(fed._MAX_HOSTS):
            assert fed.ingest(f"h{i:02d}", _export())
        assert not fed.ingest("h-overflow", _export())
        assert fed.dropped_hosts == 1
        # A KNOWN host always re-ingests (updates, not growth).
        assert fed.ingest("h00", _export(err=3))
        assert len(fed.hosts) == fed._MAX_HOSTS

    def test_burns_expose_the_one_burning_host(self):
        fed = telemetry.FleetSloStats()
        now = [100.0]
        fed.configure(clock=lambda: now[0])
        fed.ingest("hostA", _export(err=0, ok=100, slow=0, fast=100))
        fed.ingest("hostB", _export(err=50, ok=50, slow=80, fast=20))
        doc = fed.burns()
        assert doc["hosts"]["hostA"]["availability"]["fast"] == 0.0
        # hostB burns half its requests against a 99.9% target.
        assert doc["hosts"]["hostB"]["availability"]["fast"] > 100.0
        # The fleet-wide burn sits between the two, well above zero.
        fleet = doc["fleet"]["availability"]["fast"]
        assert 0.0 < fleet < \
            doc["hosts"]["hostB"]["availability"]["fast"]
        assert doc["fleet"]["latency"]["fast"] > 0.0

    def test_aged_buckets_fall_out_of_the_fast_window(self):
        fed = telemetry.FleetSloStats()
        now = [0.0]
        fed.configure(clock=lambda: now[0])
        fed.ingest("hostB", _export(err=10, ok=0, age=1.0))
        assert fed.burns()["hosts"]["hostB"][
            "availability"]["fast"] > 0.0
        now[0] += 120.0                 # past the 60 s fast window
        doc = fed.burns()
        assert doc["hosts"]["hostB"]["availability"]["fast"] == 0.0
        assert doc["hosts"]["hostB"]["availability"]["slow"] > 0.0

    def test_metric_lines_shape_and_emit_when_live(self):
        fed = telemetry.FleetSloStats()
        assert fed.metric_lines() == []              # emit-when-live
        fed.ingest("hostB", _export(err=5, ok=5))
        lines = fed.metric_lines()
        assert any(l.startswith("imageregion_fleet_slo_hosts") and
                   l.endswith(" 1") for l in lines)
        assert any('imageregion_fleet_slo_burn_rate{slo="availability"'
                   in l for l in lines)
        assert any('imageregion_fleet_slo_host_burn_rate{host="hostB"'
                   in l for l in lines)

    def test_fed_slo_rides_robustness_exposition(self):
        telemetry.FED_SLO.ingest("hostB", _export(err=5, ok=5))
        lines = telemetry.robustness_metric_lines()
        assert any("imageregion_fleet_slo_burn_rate" in l
                   for l in lines)


# ------------------------------------------------------- reset contract

class TestControlPlaneResetContract:
    def test_reset_clears_decisions_fed_slo_and_ledger(self):
        decisions.LEDGER.configure(ring_size=64, spool_dir="/tmp/x",
                                   outcome_horizon_ticks=7,
                                   host="hostZ")
        decisions.record("autoscaler", "up", member="m0")
        telemetry.FED_SLO.ingest("hostB", _export(err=5, ok=5))

        telemetry.reset()

        assert telemetry.DECISIONS.counts == {}
        assert telemetry.FED_SLO.hosts == {}
        assert telemetry.FED_SLO.dropped_hosts == 0
        assert decisions.LEDGER.snapshot() == []
        st = decisions.LEDGER.status()
        assert st["records_total"] == 0
        assert st["spool_dir"] is None
        assert st["host"] is None
        assert st["outcome_horizon_ticks"] == 3
        lines = telemetry.robustness_metric_lines()
        assert not any("imageregion_decision_total" in l or
                       "imageregion_fleet_slo" in l for l in lines)
