"""Wire protocol v3: the streaming zero-copy sidecar transport.

Covers the three legs and their degradations:

* scatter-gather frame coalescing (FrameWriter) + the ``respond()``
  drain-under-lock regression;
* the same-host shared-memory ring (server.shmring) — allocation,
  wrap, exhaustion fallback, hostile-descriptor validation;
* progressive chunk streaming — byte-exact vs the v2 single-frame
  body AND vs the jax-free refimpl golden render;
* mixed-version peers: v3 client <-> v2 server and v2 client <-> v3
  server round-trips (per-feature degradation, no hangs, identical
  bytes);
* a seeded frame/descriptor fuzz: truncated/garbled frames, ring
  descriptors past the ring and alien chunk ``seq`` all degrade to
  clean op-errors or a clean reconnect — never a wedged connection;
* the checked-in golden v2+v3 frame corpus (tests/data/wire/): a
  protocol edit that breaks old-frame decoding fails HERE, in tier-1,
  instead of breaking a rolling deploy.
"""

import asyncio
import json
import os
import struct

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.server.app import create_app
from omero_ms_image_region_tpu.server.config import (AppConfig,
                                                     SidecarConfig,
                                                     WireConfig)
from omero_ms_image_region_tpu.server.shmring import RingError, ShmRing
from omero_ms_image_region_tpu.server.sidecar import (FrameWriter,
                                                      SidecarClient,
                                                      _pack,
                                                      _read_frame,
                                                      run_sidecar)
from omero_ms_image_region_tpu.utils import telemetry

IMG = 3
H = W = 64

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "data", "wire")

URL = (f"/webgateway/render_image_region/{IMG}/0/0"
       f"?c=1|0:60000$FF0000&m=g&format=png")
CTX_PARAMS = {"imageId": str(IMG), "theZ": "0", "theT": "0",
              "c": "1|0:60000$FF0000", "m": "g", "format": "png"}


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(21)
    planes = rng.integers(0, 60000, size=(2, 2, H, W)).astype(np.uint16)
    build_pyramid(planes, str(tmp_path / str(IMG)), chunk=(32, 32),
                  n_levels=1)
    return str(tmp_path)


async def _wait_socket(sock, task):
    for _ in range(200):
        if task.done():
            raise AssertionError(
                f"sidecar died at startup: {task.exception()!r}")
        if os.path.exists(sock):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("sidecar socket never appeared")


async def _with_sidecar(data_dir, sock, body, config=None):
    cfg = config or AppConfig(data_dir=data_dir)
    task = asyncio.create_task(run_sidecar(cfg, sock))
    try:
        await _wait_socket(sock, task)
        return await body()
    finally:
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass


def _image_ctx():
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    return ImageRegionCtx.from_params(dict(CTX_PARAMS), None)


# ------------------------------------------------------------- shm ring

def test_shmring_alloc_release_and_wrap():
    ring = ShmRing.create(4096)
    try:
        # Simple round trip.
        off = ring.alloc_write(b"x" * 100)
        assert off == 0
        assert ring.read_release(off, 100) == b"x" * 100
        assert ring.tail == 100
        off2 = ring.alloc_write(b"y" * 3000)
        assert off2 == 100
        assert ring.read_release(off2, 3000) == b"y" * 3000
        # A body that would cross the end skips to the next lap (996
        # dead tail bytes); the consumer's release frees the skipped
        # pad implicitly.
        off3 = ring.alloc_write(b"z" * 1500)
        assert off3 == 4096      # pos 3100 + 1500 > size -> next lap
        assert off3 % 4096 == 0
        assert ring.read_release(off3, 1500) == b"z" * 1500
        # Exhaustion: a body bigger than the free window is a clean
        # None (socket fallback), not an overwrite.
        a = ring.alloc_write(b"a" * 2000)
        assert a is not None
        assert ring.alloc_write(b"b" * 2200) is None
        assert ring.read_release(a, 2000) == b"a" * 2000
        assert ring.alloc_write(b"b" * 2200) is not None   # freed now
        # Oversize and empty bodies never allocate.
        assert ring.alloc_write(b"") is None
        assert ring.alloc_write(b"c" * 5000) is None
    finally:
        ring.close()


def test_shmring_descriptor_validation():
    ring = ShmRing.create(4096)
    try:
        off = ring.alloc_write(b"d" * 256)
        # Beyond head (unwritten), behind tail (released), wrapping,
        # oversize, non-integer: all clean RingErrors.
        with pytest.raises(RingError):
            ring.read_release(off + 1, 256)
        with pytest.raises(RingError):
            ring.read_release(off, 10 ** 9)
        with pytest.raises(RingError):
            ring.read_release("junk", 16)
        assert ring.read_release(off, 256) == b"d" * 256
        with pytest.raises(RingError):
            ring.read_release(off, 256)          # already released
    finally:
        ring.close()


def test_shmring_attach_validates_header():
    ring = ShmRing.create(8192)
    try:
        peer = ShmRing.attach(ring.name, 8192)
        off = ring.alloc_write(b"cross" * 10)
        assert peer.read_release(off, 50) == b"cross" * 10
        assert ring.tail == 50                   # shared cursor
        peer.close()
        with pytest.raises(RingError):
            ShmRing.attach(ring.name, 4096)      # size mismatch
    finally:
        ring.close()


# ----------------------------------------------- FrameWriter coalescing

class _FakeWriter:
    """StreamWriter stand-in: collects buffers; drain() blocks until
    released (the slow-reading-peer simulation)."""

    def __init__(self):
        self.flushes = []          # list of buffer-lists per writelines
        self.gate = asyncio.Event()
        self.gate.set()
        self.drains = 0

    def writelines(self, bufs):
        self.flushes.append([bytes(b) for b in bufs])

    def write(self, b):
        self.flushes.append([bytes(b)])

    async def drain(self):
        self.drains += 1
        await self.gate.wait()

    def close(self):
        pass


def test_framewriter_coalesces_concurrent_frames():
    async def scenario():
        w = _FakeWriter()
        fw = FrameWriter(w)
        try:
            # Enqueued in one tick -> ONE flush, one drain, N frames.
            await asyncio.gather(*(fw.send({"id": i}) for i in range(5)))
            assert len(w.flushes) == 1
            assert w.drains == 1
            assert len(w.flushes[0]) == 5
        finally:
            fw.close()

    asyncio.run(scenario())


def test_framewriter_drain_not_under_a_lock():
    """The respond() regression: with the first flush's drain BLOCKED
    (slow-reading peer), later responders must still enqueue and
    complete their handler-side work — under the old write-lock form
    every respond() serialized behind the stalled drain.  When the
    peer drains, the backlog leaves as one coalesced flush."""
    async def scenario():
        w = _FakeWriter()
        fw = FrameWriter(w)
        try:
            w.gate.clear()                      # peer stops reading
            first = asyncio.create_task(fw.send({"id": 1}))
            await asyncio.sleep(0.05)
            assert w.drains == 1 and not first.done()
            # Two more senders: they enqueue immediately (no lock to
            # park on) even though the drain is stalled.
            s2 = asyncio.create_task(fw.send({"id": 2}))
            s3 = asyncio.create_task(fw.send({"id": 3}))
            await asyncio.sleep(0.05)
            assert len(fw._pending) == 2        # queued, not blocked on
            assert w.drains == 1                # ... the stalled drain
            w.gate.set()                        # peer reads again
            await asyncio.gather(first, s2, s3)
            # The backlog flushed as ONE coalesced writelines.
            assert len(w.flushes) == 2
            assert len(w.flushes[1]) == 2
            assert telemetry.WIRE.flushes >= 2
        finally:
            fw.close()

    telemetry.WIRE.reset()
    asyncio.run(scenario())


def test_framewriter_failure_fails_queued_senders():
    class _DeadWriter(_FakeWriter):
        def writelines(self, bufs):
            raise ConnectionResetError("peer gone")

    async def scenario():
        fw = FrameWriter(_DeadWriter())
        with pytest.raises(ConnectionError):
            await fw.send({"id": 1})
        # The writer is latched dead: later sends refuse immediately.
        with pytest.raises(ConnectionError):
            await fw.send({"id": 2})
        fw.close()

    asyncio.run(scenario())


# ------------------------------------------------------- golden corpus

async def _parse_frames(data: bytes):
    reader = asyncio.StreamReader()
    reader.feed_data(data)
    reader.feed_eof()
    frames = []
    while True:
        try:
            frames.append(await _read_frame(reader))
        except asyncio.IncompleteReadError:
            break
    return frames


def test_golden_corpus_roundtrips_byte_identical():
    """Every checked-in v2 and v3 frame must decode with today's code
    and re-encode to the EXACT original bytes — the compatibility
    contract a rolling deploy depends on."""
    names = sorted(n for n in os.listdir(CORPUS_DIR)
                   if n.endswith(".bin"))
    assert len(names) >= 13, names
    for name in names:
        with open(os.path.join(CORPUS_DIR, name), "rb") as f:
            blob = f.read()
        frames = asyncio.run(_parse_frames(blob))
        assert frames, name
        re_encoded = b"".join(_pack(h, b) for h, b in frames)
        assert re_encoded == blob, f"{name} did not round-trip"


def test_golden_corpus_decodes_expected_semantics():
    def load(name):
        with open(os.path.join(CORPUS_DIR, name), "rb") as f:
            return asyncio.run(_parse_frames(f.read()))

    [(h, b)] = load("v2_request_image.bin")
    assert (h["op"], h["v"], h["id"]) == ("image", 2, 1)
    assert "stream" not in h and "ring" not in h
    [(h, b)] = load("v2_request_plane_put.bin")
    assert h["digest"] == "aa" * 16 and len(b) == 32
    [(h, b)] = load("v3_hello.bin")
    assert h["op"] == "hello" and h["v"] == 3
    assert h["rings"]["c2s"]["size"] == 33554432
    [(h, b)] = load("v3_ring_descriptor.bin")
    assert h["ring"] == [0, 512] and b == b""
    # A coalesced flush is plain frame concatenation: four frames, in
    # order, chunk seqs intact, fin carrying the status.
    frames = load("v3_coalesced_flush.bin")
    assert [f[0].get("seq") for f in frames] == [None, 0, 1, None]
    assert frames[-1][0]["status"] == 200 and frames[-1][0]["fin"]
    assert frames[1][1] + frames[2][1] == b"CHUNK-0-CHUNK-1"


# --------------------------------------------------- mixed-version peers

async def _v2_server(sock, render_body: bytes):
    """A previous-round (v2) sidecar stand-in: single-frame responses,
    scalar+batched plane ops, and 400 on unknown ops (hello included) —
    exactly the degrade surface the mixed-fleet contract documents."""
    resident = set()

    async def on_conn(reader, writer):
        try:
            while True:
                header, body = await _read_frame(reader)
                op = header.get("op")
                rid = header.get("id")
                if op in ("image", "mask"):
                    # v2 ignores the unknown ``stream`` key: ONE frame.
                    out = _pack({"id": rid, "status": 200}, render_body)
                elif op == "plane_probe":
                    digests = header.get("digests")
                    if isinstance(digests, list):
                        doc = {"enabled": True,
                               "resident": [d in resident
                                            for d in digests]}
                    else:
                        doc = {"enabled": True,
                               "resident": header.get("digest")
                               in resident}
                    out = _pack({"id": rid, "status": 200},
                                json.dumps(doc).encode())
                elif op == "plane_put":
                    was = header["digest"] in resident
                    resident.add(header["digest"])
                    out = _pack({"id": rid, "status": 200},
                                json.dumps({"digest": header["digest"],
                                            "resident": was}).encode())
                elif op == "ping":
                    out = _pack({"id": rid, "status": 200},
                                json.dumps({"ok": True}).encode())
                else:
                    out = _pack({"id": rid, "status": 400,
                                 "error": f"unknown op {op!r}"})
                writer.write(out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    return await asyncio.start_unix_server(on_conn, path=sock)


def test_v3_client_against_v2_server_degrades_per_feature(tmp_path):
    """v3 client <-> v2 server: the hello answers 400 (segments are
    destroyed, socket bodies), streamed calls degrade to the v2
    single-frame body, plane staging still dedups — no hangs, bytes
    identical to the v2 contract."""
    sock = str(tmp_path / "v2.sock")
    render_body = b"V2-RENDER-" * 400

    async def scenario():
        server = await _v2_server(sock, render_body)
        telemetry.WIRE.reset()
        client = SidecarClient(sock)
        try:
            # Unary round trip.
            resp_header, payload = await client.call_full("image", {})
            assert resp_header["status"] == 200
            assert bytes(payload) == render_body
            # The hello was declined: no ring on this connection.
            assert telemetry.WIRE.ring_negotiated == 0
            assert telemetry.WIRE.ring_declined >= 1
            assert client._conn.peer_v3 is False
            assert client._conn.recv_ring is None
            # Streamed call: one chunk, byte-identical.
            chunks = [c async for c in client.call_stream("image", {})]
            assert b"".join(chunks) == render_body
            # Bulk staging: uploads once, dedups on repeat.
            rng = np.random.default_rng(3)
            arrs = [rng.integers(0, 60000, size=(1, 16, 16))
                    .astype(np.uint16) for _ in range(3)]
            first = await client.stage_planes(arrs)
            assert [r for _, r in first] == [False] * 3
            again = await client.stage_planes(
                [a.copy() for a in arrs])
            assert [r for _, r in again] == [True] * 3
            return True
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    assert asyncio.run(scenario())


def test_v2_client_against_v3_server_single_frame(data_dir, tmp_path):
    """v2 client <-> v3 server: no hello, no ``stream`` key — the
    server answers exactly one v2 frame whose body is byte-identical
    to what a v3 client (unary AND streamed) gets from the same
    sidecar."""
    sock = str(tmp_path / "v3.sock")

    async def body():
        ctx = _image_ctx()
        # Raw previous-round client: plain frames, no handshake.
        reader, writer = await asyncio.open_unix_connection(sock)
        try:
            writer.write(_pack({"id": 9, "op": "image",
                                "ctx": ctx.to_json(), "v": 2}))
            await writer.drain()
            header, v2_body = await _read_frame(reader)
            assert header["status"] == 200
            assert "fin" not in header and "ring" not in header
        finally:
            writer.close()
        # v3 client, unary and streamed, against the same server.
        client = SidecarClient(sock)
        try:
            resp_header, unary = await client.call_full(
                "image", ctx.to_json())
            assert resp_header["status"] == 200
            chunks = [c async for c in
                      client.call_stream("image", ctx.to_json())]
        finally:
            await client.close()
        assert bytes(unary) == bytes(v2_body)
        assert b"".join(chunks) == bytes(v2_body)
        return True

    assert asyncio.run(_with_sidecar(data_dir, sock, body))


# ------------------------------------------------ streamed byte-exactness

def test_streamed_chunks_concatenate_to_v2_body(data_dir, tmp_path):
    """With the chunk bound forced small, a streamed render really
    splits into multiple ``seq`` frames — and their concatenation is
    byte-identical to the unary (v2-shaped) answer."""
    sock = str(tmp_path / "render.sock")
    cfg = AppConfig(data_dir=data_dir,
                    wire=WireConfig(chunk_max_bytes=4096))

    async def body():
        ctx = _image_ctx()
        client = SidecarClient(sock)
        try:
            telemetry.WIRE.reset()
            _, unary = await client.call_full("image", ctx.to_json())
            chunks = [c async for c in
                      client.call_stream("image", ctx.to_json())]
            assert len(chunks) > 1, \
                f"body of {len(bytes(unary))} B did not chunk"
            assert b"".join(chunks) == bytes(unary)
            assert telemetry.WIRE.streams >= 1
            assert telemetry.WIRE.chunks >= len(chunks)
            return True
        finally:
            await client.close()

    assert asyncio.run(_with_sidecar(data_dir, sock, body, config=cfg))


def test_streamed_http_matches_combined_and_refimpl(data_dir, tmp_path):
    """End-to-end byte exactness: the chunked HTTP response through
    frontend -> sidecar equals the combined single-process answer AND
    the jax-free refimpl golden render (server.degraded) — streaming
    changed WHEN bytes leave, never WHICH bytes."""
    sock = str(tmp_path / "render.sock")

    async def split_body():
        app = create_app(AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            wire=WireConfig(chunk_max_bytes=4096)))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            body = await r.read()
            assert r.status == 200
            assert r.headers["Content-Type"] == "image/png"
            m = await (await client.get("/metrics")).text()
            assert "imageregion_wire_frames_per_flush" in m
            assert "imageregion_wire_streams_total" in m
            return body
        finally:
            await client.close()

    streamed = asyncio.run(_with_sidecar(
        data_dir, sock, split_body,
        config=AppConfig(data_dir=data_dir,
                         wire=WireConfig(chunk_max_bytes=4096))))

    async def combined():
        app = create_app(AppConfig(data_dir=data_dir))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            assert r.status == 200
            return await r.read()
        finally:
            await client.close()

    assert streamed == asyncio.run(combined())

    # The refimpl golden: the degraded CPU handler renders the same
    # ctx through the jax-free reference pipeline.
    from omero_ms_image_region_tpu.server.degraded import (
        DegradedCpuHandler)
    golden = asyncio.run(DegradedCpuHandler(
        AppConfig(data_dir=data_dir)).render_image_region(_image_ctx()))
    assert streamed == golden


def test_streaming_disabled_restores_unary_responses(data_dir,
                                                     tmp_path):
    """wire.streaming: false is the A/B escape hatch — plain buffered
    responses, batcher barrier settlement, identical bytes."""
    sock = str(tmp_path / "render.sock")

    async def body():
        app = create_app(AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(socket=sock, role="frontend"),
            wire=WireConfig(streaming=False)))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(URL)
            body = await r.read()
            assert r.status == 200
            # Buffered (non-chunked) answers carry Content-Length.
            assert "Content-Length" in r.headers
            return body
        finally:
            await client.close()

    off = asyncio.run(_with_sidecar(
        data_dir, sock, body,
        config=AppConfig(data_dir=data_dir,
                         wire=WireConfig(streaming=False))))

    async def combined():
        app = create_app(AppConfig(data_dir=data_dir))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await (await client.get(URL)).read()
        finally:
            await client.close()

    assert off == asyncio.run(combined())


# ------------------------------------------------ first-tile-out settle

def test_first_tile_out_settles_before_barrier():
    """Deterministic mechanism gate for first-tile-out: while the
    encode tail is still running (later tiles undelivered), an earlier
    tile's future is ALREADY resolved with its exact bytes.  The
    smoke bench's timing numbers ride on this; a regression back to
    barrier settlement fails here, not in a jittery latency compare."""
    from omero_ms_image_region_tpu.server.batcher import (
        BatchingRenderer, _Pending)

    async def scenario():
        loop = asyncio.get_running_loop()
        renderer = BatchingRenderer()
        group = [_Pending(raw=None, settings=None, h=1, w=1,
                          future=loop.create_future())
                 for _ in range(3)]
        cb = renderer._early_settle_cb(group)
        assert cb is not None
        # The encode worker thread delivers tile 0 only.
        await asyncio.to_thread(cb, 0, b"tile-0")
        await asyncio.wait_for(group[0].future, 2.0)
        assert group[0].future.result() == b"tile-0"
        assert not group[1].future.done()       # tail still encoding
        assert not group[2].future.done()
        # Padded batch entries past the group are ignored.
        await asyncio.to_thread(cb, 7, b"pad")
        # The rest lands; a final barrier settle skipping done futures
        # (the production path) would now find 1 and 2 already here.
        await asyncio.to_thread(cb, 1, b"tile-1")
        await asyncio.to_thread(cb, 2, b"tile-2")
        await asyncio.wait_for(group[2].future, 2.0)
        assert [p.future.result() for p in group] == \
            [b"tile-0", b"tile-1", b"tile-2"]
        # wire.streaming: false reverts to barrier settlement.
        renderer.first_tile_out = False
        assert renderer._early_settle_cb(group) is None
        # Harness-driven groups (no waiter futures) are a no-op, not
        # a crash.
        renderer.first_tile_out = True
        bare = [_Pending(raw=None, settings=None, h=1, w=1)]
        cb2 = renderer._early_settle_cb(bare)
        cb2(0, b"ignored")
        return True

    assert asyncio.run(scenario())


# ----------------------------------------------------------- frame fuzz

def _mutate(rng, data: bytes) -> bytes:
    b = bytearray(data)
    for _ in range(int(rng.integers(1, 6))):
        kind = rng.integers(0, 4)
        if kind == 0 and len(b) > 4:
            b[int(rng.integers(0, len(b)))] = int(rng.integers(0, 256))
        elif kind == 1 and len(b) > 12:
            del b[int(rng.integers(8, len(b))):]
        elif kind == 2 and len(b) > 16:
            i = int(rng.integers(4, len(b) - 4))
            del b[i:i + int(rng.integers(1, 12))]
        else:
            i = int(rng.integers(0, len(b)))
            b[i:i] = rng.integers(0, 256, int(rng.integers(1, 8)),
                                  dtype=np.uint8).tobytes()
    return bytes(b)


def test_frame_fuzz_never_wedges_the_server(data_dir, tmp_path):
    """scripts/fuzz_decoders.py-style mutation fuzz over the v3
    framing, fed to a LIVE sidecar: every mutated frame either answers
    a clean error frame or drops the connection — and after the whole
    campaign the server still serves a fresh client.  No hangs, no
    unhandled exceptions wedging the accept loop."""
    sock = str(tmp_path / "render.sock")
    seeds = []
    for name in ("v2_request_image.bin", "v3_request_image_stream.bin",
                 "v3_hello.bin", "v3_chunk_seq0.bin",
                 "v3_ring_descriptor.bin", "v2_request_plane_put.bin"):
        with open(os.path.join(CORPUS_DIR, name), "rb") as f:
            seeds.append(f.read())

    async def body():
        rng = np.random.default_rng(1234)
        for i in range(48):
            blob = _mutate(rng, seeds[i % len(seeds)])
            try:
                reader, writer = await asyncio.open_unix_connection(
                    sock)
            except OSError:
                raise AssertionError("server stopped accepting")
            try:
                writer.write(blob)
                try:
                    await writer.drain()
                    # Half-close so a truncation-mutated frame reads
                    # as EOF (an endlessly-open partial frame is a
                    # slow client, not a protocol input).  Then: a
                    # clean error frame, or the server closing — both
                    # contract-clean; a HANG is the bug class hunted.
                    writer.write_eof()
                    await asyncio.wait_for(reader.read(1 << 16),
                                           timeout=5.0)
                except (asyncio.TimeoutError, ConnectionError,
                        OSError):
                    raise AssertionError(
                        f"iter {i}: server wedged on {blob[:40]!r}...")
            finally:
                writer.close()
        # The campaign over, a fresh well-formed client still renders.
        client = SidecarClient(sock)
        try:
            status, _ = await client.call("ping", {})
            assert status == 200
            resp_header, payload = await client.call_full(
                "image", _image_ctx().to_json())
            assert resp_header["status"] == 200 and len(payload) > 0
        finally:
            await client.close()
        return True

    assert asyncio.run(_with_sidecar(data_dir, sock, body))


def test_ring_descriptor_past_ring_is_clean_op_error(data_dir,
                                                     tmp_path):
    """A hostile ring descriptor (offset/length outside the live
    window) answers a 400 op-error and drops the connection — never an
    out-of-window read, never a wedge; the next client serves fine."""
    sock = str(tmp_path / "render.sock")

    async def body():
        rings = (ShmRing.create(1 << 20), ShmRing.create(1 << 20))
        reader, writer = await asyncio.open_unix_connection(sock)
        try:
            writer.write(_pack({
                "id": 1, "op": "hello", "v": 3,
                "rings": {"c2s": {"name": rings[0].name,
                                  "size": 1 << 20},
                          "s2c": {"name": rings[1].name,
                                  "size": 1 << 20}}}))
            await writer.drain()
            header, hello_body = await _read_frame(reader)
            assert header["status"] == 200
            assert json.loads(bytes(hello_body).decode())["ring"]
            # Descriptor way past anything ever written.
            writer.write(_pack({"id": 2, "op": "plane_put", "ctx": {},
                                "v": 3, "digest": "ee" * 16,
                                "dtype": "uint16", "shape": [1, 4, 4],
                                "ring": [10 ** 9, 4096]}))
            await writer.drain()
            header, err_body = await _read_frame(reader)
            assert header["status"] == 400
            assert "ring" in header.get("error", "")
            # The server then drops the (ring-desynced) connection.
            assert await reader.read(4) == b""
        finally:
            writer.close()
            for r in rings:
                r.close()
        # A fresh client is unaffected.
        client = SidecarClient(sock)
        try:
            status, _ = await client.call("ping", {})
            assert status == 200
        finally:
            await client.close()
        return True

    assert asyncio.run(_with_sidecar(data_dir, sock, body))


def test_alien_chunk_seq_fails_stream_cleanly(tmp_path):
    """A v3 peer emitting reordered/alien ``seq`` chunk frames fails
    the stream with a clean ConnectionError (never spliced bytes) and
    the client recovers on a fresh connection."""
    sock = str(tmp_path / "alien.sock")

    async def on_conn(reader, writer):
        try:
            while True:
                header, _ = await _read_frame(reader)
                rid = header.get("id")
                if header.get("op") == "hello":
                    writer.write(_pack(
                        {"id": rid, "status": 200},
                        json.dumps({"v": 3, "ring": False}).encode()))
                elif header.get("op") == "ping":
                    writer.write(_pack({"id": rid, "status": 200},
                                       b"{}"))
                else:
                    # Alien seq: starts at 7 instead of 0.
                    writer.write(_pack({"id": rid, "seq": 7},
                                       b"EVIL"))
                    writer.write(_pack({"id": rid, "status": 200,
                                        "fin": True}))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def scenario():
        server = await asyncio.start_unix_server(on_conn, path=sock)
        client = SidecarClient(sock, retry=None)
        try:
            with pytest.raises(ConnectionError) as ei:
                async for _ in client.call_stream("image", {}):
                    raise AssertionError("alien chunk must not yield")
            assert "seq" in str(ei.value)
            # Clean recovery on a new connection generation.
            status, _ = await client.call("ping", {})
            assert status == 200
            return True
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    assert asyncio.run(scenario())


def test_ring_rides_mb_scale_bodies_end_to_end(data_dir, tmp_path):
    """Same-host staging really crosses the ring: MB-scale plane_put
    bodies hit the ring (descriptor frames on the socket), and the
    plane is verified + resident exactly as on the socket path."""
    sock = str(tmp_path / "render.sock")

    async def body():
        telemetry.WIRE.reset()
        client = SidecarClient(sock)
        rng = np.random.default_rng(8)
        arr = rng.integers(0, 60000, size=(1, 512, 512)) \
            .astype(np.uint16)
        try:
            digest, resident = await client.stage_plane(arr)
            assert resident is False
            assert telemetry.WIRE.ring_negotiated >= 1
            assert telemetry.WIRE.ring_hits >= 1
            assert telemetry.WIRE.ring_bytes >= arr.nbytes
            # Same content again: digest-resident, zero new bodies.
            hits0 = telemetry.WIRE.ring_hits
            _, resident2 = await client.stage_plane(arr.copy())
            assert resident2 is True
            assert telemetry.WIRE.ring_hits == hits0
            return True
        finally:
            await client.close()

    assert asyncio.run(_with_sidecar(data_dir, sock, body))


def test_ring_exhaustion_falls_back_to_socket(tmp_path):
    """Bodies that outgrow the ring window fall back to socket frames
    per-body (counted, never an error)."""
    async def scenario():
        w = _FakeWriter()
        fw = FrameWriter(w)
        ring = ShmRing.create(4096)
        fw.ring = ring
        fw.ring_min_bytes = 16
        try:
            telemetry.WIRE.reset()
            await fw.send({"id": 1}, b"r" * 1000)      # rides the ring
            await fw.send({"id": 2}, b"s" * 8000)      # too big: socket
            assert telemetry.WIRE.ring_hits == 1
            assert telemetry.WIRE.ring_fallbacks == 1
            # The descriptor frame has no socket body; the fallback
            # frame ships prefix + body buffers.
            assert len(w.flushes[0][0]) < 100
            flat = b"".join(b for bufs in w.flushes for b in bufs)
            assert b"s" * 8000 in flat
            assert b"r" * 1000 not in flat
        finally:
            fw.close()
            ring.close()

    asyncio.run(scenario())
