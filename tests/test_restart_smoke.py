"""bench.py --smoke --restart as a tier-1 gate: the warm-state
persistence acceptance path — kill + restart serves the previously-seen
working set from the disk tier + deserialized executables, byte-
identical, without wire fetches or XLA compiles."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_bench_restart_smoke(capsys):
    import bench

    t0 = time.monotonic()
    out = bench.bench_restart_smoke()
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"restart smoke took {elapsed:.0f}s"

    # Acceptance: the repeat working set serves warm — no device
    # dispatch (hence no wire fetch) for >= 90% of it.
    assert out["restart_warm_hit_rate"] >= 0.9, out
    # The rehydrated first tile is byte-identical to the pre-restart
    # render AND to the jax-free refimpl golden render.
    assert out["restart_bytes_identical"] is True
    assert out["restart_first_tile_identical"] is True
    # No XLA compile served the restart window, and the executable
    # ladder really deserialized from disk (the mechanism a true
    # process restart rides).
    assert out["restart_compile_events"] == 0
    assert out["rehydrate_executables_loaded"] >= 1
    assert out["rehydrate_planes_restaged"] >= 1
    assert out["restart_time_to_first_tile_ms"] > 0

    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["metric"] == "restart_smoke"
