"""Elastic fleet autoscaler (server.autoscaler).

Three layers:

* POLICY — hysteresis/hold/cooldown/floor/ceiling over a fake router
  (pure decisions, injectable clock).
* SAFETY — the floor invariant property-tested over the REAL
  ``FleetRouter`` with seeded random trajectories of concurrent
  scale-down ticks, member deaths/revivals and operator drains: the
  number of non-draining members never goes below the floor, no
  member is double-drained, and operator drains are never undrained
  by the controller.
* THE DRILL — a real 3-member fleet under open-loop load-model
  bursts: scale down to the floor, joiners come back WARM
  (pre-stage-back asserted member by member), a full grow-and-shrink
  cycle with ZERO 5xx-without-shed, and no flapping beyond the
  cooldown bound.
"""

import asyncio
import os
import random
import tempfile

import numpy as np
import pytest

from omero_ms_image_region_tpu.flagship import synthetic_wsi_tiles
from omero_ms_image_region_tpu.io.devicecache import DeviceRawCache
from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.parallel.fleet import (
    FleetImageHandler, FleetRouter, LocalMember, build_local_members)
from omero_ms_image_region_tpu.server.admission import (
    AdmissionController)
from omero_ms_image_region_tpu.server.app import build_services
from omero_ms_image_region_tpu.server.autoscaler import Autoscaler
from omero_ms_image_region_tpu.server.config import (AppConfig,
                                                     BatcherConfig,
                                                     RawCacheConfig,
                                                     RendererConfig)
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.server.singleflight import SingleFlight
from omero_ms_image_region_tpu.services.loadmodel import (
    LoadModel, run_open_loop)
from omero_ms_image_region_tpu.utils import telemetry

GRID = 4
EDGE = 64


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(scope="module")
def data_dir():
    rng = np.random.default_rng(33)
    with tempfile.TemporaryDirectory() as tmp:
        planes = synthetic_wsi_tiles(
            rng, 2, 1, GRID * EDGE, GRID * EDGE).reshape(
            2, 1, GRID * EDGE, GRID * EDGE)
        build_pyramid(planes, os.path.join(tmp, "1"), n_levels=1)
        yield tmp


# ------------------------------------------------------------ fakes

class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeMember:
    remote = False

    def __init__(self, name):
        self.name = name
        self.healthy = True
        self.draining = False
        self.drain_intent = None


class _FakeRouter:
    """Pure-policy router: membership flags + a settable depth."""

    def __init__(self, n, lane_width=2):
        self.order = [f"m{i}" for i in range(n)]
        self.members = {name: _FakeMember(name) for name in self.order}
        self.lane_width = lane_width
        self.depth = 0
        self.drains = []
        self.undrains = []

    def queue_depth(self):
        return self.depth

    async def drain_member(self, name, intent="operator", **_kw):
        member = self.members[name]
        member.draining = True
        member.drain_intent = intent
        self.drains.append((name, intent))
        await asyncio.sleep(0)
        return {"member": name, "intent": intent}

    def undrain_member(self, name):
        member = self.members[name]
        member.draining = False
        member.drain_intent = None
        self.undrains.append(name)

    def draining_members(self, intent=None):
        return [n for n in self.order
                if self.members[n].draining
                and (intent is None
                     or self.members[n].drain_intent == intent)]


def _config(**overrides):
    raw = {"fleet": {"enabled": True, "members": 3},
           "autoscaler": {"enabled": True, "hold-ticks": 2,
                          "cooldown-s": 30,
                          "queue-high-per-lane": 3,
                          "queue-low-per-lane": 0.5,
                          **overrides}}
    return AppConfig.from_dict(raw).autoscaler


async def _ticks(scaler, n, advance=None, clock=None):
    out = []
    for _ in range(n):
        if advance is not None:
            clock.advance(advance)
        out.append(scaler.tick())
        await scaler.wait_op()
    return out


class TestPolicy:
    def test_hold_then_scale_down_with_autoscale_intent(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(), router, clock=clock)
            # depth 0 <= low watermark: wants down, held one tick.
            assert scaler.tick() is None
            verdict = scaler.tick()
            await scaler.wait_op()
            assert verdict == "down"
            assert router.drains == [("m2", "autoscale")]
            assert router.members["m2"].draining
            assert scaler.active_members() == ["m0", "m1"]
            assert telemetry.AUTOSCALER.transitions == {"down": 1}
            kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
            assert "autoscale.down" in kinds

        asyncio.run(main())

    def test_cooldown_blocks_consecutive_transitions(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(floor=1), router, clock=clock)
            assert (await _ticks(scaler, 2))[-1] == "down"
            # Still under cooldown: the next sustained want is refused.
            assert (await _ticks(scaler, 2))[-1] == "blocked:cooldown"
            clock.advance(31)
            # The held streak transitions on the first post-cooldown
            # tick.
            assert "down" in await _ticks(scaler, 2)
            assert telemetry.AUTOSCALER.blocked.get("cooldown") == 1

        asyncio.run(main())

    def test_floor_blocks_the_last_members(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(2)
            scaler = Autoscaler(_config(floor=2), router, clock=clock)
            verdicts = await _ticks(scaler, 3)
            assert "down" not in verdicts
            assert verdicts[-1] == "blocked:floor"
            assert router.drains == []

        asyncio.run(main())

    def test_scale_up_rejoins_the_last_parked_member(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(), router, clock=clock)
            await _ticks(scaler, 2)                 # down: m2
            clock.advance(31)
            await _ticks(scaler, 2)                 # down: m1
            clock.advance(31)
            router.depth = 100                      # lanes saturate
            verdict = (await _ticks(scaler, 2))[-1]
            assert verdict == "up"
            assert router.undrains == ["m1"]        # LIFO rejoin
            clock.advance(31)
            assert (await _ticks(scaler, 2))[-1] == "up"
            assert router.undrains == ["m1", "m2"]
            assert telemetry.AUTOSCALER.transitions == {"down": 2,
                                                        "up": 2}

        asyncio.run(main())

    def test_ceiling_blocks_growth(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(ceiling=3), router,
                                clock=clock)
            router.depth = 100
            assert (await _ticks(scaler, 2))[-1] == "blocked:ceiling"

        asyncio.run(main())

    def test_never_undrains_an_operator_drain(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(), router, clock=clock)
            # Operator parks m2 out-of-band.
            await router.drain_member("m2", intent="operator")
            router.depth = 100
            verdict = (await _ticks(scaler, 2))[-1]
            assert verdict == "blocked:no-member"
            assert router.undrains == []

        asyncio.run(main())

    def test_pressure_critical_wants_up(self):
        class _Gov:
            level = 2

        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(), router, governor=_Gov(),
                                clock=clock)
            await router.drain_member("m2", intent="autoscale")
            scaler._scaled_down.append("m2")
            # Queue is empty but the governor reads critical: grow.
            assert (await _ticks(scaler, 2))[-1] == "up"

        asyncio.run(main())

    def test_demand_signal_scales_both_ways(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            demand = {"tps": 0.0}
            scaler = Autoscaler(
                _config(**{"lane-capacity-tps": 10}), router,
                demand_source=lambda: demand["tps"], clock=clock)
            # Predicted demand over routable capacity (3*2*10=60):
            # scale up even with an empty queue... but nothing is
            # parked yet, so the refusal names the reason.
            demand["tps"] = 100.0
            # Every member already active: the growth want forms
            # (queue is empty — only demand drives it) and stops at
            # the ceiling.
            assert (await _ticks(scaler, 2))[-1] == "blocked:ceiling"
            # Demand under the post-shrink capacity: down proceeds.
            demand["tps"] = 20.0
            clock.advance(31)
            assert (await _ticks(scaler, 2))[-1] == "down"
            # Demand above post-shrink capacity: down refused (the
            # want never forms, so the verdict is steady None).
            clock.advance(31)
            demand["tps"] = 35.0
            assert (await _ticks(scaler, 3)) == [None, None, None]

        asyncio.run(main())

    def test_status_doc(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            scaler = Autoscaler(_config(), router, clock=clock)
            await _ticks(scaler, 2)
            doc = scaler.status()
            assert doc["floor"] == 1 and doc["ceiling"] == 3
            assert doc["active"] == ["m0", "m1"]
            assert doc["autoscale_drained"] == ["m2"]
            assert doc["cooldown_remaining_s"] > 0
            assert doc["transitions"][-1]["action"] == "down"
            assert "queue_per_lane" in doc["signals"]

        asyncio.run(main())


# ------------------------------------------------- floor property test

class _StubMember:
    """Minimal member for the REAL FleetRouter: membership,
    drain-handoff and shard surfaces only (no rendering)."""

    remote = False

    def __init__(self, name):
        self.name = name
        self.healthy = True
        self.draining = False
        self.drain_intent = None

    def mark_down(self):
        self.healthy = False

    def revive(self):
        self.healthy = True

    def queue_depth(self):
        return 0

    def resident_digests(self):
        return set()

    def resident_planes(self):
        return 0

    async def shard_manifest(self, limit=0):
        return []

    async def prestage_manifest(self, entries):
        return 0


class _DepthRouter(FleetRouter):
    """Real router with a settable queue-depth reading (the policy
    signal) — drain/undrain/membership stay the real code paths."""

    depth_override = 0

    def queue_depth(self):
        return self.depth_override


class TestFloorProperty:
    def test_floor_holds_under_concurrent_ticks_and_deaths(self):
        """Seeded random trajectories: bursts of ticks WITHOUT
        awaiting the drain op (concurrent-tick races), random member
        deaths/revivals, random operator drains/undrains, random
        queue spikes.  Invariants at EVERY step: non-draining members
        never fall below the floor; a member is never drained twice
        concurrently; operator drains stay drained."""
        rng = random.Random(2026)

        async def trial(trial_i):
            n = rng.choice((2, 3, 4, 5))
            floor = rng.randrange(1, n)
            members = [_StubMember(f"m{i}") for i in range(n)]
            router = _DepthRouter(members, lane_width=2,
                                  steal_min_backlog=0)
            clock = _FakeClock()
            scaler = Autoscaler(
                _config(floor=floor, **{"hold-ticks": 1,
                                        "cooldown-s": 0}),
                router, clock=clock,
                drain_kwargs={"prestage": False,
                              "settle_timeout_s": 0.2})
            operator_drained = set()
            downs = 0
            try:
                for _ in range(80):
                    move = rng.random()
                    name = rng.choice(router.order)
                    member = router.members[name]
                    if move < 0.15:
                        member.mark_down()
                    elif move < 0.30:
                        member.revive()
                    elif move < 0.40 and not member.draining:
                        # Model the /admin/drain guard: operators
                        # cannot drain the last routable member.
                        if [m for m in router.order
                                if router._routable(m)
                                and m != name]:
                            await router.drain_member(
                                name, prestage=False,
                                settle_timeout_s=0.2)
                            operator_drained.add(name)
                    elif move < 0.45 and name in operator_drained:
                        router.undrain_member(name)
                        operator_drained.discard(name)
                    elif move < 0.55:
                        router.depth_override = rng.choice(
                            (0, 0, 200))
                    else:
                        for _ in range(rng.randrange(1, 4)):
                            clock.advance(1)
                            verdict = scaler.tick()
                            if verdict == "down":
                                downs += 1
                                # THE floor property: every down the
                                # CONTROLLER issues leaves at least
                                # ``floor`` members active AND
                                # routable, whatever the operator and
                                # the deaths did around it.
                                active_now = [
                                    m for m in router.order
                                    if not router.members[m]
                                    .draining]
                                routable_now = [
                                    m for m in active_now
                                    if router.members[m].healthy]
                                assert len(active_now) >= floor, \
                                    f"trial {trial_i}: down " \
                                    f"breached the active floor"
                                assert len(routable_now) >= floor, \
                                    f"trial {trial_i}: down " \
                                    f"breached the routable floor"
                        await scaler.wait_op()
                    # ---- invariants, checked EVERY step ----
                    active = [m for m in router.order
                              if not router.members[m].draining]
                    if not operator_drained:
                        # With no operator interference the global
                        # bound holds outright (operators may
                        # legitimately park past the autoscaler's
                        # floor — the controller just never helps).
                        assert len(active) >= floor, \
                            f"trial {trial_i}: floor breached"
                    assert len(router.draining_members()) == len(
                        set(router.draining_members()))
                    for op_name in operator_drained:
                        # The controller never resurrects an
                        # operator's drain.
                        assert (router.members[op_name].draining
                                or op_name not in
                                scaler._scaled_down), \
                            f"trial {trial_i}: operator drain undone"
                        assert router.members[op_name] \
                            .drain_intent != "autoscale" \
                            or not router.members[op_name].draining
            finally:
                await scaler.wait_op()
                await router.close()
            return downs

        total_downs = 0
        for trial_i in range(12):
            total_downs += asyncio.run(trial(trial_i))
        # The trajectories really exercised scale-downs (a vacuous
        # pass would prove nothing).
        assert total_downs > 5


# ------------------------------------------------------------ the drill

class TestElasticityDrill:
    def test_full_grow_and_shrink_cycle_with_warm_joiners(
            self, data_dir):
        """THE acceptance drill: idle -> scale down to the floor ->
        open-loop burst (load model arrivals) grows the fleet back
        member by member, each joiner provably WARM (pre-stage-back:
        its drained shard is HBM-resident again, and its first owned
        requests hit >= 0.8) -> quiet -> shrink back to the floor.
        Zero 5xx-without-shed across the whole drill; transitions
        bounded by the cooldown (no flapping)."""
        exec_ms = 50.0
        cooldown = 60.0

        class VirtualDeviceMember(LocalMember):
            async def render(self, ctx, adopt_cache=True):
                data = await super().render(ctx, adopt_cache)
                await asyncio.sleep(exec_ms / 1000.0)
                return data

        def working_set():
            out = []
            for v in range(2):
                for x in range(GRID):
                    for y in range(GRID):
                        w = 30000 + v * 800
                        out.append(ImageRegionCtx.from_params({
                            "imageId": "1", "theZ": "0", "theT": "0",
                            "tile": f"0,{x},{y},{EDGE},{EDGE}",
                            "format": "png", "m": "c",
                            "c": f"1|0:{w}$FF0000,"
                                 f"2|0:{w - 700}$00FF00",
                        }))
            return out

        model = LoadModel(viewers=48, seed=37, duration_s=60.0,
                          grid=GRID, diurnal_amplitude=0.0,
                          bulk_fraction=0.0, mask_fraction=0.0,
                          zoom_fraction=0.0)
        natural = model.events()

        async def drill():
            config = AppConfig(
                data_dir=data_dir,
                batcher=BatcherConfig(enabled=False),
                raw_cache=RawCacheConfig(enabled=True,
                                         prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0))
            services = build_services(config)
            members = [VirtualDeviceMember(
                m.name, m.handler, m.services,
                down_cooldown_s=m.down_cooldown_s,
                byte_cache_prechecked=m.byte_cache_prechecked)
                for m in build_local_members(config, services, 3)]
            router = FleetRouter(members, lane_width=2,
                                 steal_min_backlog=0)
            handler = FleetImageHandler(
                router, single_flight=SingleFlight(),
                admission=AdmissionController(4096, renderer=router),
                base_services=services)
            clock = _FakeClock()
            scaler = Autoscaler(
                _config(floor=1, **{
                    "hold-ticks": 1,
                    "cooldown-s": cooldown,
                    "queue-high-per-lane": 2.0,
                    "queue-low-per-lane": 0.25,
                }), router, clock=clock,
                drain_kwargs={"prestage": True, "max_planes": 256,
                              "settle_timeout_s": 10.0})

            async def submit(arrival):
                sid = int(arrival.session.rsplit("-", 1)[1])
                w = 21000 + (sid * 131 + arrival.step * 37) % 18000
                ctx = ImageRegionCtx.from_params({
                    "imageId": "1", "theZ": "0", "theT": "0",
                    "tile": f"0,{arrival.x},{arrival.y},{EDGE},"
                            f"{EDGE}",
                    "format": "png", "m": "c",
                    "c": f"1|0:{w}$FF0000,2|0:{w - 900}$00FF00",
                })
                ctx.omero_session_key = arrival.session
                out = await handler.render_image_region(ctx)
                assert out

            reports = []
            try:
                working = working_set()
                # Warm the whole working set: every member's shard
                # holds planes to hand over.
                await asyncio.gather(*(
                    handler.render_image_region(c) for c in working))
                shard_at_drain = {}

                # ---- RAMP DOWN to the floor (quiet fleet) ----
                for expect in ("m2", "m1"):
                    clock.advance(cooldown + 1)
                    shard_at_drain[expect] = set(
                        router.members[expect].resident_digests())
                    verdict = scaler.tick()
                    await scaler.wait_op()
                    assert verdict == "down", verdict
                    assert router.members[expect].draining
                    assert router.members[expect].drain_intent == \
                        "autoscale"
                clock.advance(cooldown + 1)
                assert scaler.tick() == "blocked:floor"
                assert scaler.active_members() == ["m0"]

                # "Restart" the parked members: cold HBM (exactly
                # what a real scale-down teardown drops).
                for name in ("m1", "m2"):
                    member = router.members[name]
                    member.services.raw_cache = DeviceRawCache(
                        member.services.raw_cache.max_bytes)

                # ---- RAMP UP: open-loop bursts grow the fleet ----
                # member by member; each joiner must come back WARM.
                for expect in ("m1", "m2"):
                    nominal_m0 = 2 * 1000.0 / exec_ms     # 40 tps
                    burst = model.window(3.0 * nominal_m0, 2.0,
                                         natural)
                    burst_task = asyncio.create_task(
                        run_open_loop(submit, burst))
                    grown = None
                    for _ in range(400):
                        # Tick only once the queue signal is live:
                        # the drill's fake clock jumps past the
                        # cooldown per tick, so an empty-queue tick
                        # between bursts would read as a sustained
                        # quiet period and scale DOWN mid-ramp.
                        if router.queue_depth() >= 2 * 2 * 2:
                            clock.advance(cooldown + 1)
                            verdict = scaler.tick()
                            if verdict == "up":
                                grown = verdict
                                break
                        await asyncio.sleep(0.01)
                    assert grown == "up", "burst never grew the fleet"
                    assert not router.members[expect].draining
                    reports.append(await burst_task)
                    # Pre-stage-back: the drain-time shard manifest
                    # replayed into the joiner — resident BEFORE we
                    # measure its first owned requests.
                    task = router.last_undrain_prestage
                    assert task is not None, \
                        f"{expect}: no pre-stage-back scheduled"
                    await task
                    member = router.members[expect]
                    back = set(member.resident_digests())
                    assert shard_at_drain[expect] <= back, \
                        f"{expect}: rejoined cold " \
                        f"({len(back)}/{len(shard_at_drain[expect])})"
                    # Warm-hit rate on the joiner's owned working
                    # set (quiet fleet — the burst settled above).
                    owned = [c for c in working
                             if router.owner_of(c) == expect]
                    if owned:
                        hits_before = member.services.raw_cache.hits
                        for c in owned:
                            await handler.render_image_region(c)
                        rate = (member.services.raw_cache.hits
                                - hits_before) / len(owned)
                        assert rate >= 0.8, \
                            f"{expect}: warm-hit {rate:.2f} < 0.8"

                # ---- RAMP DOWN again (the shrink half) ----
                for _ in range(2):
                    clock.advance(cooldown + 1)
                    verdict = scaler.tick()
                    await scaler.wait_op()
                    assert verdict == "down", verdict
                assert scaler.active_members() == ["m0"]
            finally:
                await router.close()
                services.pixels_service.close()
            return scaler, reports

        scaler, reports = asyncio.run(drill())
        # Zero 5xx-without-shed across every open-loop burst (with
        # the admission bound this high, zero sheds too).
        for report in reports:
            assert report.errors == [], report.errors[:3]
            assert report.sheds == 0
            assert report.served > 0
        # One full grow-and-shrink cycle, exactly — flapping bounded
        # by the cooldown: every consecutive transition pair is
        # separated by at least the cooldown on the policy clock.
        actions = [t["action"] for t in scaler.transitions]
        assert actions == ["down", "down", "up", "up", "down", "down"]
        times = [t["t"] for t in scaler.transitions]
        assert all(b - a >= cooldown
                   for a, b in zip(times, times[1:]))
        assert telemetry.AUTOSCALER.transitions == {"down": 4,
                                                    "up": 2}
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "autoscale.down" in kinds and "autoscale.up" in kinds


# -------------------------------------------------- app-level surfaces

def _app_config(data_dir, **autoscaler_overrides):
    config = AppConfig.from_dict({
        "data-dir": data_dir,
        "batcher": {"enabled": False},
        "raw-cache": {"enabled": True, "prefetch": False},
        "renderer": {"cpu-fallback-max-px": 0},
        "fleet": {"enabled": True, "members": 2},
        "autoscaler": {"enabled": True, "interval-s": 30,
                       **autoscaler_overrides},
    })
    return config


class TestAppSurfaces:
    def test_admin_autoscaler_status_endpoint(self, data_dir):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app

        async def scenario():
            client = TestClient(TestServer(
                create_app(_app_config(data_dir))))
            await client.start_server()
            try:
                r = await client.get("/admin/autoscaler")
                assert r.status == 200
                doc = await r.json()
                assert doc["enabled"] is True
                assert doc["floor"] == 1 and doc["ceiling"] == 2
                assert doc["active"] == ["m0", "m1"]
                assert "queue_per_lane" in doc["signals"]
                # /readyz carries the controller annotation.
                body = await (await client.get("/readyz")).json()
                assert body["checks"]["autoscaler"] == \
                    "2/2 active (floor 1)"
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_autoscaler_disabled_answers_400(self, data_dir):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import create_app

        async def scenario():
            config = AppConfig(
                data_dir=data_dir,
                batcher=BatcherConfig(enabled=False),
                raw_cache=RawCacheConfig(enabled=True,
                                         prefetch=False),
                renderer=RendererConfig(cpu_fallback_max_px=0))
            client = TestClient(TestServer(create_app(config)))
            await client.start_server()
            try:
                r = await client.get("/admin/autoscaler")
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_autoscale_drain_never_trips_fail_readyz(self, data_dir):
        """THE drain-flavor satellite: with ``drain.fail-readyz`` ON,
        an operator drain answers /readyz 503 (the rolling-restart
        posture) but an AUTOSCALE drain of the same member keeps
        /readyz 200 and annotates — a routine scale-down must not
        read as the instance leaving rotation."""
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import (
            FLEET_ROUTER_KEY, create_app)

        async def scenario(intent):
            config = _app_config(data_dir)
            config.drain.fail_readyz = True
            app = create_app(config)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                router = app[FLEET_ROUTER_KEY]
                await router.drain_member(
                    "m1", prestage=False, settle_timeout_s=2.0,
                    intent=intent)
                r = await client.get("/readyz")
                body = await r.json()
                status, note = r.status, body["checks"]["drain"]
                router.undrain_member("m1")
                assert (await client.get("/readyz")).status == 200
                return status, note
            finally:
                await client.close()

        status, note = asyncio.run(scenario("operator"))
        assert status == 503 and note == "draining: m1"
        status, note = asyncio.run(scenario("autoscale"))
        assert status == 200
        assert note == "draining: m1(autoscale)"

    def test_drain_status_carries_the_intent(self, data_dir):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import (
            FLEET_ROUTER_KEY, create_app)

        async def scenario():
            app = create_app(_app_config(data_dir))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                router = app[FLEET_ROUTER_KEY]
                await router.drain_member(
                    "m1", prestage=False, settle_timeout_s=2.0,
                    intent="autoscale")
                doc = await (await client.get("/admin/drain")).json()
                assert doc["members"]["m1"]["intent"] == "autoscale"
                assert doc["members"]["m0"]["intent"] is None
                # Operator undrain reclaims the member: intent clears.
                r = await client.post("/admin/undrain?member=m1")
                doc = await r.json()
                assert doc["members"]["m1"]["intent"] is None
            finally:
                await client.close()

        asyncio.run(scenario())


class TestQuiesceReadyzPosture:
    def test_sigterm_quiesce_still_trips_fail_readyz(self, data_dir):
        """The SIGTERM shutdown chain quiesces members by flipping
        ``draining`` with NO intent — that must keep pulling the
        instance under ``drain.fail-readyz`` exactly like an operator
        drain (only the explicit ``autoscale`` flavor is exempt)."""
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.server.app import (
            FLEET_ROUTER_KEY, create_app)

        async def scenario():
            config = _app_config(data_dir)
            config.drain.fail_readyz = True
            app = create_app(config)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                router = app[FLEET_ROUTER_KEY]
                # The quiesce hook's exact effect (server.shutdown):
                # draining flag only, no intent.
                for name in router.order:
                    router.members[name].draining = True
                assert (await client.get("/readyz")).status == 503
            finally:
                await client.close()

        asyncio.run(scenario())


# ------------------------------------------------ unit lifecycle drill

class _FakeProc:
    def __init__(self):
        self.alive = True
        self.terminated = 0
        self.pid = 4242

    def poll(self):
        return None if self.alive else 0

    def terminate(self):
        self.terminated += 1
        self.alive = False

    def wait(self, timeout=None):
        return 0

    def kill(self):
        self.alive = False


class TestUnitLifecycle:
    """PR 13 follow-on: the supervisor actually STOPS parked sidecar
    units (after their drain settles — the warm handoff needs the
    live process) and RESTARTS them before undrain on scale-up,
    instead of parking pre-provisioned warm processes."""

    def _lifecycle(self, names):
        from omero_ms_image_region_tpu.server.sidecar import (
            SidecarUnit, SidecarUnitLifecycle)
        spawned = []

        def spawn_fn():
            proc = _FakeProc()
            spawned.append(proc)
            return proc

        lc = SidecarUnitLifecycle(
            {n: SidecarUnit(n, spawn_fn) for n in names})
        return lc, spawned

    def test_unit_start_stop_idempotent(self):
        lc, spawned = self._lifecycle(["m0"])
        lc.start("m0")
        lc.start("m0")                      # no double spawn
        assert len(spawned) == 1 and lc.alive("m0")
        lc.stop("m0")
        lc.stop("m0")                       # no double terminate
        assert spawned[0].terminated == 1 and not lc.alive("m0")
        lc.start("m0")                      # restart spawns fresh
        assert len(spawned) == 2
        lc.stop("unknown")                  # unknown member: no-op
        assert telemetry.FLIGHT is not None

    def test_drill_scale_down_stops_unit_scale_up_restarts_first(self):
        """THE drill: park a member -> its drain completes -> its
        PROCESS stops; demand returns -> the unit respawns and only
        then does the member undrain (routes never land on a dead
        socket).  Order is asserted through an event tape."""
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(3)
            lc, spawned = self._lifecycle(router.order)
            lc.start_all()
            assert all(lc.alive(n) for n in router.order)
            tape = []

            real_drain = router.drain_member

            async def drain_spy(name, **kw):
                tape.append(("drain", name))
                return await real_drain(name, **kw)

            router.drain_member = drain_spy
            real_undrain = router.undrain_member
            router.undrain_member = \
                lambda name: (tape.append(("undrain", name)),
                              real_undrain(name))[1]

            unit = lc.units["m2"]
            real_stop, real_start = unit.stop, unit.start
            unit.stop = lambda *a, **k: (tape.append(("stop", "m2")),
                                         real_stop(*a, **k))[1]
            unit.start = lambda: (tape.append(("start", "m2")),
                                  real_start())[1]

            scaler = Autoscaler(_config(), router, lifecycle=lc,
                                clock=clock)
            verdicts = await _ticks(scaler, 2)
            assert verdicts[-1] == "down"
            # The parked member's PROCESS is gone; the others live.
            assert not lc.alive("m2")
            assert lc.alive("m0") and lc.alive("m1")
            assert tape == [("drain", "m2"), ("stop", "m2")]

            clock.advance(31)
            router.depth = 100              # lanes saturate: want up
            verdict = (await _ticks(scaler, 2))[-1]
            assert verdict == "up"
            assert lc.alive("m2")           # respawned
            assert not router.members["m2"].draining
            # Start STRICTLY before undrain.
            assert tape == [("drain", "m2"), ("stop", "m2"),
                            ("start", "m2"), ("undrain", "m2")]
            kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
            assert "autoscale.unit-stop" in kinds
            assert "autoscale.unit-start" in kinds

        asyncio.run(main())

    def test_failed_respawn_reparks_the_member_for_retry(self):
        async def main():
            clock = _FakeClock()
            router = _FakeRouter(2)

            from omero_ms_image_region_tpu.server.sidecar import (
                SidecarUnit, SidecarUnitLifecycle)
            attempts = []

            def flaky_spawn():
                attempts.append(1)
                if len(attempts) < 3:
                    raise RuntimeError("socket never appeared")
                return _FakeProc()

            lc = SidecarUnitLifecycle(
                {"m1": SidecarUnit("m1", flaky_spawn)})
            scaler = Autoscaler(_config(floor=1), router,
                                lifecycle=lc, clock=clock)
            assert (await _ticks(scaler, 2))[-1] == "down"
            clock.advance(31)
            router.depth = 100
            # First up attempt: spawn fails, the member stays parked
            # (draining, autoscale intent) and is retried later.
            assert (await _ticks(scaler, 2))[-1] == "up"
            assert router.members["m1"].draining
            assert scaler._scaled_down == ["m1"]
            clock.advance(31)
            assert (await _ticks(scaler, 2))[-1] == "up"
            assert router.members["m1"].draining          # failed again
            clock.advance(31)
            assert (await _ticks(scaler, 2))[-1] == "up"
            assert not router.members["m1"].draining      # third's a charm
            assert lc.alive("m1")

        asyncio.run(main())
