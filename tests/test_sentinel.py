"""server/sentinel.py + telemetry.SentinelStats — the live
perf-regression sentinel.

Covered contracts:

* ``telemetry.reset()`` clears the sentinel accumulator (the
  test-isolation contract every suite here leans on);
* every ``imageregion_sentinel_*`` family lints clean against the
  committed cardinality budget, HELP/TYPE exactly once;
* the (route-class, shape-bucket) vocabularies are CLOSED — unknown
  routes and huge payloads land in the overflow classes, never a new
  series;
* the drift engine on a virtual clock: warmup -> confirmed drift
  (exactly once, with ledger record and one complete bundle,
  manifest written last) -> recovery;
* the committed-watermark latency floor suppresses baseline-relative
  drift verdicts;
* learned baselines round-trip through export/load (the warm-state
  manifest path).
"""

import importlib.util
import json
import os

import pytest

from omero_ms_image_region_tpu.server import sentinel as sentinel_mod
from omero_ms_image_region_tpu.server.sentinel import (
    ROUTE_CLASSES, SHAPE_BUCKETS, SentinelEngine, route_class,
    shape_bucket)
from omero_ms_image_region_tpu.utils import decisions, telemetry

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    decisions.LEDGER.reset()
    yield
    telemetry.reset()
    decisions.LEDGER.reset()


@pytest.fixture(scope="module")
def lint():
    return _load_script("metrics_lint")


@pytest.fixture(scope="module")
def budget(lint):
    return lint.load_budget()


def _summary(member="local", verdict="ok", **over):
    doc = {
        "member": member, "verdict": verdict, "ticks": 3,
        "observations": 240, "drifting": [],
        "throughput_drift": False, "tiles_per_s": 48.0,
        "watermark_tiles_per_s": 40.0,
        "routes": {"render_image_region":
                   {"n": 240, "p99_ms": 31.5,
                    "baseline_p99_ms": 30.0}},
        "keys": {}, "last_bundle": None,
    }
    doc.update(over)
    return doc


class TestResetContract:
    def test_reset_clears_sentinel_accumulator(self):
        telemetry.SENTINEL.set_local(_summary())
        telemetry.SENTINEL.ingest("peer", _summary(member="peer",
                                                   verdict="drifting"))
        telemetry.SENTINEL.count_drift()
        telemetry.SENTINEL.count_bundle()
        telemetry.SENTINEL.count_bundle(error=True)
        assert telemetry.SENTINEL.export() is not None
        assert telemetry.SENTINEL.metric_lines()

        telemetry.reset()

        assert telemetry.SENTINEL.export() is None
        merged = telemetry.SENTINEL.merged()
        assert merged["verdict"] == "ok"
        assert merged["members"] == {}
        assert merged["drifts"] == 0
        assert merged["bundles"] == 0
        assert merged["bundle_errors"] == 0
        # emit-when-live: a reset accumulator exports no series.
        assert telemetry.SENTINEL.metric_lines() == []

    def test_merged_folds_local_and_peers(self):
        telemetry.SENTINEL.set_local(_summary(member="m0"))
        telemetry.SENTINEL.ingest(
            "m1", _summary(member="m1", verdict="drifting"))
        merged = telemetry.SENTINEL.merged()
        assert set(merged["members"]) == {"m0", "m1"}
        assert merged["verdict"] == "drifting"
        assert merged["drifting_members"] == ["m1"]

    def test_ingest_rejects_garbage_and_bounds_members(self):
        assert not telemetry.SENTINEL.ingest("m1", None)
        assert not telemetry.SENTINEL.ingest("m1", {"no": "verdict"})
        assert not telemetry.SENTINEL.ingest("", _summary())
        for i in range(telemetry.SentinelStats._MAX_MEMBERS):
            assert telemetry.SENTINEL.ingest(f"m{i}", _summary())
        assert not telemetry.SENTINEL.ingest("overflow", _summary())
        assert telemetry.SENTINEL.merged()["dropped_members"] == 1


class TestMetricsBudget:
    def test_sentinel_families_lint_clean(self, lint, budget):
        telemetry.SENTINEL.set_local(_summary())
        telemetry.SENTINEL.ingest(
            "m1", _summary(member="m1", verdict="drifting"))
        telemetry.SENTINEL.count_drift()
        text = telemetry.finalize_exposition(
            telemetry.request_metric_lines(exemplars=True))
        assert "imageregion_sentinel_drift " in text
        assert 'imageregion_sentinel_live_p99_ms{' in text
        assert 'imageregion_sentinel_member_drift{member="m1"}' \
            in text
        assert lint.lint_exposition(text, budget) == []

    def test_help_type_emitted_once(self):
        telemetry.SENTINEL.set_local(_summary())
        text = telemetry.finalize_exposition(
            telemetry.request_metric_lines())
        for family in ("imageregion_sentinel_drift",
                       "imageregion_sentinel_ticks_total",
                       "imageregion_sentinel_live_p99_ms"):
            assert text.count(f"# HELP {family} ") == 1
            assert text.count(f"# TYPE {family} ") == 1

    def test_every_sentinel_family_registered(self):
        for family in telemetry.METRIC_TYPES:
            if family.startswith("imageregion_sentinel_"):
                assert family in telemetry.METRIC_HELP


class TestClosedVocabularies:
    def test_route_class_maps_unknowns_to_other(self):
        for route in ROUTE_CLASSES:
            assert route_class(route) == route
        assert route_class("render_thumbnail") == "other"
        assert route_class("") == "other"

    def test_shape_bucket_ladder(self):
        assert shape_bucket(0) == "s4k"
        assert shape_bucket(4096) == "s4k"
        assert shape_bucket(4097) == "s16k"
        assert shape_bucket(1 << 20) == "s1m"
        assert shape_bucket(1 << 40) == "sbig"
        assert shape_bucket(-5) == "s4k"

    def test_observe_never_mints_open_keys(self):
        eng = SentinelEngine(member="t", bundle_dir="")
        eng.observe("render_image_region", 65536, 10.0)
        eng.observe("totally/new/route", 65536, 10.0)
        eng.observe("another?weird=1", 1 << 33, 10.0)
        for route, shape in eng._keys:
            assert route in ROUTE_CLASSES
            assert shape in SHAPE_BUCKETS
        assert ("other", "s64k") in eng._keys
        assert ("other", "sbig") in eng._keys


def _make_engine(tmp_path, clk, **over):
    kwargs = dict(
        member="t0",
        tick_interval_s=5.0,
        confirm_ticks=2,
        recover_ticks=2,
        min_samples=8,
        warmup_ticks=2,
        drift_ratio=1.5,
        baseline_alpha=0.2,
        bundle_dir=str(tmp_path),
        max_bundles=3,
        profile_ms=10,
        watermarks={"bench": {
            "p50_service_tile_ms_ex_rtt": {"value": 5.0},
            "service_tiles_per_sec": {"value": 0.001}}},
        clock=lambda: clk[0],
        profile_fn=lambda directory, ms: {"skipped": "test"},
        flight_fn=lambda: {"events": [{"kind": "test"}]},
        costs_fn=lambda: [{"trace": "t-1"}],
        exemplars_fn=lambda: {"render_image_region": []},
    )
    kwargs.update(over)
    return SentinelEngine(**kwargs)


def _feed(engine, center_ms, n=12):
    for i in range(n):
        engine.observe("render_image_region", 65536,
                       center_ms * (1.0 + 0.03 * (i % 4)))


def _tick(engine, clk):
    clk[0] += 5.0
    return engine.tick()


class TestDriftLifecycle:
    def test_confirm_capture_recover(self, tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk)

        # Warmup: learn the 12ms baseline.
        for _ in range(3):
            _feed(eng, 12.0)
            s = _tick(eng, clk)
            assert s["verdict"] == "ok"

        # Step to 40ms: first breach tick must NOT confirm...
        _feed(eng, 40.0)
        s = _tick(eng, clk)
        assert s["verdict"] == "ok"
        assert not os.listdir(tmp_path)
        # ...the second (confirm_ticks=2) must, exactly once.
        _feed(eng, 40.0)
        s = _tick(eng, clk)
        assert s["verdict"] == "drifting"
        assert s["drifting"] == ["render_image_region|s64k"]
        assert eng.verdict == "drifting"

        drift_records = [r for r in decisions.LEDGER.snapshot()
                         if r["kind"] == "sentinel"
                         and r["verdict"] == "drift"]
        assert len(drift_records) == 1
        assert drift_records[0]["detail"]["keys"] == \
            ["render_image_region|s64k"]

        # One complete bundle: every artifact present, manifest last.
        bundles = os.listdir(tmp_path)
        assert len(bundles) == 1
        bdir = os.path.join(tmp_path, bundles[0])
        with open(os.path.join(bdir, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["kind"] == "sentinel_incident"
        assert manifest["member"] == "t0"
        for key in ("flight", "costs", "sketch_diff", "exemplars",
                    "profile"):
            fname = manifest["files"][key]
            assert fname, f"missing artifact {key}"
            assert os.path.exists(os.path.join(bdir, fname))
        assert s["last_bundle"] == bdir

        # A STILL-drifting tick re-fires neither record nor bundle.
        _feed(eng, 40.0)
        s = _tick(eng, clk)
        assert s["verdict"] == "drifting"
        assert len(os.listdir(tmp_path)) == 1
        assert len([r for r in decisions.LEDGER.snapshot()
                    if r["verdict"] == "drift"]) == 1

        # Recovery: recover_ticks=2 clean windows clear the verdict.
        _feed(eng, 12.0)
        assert _tick(eng, clk)["verdict"] == "drifting"
        _feed(eng, 12.0)
        s = _tick(eng, clk)
        assert s["verdict"] == "ok"
        assert eng.verdict == "ok"
        recovered = [r for r in decisions.LEDGER.snapshot()
                     if r["kind"] == "sentinel"
                     and r["verdict"] == "recovered"]
        assert len(recovered) == 1
        assert telemetry.SENTINEL.merged()["recoveries"] == 1

    def test_quiet_window_neither_confirms_nor_recovers(self,
                                                        tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk)
        for _ in range(3):
            _feed(eng, 12.0)
            _tick(eng, clk)
        _feed(eng, 40.0)
        _tick(eng, clk)
        # Under min_samples: no verdict either way, streak untouched.
        _feed(eng, 40.0, n=3)
        s = _tick(eng, clk)
        assert s["verdict"] == "ok"
        # The NEXT full breach window completes the confirmation —
        # the quiet window did not reset the streak.
        _feed(eng, 40.0)
        assert _tick(eng, clk)["verdict"] == "drifting"

    def test_drifted_era_does_not_teach_baseline(self, tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk)
        for _ in range(3):
            _feed(eng, 12.0)
            _tick(eng, clk)
        base = eng._keys[("render_image_region", "s64k")].baseline_p99
        for _ in range(4):
            _feed(eng, 40.0)
            _tick(eng, clk)
        st = eng._keys[("render_image_region", "s64k")]
        assert st.baseline_p99 == base

    def test_watermark_floor_suppresses_drift(self, tmp_path):
        clk = [0.0]
        # Committed p50 mark of 200ms: a 40ms p99 is under the floor
        # so the baseline-relative breach must not fire.
        eng = _make_engine(tmp_path, clk, watermarks={"bench": {
            "p50_service_tile_ms_ex_rtt": {"value": 200.0},
            "service_tiles_per_sec": {"value": 0.001}}})
        for _ in range(3):
            _feed(eng, 12.0)
            _tick(eng, clk)
        for _ in range(4):
            _feed(eng, 40.0)
            s = _tick(eng, clk)
            assert s["verdict"] == "ok"
        assert not os.listdir(tmp_path)

    def test_bundle_retention_sweep(self, tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk, max_bundles=2)
        for i in range(4):
            os.makedirs(os.path.join(
                tmp_path, f"sentinel-0101-{i:04d}"))
        eng._sweep_bundles()
        assert len(os.listdir(tmp_path)) == 2


class TestBaselinePersistence:
    def test_export_load_round_trip(self, tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk)
        for _ in range(3):
            _feed(eng, 12.0)
            _tick(eng, clk)
        doc = eng.export_baseline()
        assert doc["version"] == 1
        assert "render_image_region|s64k" in doc["baselines"]

        clk2 = [0.0]
        fresh = _make_engine(tmp_path, clk2)
        assert fresh.load_baseline(doc) == 1
        st = fresh._keys[("render_image_region", "s64k")]
        assert st.baseline_p99 == pytest.approx(
            doc["baselines"]["render_image_region|s64k"]["p99"])
        # Restored keys count as warmed: the very next breach window
        # starts the confirmation streak without re-learning.
        assert st.baseline_ticks >= fresh.warmup_ticks

    def test_load_skips_foreign_and_open_keys(self, tmp_path):
        clk = [0.0]
        eng = _make_engine(tmp_path, clk)
        assert eng.load_baseline(None) == 0
        assert eng.load_baseline({"version": 99}) == 0
        n = eng.load_baseline({"version": 1, "baselines": {
            "render_image_region|s64k": {"p50": 1.0, "p99": 2.0,
                                         "ticks": 5},
            "made_up_route|s64k": {"p99": 2.0},       # open route
            "render_image_region|s9k": {"p99": 2.0},  # open shape
            "render_image|s4k": {"p99": "NaNope"},    # non-numeric
        }})
        assert n == 1
        assert list(eng._keys) == [("render_image_region", "s64k")]


class TestInstallIdiom:
    def test_install_active_uninstall(self):
        eng = SentinelEngine(member="t", bundle_dir="")
        try:
            assert sentinel_mod.install(eng) is eng
            assert sentinel_mod.active() is eng
        finally:
            sentinel_mod.uninstall()
        assert sentinel_mod.active() is None
