"""Data-parallel device fleet (``parallel.fleet``): consistent-hash
routing stability, HBM shard accounting, bounded work stealing, and the
deterministic member-death chaos drill.

The hash-ring goldens are the load-bearing tests here: the ring is the
fleet's shard map, so ANY change to its math silently re-homes every
plane in every deployed HBM cache.  A deliberate ring change must
re-pin the goldens — and accept that rollouts pay a full re-stage."""

import asyncio
import time

import pytest

from omero_ms_image_region_tpu.parallel.fleet import (
    FleetImageHandler, FleetRouter, HashRing, LocalMember,
    plane_route_key)
from omero_ms_image_region_tpu.server.config import HotkeyConfig
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.utils import decisions, telemetry


def _ctx(image_id="1", z="0", t="0", tile="0,0,0,128,128", **extra):
    params = {"imageId": image_id, "theZ": z, "theT": t, "m": "c"}
    if tile is not None:
        params["tile"] = tile
    params.update(extra)
    return ImageRegionCtx.from_params(params)


# ------------------------------------------------------------ hash ring

class TestHashRing:
    def test_golden_assignments_pinned(self):
        """Digest->member map is FROZEN.  A failure here means the
        ring's hash math changed and every deployed fleet's HBM shard
        map would silently reshuffle on restart — re-pin only for a
        deliberate, migration-aware ring change."""
        ring = HashRing(["m0", "m1", "m2", "m3"], replicas=64)
        golden = {
            "plane-000": "m3", "plane-001": "m0", "plane-002": "m2",
            "plane-003": "m0", "plane-004": "m2", "plane-005": "m2",
            "plane-006": "m3", "plane-007": "m3", "plane-008": "m0",
            "plane-009": "m0", "plane-010": "m1", "plane-011": "m1",
        }
        assert {k: ring.member(k) for k in golden} == golden

    def test_golden_failover_chain_pinned(self):
        """The failover order is part of the contract too: a dead
        member's keys move to a DETERMINISTIC successor."""
        ring = HashRing(["m0", "m1", "m2", "m3"], replicas=64)
        assert ring.chain("plane-000") == ["m3", "m2", "m0", "m1"]

    def test_deterministic_across_instances(self):
        a = HashRing(["m0", "m1", "m2"], replicas=32)
        b = HashRing(["m0", "m1", "m2"], replicas=32)
        keys = [f"k{i}" for i in range(200)]
        assert [a.member(k) for k in keys] == [b.member(k) for k in keys]

    def test_keyspace_split_near_uniform(self):
        ring = HashRing([f"m{i}" for i in range(4)], replicas=64)
        counts = {}
        for i in range(10000):
            owner = ring.member(f"k{i}")
            counts[owner] = counts.get(owner, 0) + 1
        for owner, n in counts.items():
            # Fair share is 2500; virtual nodes keep every member
            # within a loose band of it.
            assert 1500 < n < 3500, (owner, counts)

    @pytest.mark.parametrize("n", [4, 8])
    def test_remap_bound_on_member_leave(self, n):
        """The consistent-hash contract: removing one of N members
        moves only that member's keys (~1/N of the space) — every
        other key keeps its owner, so a membership change can never
        silently re-home the whole fleet's HBM cache."""
        members = [f"m{i}" for i in range(n)]
        before = HashRing(members, replicas=64)
        after = HashRing(members[:-1], replicas=64)
        keys = [f"k{i}" for i in range(10000)]
        moved = sum(1 for k in keys
                    if before.member(k) != after.member(k))
        # Expected fraction is exactly the departed member's share.
        departed = sum(1 for k in keys
                       if before.member(k) == members[-1])
        assert moved == departed
        assert moved / len(keys) < (1.0 / n) * 1.6 + 0.02

    def test_remap_bound_on_member_join(self):
        """Joining an (N+1)th member steals ~1/(N+1) of the space and
        nothing else changes hands."""
        before = HashRing(["m0", "m1", "m2", "m3"], replicas=64)
        after = HashRing(["m0", "m1", "m2", "m3", "m4"], replicas=64)
        keys = [f"k{i}" for i in range(10000)]
        moved = [k for k in keys
                 if before.member(k) != after.member(k)]
        # Every moved key moved TO the joiner, never between old
        # members.
        assert all(after.member(k) == "m4" for k in moved)
        assert len(moved) / len(keys) < (1.0 / 5) * 1.6 + 0.02

    def test_validation(self):
        with pytest.raises(ValueError):
            HashRing([])
        with pytest.raises(ValueError):
            HashRing(["m0", "m0"])


class TestPlaneRouteKey:
    def test_settings_do_not_move_the_shard(self):
        """Re-window / re-color / format changes hash to the SAME
        member: the route key is the source plane's identity, which is
        what makes the HBM tier shard instead of duplicate."""
        base = _ctx(c="1|0:60000$FF0000")
        rewindow = _ctx(c="1|1000:30000$00FF00")
        reformat = _ctx(c="1|0:60000$FF0000", format="png")
        assert plane_route_key(base) == plane_route_key(rewindow)
        assert plane_route_key(base) == plane_route_key(reformat)

    def test_plane_identity_moves_the_shard(self):
        seen = {plane_route_key(_ctx()),
                plane_route_key(_ctx(z="1")),
                plane_route_key(_ctx(t="1")),
                plane_route_key(_ctx(tile="0,1,0,128,128")),
                plane_route_key(_ctx(image_id="9"))}
        assert len(seen) == 5

    def test_golden_route_keys_pinned(self):
        """Route-key digests frozen alongside the ring goldens — the
        two together pin the full digest->member path."""
        assert plane_route_key(_ctx()) == \
            "673758f592968bbaa5606b21d12bff3b"
        assert plane_route_key(_ctx(tile="0,1,0,128,128")) == \
            "08d8586d9be30dd7e71d112376e59ef7"
        assert plane_route_key(_ctx(z="3")) == \
            "7fad960a17faea5a64e1143f33e7c8ee"


# --------------------------------------------------------------- router

class _FakeHandler:
    """Duck-typed ImageRegionHandler: records (ctx, adopt_cache) calls,
    optionally delays, optionally dies (ConnectionError) after N
    successful renders."""

    def __init__(self, name, delay_s=0.0, die_after=None):
        self.name = name
        self.calls = []
        self.delay_s = delay_s
        self.die_after = die_after

    async def render_image_region(self, ctx, adopt_cache=True):
        if self.die_after is not None \
                and len(self.calls) >= self.die_after:
            raise ConnectionError(f"{self.name} killed by chaos drill")
        self.calls.append((ctx, adopt_cache))
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        return f"{self.name}".encode()


def _fleet(n, lane_width=1, steal_min_backlog=0, **handler_kw):
    handlers = [_FakeHandler(f"m{i}", **handler_kw) for i in range(n)]
    members = [LocalMember(f"m{i}", handlers[i]) for i in range(n)]
    router = FleetRouter(members, lane_width=lane_width,
                         steal_min_backlog=steal_min_backlog)
    return router, handlers


class TestFleetRouter:
    def setup_method(self):
        telemetry.reset()

    def test_routes_by_plane_identity(self):
        """Every render of one plane — whatever its settings — lands
        on the ring owner's handler; distinct planes spread."""
        async def main():
            router, handlers = _fleet(4)
            try:
                ctxs = [_ctx(tile=f"0,{x},{y},128,128")
                        for x in range(3) for y in range(3)]
                ctxs += [_ctx(c="1|5:999$00FF00")]       # re-window
                out = await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs))
                assert all(out)
                by_member = {h.name: len(h.calls) for h in handlers}
                assert sum(by_member.values()) == len(ctxs)
                # The re-window of tile (0,0) went to tile (0,0)'s
                # owner (golden: m3).
                owner = router.ring.member(plane_route_key(ctxs[0]))
                assert owner == "m3"
                tile00 = [h for h in handlers if h.name == owner][0]
                settings_seen = {id(c) for c, _ in tile00.calls}
                assert id(ctxs[0]) in settings_seen
                assert id(ctxs[-1]) in settings_seen
            finally:
                await router.close()

        asyncio.run(main())

    def test_full_plane_and_projection_pin_to_mesh_lane(self):
        """Full-plane and z-projection jobs go to member 0 — the lane
        whose renderer is the lockstep MeshRenderer in mesh
        deployments — and never shard."""
        async def main():
            router, handlers = _fleet(4)
            try:
                full = _ctx(tile=None)
                proj = _ctx(tile=None, p="intmax|0:3")
                await router.dispatch(full)
                await router.dispatch(proj)
                assert len(handlers[0].calls) == 2
            finally:
                await router.close()

        asyncio.run(main())

    def test_work_stealing_is_bounded_and_cache_neutral(self):
        """A backlogged member's OLDEST work is stolen by idle peers;
        stolen renders carry adopt_cache=False so stealing never
        fragments the shard map."""
        async def main():
            router, handlers = _fleet(
                4, lane_width=1, steal_min_backlog=2, delay_s=0.01)
            try:
                # 12 renders of ONE plane identity: all owned by m3
                # (golden), so its queue backs up past the threshold
                # and the three idle members steal.
                ctxs = [_ctx(c=f"1|{i}:60000$FF0000")
                        for i in range(12)]
                out = await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs))
                assert all(out)
                owner = [h for h in handlers if h.name == "m3"][0]
                others = [h for h in handlers if h.name != "m3"]
                stolen = [c for h in others for c in h.calls]
                assert stolen, "no work was stolen from the backlog"
                # Every stolen render declined cache adoption; every
                # owned render adopted.
                assert all(adopt is False for _, adopt in stolen)
                assert all(adopt is True for _, adopt in owner.calls)
                assert telemetry.FLEET.totals()["stolen"] \
                    == len(stolen)
            finally:
                await router.close()

        asyncio.run(main())

    def test_steal_disabled_at_zero_threshold(self):
        async def main():
            router, handlers = _fleet(
                4, lane_width=1, steal_min_backlog=0, delay_s=0.002)
            try:
                ctxs = [_ctx(c=f"1|{i}:60000$FF0000")
                        for i in range(8)]
                await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs))
                owner = [h for h in handlers if h.name == "m3"][0]
                assert len(owner.calls) == len(ctxs)
                assert telemetry.FLEET.totals()["stolen"] == 0
            finally:
                await router.close()

        asyncio.run(main())

    def test_fleet_depth_counts_queued_and_inflight(self):
        async def main():
            router, _ = _fleet(2, lane_width=1, delay_s=0.05)
            try:
                tasks = [asyncio.create_task(router.dispatch(_ctx(
                    c=f"1|{i}:60000$FF0000"))) for i in range(4)]
                await asyncio.sleep(0.02)
                assert router.queue_depth() >= 1
                await asyncio.gather(*tasks)
                assert router.queue_depth() == 0
            finally:
                await router.close()

        asyncio.run(main())

    def test_lanes_do_not_inherit_the_first_requests_deadline(self):
        """Lane tasks are spawned lazily from the FIRST dispatch's
        context: they must be detached from its deadline contextvar,
        or every later render inherits that budget and the whole
        fleet 504s forever once it expires."""
        from omero_ms_image_region_tpu.utils import transient

        class _DeadlineAware(_FakeHandler):
            async def render_image_region(self, ctx,
                                          adopt_cache=True):
                transient.check_deadline("render pipeline")
                return await super().render_image_region(
                    ctx, adopt_cache)

        async def main():
            handlers = [_DeadlineAware(f"m{i}") for i in range(2)]
            members = [LocalMember(f"m{i}", handlers[i])
                       for i in range(2)]
            router = FleetRouter(members, lane_width=1)
            try:
                with transient.deadline_scope(80):
                    assert await router.dispatch(_ctx())
                await asyncio.sleep(0.12)   # first budget now dead
                # Budget-free requests keep serving on every member.
                for i in range(4):
                    assert await router.dispatch(
                        _ctx(c=f"1|{i}:60000$FF0000"))
            finally:
                await router.close()

        asyncio.run(main())

    def test_local_oserror_is_a_request_failure_not_member_death(self):
        """A missing/truncated source file (OSError from a LOCAL
        render) fails that one request; the member stays in the ring
        and keeps serving — one bad file must never cascade into
        marking the whole fleet down."""
        class _BadFileHandler(_FakeHandler):
            async def render_image_region(self, ctx,
                                          adopt_cache=True):
                if ctx.z == 1:
                    raise FileNotFoundError("pyramid level missing")
                return await super().render_image_region(
                    ctx, adopt_cache)

        async def main():
            handlers = [_BadFileHandler(f"m{i}") for i in range(2)]
            members = [LocalMember(f"m{i}", handlers[i])
                       for i in range(2)]
            router = FleetRouter(members, lane_width=1)
            try:
                with pytest.raises(FileNotFoundError):
                    await router.dispatch(_ctx(z="1"))
                assert router.healthy_members() == ["m0", "m1"]
                assert telemetry.FLEET.totals()["failed_over"] == 0
                assert await router.dispatch(_ctx())
            finally:
                await router.close()

        asyncio.run(main())

    def test_pinned_mesh_jobs_are_never_stolen(self):
        """Full-plane/z-projection work pins to member 0's lockstep
        lane even under backlog: an idle peer must not steal it onto
        a plain single-device renderer."""
        async def main():
            router, handlers = _fleet(
                3, lane_width=1, steal_min_backlog=2, delay_s=0.02)
            try:
                ctxs = [_ctx(tile=None, p="intmax|0:1")
                        for _ in range(6)]
                out = await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs))
                assert all(out)
                assert len(handlers[0].calls) == 6
                assert telemetry.FLEET.totals()["stolen"] == 0
            finally:
                await router.close()

        asyncio.run(main())

    def test_close_fails_pending_cleanly(self):
        async def main():
            router, _ = _fleet(2, lane_width=1, delay_s=0.2)
            try:
                tasks = [asyncio.create_task(router.dispatch(_ctx(
                    c=f"1|{i}:60000$FF0000"))) for i in range(6)]
                await asyncio.sleep(0.02)
            finally:
                await router.close()
            results = await asyncio.gather(*tasks,
                                           return_exceptions=True)
            # Whatever was in flight either finished or failed with
            # the shutdown error — never a hang, never a bare cancel.
            for r in results:
                assert isinstance(r, (bytes, RuntimeError,
                                      ConnectionError)), r

        asyncio.run(main())


# ---------------------------------------------------------- chaos drill

class TestFleetChaos:
    def setup_method(self):
        telemetry.reset()

    def test_member_death_mid_burst_zero_failures(self):
        """The acceptance drill: kill one member mid-burst.  Its shard
        fails over hash-ring-next, its queued work is re-assigned, and
        EVERY request still gets bytes — zero 5xx-without-shed."""
        async def main():
            handlers = [_FakeHandler(f"m{i}", delay_s=0.005)
                        for i in range(4)]
            # m3 (the golden owner of the hot plane) dies after 2
            # successful renders — deterministically, mid-burst.
            handlers[3].die_after = 2
            members = [LocalMember(f"m{i}", handlers[i])
                       for i in range(4)]
            router = FleetRouter(members, lane_width=1,
                                 steal_min_backlog=0)
            try:
                ctxs = [_ctx(c=f"1|{i}:60000$FF0000")
                        for i in range(10)]
                out = await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs),
                    return_exceptions=True)
                assert all(isinstance(b, bytes) for b in out), out
                # The victim is down; its shard's new owner is the
                # ring's next healthy member (golden chain for the
                # hot plane's route key: m3 -> m0 -> m2 -> m1).
                assert not members[3].healthy
                assert router.owner_of(ctxs[0]) == "m0"
                totals = telemetry.FLEET.totals()
                assert totals["failed_over"] >= 1
                # The failed-over work ran on the successor (ADOPTING
                # — it is the shard's new ring owner, not a thief).
                m0 = handlers[0]
                assert any(adopt is True for _, adopt in m0.calls)
            finally:
                await router.close()

        asyncio.run(main())

    def test_revived_member_rejoins_the_ring(self):
        async def main():
            router, handlers = _fleet(4)
            try:
                victim = router.members["m3"]
                victim.mark_down()
                hot = _ctx()
                assert router.owner_of(hot) == "m0"
                victim.revive()
                assert router.owner_of(hot) == "m3"
            finally:
                await router.close()

        asyncio.run(main())

    def test_stolen_work_returns_to_its_healthy_owner(self):
        """A dead STEALER's loot goes home: failover excludes the
        member that failed, not ``work.owner`` — in a 2-member fleet
        the healthy shard owner must serve it (not a 503)."""
        from omero_ms_image_region_tpu.parallel.fleet import _Work

        async def main():
            router, handlers = _fleet(2)
            try:
                ctx = _ctx()          # 2-member golden owner: m0
                assert router.owner_of(ctx) == "m0"
                work = _Work(ctx,
                             asyncio.get_running_loop()
                             .create_future(), "m0", None)
                work.stolen = True    # m1 stole it, then died
                router.members["m1"].mark_down()
                router._route_failover(work)
                assert work.owner == "m0"
                assert work.stolen is False
                assert work in router._queues["m0"]
            finally:
                await router.close()

        asyncio.run(main())

    def test_failover_disabled_fails_shard_with_member(self):
        """fleet.failover=false contract: a dead member's requests —
        in flight AND queued — fail as the member does; nothing is
        re-homed, nothing adopts."""
        async def main():
            handlers = [_FakeHandler(f"m{i}", delay_s=0.005)
                        for i in range(4)]
            handlers[3].die_after = 0      # hot-plane owner is dead
            members = [LocalMember(f"m{i}", handlers[i])
                       for i in range(4)]
            router = FleetRouter(members, lane_width=1,
                                 steal_min_backlog=0, failover=False)
            try:
                ctxs = [_ctx(c=f"1|{i}:60000$FF0000")
                        for i in range(6)]
                out = await asyncio.gather(
                    *(router.dispatch(c) for c in ctxs),
                    return_exceptions=True)
                assert all(isinstance(r, ConnectionError)
                           for r in out), out
                assert telemetry.FLEET.totals()["failed_over"] == 0
                assert not handlers[0].calls and not handlers[1].calls
            finally:
                await router.close()

        asyncio.run(main())

    def test_failover_disabled_new_arrivals_fail_too(self):
        """owner_of's contract symmetry with _fail_queue: with
        failover off, requests arriving AFTER a member's death still
        route to the dead owner and fail — silently re-homing them
        onto the ring successor (with adopt and no failed_over tick)
        would be exactly the shard migration the operator disabled."""
        async def main():
            handlers = [_FakeHandler(f"m{i}") for i in range(4)]
            handlers[3].die_after = 0      # hot-plane owner is dead
            members = [LocalMember(f"m{i}", handlers[i])
                       for i in range(4)]
            router = FleetRouter(members, lane_width=1,
                                 steal_min_backlog=0, failover=False)
            try:
                with pytest.raises(ConnectionError):
                    await router.dispatch(_ctx())
                assert not members[3].healthy
                # A fresh request for the dead member's shard.
                with pytest.raises(ConnectionError):
                    await router.dispatch(_ctx(c="1|9:60000$FF0000"))
                assert telemetry.FLEET.totals()["failed_over"] == 0
                assert not any(h.calls for h in handlers)
            finally:
                await router.close()

        asyncio.run(main())

    def test_local_member_readmits_after_cooldown(self):
        """LocalMember down state is a COOLDOWN, not a latch: the
        combined role's members share host-side services, so one
        transient outage (metadata DB, network pixel store) can mark
        every member down within a single failover chain — without
        timed re-admission the whole fleet would stay dead until a
        process restart."""
        member = LocalMember("m0", _FakeHandler("m0"),
                             down_cooldown_s=0.01)
        member.mark_down()
        assert not member.healthy
        time.sleep(0.03)
        assert member.healthy

    def test_fast_fail_does_not_extend_cooldown(self):
        """A request routed to an ALREADY-down member fast-fails
        without re-marking it down.  Re-marking would push the
        cooldown forward on every routed request, so any shard seeing
        >= 1 request per cooldown window would keep its member down
        forever after the outage healed (the shared-service case:
        every member down, owner_of still hands the ring owner the
        call so the 503 contract surfaces)."""
        async def main():
            router, _handlers = _fleet(2)
            try:
                for m in router.members.values():
                    m.mark_down()
                marks = {n: m._down_until
                         for n, m in router.members.items()}
                with pytest.raises(ConnectionError):
                    await router.dispatch(_ctx())
                # No member's cooldown moved: the fast-fail is not a
                # fresh death observation.
                assert {n: m._down_until
                        for n, m in router.members.items()} == marks
            finally:
                await router.close()

        asyncio.run(main())

    def test_fleet_recovers_under_steady_traffic_after_outage(self):
        """Requests keep arriving while every member is down; once the
        cooldown expires the fleet serves again — traffic during the
        outage must not have re-latched the members."""
        async def main():
            handlers = [_FakeHandler(f"m{i}") for i in range(2)]
            members = [LocalMember(f"m{i}", handlers[i],
                                   down_cooldown_s=0.1)
                       for i in range(2)]
            router = FleetRouter(members, lane_width=1,
                                 steal_min_backlog=0)
            try:
                for m in members:
                    m.mark_down()
                deadline = time.monotonic() + 0.15
                while time.monotonic() < deadline:
                    try:
                        await router.dispatch(_ctx())
                        break          # cooldown expired, served
                    except ConnectionError:
                        await asyncio.sleep(0.01)
                assert await router.dispatch(_ctx())
                assert all(m.healthy for m in members)
            finally:
                await router.close()

        asyncio.run(main())

    def test_prechecked_member_skips_member_level_byte_cache(self):
        """build_local_members marks its members byte_cache_prechecked
        — the fleet handler probed the shared byte tier and ran the
        caller's ACL immediately before dispatch, so the member-level
        handler must skip its duplicate probe (a guaranteed-miss walk
        of the memory/disk byte tiers on every routed render)."""
        class _Spy:
            kwargs = None

            async def render_image_region(self, ctx, adopt_cache=True,
                                          skip_byte_cache=False):
                self.kwargs = {"adopt_cache": adopt_cache,
                               "skip_byte_cache": skip_byte_cache}
                return b"x"

        async def main():
            spy = _Spy()
            member = LocalMember("m0", spy,
                                 byte_cache_prechecked=True)
            assert await member.render(_ctx()) == b"x"
            assert spy.kwargs == {"adopt_cache": True,
                                  "skip_byte_cache": True}
            # Default members (tests, duck-typed handlers) keep the
            # two-arg call shape.
            spy2 = _Spy()

            class _TwoArg:
                async def render_image_region(self, ctx,
                                              adopt_cache=True):
                    spy2.kwargs = {"adopt_cache": adopt_cache}
                    return b"y"

            member2 = LocalMember("m1", _TwoArg())
            assert await member2.render(_ctx(),
                                        adopt_cache=False) == b"y"
            assert spy2.kwargs == {"adopt_cache": False}

        asyncio.run(main())

    def test_timed_out_dispatch_is_never_rendered(self):
        """A waiter whose budget dies while its unit is QUEUED cancels
        the unit: the lane skips it instead of rendering bytes nobody
        will retrieve."""
        from omero_ms_image_region_tpu.utils import transient

        async def main():
            router, handlers = _fleet(1, lane_width=1, delay_s=0.15)
            try:
                blocker = asyncio.create_task(
                    router.dispatch(_ctx(c="1|1:60000$FF0000")))
                await asyncio.sleep(0.02)   # lane busy on blocker
                with transient.deadline_scope(30):
                    with pytest.raises(
                            transient.DeadlineExceededError):
                        await router.dispatch(
                            _ctx(c="1|2:60000$FF0000"))
                await blocker
                await asyncio.sleep(0.05)   # lane drains the queue
                # Only the blocker ever rendered.
                assert len(handlers[0].calls) == 1
            finally:
                await router.close()

        asyncio.run(main())

    def test_all_members_down_surfaces_connection_error(self):
        """Total fleet death maps to the ConnectionError -> 503
        contract, never an unroutable internal error."""
        async def main():
            router, handlers = _fleet(2)
            for h in handlers:
                h.die_after = 0
            try:
                with pytest.raises(ConnectionError):
                    await router.dispatch(_ctx())
            finally:
                await router.close()

        asyncio.run(main())


# ----------------------------------------------------- fleet-wide tiers

class TestFleetImageHandler:
    def setup_method(self):
        telemetry.reset()

    def test_single_flight_coalesces_fleet_wide(self):
        """Identical renders coalesce ABOVE the router: one member
        executes once, every waiter shares the bytes."""
        from omero_ms_image_region_tpu.server.singleflight import (
            SingleFlight)

        async def main():
            router, handlers = _fleet(4, delay_s=0.02)
            handler = FleetImageHandler(router,
                                        single_flight=SingleFlight())
            try:
                ctx = _ctx()
                out = await asyncio.gather(
                    *(handler.render_image_region(ctx)
                      for _ in range(8)))
                assert len(set(out)) == 1
                assert sum(len(h.calls) for h in handlers) == 1
            finally:
                await router.close()

        asyncio.run(main())

    def test_admission_sees_total_fleet_depth(self):
        """The router IS the admission controller's renderer: its
        queue_depth() spans every member, so shedding triggers on the
        fleet's total backlog."""
        from omero_ms_image_region_tpu.server.admission import (
            AdmissionController)
        from omero_ms_image_region_tpu.server.errors import (
            OverloadedError)

        async def main():
            router, _ = _fleet(2, lane_width=1, delay_s=0.05)
            admission = AdmissionController(2, renderer=router)
            handler = FleetImageHandler(router, admission=admission)
            try:
                out = await asyncio.gather(
                    *(handler.render_image_region(_ctx(
                        c=f"1|{i}:60000$FF0000")) for i in range(6)),
                    return_exceptions=True)
                served = [r for r in out if isinstance(r, bytes)]
                shed = [r for r in out
                        if isinstance(r, OverloadedError)]
                # The bound is FLEET-wide: 2 admitted across both
                # members (each member's own queue never filled), the
                # rest shed 503+Retry-After.
                assert len(served) >= 2
                assert shed, out
                assert all(isinstance(r, (bytes, OverloadedError))
                           for r in out)
            finally:
                await router.close()

        asyncio.run(main())


    def test_combined_acl_gates_every_coalesced_caller(self,
                                                       monkeypatch):
        """The render_identity_key contract: ACL gates PER CALLER
        before the shared render is awaited — a follower session that
        cannot read the image gets its 404 even while an authorized
        leader's render is in flight."""
        from omero_ms_image_region_tpu.server import handler as hmod
        from omero_ms_image_region_tpu.server.errors import (
            NotFoundError)
        from omero_ms_image_region_tpu.server.singleflight import (
            SingleFlight)

        class _NoCache:
            async def get(self, key):
                return None

        class _Services:
            class caches:
                image_region = _NoCache()

        async def fake_can_read(services, object_type, object_id,
                                session_key):
            return session_key != "intruder"

        monkeypatch.setattr(hmod, "check_can_read", fake_can_read)

        async def main():
            router, handlers = _fleet(2, delay_s=0.05)
            fleet_handler = FleetImageHandler(
                router, single_flight=SingleFlight(),
                base_services=_Services())
            try:
                allowed = _ctx()
                allowed.omero_session_key = "viewer"
                denied = _ctx()
                denied.omero_session_key = "intruder"
                leader = asyncio.create_task(
                    fleet_handler.render_image_region(allowed))
                await asyncio.sleep(0.01)   # leader render in flight
                with pytest.raises(NotFoundError):
                    await fleet_handler.render_image_region(denied)
                assert await leader
                # The denied caller never reached a member.
                assert sum(len(h.calls) for h in handlers) == 1
            finally:
                await router.close()

        asyncio.run(main())

    def test_proxy_fleet_coalesces_per_session_only(self):
        """A proxy fleet (no local ACL services) folds the session
        into the single-flight key: identical renders from DIFFERENT
        sessions each reach a member (whose sidecar runs the full ACL
        gate on its own ctx); same-session duplicates still coalesce."""
        from omero_ms_image_region_tpu.server.singleflight import (
            SingleFlight)

        async def main():
            router, handlers = _fleet(2, delay_s=0.03)
            fleet_handler = FleetImageHandler(
                router, single_flight=SingleFlight())
            try:
                def ctx_for(session):
                    c = _ctx()
                    c.omero_session_key = session
                    return c

                out = await asyncio.gather(
                    fleet_handler.render_image_region(ctx_for("a")),
                    fleet_handler.render_image_region(ctx_for("a")),
                    fleet_handler.render_image_region(ctx_for("b")))
                assert all(out)
                # Two member renders: sessions a (coalesced x2) + b.
                assert sum(len(h.calls) for h in handlers) == 2
            finally:
                await router.close()

        asyncio.run(main())

    def test_total_fleet_death_serves_degraded_fallback(self):
        """With every member gone, a configured DegradedCpuHandler
        keeps tiles servable — but a LIVE fleet's errors never fall
        back."""
        class _Fallback:
            def __init__(self):
                self.calls = 0

            async def render_image_region(self, ctx):
                self.calls += 1
                return b"degraded-bytes"

        async def main():
            router, handlers = _fleet(2)
            fallback = _Fallback()
            fleet_handler = FleetImageHandler(router,
                                              fallback=fallback)
            try:
                for m in router.members.values():
                    m.mark_down()
                out = await fleet_handler.render_image_region(_ctx())
                assert out == b"degraded-bytes"
                assert fallback.calls == 1
                # Fleet back: members serve, fallback stays cold.
                for m in router.members.values():
                    m.revive()
                out = await fleet_handler.render_image_region(_ctx())
                assert out != b"degraded-bytes"
                assert fallback.calls == 1
            finally:
                await router.close()

        asyncio.run(main())


# ------------------------------------------- hot-plane replication

class TestHotPlaneReplication:
    """Lifecycle property drill for popularity-aware placement: a
    route promoted past the heat threshold gets a DETERMINISTIC ring-
    chain prefix as its replica set, demotion is hysteretic and driven
    by the live dispatch path, re-promotion reuses the identical
    prefix, and the per-epoch staging guard never double-stages.  The
    ring goldens above stay the authority on WHERE the prefix points —
    these tests only consume ``chain()``, never re-derive it."""

    def setup_method(self):
        telemetry.reset()
        decisions.LEDGER.reset()

    def teardown_method(self):
        decisions.LEDGER.reset()

    def _hot_fleet(self, n=4, threshold=5.0, decay_s=10.0, **kw):
        handlers = [_FakeHandler(f"m{i}") for i in range(n)]
        members = [LocalMember(f"m{i}", handlers[i])
                   for i in range(n)]
        clk = {"t": 0.0}
        router = FleetRouter(
            members, lane_width=1, steal_min_backlog=0,
            hotkey=HotkeyConfig(enabled=True, threshold=threshold,
                                decay_s=decay_s, max_replicas=2,
                                **kw))
        # Injectable heat clock: the whole thermal trajectory —
        # promotion, hysteresis, re-promotion — is deterministic.
        router._heat.clock = lambda: clk["t"]
        return router, handlers, clk

    def test_promote_demote_repromote_deterministic(self):
        async def main():
            router, handlers, clk = self._hot_fleet()
            try:
                hot = _ctx()
                cool = _ctx(tile="0,2,2,128,128")
                route = plane_route_key(hot)
                chain = router.ring.chain(route)
                # Below threshold: nothing promotes.
                for _ in range(4):
                    await router.dispatch(hot)
                assert not router.is_hot_route(route)
                assert router.replica_set(route) == chain[:1]
                # The 5th observation crosses threshold=5: the route
                # gets exactly the 2-member chain prefix, owner first.
                await router.dispatch(hot)
                assert router.is_hot_route(route)
                first = router.replica_set(route)
                assert first == chain[:2]
                assert router.replica_pressure() >= 1.0
                await asyncio.gather(          # let the stage task run
                    *list(router._putback_tasks),
                    return_exceptions=True)
                # Hysteresis: at demote_fraction=0.5 the route stays
                # promoted while heat > 2.5 (5 * e^-0.5 ~ 3.03)...
                clk["t"] = 5.0
                await router.dispatch(cool)
                assert router.is_hot_route(route)
                # ...and the LIVE dispatch path demotes it once decay
                # crosses under (5 * e^-0.8 ~ 2.25 at t=8).
                clk["t"] = 8.0
                await router.dispatch(cool)
                assert not router.is_hot_route(route)
                assert router.replica_set(route) == chain[:1]
                # Re-promotion from the residual heat rebuilds the
                # IDENTICAL prefix — replicas never wander.
                for _ in range(3):
                    await router.dispatch(hot)
                assert router.is_hot_route(route)
                assert router.replica_set(route) == first
                await asyncio.gather(*list(router._putback_tasks),
                                     return_exceptions=True)
                totals = telemetry.HOTKEY.totals()
                assert totals["promoted"] == 2
                assert totals["demoted"] == 1
                # The full promote/demote/re-promote cycle never
                # double-stages a (route, replica) pair...
                assert totals["duplicate_staged"] == 0
                # ...and a forced second stage inside one epoch trips
                # the guard instead of re-shipping the slice.
                await router._stage_replicas(route, first)
                assert telemetry.HOTKEY.totals()[
                    "duplicate_staged"] == len(first) - 1
                # Both transitions are on the decision ledger.
                ledger = decisions.LEDGER.snapshot()
                verdicts = [r["verdict"] for r in ledger
                            if r["kind"] == "hotkey"]
                assert verdicts.count("promoted") == 2
                assert verdicts.count("demoted") == 1
            finally:
                await router.close()

        asyncio.run(main())

    def test_unroutable_replicas_drop_within_one_transition(self):
        """Drains and deaths fall out of the balanced read set on the
        very NEXT routing decision — no grace window in which reads
        keep landing on a member that can no longer serve them."""
        async def main():
            router, handlers, clk = self._hot_fleet()
            try:
                hot = _ctx()
                route = plane_route_key(hot)
                for _ in range(5):
                    await router.dispatch(hot)
                owner, replica = router.replica_set(route)
                # Idle fleet: ties break in chain order, owner wins.
                assert router._serving_member(route) == owner
                # Draining replica: immediately out of the read set.
                router.members[replica].draining = True
                assert router._serving_member(route) == owner
                router.members[replica].draining = False
                # Dead owner: the surviving replica serves reads.
                router.members[owner].mark_down()
                assert router._serving_member(route) == replica
                # Whole replica set unroutable: plain chain walk, so
                # deaths degrade exactly like an unpromoted route.
                router.members[replica].mark_down()
                assert router._serving_member(route) \
                    == router.ring.chain(route)[2]
                # Promotion state itself is untouched by the outage.
                assert router.is_hot_route(route)
            finally:
                await router.close()

        asyncio.run(main())

    def test_shed_replicas_demotes_everything(self):
        """The cache-pressure ladder's hook: one call returns the
        fleet to R=1 everywhere (HBM reclaim itself is the eviction
        ladder's job — shedding only removes the routing protection)."""
        async def main():
            router, handlers, clk = self._hot_fleet()
            try:
                a, b = _ctx(), _ctx(z="3")
                for _ in range(5):
                    await router.dispatch(a)
                    await router.dispatch(b)
                assert router.hot_route_count() == 2
                assert router.shed_replicas() == 2
                assert router.hot_route_count() == 0
                assert router.replica_set(plane_route_key(a)) \
                    == router.ring.chain(plane_route_key(a))[:1]
                # Re-heating re-promotes cleanly after a shed.
                for _ in range(5):
                    await router.dispatch(a)
                assert router.is_hot_route(plane_route_key(a))
                assert telemetry.HOTKEY.totals()[
                    "duplicate_staged"] == 0
            finally:
                await router.close()

        asyncio.run(main())


# ------------------------------------------------------------ telemetry

class TestFleetTelemetry:
    def setup_method(self):
        telemetry.reset()

    def test_metric_lines_and_exposition(self):
        async def main():
            router, _ = _fleet(3)
            try:
                await router.dispatch(_ctx())
                router.members["m1"].mark_down()
                lines = telemetry.fleet_metric_lines(router)
                text = telemetry.finalize_exposition(lines)
                assert "imageregion_fleet_members 3" in text
                assert "imageregion_fleet_members_healthy 2" in text
                assert ('imageregion_fleet_member_healthy'
                        '{member="m1"} 0') in text
                assert 'imageregion_fleet_routed_total{member=' in text
                # Every family annotated exactly once.
                for fam in ("imageregion_fleet_members",
                            "imageregion_fleet_member_depth",
                            "imageregion_fleet_routed_total"):
                    assert text.count(f"# TYPE {fam} ") == 1
                    assert text.count(f"# HELP {fam} ") == 1
            finally:
                await router.close()

        asyncio.run(main())

    def test_member_label_cardinality_bounded(self):
        for i in range(200):
            telemetry.FLEET.count_routed(f"bogus-{i}")
        assert len(telemetry.FLEET.routed) \
            <= telemetry.FleetStats._MAX_MEMBERS + 1
        assert telemetry.FLEET.routed.get("_overflow", 0) > 0

    def test_reset_clears_fleet_counters(self):
        telemetry.FLEET.count_routed("m0")
        telemetry.FLEET.count_stolen("m1")
        telemetry.FLEET.count_failed_over("m2")
        telemetry.reset()
        assert telemetry.FLEET.totals() == {
            "routed": 0, "stolen": 0, "failed_over": 0}
        assert telemetry.FLEET.metric_lines() == []
