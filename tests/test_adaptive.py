"""Adaptive wire-engine controller: EWMA engine flips on injected
bandwidth signals, hysteresis, idle/steady-state re-probes, and the
batcher's queue-pressure batch growth (VERDICT r3 item 1)."""

import asyncio

import numpy as np
import pytest

from omero_ms_image_region_tpu.utils.adaptive import (
    MIN_OBSERVATION_BYTES, AdaptiveEngine)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def mb(rate_mb_s, nbytes=4 << 20):
    """(nbytes, seconds) pair observing the given rate."""
    return nbytes, nbytes / 1e6 / rate_mb_s


class TestAdaptiveEngine:
    def test_flips_to_huffman_when_link_craters(self):
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              probe=lambda: 100.0)
        assert ctrl.engine == "sparse"
        for _ in range(8):
            ctrl.observe_fetch(*mb(3.0))
        assert ctrl.engine == "huffman"
        assert ctrl.switches == 1

    def test_flips_back_on_probed_recovery(self):
        clock = FakeClock()
        probes = []

        def probe():
            probes.append(clock.t)
            return 200.0

        ctrl = AdaptiveEngine(initial_rate_mb_s=3.0, probe=probe,
                              clock=clock, reprobe_interval_s=20.0)
        assert ctrl.engine == "huffman"
        # Steady huffman traffic: small fetches carry no bandwidth
        # signal, so recovery is only observable via the re-probe.
        assert ctrl.current() == "huffman"     # not yet due
        clock.t += 21.0
        assert ctrl.current() == "sparse"      # probed 200 MB/s
        assert probes and ctrl.switches == 1

    def test_hysteresis_holds_inside_band(self):
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              crossover_mb_s=12.0, hysteresis=0.25)
        assert ctrl.engine == "sparse"
        # 11 MB/s is below the crossover but inside the +-25% band.
        for _ in range(20):
            ctrl.observe_fetch(*mb(11.0))
        assert ctrl.engine == "sparse"
        for _ in range(20):
            ctrl.observe_fetch(*mb(8.0))       # clearly below the band
        assert ctrl.engine == "huffman"

    def test_small_fetches_carry_no_signal(self):
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0)
        ctrl.observe_fetch(MIN_OBSERVATION_BYTES - 1, 10.0)  # ~0 MB/s
        assert ctrl.engine == "sparse"
        assert ctrl.rate_mb_s == 100.0

    def test_idle_gap_triggers_reprobe(self):
        clock = FakeClock()
        rates = [3.0]
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              probe=lambda: rates[0], clock=clock,
                              idle_reprobe_s=30.0)
        assert ctrl.current() == "sparse"      # fresh, no probe
        clock.t += 31.0
        assert ctrl.current() == "huffman"     # idle probe saw 3 MB/s

    def test_failed_probe_keeps_engine(self):
        clock = FakeClock()

        def probe():
            raise OSError("link down")

        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0, probe=probe,
                              clock=clock, idle_reprobe_s=30.0)
        clock.t += 31.0
        assert ctrl.current() == "sparse"


class TestBatcherIntegration:
    def test_fetch_observer_feeds_controller(self):
        """The jpegenc fetchers report wire fetches to the observer."""
        from omero_ms_image_region_tpu.ops import jpegenc

        seen = []
        jpegenc.set_fetch_observer(
            lambda n, s, c=False: seen.append((n, s, c)))
        try:
            f = jpegenc.SparseWireFetcher(256, 256, cap=1024)
            width = f.width
            buf = np.zeros((2, width), np.uint8)
            f.fetch(buf)
            assert seen and seen[0][0] > 0
            # The first fetch of a dispatched program is flagged as
            # compute-conflated (its rate is only a lower bound).
            assert seen[0][2] is True
        finally:
            jpegenc.set_fetch_observer(None)

    def test_batcher_consults_controller_per_group(self, monkeypatch):
        """An engine flip between groups changes the dispatched wire
        format (the injected-signal end-to-end check)."""
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops import jpegenc
        from omero_ms_image_region_tpu.ops.render import pack_settings
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        engines_used = []
        real = jpegenc.render_batch_to_jpeg

        def spying(*args, **kwargs):
            engines_used.append(kwargs.get("engine"))
            return real(*args, **kwargs)

        monkeypatch.setattr(jpegenc, "render_batch_to_jpeg", spying)

        # Huge re-probe interval: on a COLD compilation cache the first
        # render takes tens of seconds, and the huffman steady-state
        # re-probe (stubbed at a healthy 100 MB/s) would flip the
        # engine back before the second assertion.  Re-probing has its
        # own tests; this one is about per-group consultation.
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              probe=lambda: 100.0,
                              reprobe_interval_s=1e9,
                              idle_reprobe_s=1e9)
        r = BatchingRenderer(max_batch=2, linger_ms=0.0,
                             jpeg_engine="sparse",
                             engine_controller=ctrl)
        rdef = flagship_rdef(1)
        settings = pack_settings(rdef)
        raw = np.random.default_rng(0).uniform(
            0, 60000, (1, 64, 64)).astype(np.float32)

        async def one():
            return await r.render_jpeg(raw, settings, 80, 64, 64)

        loop = asyncio.new_event_loop()
        try:
            body = loop.run_until_complete(one())
            assert body[:2] == b"\xff\xd8"
            assert engines_used[-1] == "sparse"
            # Inject a cratered link; the next group must go huffman.
            for _ in range(8):
                ctrl.observe_fetch(*mb(3.0))
            body = loop.run_until_complete(one())
            assert body[:2] == b"\xff\xd8"
            assert engines_used[-1] == "huffman"
        finally:
            loop.run_until_complete(r.close())
            loop.close()

    def test_queue_pressure_grows_batch(self):
        """Sustained full-batch backlog doubles max_batch up to the
        limit; light load never grows it."""
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        r = BatchingRenderer(max_batch=2, linger_ms=1.0,
                             max_batch_limit=8)
        rdef = flagship_rdef(1)
        settings = pack_settings(rdef)
        rng = np.random.default_rng(1)

        async def flood(n):
            raws = [rng.uniform(0, 60000, (1, 32, 32)).astype(
                np.float32) for _ in range(n)]
            return await asyncio.gather(
                *[r.render(raw, settings) for raw in raws])

        loop = asyncio.new_event_loop()
        try:
            out = loop.run_until_complete(flood(64))
            assert len(out) == 64
            assert 2 < r.max_batch <= 8
        finally:
            loop.run_until_complete(r.close())
            loop.close()


class TestLingerBypass:
    def test_lone_idle_request_skips_linger(self, monkeypatch):
        """A single request on an idle renderer dispatches immediately
        (single-tile p50 must not pay the coalescing linger)."""
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        sleeps = []
        real_sleep = asyncio.sleep

        async def spy_sleep(s):
            if s > 0:
                sleeps.append(s)
            await real_sleep(0)

        r = BatchingRenderer(max_batch=8, linger_ms=50.0)
        rdef = flagship_rdef(1)
        settings = pack_settings(rdef)
        raw = np.zeros((1, 32, 32), np.float32)

        async def one():
            monkeypatch.setattr(asyncio, "sleep", spy_sleep)
            try:
                return await r.render(raw, settings)
            finally:
                monkeypatch.setattr(asyncio, "sleep", real_sleep)

        loop = asyncio.new_event_loop()
        try:
            out = loop.run_until_complete(one())
            assert out.shape == (32, 32)
            assert 0.05 not in sleeps    # the linger was bypassed
        finally:
            loop.run_until_complete(r.close())
            loop.close()


def test_mesh_multihost_disables_batch_growth(monkeypatch):
    """Host-local max_batch growth would diverge multi-host SPMD
    launches; the mesh renderer disables it when process_count > 1."""
    import jax

    from omero_ms_image_region_tpu.parallel.mesh import (
        make_mesh, resolve_devices)
    from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

    if len(resolve_devices(8)) < 8:
        pytest.skip("no 8-wide device pool")
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    r = MeshRenderer(make_mesh(8, chan_parallel=1))
    assert r._growth_enabled is False
    r2 = BatchingRendererForTest()
    assert r2._growth_enabled is True


def BatchingRendererForTest():
    from omero_ms_image_region_tpu.server.batcher import BatchingRenderer
    return BatchingRenderer(max_batch=2, linger_ms=0.0)


class TestConflatedSamples:
    def test_low_conflated_reading_never_flips_directly(self):
        probes = []
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              probe=lambda: probes.append(1) or 3.0)
        for _ in range(3):
            ctrl.observe_fetch(*mb(2.0), conflated=True)
        assert ctrl.engine == "sparse"       # no direct flip
        assert ctrl.rate_mb_s == 100.0       # EWMA untouched

    def test_suspicion_streak_forces_probe(self):
        clock = FakeClock()
        probes = []

        def probe():
            probes.append(clock.t)
            return 3.0

        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0, probe=probe,
                              clock=clock)
        for _ in range(ctrl.SUSPECT_STREAK):
            ctrl.observe_fetch(*mb(2.0), conflated=True)
        assert ctrl.current() == "huffman"   # probe saw the real 3 MB/s
        assert len(probes) == 1

    def test_high_conflated_reading_counts(self):
        ctrl = AdaptiveEngine(initial_rate_mb_s=3.0,
                              probe=lambda: 3.0)
        assert ctrl.engine == "huffman"
        for _ in range(8):
            # Lower bound 100 MB/s: the link carried at least that.
            ctrl.observe_fetch(*mb(100.0), conflated=True)
        assert ctrl.engine == "sparse"


class TestFlipUnderLoad:
    def test_engine_flips_mid_load_are_safe(self):
        """The controller flipping engines WHILE concurrent groups are
        in flight (pipeline_depth > 1, worker threads reading
        ``current()`` racily) must never corrupt output: every JPEG
        decodes, whatever engine its group drew."""
        from omero_ms_image_region_tpu import codecs
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        # Stubbed-probe re-probes disabled for the same cold-cache
        # reason as test_batcher_consults_controller_per_group; the
        # flipper task is the only rate source.
        ctrl = AdaptiveEngine(initial_rate_mb_s=100.0,
                              probe=lambda: 100.0,
                              reprobe_interval_s=1e9,
                              idle_reprobe_s=1e9)
        r = BatchingRenderer(max_batch=4, linger_ms=0.5,
                             jpeg_engine="sparse",
                             engine_controller=ctrl,
                             pipeline_depth=3)
        rdef = flagship_rdef(2)
        settings = pack_settings(rdef)
        rng = np.random.default_rng(9)
        tiles = [rng.uniform(0, 60000, (2, 48, 48)).astype(np.float32)
                 for _ in range(24)]

        async def flipper():
            # Alternate cratered/recovered signals while renders run.
            for k in range(12):
                rate = 3.0 if k % 2 == 0 else 100.0
                for _ in range(8):
                    ctrl.observe_fetch(*mb(rate))
                await asyncio.sleep(0.002)

        async def main():
            jobs = [r.render_jpeg(t, settings, 80, 48, 48)
                    for t in tiles]
            out, _ = await asyncio.gather(asyncio.gather(*jobs),
                                          flipper())
            return out

        loop = asyncio.new_event_loop()
        try:
            bodies = loop.run_until_complete(main())
        finally:
            loop.run_until_complete(r.close())
            loop.close()
        assert len(bodies) == 24
        assert ctrl.switches >= 2   # flips really happened mid-run
        for b in bodies:
            rgba = codecs.decode_to_rgba(b)
            assert rgba.shape[:2] == (48, 48)
