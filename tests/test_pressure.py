"""Pressure governor + brownout ladder (server.pressure).

The load-bearing claim is the PROPERTY test: for ANY pressure
trajectory, ladder steps engage in configured order, the engaged set
is always a prefix of the ladder, steps release in exact reverse with
hysteresis (never before ``release_hold_ticks`` consecutive ok ticks),
and interactive-availability shedding (``tighten_admission``) is never
engaged without bulk shedding (``shed_bulk``) already engaged.
"""

import asyncio
import random

import pytest

from omero_ms_image_region_tpu.server import pressure
from omero_ms_image_region_tpu.server.admission import (
    AdmissionController)
from omero_ms_image_region_tpu.server.config import AppConfig
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.server.errors import OverloadedError
from omero_ms_image_region_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    pressure.uninstall()
    yield
    pressure.uninstall()
    telemetry.reset()


def _governor(ladder=None, actuators=None, **overrides):
    """A governor driven by ONE controllable 'queue' signal."""
    raw = {"pressure": {"enabled": True, **overrides}}
    if ladder is not None:
        raw["pressure"]["ladder"] = list(ladder)
    config = AppConfig.from_dict(raw).pressure
    value = {"queue": 0.0}
    gov = pressure.PressureGovernor(
        config, actuators or {}, {"queue": lambda: value["queue"]})
    return gov, value, config


# Signal values that deterministically produce each level through the
# classifier (high=48 default: ok < low=16, elevated >= 48, critical
# >= 48 * 1.25).
_LEVEL_VALUES = {0: 0.0, 1: 48.0, 2: 60.0}


class TestLadderProperty:
    def test_any_trajectory_engages_in_order_releases_in_reverse(self):
        rng = random.Random(1234)
        for trial in range(20):
            telemetry.reset()
            gov, value, config = _governor()
            ladder = gov.ladder
            engaged_history = [tuple()]
            ok_streak = 0
            for tick in range(120):
                level = rng.choice((0, 0, 1, 1, 2))
                value["queue"] = _LEVEL_VALUES[level]
                gov.tick()
                now = tuple(gov.engaged_steps())
                prev = engaged_history[-1]
                # Always a PREFIX of the configured ladder.
                assert now == ladder[:len(now)]
                if len(now) == len(prev) + 1:
                    # Engaged exactly the next step, in order.
                    assert now[:len(prev)] == prev
                elif len(now) == len(prev) - 1:
                    # Released exactly the LAST step (reverse order),
                    # and only after the hysteresis hold of ok ticks.
                    assert prev[:len(now)] == now
                    assert ok_streak + 1 >= config.release_hold_ticks
                else:
                    # No multi-step jumps, ever.
                    assert now == prev
                # The availability-ordering invariant: interactive
                # shedding never without bulk shedding.
                if "tighten_admission" in now:
                    assert "shed_bulk" in now
                ok_streak = ok_streak + 1 if level == 0 else 0
                engaged_history.append(now)

    def test_sustained_critical_walks_whole_ladder_then_recovers(self):
        gov, value, config = _governor()
        value["queue"] = _LEVEL_VALUES[2]
        for _ in range(len(gov.ladder) + 2):
            gov.tick()
        assert gov.engaged_steps() == list(gov.ladder)
        assert gov.level == pressure.LEVEL_CRITICAL
        value["queue"] = 0.0
        # Release is one step per release_hold_ticks, reverse order.
        for expect in range(len(gov.ladder) - 1, -1, -1):
            for _ in range(config.release_hold_ticks):
                gov.tick()
            assert gov.engaged_steps() == list(gov.ladder[:expect])
        assert gov.level == pressure.LEVEL_OK

    def test_elevated_engages_slower_than_critical(self):
        gov, value, config = _governor()
        value["queue"] = _LEVEL_VALUES[1]
        gov.tick()
        assert gov.engaged_steps() == []     # hold not yet met
        for _ in range(config.step_hold_ticks - 1):
            gov.tick()
        assert len(gov.engaged_steps()) == 1

    def test_signal_hysteresis_holds_level_between_watermarks(self):
        gov, value, _ = _governor()
        value["queue"] = 48.0
        gov.tick()
        assert gov.level == pressure.LEVEL_ELEVATED
        # Between low (16) and high (48): stays elevated.
        value["queue"] = 30.0
        gov.tick()
        assert gov.level == pressure.LEVEL_ELEVATED
        # Below low: drops to ok.
        value["queue"] = 10.0
        gov.tick()
        assert gov.level == pressure.LEVEL_OK

    def test_transitions_and_level_ride_telemetry(self):
        gov, value, _ = _governor()
        value["queue"] = _LEVEL_VALUES[2]
        gov.tick()
        assert telemetry.PRESSURE.level == 2
        assert telemetry.PRESSURE.steps_engaged[gov.ladder[0]] == 1
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "pressure.level" in kinds
        assert "pressure.step" in kinds


class TestPrefetchBudget:
    """The continuous prefetch budget (PR 10): a pure function of the
    folded level and the ``pause_prefetch`` ladder state — it scales
    DOWN with pressure before the binary pause engages, and whatever
    path the level took down, the identical path back up restores the
    identical budgets in reverse."""

    def _expected(self, gov, config):
        if gov.step_engaged("pause_prefetch"):
            return 0.0
        if gov.level >= pressure.LEVEL_CRITICAL:
            return config.prefetch_budget_critical
        if gov.level >= pressure.LEVEL_ELEVATED:
            return config.prefetch_budget_elevated
        return 1.0

    def test_budget_is_a_pure_function_over_any_trajectory(self):
        rng = random.Random(4321)
        for trial in range(10):
            telemetry.reset()
            gov, value, config = _governor()
            for tick in range(120):
                value["queue"] = _LEVEL_VALUES[rng.choice(
                    (0, 0, 1, 1, 2))]
                gov.tick()
                budget = gov.prefetch_budget()
                assert budget == self._expected(gov, config)
                # The binary pause is exactly the budget's floor.
                assert (budget == 0.0) == gov.step_engaged(
                    "pause_prefetch")
                # Published gauge follows every transition.
                assert telemetry.PREFETCH.budget_scale == budget

    def test_budget_scales_down_before_pause_and_releases_reverse(
            self):
        """A rising-pressure trajectory (ok -> elevated -> critical)
        cuts the budget via the LEVEL strictly before the ladder's
        binary ``pause_prefetch`` floors it at 0; release walks the
        ladder back in reverse and the budget restores with it."""
        gov, value, config = _governor()
        budgets = [gov.prefetch_budget()]

        def tick():
            gov.tick()
            budgets.append(gov.prefetch_budget())

        value["queue"] = _LEVEL_VALUES[1]    # elevated: holds lag
        tick()
        assert not gov.step_engaged("pause_prefetch")
        assert gov.prefetch_budget() == \
            config.prefetch_budget_elevated   # scaled BEFORE pause
        value["queue"] = _LEVEL_VALUES[2]
        while not gov.step_engaged("pause_prefetch"):
            tick()
        down_path = [b for b, prev in zip(budgets, [None] + budgets)
                     if b != prev]
        assert down_path[0] == 1.0
        assert down_path[-1] == 0.0
        # The continuous cut came strictly before the binary floor.
        assert config.prefetch_budget_elevated in down_path[1:-1]
        # Release: the ladder lifts pause (reverse order: it released
        # LAST of the engaged steps) and the budget restores fully.
        value["queue"] = 0.0
        while gov.engaged != 0 or gov.level != pressure.LEVEL_OK:
            tick()
        assert not gov.step_engaged("pause_prefetch")
        assert budgets[-1] == 1.0
        # Budget-zero spans exactly the pause engagement: once the
        # release walk lifted it, the budget never read 0 again.
        lifted = len(budgets) - 1 - budgets[::-1].index(0.0)
        assert all(b == 1.0 for b in budgets[lifted + 1:])

    def test_elevated_level_halves_before_critical_quarters(self):
        gov, value, config = _governor()
        value["queue"] = _LEVEL_VALUES[1]
        gov.tick()
        assert gov.prefetch_budget() == \
            config.prefetch_budget_elevated == 0.5
        value["queue"] = _LEVEL_VALUES[2]
        gov.tick()
        # Critical level quarters even while pause is not yet engaged
        # (step holds lag the level).
        if not gov.step_engaged("pause_prefetch"):
            assert gov.prefetch_budget() == \
                config.prefetch_budget_critical == 0.25

    def test_budget_transitions_ride_the_flight_recorder(self):
        gov, value, _ = _governor()
        value["queue"] = _LEVEL_VALUES[1]
        gov.tick()                           # elevated, pause lags
        events = [e for e in telemetry.FLIGHT.snapshot()
                  if e["kind"] == "prefetch.budget"]
        assert events and events[-1]["scale"] == 0.5
        assert events[-1]["prev"] == 1.0
        assert events[-1]["paused"] is False
        value["queue"] = _LEVEL_VALUES[2]
        while not gov.step_engaged("pause_prefetch"):
            gov.tick()
        events = [e for e in telemetry.FLIGHT.snapshot()
                  if e["kind"] == "prefetch.budget"]
        assert events[-1]["scale"] == 0.0
        assert events[-1]["paused"] is True

    def test_budget_config_validation_is_monotone(self):
        with pytest.raises(ValueError):
            AppConfig.from_dict({"pressure": {
                "enabled": True,
                "prefetch-budget-elevated": 0.2,
                "prefetch-budget-critical": 0.6}})


class TestCgroupRssDefaults:
    """Satellite: host-RSS watermarks default from the cgroup memory
    limit (v2 ``memory.max``, v1 fallback) when the knob is unset —
    the explicit knob always wins."""

    def test_v2_limit_parses_to_mb(self, tmp_path):
        v2 = tmp_path / "memory.max"
        v2.write_text("1073741824\n")
        assert pressure.read_cgroup_memory_limit_mb(
            v2_path=str(v2), v1_path=str(tmp_path / "nope")) == 1024.0

    def test_v2_max_means_unlimited(self, tmp_path):
        v2 = tmp_path / "memory.max"
        v2.write_text("max\n")
        assert pressure.read_cgroup_memory_limit_mb(
            v2_path=str(v2), v1_path=str(tmp_path / "nope")) is None

    def test_v1_fallback_and_absurd_limit_means_unlimited(
            self, tmp_path):
        v1 = tmp_path / "memory.limit_in_bytes"
        v1.write_text("536870912\n")
        assert pressure.read_cgroup_memory_limit_mb(
            v2_path=str(tmp_path / "nope"), v1_path=str(v1)) == 512.0
        v1.write_text(str(1 << 62))          # PAGE_COUNTER_MAX class
        assert pressure.read_cgroup_memory_limit_mb(
            v2_path=str(tmp_path / "nope"), v1_path=str(v1)) is None

    def test_not_in_a_cgroup_means_none(self, tmp_path):
        assert pressure.read_cgroup_memory_limit_mb(
            v2_path=str(tmp_path / "a"),
            v1_path=str(tmp_path / "b")) is None

    def test_defaults_applied_only_when_knob_unset(self):
        config = AppConfig().pressure
        assert config.host_rss_high_mb == 0     # unset by default
        pressure.apply_cgroup_rss_defaults(config, limit_mb=1000.0)
        assert config.host_rss_high_mb == 800.0
        assert config.host_rss_low_mb == 650.0

    def test_explicit_knob_always_wins(self):
        config = AppConfig.from_dict({"pressure": {
            "enabled": True, "host-rss-high-mb": 300,
            "host-rss-low-mb": 200}}).pressure
        pressure.apply_cgroup_rss_defaults(config, limit_mb=1000.0)
        assert config.host_rss_high_mb == 300
        assert config.host_rss_low_mb == 200

    def test_no_limit_leaves_the_signal_disabled(self):
        config = AppConfig().pressure
        pressure.apply_cgroup_rss_defaults(config, limit_mb=None)
        assert config.host_rss_high_mb == 0


class TestActuators:
    def test_actuator_hooks_fire_on_engage_and_release(self):
        calls = []
        actuators = {
            "pause_prefetch": pressure.StepActuator(
                engage=lambda: calls.append("engage"),
                release=lambda: calls.append("release"),
                while_engaged=lambda: calls.append("held")),
        }
        gov, value, config = _governor(ladder=("pause_prefetch",),
                                       actuators=actuators)
        value["queue"] = _LEVEL_VALUES[2]
        gov.tick()
        assert calls == ["engage", "held"]
        gov.tick()
        assert calls[-1] == "held"
        value["queue"] = 0.0
        for _ in range(config.release_hold_ticks):
            gov.tick()
        assert calls[-1] == "release"

    def test_failing_actuator_never_stalls_the_ladder(self):
        def boom():
            raise RuntimeError("actuator bug")
        gov, value, _ = _governor(
            ladder=("pause_prefetch", "shed_bulk"),
            actuators={"pause_prefetch":
                       pressure.StepActuator(engage=boom)})
        value["queue"] = _LEVEL_VALUES[2]
        gov.tick()
        gov.tick()
        assert gov.engaged_steps() == ["pause_prefetch", "shed_bulk"]

    def test_build_actuators_pause_and_evict(self):
        """The standard wiring really flips the prefetcher/warmstate
        flags and walks the HBM cache to low water."""
        import numpy as np

        from omero_ms_image_region_tpu.io.devicecache import (
            DeviceRawCache)

        class Services:
            pass

        cache = DeviceRawCache(max_bytes=4096, digest_index=False)
        for i in range(4):
            cache.get_or_load(
                ("k", i), lambda i=i: np.full((16, 16), i,
                                              np.uint16))
        assert cache.size_bytes > 0

        class Flagged:
            paused = False

        services = Services()
        services.prefetcher = Flagged()
        services.warmstate = Flagged()
        services.raw_cache = cache
        services.caches = None
        services.renderer = None
        config = AppConfig.from_dict(
            {"pressure": {"enabled": True,
                          "evict-to-frac": 0.25}}).pressure
        actuators = pressure.build_actuators(config,
                                             services=services)
        actuators["pause_prefetch"].engage()
        actuators["pause_snapshots"].engage()
        assert services.prefetcher.paused is True
        assert services.warmstate.paused is True
        before = cache.size_bytes
        actuators["evict_caches"].engage()
        assert cache.size_bytes <= max(1, int(4096 * 0.25)) \
            or cache.size_bytes < before
        actuators["pause_prefetch"].release()
        assert services.prefetcher.paused is False


def _tile_ctx():
    return ImageRegionCtx.from_params({
        "imageId": "1", "theZ": "0", "theT": "0",
        "tile": "0,0,0,64,64", "format": "jpeg", "m": "c",
        "c": "1|0:60000$FF0000"})


def _bulk_ctx():
    return ImageRegionCtx.from_params({
        "imageId": "1", "theZ": "0", "theT": "0",
        "format": "jpeg", "m": "c", "c": "1|0:60000$FF0000"})


class TestConsumerHooks:
    def _installed(self, engaged_steps):
        gov, value, _ = _governor()
        value["queue"] = _LEVEL_VALUES[2]
        while len(gov.engaged_steps()) < len(engaged_steps):
            gov.tick()
            assert set(gov.engaged_steps()) <= set(gov.ladder)
        assert gov.engaged_steps() == list(engaged_steps)
        pressure.install(gov)
        return gov

    def test_admission_tightens_under_pressure(self):
        gov = self._installed(list(
            AppConfig().pressure.ladder))       # all steps engaged
        admission = AdmissionController(max_queue=100)
        assert admission.effective_max_queue() == 25   # scale 0.25
        admission.inflight = 25
        with pytest.raises(OverloadedError):
            admission.admit()
        assert telemetry.RESILIENCE.shed.get("pressure") == 1
        pressure.uninstall()
        assert admission.effective_max_queue() == 100

    def test_bulk_sheds_before_interactive(self):
        ladder = AppConfig().pressure.ladder
        self._installed(list(ladder[:ladder.index("shed_bulk") + 1]))
        with pytest.raises(OverloadedError):
            pressure.shed_bulk_under_pressure(_bulk_ctx())
        # Interactive tiles pass the same gate untouched.
        pressure.shed_bulk_under_pressure(_tile_ctx())
        assert telemetry.RESILIENCE.shed.get("pressure-bulk") == 1

    def test_quality_cap_hits_interactive_tiles_only(self):
        ladder = AppConfig().pressure.ladder
        self._installed(list(
            ladder[:ladder.index("drop_quality") + 1]))
        tile = _tile_ctx()
        assert pressure.pressure_quality(90, tile) == 60
        assert getattr(tile, "_pressure_quality_capped") is True
        bulk = _bulk_ctx()
        assert pressure.pressure_quality(90, bulk) == 90
        # Below the cap: untouched, and no cache-skip mark.
        tile2 = _tile_ctx()
        assert pressure.pressure_quality(50, tile2) == 50
        assert not getattr(tile2, "_pressure_quality_capped", False)

    def test_lane_cap_actuator_on_batcher(self):
        from omero_ms_image_region_tpu.server.batcher import (
            BatchingRenderer)

        async def scenario():
            renderer = BatchingRenderer(max_batch=2, linger_ms=0)
            config = AppConfig.from_dict(
                {"pressure": {"enabled": True,
                              "lane-cap": 1}}).pressure

            class Services:
                pass
            services = Services()
            services.renderer = renderer
            services.prefetcher = None
            services.warmstate = None
            services.raw_cache = None
            services.caches = None
            actuators = pressure.build_actuators(config,
                                                 services=services)
            actuators["cap_lanes"].engage()
            assert renderer._lane_cap == 1
            actuators["cap_lanes"].release()
            assert renderer._lane_cap == 0
            await renderer.close()

        asyncio.run(scenario())
