"""Session-aware serving: the viewport model, per-session fairness
token buckets, tiered QoS dequeue, and the predictive budgeted
prefetcher (services.viewport / server.admission / parallel.fleet /
services.prefetch).

The session identity under test everywhere is
``ctx.omero_session_key`` — the ONE identity the session middleware
resolves, the fleet single-flight folds (PR 8), the token buckets
meter, and the viewport tracker models.  A dedicated test asserts the
buckets and the single-flight read the SAME ctx attribute (no second
session-resolution path).
"""

import asyncio
import threading

import numpy as np
import pytest

from omero_ms_image_region_tpu.server import pressure
from omero_ms_image_region_tpu.server.admission import (
    AdmissionController, SessionTokenBuckets)
from omero_ms_image_region_tpu.server.config import AppConfig
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.server.errors import OverloadedError
from omero_ms_image_region_tpu.services.viewport import (
    TilePrediction, ViewportTracker)
from omero_ms_image_region_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    pressure.uninstall()
    yield
    pressure.uninstall()
    telemetry.reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------- viewport model

class TestViewportTracker:
    def _pan(self, tracker, key, points, image_id=1, resolution=0):
        for x, y in points:
            tracker.observe(key, image_id, 0, 0, resolution, x, y)

    def test_pan_velocity_is_median_of_deltas(self):
        tracker = ViewportTracker(clock=FakeClock())
        self._pan(tracker, "s", [(0, 0), (1, 0), (2, 0), (3, 0)])
        assert tracker.velocity("s") == (1, 0)

    def test_predict_extrapolates_lookahead_steps(self):
        tracker = ViewportTracker(clock=FakeClock())
        self._pan(tracker, "s", [(2, 5), (3, 5), (4, 5)])
        preds = tracker.predict("s", lookahead=2)
        assert [(p.x, p.y, p.step) for p in preds] == [
            (5, 5, 1), (6, 5, 2)]
        assert all(p.resolution == 0 and p.z == 0 and p.t == 0
                   and p.image_id == 1 for p in preds)

    def test_diagonal_and_negative_velocity(self):
        tracker = ViewportTracker(clock=FakeClock())
        self._pan(tracker, "s", [(5, 5), (4, 6), (3, 7)])
        assert tracker.velocity("s") == (-1, 1)
        preds = tracker.predict("s", lookahead=2)
        assert [(p.x, p.y) for p in preds] == [(2, 8), (1, 9)]

    def test_prediction_stops_at_the_lattice_edge(self):
        tracker = ViewportTracker(clock=FakeClock())
        self._pan(tracker, "s", [(1, 0), (0, 0)])   # heading off-plane
        assert tracker.predict("s", lookahead=3) == []

    def test_no_trajectory_means_no_predictions(self):
        tracker = ViewportTracker(clock=FakeClock())
        tracker.observe("s", 1, 0, 0, 0, 3, 3)
        assert tracker.velocity("s") is None
        assert tracker.predict("s") == []
        assert tracker.predict("never-seen") == []

    def test_image_switch_breaks_the_trajectory(self):
        tracker = ViewportTracker(clock=FakeClock())
        self._pan(tracker, "s", [(0, 0), (1, 0)], image_id=1)
        tracker.observe("s", 2, 0, 0, 0, 7, 7)   # teleport: new image
        assert tracker.velocity("s") is None

    def test_stale_observations_never_vote(self):
        clock = FakeClock()
        tracker = ViewportTracker(clock=clock)
        self._pan(tracker, "s", [(0, 0), (1, 0)])
        clock.t += 60.0                      # the viewer had a coffee
        assert tracker.velocity("s") is None

    def test_resume_after_pause_does_not_vote_the_teleport_delta(self):
        """A pause then a resume at a distant tile: the single
        (stale_prev, fresh_cur) pair spanning the pause must not
        become the lone velocity vote — the intra-pair gap is as
        disqualifying as absolute staleness."""
        clock = FakeClock()
        tracker = ViewportTracker(clock=clock)
        self._pan(tracker, "s", [(0, 0), (1, 0)])
        clock.t += 60.0
        tracker.observe("s", 1, 0, 0, 0, 35, 0)    # teleport resume
        assert tracker.velocity("s") is None       # no (34, 0) vote
        tracker.observe("s", 1, 0, 0, 0, 36, 0)
        # Two FRESH observations re-establish the real velocity.
        assert tracker.velocity("s") == (1, 0)

    def test_zoom_in_predicts_the_four_children(self):
        tracker = ViewportTracker(clock=FakeClock())
        tracker.observe("s", 1, 0, 0, 2, 3, 1)
        tracker.observe("s", 1, 0, 0, 1, 3, 1)   # index DOWN = zoom in
        assert tracker.zoom_direction("s") == -1
        preds = tracker.predict("s")
        assert {(p.resolution, p.x, p.y) for p in preds} == {
            (0, 6, 2), (0, 7, 2), (0, 6, 3), (0, 7, 3)}

    def test_zoom_out_predicts_the_parent(self):
        tracker = ViewportTracker(clock=FakeClock())
        tracker.observe("s", 1, 0, 0, 0, 6, 2)
        tracker.observe("s", 1, 0, 0, 1, 6, 2)
        assert tracker.zoom_direction("s") == 1
        preds = tracker.predict("s", max_level=4)
        assert {(p.resolution, p.x, p.y) for p in preds} == {
            (2, 3, 1)}

    def test_zoom_past_max_level_predicts_nothing(self):
        tracker = ViewportTracker(clock=FakeClock())
        tracker.observe("s", 1, 0, 0, 0, 2, 2)
        tracker.observe("s", 1, 0, 0, 1, 2, 2)
        assert tracker.predict("s", max_level=1) == []

    def test_lru_bound_evicts_oldest_session(self):
        tracker = ViewportTracker(max_sessions=2, clock=FakeClock())
        self._pan(tracker, "a", [(0, 0), (1, 0)])
        self._pan(tracker, "b", [(0, 0), (1, 0)])
        self._pan(tracker, "c", [(0, 0), (1, 0)])
        assert len(tracker) == 2
        assert tracker.evictions == 1
        assert tracker.velocity("a") is None       # evicted
        assert tracker.velocity("c") == (1, 0)
        assert telemetry.SESSIONS.evicted == 1
        assert telemetry.SESSIONS.tracked == 2

    def test_sessionless_traffic_shares_the_anonymous_state(self):
        tracker = ViewportTracker(clock=FakeClock())
        tracker.observe(None, 1, 0, 0, 0, 0, 0)
        tracker.observe("", 1, 0, 0, 0, 1, 0)
        assert len(tracker) == 1
        assert tracker.velocity(None) == (1, 0)

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            ViewportTracker(max_sessions=0)
        with pytest.raises(ValueError):
            ViewportTracker(history=1)

    def test_predictions_are_frozen_value_objects(self):
        p = TilePrediction(1, 0, 0, 0, 2, 3)
        with pytest.raises(Exception):
            p.x = 9


# ------------------------------------------- per-session token buckets

class TestSessionTokenBuckets:
    def test_burst_then_refused_then_refills(self):
        clock = FakeClock()
        buckets = SessionTokenBuckets(refill_per_s=2.0, burst=3.0,
                                      clock=clock)
        assert all(buckets.try_take("s") for _ in range(3))
        assert buckets.try_take("s") is False
        assert buckets.refused_total == 1
        clock.t += 1.0                       # refills 2 tokens
        assert buckets.try_take("s")
        assert buckets.try_take("s")
        assert buckets.try_take("s") is False

    def test_retry_after_reports_the_honest_deficit(self):
        clock = FakeClock()
        buckets = SessionTokenBuckets(refill_per_s=2.0, burst=1.0,
                                      clock=clock)
        assert buckets.try_take("s")
        assert buckets.retry_after_s("s") == pytest.approx(0.5)
        # A 4-token bulk draw against an empty bucket: 2 s at 2/s.
        assert buckets.retry_after_s("s", cost=4.0) == \
            pytest.approx(2.0)

    def test_bulk_cost_drains_faster(self):
        buckets = SessionTokenBuckets(refill_per_s=1.0, burst=8.0,
                                      bulk_cost=4.0,
                                      clock=FakeClock())
        assert buckets.try_take("s", cost=buckets.bulk_cost)
        assert buckets.try_take("s", cost=buckets.bulk_cost)
        assert buckets.try_take("s", cost=buckets.bulk_cost) is False
        # The same budget would have served 8 interactive tiles.
        assert all(buckets.try_take("t") for _ in range(8))

    def test_sessions_are_isolated(self):
        buckets = SessionTokenBuckets(refill_per_s=1.0, burst=1.0,
                                      clock=FakeClock())
        assert buckets.try_take("hog")
        assert buckets.try_take("hog") is False
        assert buckets.try_take("calm")    # untouched by the hog

    def test_anonymous_traffic_shares_one_bucket(self):
        buckets = SessionTokenBuckets(refill_per_s=1.0, burst=2.0,
                                      clock=FakeClock())
        assert buckets.try_take(None)
        assert buckets.try_take("")
        assert buckets.try_take(None) is False

    def test_lru_bound_evicted_session_restarts_full(self):
        buckets = SessionTokenBuckets(refill_per_s=0.001, burst=1.0,
                                      max_sessions=2,
                                      clock=FakeClock())
        assert buckets.try_take("a")
        assert buckets.try_take("a") is False
        buckets.try_take("b")
        buckets.try_take("c")                # evicts "a"
        assert len(buckets) == 2
        assert buckets.try_take("a")         # full burst again

    def test_constructor_validation(self):
        for kw in ({"refill_per_s": 0.0}, {"burst": 0.5},
                   {"max_sessions": 0}, {"bulk_cost": 0.5}):
            with pytest.raises(ValueError):
                SessionTokenBuckets(**{"refill_per_s": 1.0,
                                       "burst": 1.0, **kw})


# ------------------------------------------------- fairness admission

def _tile_ctx(session=None):
    ctx = ImageRegionCtx.from_params({
        "imageId": "1", "theZ": "0", "theT": "0",
        "tile": "0,0,0,64,64", "format": "jpeg", "m": "c",
        "c": "1|0:60000$FF0000"})
    ctx.omero_session_key = session
    return ctx


def _bulk_ctx(session=None):
    ctx = ImageRegionCtx.from_params({
        "imageId": "1", "theZ": "0", "theT": "0",
        "format": "jpeg", "m": "c", "c": "1|0:60000$FF0000"})
    ctx.omero_session_key = session
    return ctx


class TestFairnessAdmission:
    def _admission(self, **bucket_kw):
        clock = bucket_kw.pop("clock", FakeClock())
        buckets = SessionTokenBuckets(
            refill_per_s=bucket_kw.pop("refill_per_s", 1.0),
            burst=bucket_kw.pop("burst", 2.0),
            clock=clock, **bucket_kw)
        return AdmissionController(max_queue=100,
                                   session_buckets=buckets), clock

    def test_over_budget_session_sheds_with_fairness_reason(self):
        adm, _ = self._admission()
        adm.release(adm.admit(_tile_ctx("hog")))
        adm.release(adm.admit(_tile_ctx("hog")))
        with pytest.raises(OverloadedError) as ei:
            adm.admit(_tile_ctx("hog"))
        # Retry-After covers the bucket's actual deficit.
        assert ei.value.retry_after_s >= 1.0
        assert telemetry.RESILIENCE.shed.get("fairness") == 1
        assert telemetry.QOS.shed.get("interactive") == 1
        kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
        assert "qos.shed" in kinds
        # A fairness shed never claims a slot.
        assert adm.inflight == 0

    def test_other_sessions_admission_is_untouched(self):
        adm, _ = self._admission()
        adm.release(adm.admit(_tile_ctx("hog")))
        adm.release(adm.admit(_tile_ctx("hog")))
        with pytest.raises(OverloadedError):
            adm.admit(_tile_ctx("hog"))
        # The global bound never tightened against anyone else.
        adm.release(adm.admit(_tile_ctx("calm")))

    def test_bulk_requests_draw_bulk_cost(self):
        adm, _ = self._admission(burst=4.0, bulk_cost=4.0)
        adm.release(adm.admit(_bulk_ctx("exporter")))
        with pytest.raises(OverloadedError):
            adm.admit(_bulk_ctx("exporter"))
        assert telemetry.QOS.shed.get("bulk") == 1

    def test_global_shed_refunds_the_session_tokens(self):
        """Admission granted by the fairness gate but refused by the
        GLOBAL depth bound must refund the debit: a well-behaved
        retrier during global overload is never drained into
        misattributed \"fairness\" sheds."""
        buckets = SessionTokenBuckets(refill_per_s=0.001, burst=2.0,
                                      clock=FakeClock())
        adm = AdmissionController(max_queue=1,
                                  session_buckets=buckets)
        t = adm.admit(_tile_ctx("viewer"))     # fills the queue
        for _ in range(5):                     # far past the burst
            with pytest.raises(OverloadedError):
                adm.admit(_tile_ctx("viewer"))
        # Every global shed refunded: no fairness shed ever fired...
        assert telemetry.RESILIENCE.shed.get("fairness") is None
        assert telemetry.RESILIENCE.shed.get("queue-full") == 5
        adm.release(t)
        # ...and the bucket still covers the burst minus the one
        # genuinely admitted render.
        adm.release(adm.admit(_tile_ctx("viewer")))
        with pytest.raises(OverloadedError):   # now truly over budget
            adm.admit(_tile_ctx("viewer"))
        assert telemetry.RESILIENCE.shed.get("fairness") == 1

    def test_ctx_none_preserves_anonymous_global_behavior(self):
        adm, _ = self._admission()
        for _ in range(10):                  # far past any burst
            adm.release(adm.admit())
        assert adm.shed_total == 0

    def test_no_buckets_means_sessions_unmetered(self):
        adm = AdmissionController(max_queue=100)
        for _ in range(10):
            adm.release(adm.admit(_tile_ctx("hog")))
        assert adm.shed_total == 0


# --------------------------------------------- weighted QoS dequeue

class TestQosDequeue:
    def _queue(self, weight, arrivals):
        """A _MemberQueue holding ``arrivals`` ('i'/'b' chars)."""
        from omero_ms_image_region_tpu.parallel.fleet import (
            _MemberQueue, _Work)
        queue = _MemberQueue(qos_weight=weight)
        for i, cls in enumerate(arrivals):
            ctx = (_bulk_ctx() if cls == "b"
                   else _tile_ctx())
            ctx.seq = i
            work = _Work(ctx, asyncio.Future(
                loop=asyncio.new_event_loop()), "m0", None)
            queue.append(work)
        return queue

    def _drain(self, queue):
        out = []
        while queue:
            work = queue.popleft()
            out.append("b" if work.bulk else "i")
        return out

    def test_weight_zero_is_plain_fifo(self):
        queue = self._queue(0, "bbiii")
        assert self._drain(queue) == list("bbiii")
        assert telemetry.QOS.jumps == 0

    def test_interactive_jumps_bulk_backlog(self):
        queue = self._queue(4, "bbiii")
        assert self._drain(queue) == list("iiibb")
        assert telemetry.QOS.jumps == 3
        assert telemetry.QOS.dequeued == {"interactive": 3, "bulk": 2}

    def test_bulk_cannot_starve_past_the_weight(self):
        # 6 interactive vs 2 bulk at weight 2: after every 2
        # interactive pops one bulk pops.
        queue = self._queue(2, "bbiiiiii")
        assert self._drain(queue) == list("iibiibii")

    def test_single_class_resets_the_quota(self):
        queue = self._queue(2, "iii")
        assert self._drain(queue) == list("iii")
        assert telemetry.QOS.jumps == 0

    def test_bulk_work_is_never_stealable(self):
        queue = self._queue(4, "bib")
        assert queue.steal_depth() == 1
        work = queue.steal_pop()
        assert work is not None and work.bulk is False
        assert queue.steal_depth() == 0
        assert queue.steal_pop() is None
        assert len(queue) == 2               # both bulk units remain

    def test_arrival_order_preserved_within_each_class(self):
        queue = self._queue(1, "ibib")
        drained = []
        while queue:
            work = queue.popleft()
            drained.append((("b" if work.bulk else "i"),
                            work.ctx.seq))
        assert drained == [("i", 0), ("b", 1), ("i", 2), ("b", 3)]


# ----------------------------- one session identity across the stack

class TestSessionKeyPlumbingUnderFleet:
    """PR 8's single-flight hardening resolves the caller's session
    once (``ctx.omero_session_key``); the token buckets must key on
    the SAME identity — a coalesced follower pays no tokens, two
    sessions with identical render params never share a budget."""

    def _handler(self, buckets):
        from omero_ms_image_region_tpu.parallel.fleet import (
            FleetImageHandler)
        from omero_ms_image_region_tpu.server.singleflight import (
            SingleFlight)

        dispatched = []

        class FakeRouter:
            device_lanes = 2

            async def dispatch(self, ctx):
                dispatched.append(ctx.omero_session_key)
                await asyncio.sleep(0.01)
                return b"pixels"

            def healthy_members(self):
                return ["m0"]

        admission = AdmissionController(max_queue=100,
                                        session_buckets=buckets)
        # s=None: the proxy-fleet posture whose single-flight key
        # FOLDS the session (per-session leaders).
        return FleetImageHandler(FakeRouter(),
                                 single_flight=SingleFlight(),
                                 admission=admission), dispatched

    def test_every_caller_pays_its_own_token_before_coalescing(self):
        buckets = SessionTokenBuckets(refill_per_s=0.001, burst=3.0,
                                      clock=FakeClock())
        handler, dispatched = self._handler(buckets)

        async def scenario():
            # Two CONCURRENT identical same-session requests coalesce
            # onto one leader — ONE dispatch, but the fairness gate
            # runs PER CALLER (before single-flight, like the ACL
            # gate): each request pays its own token, so coalescing
            # never launders budget.
            a, b = await asyncio.gather(
                handler.render_image_region(_tile_ctx("viewer")),
                handler.render_image_region(_tile_ctx("viewer")))
            assert a == b == b"pixels"

        asyncio.run(scenario())
        assert len(dispatched) == 1
        assert buckets.taken_total == 2
        # Both debits hit the SAME bucket the next solo request draws
        # from: one token left of the burst of three.
        assert buckets.try_take("viewer")
        assert buckets.try_take("viewer") is False

    def test_global_shed_through_the_fleet_refunds_every_caller(self):
        from omero_ms_image_region_tpu.parallel.fleet import (
            FleetImageHandler)

        class FullRouter:
            device_lanes = 1

            async def dispatch(self, ctx):   # pragma: no cover
                raise AssertionError("never admitted")

            def healthy_members(self):
                return ["m0"]

        buckets = SessionTokenBuckets(refill_per_s=0.001, burst=2.0,
                                      clock=FakeClock())
        adm = AdmissionController(max_queue=1, session_buckets=buckets)
        adm.inflight = 1                     # global bound saturated
        handler = FleetImageHandler(FullRouter(), admission=adm)

        async def scenario():
            for _ in range(4):               # far past the burst
                with pytest.raises(OverloadedError):
                    await handler.render_image_region(
                        _tile_ctx("viewer"))

        asyncio.run(scenario())
        # Every global shed refunded the caller's token: no fairness
        # shed ever fired, and the bucket still holds its burst.
        assert telemetry.RESILIENCE.shed.get("fairness") is None
        assert telemetry.RESILIENCE.shed.get("queue-full") == 4
        assert buckets.try_take("viewer")
        assert buckets.try_take("viewer")

    def test_sessions_never_share_budget_or_leader(self):
        buckets = SessionTokenBuckets(refill_per_s=0.001, burst=1.0,
                                      clock=FakeClock())
        handler, dispatched = self._handler(buckets)

        async def scenario():
            # Identical params, different sessions: the folded
            # single-flight key keeps leaders per-session, so the
            # hog's empty bucket cannot shed the calm session (and
            # the calm session's render cannot serve the hog).
            await handler.render_image_region(_tile_ctx("hog"))
            with pytest.raises(OverloadedError):
                await handler.render_image_region(_tile_ctx("hog"))
            out = await handler.render_image_region(
                _tile_ctx("calm"))
            assert out == b"pixels"

        asyncio.run(scenario())
        assert dispatched == ["hog", "calm"]
        assert telemetry.RESILIENCE.shed.get("fairness") == 1


class TestViewportWiring:
    def test_viewport_gated_on_sessions_enabled(self, tmp_path):
        """Without the session tier every request is anonymous — one
        SHARED trajectory interleaving unrelated viewers would
        predict garbage while suppressing the lattice fallback, so
        build_services only wires the viewport model when
        ``sessions.enabled`` is on."""
        from omero_ms_image_region_tpu.server.app import (
            build_services)
        from omero_ms_image_region_tpu.server.config import (
            RawCacheConfig, SessionsConfig)

        config = AppConfig(
            data_dir=str(tmp_path),
            raw_cache=RawCacheConfig(enabled=True, prefetch=True))
        services = build_services(config)
        try:
            assert services.prefetcher is not None
            assert services.prefetcher.viewport is None
        finally:
            services.prefetcher.close()
            services.pixels_service.close()

        config.sessions = SessionsConfig(enabled=True,
                                         prefetch_lookahead=3)
        services = build_services(config)
        try:
            assert services.prefetcher.viewport is not None
            assert services.prefetcher.lookahead == 3
        finally:
            services.prefetcher.close()
            services.pixels_service.close()


# ------------------------------------------------ predictive prefetch

class _FakeSrc:
    """Minimal pixel source for TilePrefetcher: records region reads,
    optionally blocking the FIRST read until released."""

    def __init__(self, block_first=False):
        self.calls = []
        self.block_first = block_first
        self.first_started = threading.Event()
        self.release = threading.Event()

    def get_region(self, z, c, t, region, level):
        first = not self.calls
        self.calls.append((region.x, region.y))
        if self.block_first and first:
            self.first_started.set()
            assert self.release.wait(5.0)
        return np.zeros((region.height, region.width), np.uint16)


def _prefetcher(viewport=None, max_workers=1, max_pending=16,
                cache=None, **kw):
    from omero_ms_image_region_tpu.io.devicecache import DeviceRawCache
    from omero_ms_image_region_tpu.services.prefetch import (
        TilePrefetcher)
    cache = cache if cache is not None else DeviceRawCache(
        digest_index=False)
    return TilePrefetcher(cache, max_workers=max_workers,
                          max_pending=max_pending,
                          viewport=viewport, **kw), cache


def _serve(prefetcher, src, x, y, session=None, levels=((96, 96),)):
    from omero_ms_image_region_tpu.server.region import RegionDef
    prefetcher.tile_served(
        src, 1, 0, 0, 0, levels,
        RegionDef(x=x, y=y, width=16, height=16), (16, 16), 2048,
        (0,), session_key=session)


class TestPredictivePrefetch:
    def test_trajectory_prefetches_predicted_tiles_not_neighbors(self):
        tracker = ViewportTracker(clock=FakeClock())
        prefetcher, cache = _prefetcher(viewport=tracker)
        src = _FakeSrc()
        try:
            _serve(prefetcher, src, 1, 2, session="s")   # no history
            prefetcher.flush()
            lattice = set(src.calls)
            assert len(lattice) == 4                     # fallback
            _serve(prefetcher, src, 2, 2, session="s")   # velocity 1,0
            prefetcher.flush()
            predicted = set(src.calls[4:])
            # The pan-ahead tiles (48,32)/(64,32) in pixels, minus any
            # the lattice already staged.
            assert predicted == {(48, 32), (64, 32)} - lattice
            assert prefetcher.predicted >= 2
            assert telemetry.PREFETCH.predicted >= 2
            kinds = [e["kind"] for e in telemetry.FLIGHT.snapshot()]
            assert "prefetch.predict" in kinds
        finally:
            prefetcher.close()

    def test_foreground_hit_accounting(self):
        tracker = ViewportTracker(clock=FakeClock())
        prefetcher, cache = _prefetcher(viewport=tracker)
        src = _FakeSrc()
        try:
            _serve(prefetcher, src, 0, 0, session="s")
            _serve(prefetcher, src, 1, 0, session="s")
            prefetcher.flush()
            assert prefetcher.staged > 0
            # The foreground read for the predicted tile finds it
            # resident and reports the hit back.
            from omero_ms_image_region_tpu.io.devicecache import (
                region_key)
            key = region_key(1, 0, 0, 0, (32, 0, 16, 16), (0,))
            assert cache.get(key) is not None
            prefetcher.note_hit(key)
            assert prefetcher.hits == 1
            assert telemetry.PREFETCH.hits == 1
            assert prefetcher.hit_rate() == pytest.approx(
                1.0 / prefetcher.staged)
            # A key this prefetcher never staged is not a hit.
            prefetcher.note_hit(("not", "ours"))
            assert prefetcher.hits == 1
        finally:
            prefetcher.close()

    def test_budget_scales_max_pending_continuously(self):
        prefetcher, _ = _prefetcher(max_pending=16)
        try:
            assert prefetcher.effective_max_pending() == 16
            prefetcher.budget_scale = 0.5
            assert prefetcher.effective_max_pending() == 8
            prefetcher.budget_scale = 0.0
            assert prefetcher.effective_max_pending() == 0
            assert prefetcher.paused is True
            prefetcher.paused = False        # ladder release
            assert prefetcher.effective_max_pending() == 16
        finally:
            prefetcher.close()

    def test_governor_budget_multiplies_in(self):
        raw = {"pressure": {"enabled": True}}
        config = AppConfig.from_dict(raw).pressure
        value = {"queue": 0.0}
        gov = pressure.PressureGovernor(
            config, {}, {"queue": lambda: value["queue"]})
        pressure.install(gov)
        prefetcher, _ = _prefetcher(max_pending=16)
        try:
            assert prefetcher.effective_budget() == 1.0
            value["queue"] = 48.0            # elevated
            gov.tick()
            assert prefetcher.effective_budget() == pytest.approx(0.5)
            assert prefetcher.effective_max_pending() == 8
            # The local ladder actuator floors it regardless of level.
            prefetcher.paused = True
            assert prefetcher.effective_budget() == 0.0
        finally:
            prefetcher.close()

    def test_pause_mid_flight_cancels_queued_work_and_flush_settles(
            self):
        """The PR 9 regression: a budget hitting zero MID-FLIGHT must
        bind queued-but-unstarted pool items — flush() during a pause
        settles without loading work nobody wants."""
        prefetcher, cache = _prefetcher(max_workers=1)
        src = _FakeSrc(block_first=True)
        try:
            _serve(prefetcher, src, 1, 1)    # 4 neighbors scheduled
            assert prefetcher.scheduled == 4
            assert src.first_started.wait(5.0)
            # Pause while one load is in flight and three are queued.
            prefetcher.paused = True
            src.release.set()
            prefetcher.flush(timeout=5.0)
            # The in-flight load completed; the queued three exited at
            # the budget check without touching the source.
            assert len(src.calls) == 1
            assert prefetcher.staged == 1
            assert len(cache) == 1
            assert telemetry.PREFETCH.skipped.get("paused") == 3
        finally:
            src.release.set()
            prefetcher.close()

    def test_budget_zero_schedules_nothing_at_all(self):
        prefetcher, _ = _prefetcher()
        src = _FakeSrc()
        try:
            prefetcher.paused = True
            _serve(prefetcher, src, 1, 1)
            prefetcher.flush()
            assert prefetcher.scheduled == 0
            assert src.calls == []
            assert telemetry.PREFETCH.skipped.get("budget") == 1
        finally:
            prefetcher.close()

    def test_fleet_route_seam_stages_into_the_owning_shard(self):
        from omero_ms_image_region_tpu.io.devicecache import (
            DeviceRawCache)

        routed_cache = DeviceRawCache(digest_index=False)
        routes = []

        def cache_for_route(route_key):
            routes.append(route_key)
            return routed_cache

        prefetcher, local_cache = _prefetcher(
            cache_for_route=cache_for_route)
        src = _FakeSrc()
        try:
            _serve(prefetcher, src, 1, 1)
            prefetcher.flush()
            # Every staged plane went to the member the router owns
            # for that plane — none into the local shard.
            assert len(routes) == 4
            assert len(routed_cache) == 4
            assert len(local_cache) == 0
        finally:
            prefetcher.close()


class TestMaskFairness:
    """Masks join the session model (the PR 10 follow-on closed by
    the autoscaler PR): ``render_shape_mask`` debits session fairness
    tokens, QoS-classed INTERACTIVE — a hostile mask-scraping session
    used to bypass the meter entirely."""

    @staticmethod
    def _mask_ctx(session, shape_id=5):
        from omero_ms_image_region_tpu.server.ctx import ShapeMaskCtx
        return ShapeMaskCtx.from_params(
            {"shapeId": str(shape_id), "color": "FF0000"}, session)

    def test_mask_ctx_is_qos_classed_interactive(self):
        ctx = self._mask_ctx("viewer")
        assert pressure.is_bulk(ctx) is False
        # ...including shape id 0 (a falsy id is still a mask).
        assert pressure.is_bulk(self._mask_ctx("v", 0)) is False

    def test_mask_scraper_sheds_on_its_own_budget(self):
        clock = FakeClock()
        buckets = SessionTokenBuckets(refill_per_s=1.0, burst=2.0,
                                      clock=clock)
        adm = AdmissionController(max_queue=100,
                                  session_buckets=buckets)
        adm.refund_session(None)
        assert adm.admit_session(self._mask_ctx("scraper"))
        assert adm.admit_session(self._mask_ctx("scraper"))
        with pytest.raises(OverloadedError):
            adm.admit_session(self._mask_ctx("scraper"))
        assert telemetry.QOS.shed.get("interactive") == 1
        # Another session's masks — and tiles — stay admitted.
        assert adm.admit_session(self._mask_ctx("calm"))
        assert adm.admit_session(_tile_ctx("calm2"))

    def test_masks_and_tiles_share_one_session_budget(self):
        """One meter per session, not per route: tiles spend the same
        bucket the masks do."""
        clock = FakeClock()
        buckets = SessionTokenBuckets(refill_per_s=1.0, burst=2.0,
                                      clock=clock)
        adm = AdmissionController(max_queue=100,
                                  session_buckets=buckets)
        assert adm.admit_session(_tile_ctx("mixed"))
        assert adm.admit_session(self._mask_ctx("mixed"))
        with pytest.raises(OverloadedError):
            adm.admit_session(self._mask_ctx("mixed"))

    def test_viewport_activity_keeps_the_session_without_a_vote(self):
        """observe_activity keeps a mask-only session live in the LRU
        (the demand figure the autoscaler reads) without polluting
        the pan trajectory."""
        clock = FakeClock()
        tracker = ViewportTracker(max_sessions=4, clock=clock)
        tracker.observe_activity("masker")
        assert len(tracker) == 1
        assert tracker.predict("masker") == []
        assert tracker.velocity("masker") is None
        # A panning session's trajectory is untouched by interleaved
        # mask activity.
        for x in range(4):
            tracker.observe("panner", 1, 0, 0, 0, x, 2)
            tracker.observe_activity("panner")
        assert tracker.velocity("panner") == (1, 0)

    def test_mask_route_sheds_503_with_fairness_and_refunds(
            self, tmp_path):
        """End to end: a mask-scraping session exhausts ITS bucket and
        gets the fairness 503 + Retry-After on the mask ROUTE; a calm
        session keeps rendering; a failed mask refunds the token."""
        import numpy as np
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.io.store import build_pyramid
        from omero_ms_image_region_tpu.models.mask import Mask
        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.services.metadata import (
            write_mask)

        root = tmp_path / "data"
        root.mkdir()
        rng = np.random.default_rng(5)
        planes = rng.integers(0, 60000,
                              size=(1, 1, 64, 64)).astype("uint16")
        build_pyramid(planes, str(root / "1"), n_levels=1)
        grid = np.zeros(64 * 64, np.uint8)
        grid[:64] = 1
        write_mask(str(root), Mask(shape_id=5, width=64, height=64,
                                   bytes_=np.packbits(grid)
                                   .tobytes()))
        config = AppConfig.from_dict({
            "data-dir": str(root),
            "batcher": {"enabled": False},
            "session-store": {"type": "static", "required": False},
            "sessions": {"enabled": True, "bucket-refill-per-s": 0.5,
                         "bucket-burst": 2},
        })

        async def scenario():
            client = TestClient(TestServer(create_app(config)))
            await client.start_server()
            try:
                url = "/webgateway/render_shape_mask/5?color=FF0000"
                scraper = {"sessionid": "scraper"}
                statuses = []
                for i in range(4):
                    r = await client.get(
                        url + f"&_v={i}", cookies=scraper)
                    statuses.append(r.status)
                    retry_after = r.headers.get("Retry-After")
                assert statuses[:2] == [200, 200]
                assert 503 in statuses[2:]
                assert retry_after is not None
                # The calm session is untouched by the scraper's shed.
                r = await client.get(url,
                                     cookies={"sessionid": "calm"})
                assert r.status == 200
                # 404 scraping is METERED too: tokens pay for the
                # attempt (the image route's contract — refunding
                # request-level failures would let a hostile session
                # scrape nonexistent shape ids unmetered forever).
                misses = {"sessionid": "misser"}
                for _ in range(2):
                    r = await client.get(
                        "/webgateway/render_shape_mask/999",
                        cookies=misses)
                    assert r.status == 404
                statuses = []
                for _ in range(2):
                    r = await client.get(
                        "/webgateway/render_shape_mask/999",
                        cookies=misses)
                    statuses.append(r.status)
                assert 503 in statuses
            finally:
                await client.close()

        asyncio.run(scenario())
        assert telemetry.RESILIENCE.shed.get("fairness", 0) >= 1

    def test_cached_masks_cost_no_tokens(self, tmp_path):
        """Tile-route footing for masks: with the shape-mask byte
        cache on, repeat views of a cached mask serve PAST the
        session's burst — already-rendered bytes never cost a token
        and never shed."""
        import numpy as np
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.io.store import build_pyramid
        from omero_ms_image_region_tpu.models.mask import Mask
        from omero_ms_image_region_tpu.server.app import create_app
        from omero_ms_image_region_tpu.services.metadata import (
            write_mask)

        root = tmp_path / "data"
        root.mkdir()
        rng = np.random.default_rng(6)
        planes = rng.integers(0, 60000,
                              size=(1, 1, 64, 64)).astype("uint16")
        build_pyramid(planes, str(root / "1"), n_levels=1)
        grid = np.zeros(64 * 64, np.uint8)
        grid[:64] = 1
        write_mask(str(root), Mask(shape_id=5, width=64, height=64,
                                   bytes_=np.packbits(grid)
                                   .tobytes()))
        config = AppConfig.from_dict({
            "data-dir": str(root),
            "batcher": {"enabled": False},
            "shape-mask-cache": {"enabled": True},
            "session-store": {"type": "static", "required": False},
            "sessions": {"enabled": True, "bucket-refill-per-s": 0.5,
                         "bucket-burst": 2},
        })

        async def scenario():
            client = TestClient(TestServer(create_app(config)))
            await client.start_server()
            try:
                url = "/webgateway/render_shape_mask/5?color=FF0000"
                viewer = {"sessionid": "repeat-viewer"}
                # 8 repeat views on a burst-2 budget: the first
                # renders (1 token), every repeat is a byte-cache hit
                # BEFORE the fairness gate — all 200, zero sheds.
                for _ in range(8):
                    r = await client.get(url, cookies=viewer)
                    assert r.status == 200
            finally:
                await client.close()

        asyncio.run(scenario())
        assert telemetry.RESILIENCE.shed.get("fairness", 0) == 0
