"""The status contract (server/errors.py): every failure mode maps to
ONE stable HTTP status with the documented body shape, in both the
sidecar wire's ``_map_status`` and the app's ``_status_of`` — and no
path ever leaks a traceback to a client."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_image_region_tpu.server.app import create_app
from omero_ms_image_region_tpu.server.config import AppConfig
from omero_ms_image_region_tpu.server.ctx import BadRequestError
from omero_ms_image_region_tpu.server.errors import (
    DeadlineExceededError, NotFoundError, OverloadedError)
from omero_ms_image_region_tpu.server.sidecar import (_map_response,
                                                      _map_status)


# ------------------------------------------------------ wire -> exception

class TestMapStatus:
    def test_200_passes_payload_through(self):
        assert _map_status(200, b"bytes") == b"bytes"

    def test_400_is_bad_request_with_message(self):
        with pytest.raises(BadRequestError, match="bad z"):
            _map_status(400, "bad z")

    def test_404_is_not_found(self):
        with pytest.raises(NotFoundError):
            _map_status(404, "")

    def test_503_is_overloaded_with_retry_after(self):
        with pytest.raises(OverloadedError) as ei:
            _map_status(503, "queue full", retry_after_s=2.5)
        assert ei.value.retry_after_s == 2.5
        # No retry_after on the wire: a sane default, not a crash.
        with pytest.raises(OverloadedError) as ei:
            _map_status(503, "")
        assert ei.value.retry_after_s > 0

    def test_504_is_deadline_exceeded(self):
        with pytest.raises(DeadlineExceededError):
            _map_status(504, "spent")

    def test_unknown_status_is_runtime_error(self):
        with pytest.raises(RuntimeError, match="500"):
            _map_status(500, "")

    def test_map_response_carries_retry_after_header_field(self):
        with pytest.raises(OverloadedError) as ei:
            _map_response({"status": 503, "error": "shed",
                           "retry_after": 4.0}, b"")
        assert ei.value.retry_after_s == 4.0
        assert "shed" in str(ei.value)


# -------------------------------------------------- exception -> response

def test_every_failure_mode_maps_to_stable_status(tmp_path,
                                                  monkeypatch):
    """One app, every exception class the chain can surface: the
    response status/body contract holds and NO raw traceback reaches
    the client (the reference's empty 404/500 bodies,
    ImageRegionMicroserviceVerticle.java:314-323, extended by the
    fault-tolerance statuses)."""
    from omero_ms_image_region_tpu.server.handler import (
        ImageRegionHandler)

    cases = [
        (BadRequestError("bad window"), 400,
         lambda r, b: b == b"bad window"),
        (NotFoundError("gone"), 404, lambda r, b: b == b""),
        (OverloadedError("shed", retry_after_s=3.0), 503,
         lambda r, b: (r.headers["Retry-After"] == "3"
                       and b"shed" in b)),
        (ConnectionError("sidecar went away"), 503,
         lambda r, b: ("Retry-After" in r.headers
                       and b"unreachable" in b)),
        (DeadlineExceededError("budget spent"), 504,
         lambda r, b: b"budget spent" in b),
        (RuntimeError("secret internal detail"), 500,
         lambda r, b: b == b""),
    ]
    # A transport drop that outlived the transient retry is weather the
    # client retries through — shed class, never a bare 500.
    from omero_ms_image_region_tpu.utils.faultinject import (
        XlaRuntimeError)
    cases.append(
        (XlaRuntimeError("connection reset by peer"), 503,
         lambda r, b: "Retry-After" in r.headers))

    async def scenario():
        app = create_app(AppConfig(data_dir=str(tmp_path)))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            for exc, want_status, check in cases:
                async def boom(self, ctx, _exc=exc):
                    raise _exc
                monkeypatch.setattr(ImageRegionHandler,
                                    "render_image_region", boom)
                r = await client.get(
                    "/webgateway/render_image_region/3/0/0?m=g")
                body = await r.read()
                assert r.status == want_status, (exc, r.status)
                assert check(r, body), (exc, body)
                assert b"Traceback" not in body, exc
                assert b"secret internal detail" not in body or \
                    want_status != 500
        finally:
            await client.close()

    asyncio.run(scenario())
