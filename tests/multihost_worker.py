"""One process of a simulated multi-host pod (CPU backend).

Launched by ``tests/test_multihost.py`` — NOT a pytest module.  The
pod's process count arrives as argv[4] (2 or 4 in the tests); each
process owns ``8 // nprocs`` virtual CPU devices and ``jax.distributed``
joins them into one 8-device slice over which the mesh-sharded render
step runs SPMD, exactly as an N-host TPU pod would.  Prints one JSON
line with per-process shard checksums (all-gathered, so the test can
assert every process observed the same global result).
"""

import json
import os
import sys


def _make_group(B=8, C=4, H=64, W=64, quality=0):
    """Deterministic batcher group (same on every process/run)."""
    import numpy as np

    from omero_ms_image_region_tpu.flagship import flagship_rdef
    from omero_ms_image_region_tpu.ops.render import pack_settings
    from omero_ms_image_region_tpu.server.batcher import _Pending

    rng = np.random.default_rng(7)
    settings = pack_settings(flagship_rdef(C))
    group = []
    for _ in range(B):
        raw = rng.uniform(0, 60000, (C, H, W)).astype(np.float32)
        group.append(_Pending(raw=raw, settings=settings, h=H, w=W,
                              quality=quality))
    return group


def _make_overflow_group(B=8, C=4, H=64, W=64, quality=85):
    """Deterministic mid-density content whose wire totals land in
    (cap, 2*cap] for every tile (probed: 10 noise columns over a flat
    background, seed 7) — forces the one-shot cap-widening rescue."""
    import numpy as np

    from omero_ms_image_region_tpu.flagship import flagship_rdef
    from omero_ms_image_region_tpu.ops.render import pack_settings
    from omero_ms_image_region_tpu.server.batcher import _Pending

    rng = np.random.default_rng(7)
    settings = pack_settings(flagship_rdef(C))
    group = []
    for _ in range(B):
        raw = np.full((C, H, W), 20000, np.float32)
        raw[:, :, :10] = rng.uniform(0, 60000, (C, H, 10)).astype(
            np.float32)
        group.append(_Pending(raw=raw, settings=settings, h=H, w=W,
                              quality=quality))
    return group


def _spy_jpeg_launches():
    """Class-level instrumentation of every sharded JPEG dispatch:
    returns the list the launches append to (leader and follower alike
    go through MeshRenderer._jpeg_step)."""
    from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

    launches = []
    orig = MeshRenderer._jpeg_step

    def spy(self, quality, cap, engine="sparse", cap_words=None):
        step = orig(self, quality, cap, engine, cap_words)

        def wrapped(*args):
            launches.append([engine, quality, cap, cap_words])
            return step(*args)
        return wrapped

    MeshRenderer._jpeg_step = spy
    return launches


def serve_overflow_mode(pid: int) -> dict:
    """Pod-wide wire-cap overflow: the leader serves two overflowing
    groups (base dispatch -> 2x rescue -> memo-started 2x); the
    follower must replay the IDENTICAL launch sequence from the
    replicated totals alone (``parallel/serve.py`` lockstep memos)."""
    import hashlib

    from omero_ms_image_region_tpu.parallel import cluster
    from omero_ms_image_region_tpu.parallel.serve import (
        MeshRenderer, run_pod_follower)

    launches = _spy_jpeg_launches()
    mesh = cluster.global_mesh(chan_parallel=2)
    if pid != 0:
        groups = run_pod_follower(mesh, jpeg_engine="huffman")
        return {"follower_groups": groups, "launches": launches}
    renderer = MeshRenderer(mesh, jpeg_engine="huffman")
    jpegs1 = renderer._render_group_jpeg(_make_overflow_group())
    jpegs2 = renderer._render_group_jpeg(_make_overflow_group())
    renderer._pod.announce(0)          # shutdown broadcast
    return {
        "launches": launches,
        "jpeg_sha": hashlib.sha256(
            b"".join(jpegs1 + jpegs2)).hexdigest(),
        "n_jpegs": len(jpegs1) + len(jpegs2),
    }


def reference_overflow_mode() -> dict:
    """Single-process 8-device digests for the overflow groups."""
    import hashlib

    from omero_ms_image_region_tpu.parallel.mesh import make_mesh
    from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

    renderer = MeshRenderer(make_mesh(8, chan_parallel=2),
                            jpeg_engine="huffman")
    jpegs1 = renderer._render_group_jpeg(_make_overflow_group())
    jpegs2 = renderer._render_group_jpeg(_make_overflow_group())
    return {
        "jpeg_sha": hashlib.sha256(
            b"".join(jpegs1 + jpegs2)).hexdigest(),
        "n_jpegs": len(jpegs1) + len(jpegs2),
    }


def serve_adaptive_mode(pid: int) -> dict:
    """Pod-coordinated LIVE engine flip: the leader's AdaptiveEngine
    observes a link-rate collapse between groups and the flip
    propagates to the follower through the per-group announcement —
    both processes must launch sparse for group 1 and huffman for
    group 2."""
    import hashlib

    from omero_ms_image_region_tpu.parallel import cluster
    from omero_ms_image_region_tpu.parallel.serve import (
        MeshRenderer, run_pod_follower)
    from omero_ms_image_region_tpu.utils.adaptive import AdaptiveEngine

    launches = _spy_jpeg_launches()
    mesh = cluster.global_mesh(chan_parallel=2)
    if pid != 0:
        groups = run_pod_follower(mesh, jpeg_engine="sparse")
        return {"follower_groups": groups, "launches": launches}
    controller = AdaptiveEngine(initial_rate_mb_s=100.0)  # fast: sparse
    renderer = MeshRenderer(mesh, jpeg_engine="sparse",
                            engine_controller=controller)
    jpegs1 = renderer._render_group_jpeg(_make_group(quality=85))
    # Simulated link collapse: big fetches now crawl (1 MB in 2 s).
    for _ in range(8):
        controller.observe_fetch(1 << 20, 2.0)
    jpegs2 = renderer._render_group_jpeg(_make_group(quality=85))
    renderer._pod.announce(0)          # shutdown broadcast
    return {
        "launches": launches,
        "engine_after": controller.engine,
        "jpeg_sha": hashlib.sha256(
            b"".join(jpegs1 + jpegs2)).hexdigest(),
    }


def serve_mode(pid: int) -> dict:
    """Leader drives a MeshRenderer; followers replay via the pod
    channel.  Returns the leader's output digests."""
    import hashlib

    import numpy as np

    from omero_ms_image_region_tpu.parallel import cluster
    from omero_ms_image_region_tpu.parallel.serve import (
        MeshRenderer, run_pod_follower)

    mesh = cluster.global_mesh(chan_parallel=2)
    if pid != 0:
        groups = run_pod_follower(mesh, jpeg_engine="huffman")
        return {"follower_groups": groups}
    renderer = MeshRenderer(mesh, jpeg_engine="huffman")
    packed = renderer._render_group(_make_group())
    jpegs = renderer._render_group_jpeg(_make_group(quality=85))
    renderer._pod.announce(0)          # shutdown broadcast
    return {
        "packed_sha": hashlib.sha256(
            b"".join(np.ascontiguousarray(p).tobytes()
                     for p in packed)).hexdigest(),
        "jpeg_sha": hashlib.sha256(b"".join(jpegs)).hexdigest(),
        "n_jpegs": len(jpegs),
    }


def reference_mode() -> dict:
    """Single-process 8-device reference for the serve-mode digests
    (run in its own clean-env subprocess: an in-pytest reference would
    see whatever default platform the outer environment registered and
    diverge numerically from the workers)."""
    import hashlib

    import numpy as np

    from omero_ms_image_region_tpu.parallel.mesh import make_mesh
    from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

    renderer = MeshRenderer(make_mesh(8, chan_parallel=2),
                            jpeg_engine="huffman")
    packed = renderer._render_group(_make_group())
    jpegs = renderer._render_group_jpeg(_make_group(quality=85))
    return {
        "packed_sha": hashlib.sha256(
            b"".join(np.ascontiguousarray(p).tobytes()
                     for p in packed)).hexdigest(),
        "jpeg_sha": hashlib.sha256(b"".join(jpegs)).hexdigest(),
        "n_jpegs": len(jpegs),
    }


def main() -> int:
    pid = int(sys.argv[1])
    coordinator = sys.argv[2]
    mode = sys.argv[3] if len(sys.argv) > 3 else "checksum"
    nprocs = int(sys.argv[4]) if len(sys.argv) > 4 else 2
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The global mesh is always 8 devices; each process owns its slice.
    ndev = 8 if mode == "reference" else 8 // nprocs
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={ndev}"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import numpy as np

    import jax

    if mode == "reference":
        out = reference_mode()
        out.update({"pid": pid, "ok": True})
        print(json.dumps(out))
        return 0
    if mode == "reference-overflow":
        out = reference_overflow_mode()
        out.update({"pid": pid, "ok": True})
        print(json.dumps(out))
        return 0
    from omero_ms_image_region_tpu.flagship import flagship_rdef
    from omero_ms_image_region_tpu.ops.render import pack_settings
    from omero_ms_image_region_tpu.parallel import cluster
    from omero_ms_image_region_tpu.parallel.mesh import (
        render_step_sharded_batched, shard_batch_batched)

    cluster.initialize(coordinator_address=coordinator,
                       num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    if mode == "serve":
        out = serve_mode(pid)
        out.update({"pid": pid, "ok": True})
        print(json.dumps(out))
        return 0
    if mode == "serve-overflow":
        out = serve_overflow_mode(pid)
        out.update({"pid": pid, "ok": True})
        print(json.dumps(out))
        return 0
    if mode == "serve-adaptive":
        out = serve_adaptive_mode(pid)
        out.update({"pid": pid, "ok": True})
        print(json.dumps(out))
        return 0

    mesh = cluster.global_mesh(chan_parallel=2)
    rng = np.random.default_rng(0)     # same stream on both processes
    B, C, H, W = 8, 4, 64, 64
    raw = rng.uniform(0, 60000, (B, C, H, W)).astype(np.float32)
    settings = pack_settings(flagship_rdef(C))
    stacked = {
        k: np.stack([settings[k]] * B)
        for k in ("window_start", "window_end", "family",
                  "coefficient", "reverse", "tables")
    }
    stacked["cd_start"] = settings["cd_start"]
    stacked["cd_end"] = settings["cd_end"]
    args = shard_batch_batched(mesh, raw, stacked)
    out = render_step_sharded_batched(mesh)(*args)

    from jax.experimental import multihost_utils
    local_sum = np.float64(sum(
        np.asarray(jax.device_get(s.data)).astype(np.float64).sum()
        for s in out.addressable_shards))
    sums = np.asarray(multihost_utils.process_allgather(local_sum))
    print(json.dumps({"pid": pid, "ok": True,
                      "shard_sums": [float(v) for v in sums.ravel()]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
