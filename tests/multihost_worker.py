"""One process of a simulated 2-process multi-host pod (CPU backend).

Launched by ``tests/test_multihost.py`` — NOT a pytest module.  Each
process owns 4 virtual CPU devices; ``jax.distributed`` joins them into
one 8-device slice and the mesh-sharded render step runs SPMD across
both, exactly as a 2-host TPU pod would.  Prints one JSON line with
per-process shard checksums (all-gathered, so the test can assert every
process observed the same global result).
"""

import json
import os
import sys


def main() -> int:
    pid = int(sys.argv[1])
    coordinator = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import numpy as np

    import jax
    from omero_ms_image_region_tpu.flagship import flagship_rdef
    from omero_ms_image_region_tpu.ops.render import pack_settings
    from omero_ms_image_region_tpu.parallel import cluster
    from omero_ms_image_region_tpu.parallel.mesh import (
        render_step_sharded_batched, shard_batch_batched)

    cluster.initialize(coordinator_address=coordinator,
                       num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    mesh = cluster.global_mesh(chan_parallel=2)
    rng = np.random.default_rng(0)     # same stream on both processes
    B, C, H, W = 8, 4, 64, 64
    raw = rng.uniform(0, 60000, (B, C, H, W)).astype(np.float32)
    settings = pack_settings(flagship_rdef(C))
    stacked = {
        k: np.stack([settings[k]] * B)
        for k in ("window_start", "window_end", "family",
                  "coefficient", "reverse", "tables")
    }
    stacked["cd_start"] = settings["cd_start"]
    stacked["cd_end"] = settings["cd_end"]
    args = shard_batch_batched(mesh, raw, stacked)
    out = render_step_sharded_batched(mesh)(*args)

    from jax.experimental import multihost_utils
    local_sum = np.float64(sum(
        np.asarray(jax.device_get(s.data)).astype(np.float64).sum()
        for s in out.addressable_shards))
    sums = np.asarray(multihost_utils.process_allgather(local_sum))
    print(json.dumps({"pid": pid, "ok": True,
                      "shard_sums": [float(v) for v in sums.ravel()]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
