"""utils/sketch.py — the sentinel's streaming rank sketch.

Two load-bearing contracts:

* bounded RELATIVE rank error — a reported quantile is within the
  ladder's geometric-midpoint error (sqrt(ratio) - 1, ~3.6% at 32
  buckets/decade) of the exact order statistic, across distributions
  that actually look like latency (uniform, lognormal, exponential,
  bimodal);
* merge associativity — shard-then-merge in ANY grouping equals one
  sketch fed everything, which is what makes the fleet-merged
  ``/debug/sentinel`` view meaningful.
"""

import math
import random

import pytest

from omero_ms_image_region_tpu.utils.sketch import RankSketch

# Worst-case relative error of the default ladder (32 buckets/decade)
# plus slack for rank interpolation at the sample sizes we test.
REL_TOL = 0.06


def _exact_quantile(values, q):
    s = sorted(values)
    idx = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
    return s[idx]


def _distributions():
    rng = random.Random(0xC0FFEE)
    return {
        "uniform": [rng.uniform(1.0, 400.0) for _ in range(5000)],
        "lognormal": [rng.lognormvariate(3.0, 0.8)
                      for _ in range(5000)],
        "exponential": [rng.expovariate(1.0 / 25.0) + 0.5
                        for _ in range(5000)],
        # The shape drift actually takes: a fast mode and a slow tail
        # mode — p50 lands in the fast mode, p90/p99 in the slow one
        # (the 80/20 split keeps every tested rank INSIDE a mode; a
        # rank sitting exactly on the mode boundary is a knife-edge
        # where neighbouring order statistics differ by 10x and no
        # quantile estimator has a meaningful relative error).
        "bimodal": ([rng.gauss(8.0, 1.0) for _ in range(4000)]
                    + [rng.gauss(120.0, 10.0) for _ in range(1000)]),
    }


class TestRankError:
    @pytest.mark.parametrize("name", ["uniform", "lognormal",
                                      "exponential", "bimodal"])
    @pytest.mark.parametrize("q", [0.5, 0.9, 0.99])
    def test_quantile_within_relative_error(self, name, q):
        values = _distributions()[name]
        sk = RankSketch()
        for v in values:
            sk.add(v)
        got = sk.quantile(q)
        want = _exact_quantile(values, q)
        assert got is not None
        # Relative bound, with absolute slack near the ladder floor
        # where a bucket spans more of the value than REL_TOL allows.
        assert abs(got - want) <= max(REL_TOL * want, 2.0 * sk.lo), \
            f"{name} q={q}: sketch {got} vs exact {want}"

    def test_monotone_in_q(self):
        values = _distributions()["lognormal"]
        sk = RankSketch()
        for v in values:
            sk.add(v)
        qs = [sk.quantile(q) for q in (0.1, 0.5, 0.9, 0.99, 1.0)]
        assert qs == sorted(qs)

    def test_empty_sketch_answers_none(self):
        sk = RankSketch()
        assert sk.quantile(0.5) is None
        assert sk.n == 0

    def test_edge_clamping(self):
        sk = RankSketch(lo=1.0, hi=100.0)
        for v in (0.0001, 0.5, 1e9, 1e12):
            sk.add(v)
        # Underflow reports the floor, overflow the ceiling — never a
        # value outside the ladder.
        assert sk.quantile(0.0) == sk.lo
        assert sk.quantile(1.0) == sk.hi


class TestMerge:
    def _shards(self, n_shards=3):
        rng = random.Random(42)
        shards = []
        for _ in range(n_shards):
            sk = RankSketch()
            for _ in range(1000):
                sk.add(rng.lognormvariate(2.5, 1.0))
            shards.append(sk)
        return shards

    def test_merge_associative_and_commutative(self):
        a, b, c = self._shards()
        left = a.copy().merge(b.copy()).merge(c.copy())
        right = a.copy().merge(b.copy().merge(c.copy()))
        swapped = c.copy().merge(a.copy()).merge(b.copy())
        assert left.counts == right.counts == swapped.counts

    def test_merge_equals_single_feed(self):
        rng = random.Random(7)
        values = [rng.expovariate(0.1) for _ in range(3000)]
        whole = RankSketch()
        parts = [RankSketch() for _ in range(4)]
        for i, v in enumerate(values):
            whole.add(v)
            parts[i % 4].add(v)
        merged = parts[0]
        for p in parts[1:]:
            merged.merge(p)
        assert merged.counts == whole.counts
        assert merged.n == len(values)

    def test_incompatible_ladder_raises(self):
        with pytest.raises(ValueError):
            RankSketch().merge(RankSketch(buckets_per_decade=16))

    def test_ladder_is_shared(self):
        # One tuple per parameter set — the merge contract and the
        # per-instance memory bound both hang on this.
        assert RankSketch().bounds is RankSketch().bounds


class TestWire:
    def test_doc_round_trip(self):
        rng = random.Random(3)
        sk = RankSketch()
        for _ in range(500):
            sk.add(rng.uniform(0.5, 5000.0))
        back = RankSketch.from_doc(sk.to_doc())
        assert back is not None
        assert back.counts == sk.counts
        assert back.quantile(0.99) == sk.quantile(0.99)

    def test_doc_is_sparse(self):
        sk = RankSketch()
        sk.add(10.0)
        doc = sk.to_doc()
        assert len(doc["counts"]) == 1

    @pytest.mark.parametrize("garbage", [
        None, "x", 17, {"v": 2}, {"v": 1},
        {"v": 1, "lo": "nope", "hi": 1.0, "b": 32},
        {"v": 1, "lo": 0.01, "hi": 1e6, "b": 32,
         "counts": {"zzz": 1}},
    ])
    def test_foreign_doc_parses_to_none(self, garbage):
        assert RankSketch.from_doc(garbage) is None

    def test_doc_out_of_range_buckets_dropped(self):
        sk = RankSketch()
        sk.add(5.0)
        doc = sk.to_doc()
        doc["counts"]["999999"] = 7    # truncated/foreign ladder tail
        back = RankSketch.from_doc(doc)
        assert back is not None
        assert back.n == 1


class TestValidation:
    def test_bad_bounds_raise(self):
        with pytest.raises(ValueError):
            RankSketch(lo=5.0, hi=1.0)
        with pytest.raises(ValueError):
            RankSketch(lo=0.0, hi=1.0)
        with pytest.raises(ValueError):
            RankSketch(buckets_per_decade=0)

    def test_reset_empties(self):
        sk = RankSketch()
        sk.add(1.0)
        sk.reset()
        assert sk.n == 0 and sk.quantile(0.5) is None

    def test_relative_error_bound_matches_ladder(self):
        # The documented bound: geometric midpoint error is
        # sqrt(ratio) - 1 for the configured buckets/decade.
        sk = RankSketch()
        ratio = 10.0 ** (1.0 / sk.buckets_per_decade)
        assert math.sqrt(ratio) - 1.0 < REL_TOL
