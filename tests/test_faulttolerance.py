"""Fault-tolerance primitives: deadlines, circuit breaker, op-aware
retry, admission control, deterministic fault injection, supervisor —
the building blocks of the frontend -> sidecar -> batcher resilience
chain (wire-level composition lives in test_sidecar_faults.py)."""

import asyncio
import random
import subprocess
import sys
import time

import numpy as np
import pytest

from omero_ms_image_region_tpu.utils import faultinject, telemetry
from omero_ms_image_region_tpu.utils.transient import (
    IDEMPOTENT_OPS, CircuitBreaker, DeadlineExceededError, RetryPolicy,
    check_deadline, clear_deadline, deadline_scope, remaining_ms,
    set_task_deadline)


# ------------------------------------------------------------- deadlines

class TestDeadlines:
    def test_scope_sets_and_restores(self):
        assert remaining_ms() is None
        with deadline_scope(50.0):
            r = remaining_ms()
            assert r is not None and 0 < r <= 50.0
            check_deadline()          # budget left: no raise
        assert remaining_ms() is None

    def test_zero_budget_disables(self):
        # Config semantics: request-deadline-ms 0 = no deadline.
        with deadline_scope(0):
            assert remaining_ms() is None

    def test_spent_budget_raises(self):
        with deadline_scope(0.0001):
            time.sleep(0.001)
            with pytest.raises(DeadlineExceededError):
                check_deadline("unit")

    def test_task_deadline_zero_means_expired(self):
        # Wire semantics: a deadline_ms HEADER of 0 is a spent budget,
        # not an unbounded one (the config-side 0 never reaches the
        # wire — the client omits the header when no deadline is set).
        async def run():
            set_task_deadline(0.0)
            with pytest.raises(DeadlineExceededError):
                check_deadline("wire")
            set_task_deadline(None)
            check_deadline("wire")
        asyncio.run(run())

    def test_clear_deadline_detaches(self):
        with deadline_scope(0.0001):
            time.sleep(0.001)
            clear_deadline()
            check_deadline()          # detached: no raise


# -------------------------------------------------------- circuit breaker

class TestCircuitBreaker:
    def test_opens_after_threshold_and_half_opens(self):
        clock = [0.0]
        b = CircuitBreaker(3, reset_after_s=5.0, clock=lambda: clock[0])
        assert b.allow() and b.state_name == "closed"
        for _ in range(2):
            b.record_failure()
        assert b.state_name == "closed"   # threshold not reached
        b.record_failure()
        assert b.state_name == "open" and not b.allow()
        assert b.opens == 1
        assert b.retry_after_s() == pytest.approx(5.0)
        clock[0] = 5.0
        # Half-open: exactly ONE caller gets the trial slot.
        assert b.state_name == "half-open"
        assert b.allow() and not b.allow()

    def test_half_open_failure_reopens_success_closes(self):
        clock = [0.0]
        b = CircuitBreaker(1, reset_after_s=2.0, clock=lambda: clock[0])
        b.record_failure()
        clock[0] = 2.0
        assert b.allow()
        b.record_failure()                # trial failed
        assert b.state_name == "open" and b.opens == 2
        clock[0] = 4.0
        assert b.allow()
        b.record_success()                # trial succeeded
        assert b.state_name == "closed" and b.allow()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(2)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state_name == "closed"   # never 2 consecutive

    def test_abandoned_half_open_probe_expires(self):
        # Regression: a probe whose caller never reported an outcome
        # (cancelled mid-call) must not wedge the breaker into
        # shedding forever — the trial slot re-opens after the reset
        # window.
        clock = [0.0]
        b = CircuitBreaker(1, reset_after_s=1.0, clock=lambda: clock[0])
        b.record_failure()
        clock[0] = 1.0
        assert b.allow()          # probe claimed... and abandoned
        assert not b.allow()
        clock[0] = 2.0
        assert b.allow()          # slot expired: a new probe may run
        b.record_success()
        assert b.state_name == "closed"


# ----------------------------------------------------------- retry policy

class TestRetryPolicy:
    def test_op_awareness(self):
        p = RetryPolicy(max_attempts=4)
        for op in IDEMPOTENT_OPS:
            assert p.attempts_for(op) == 4
        # The acceptance-critical one: a state-changing upload gets
        # exactly one attempt, no matter the configured ladder.
        assert p.attempts_for("plane_put") == 1

    def test_backoff_capped_exponential_deterministic(self):
        p = RetryPolicy(base_backoff_s=0.1, max_backoff_s=0.4,
                        jitter=0.0, rng=random.Random(3))
        assert [p.backoff_s(i) for i in range(4)] == \
            [0.1, 0.2, 0.4, 0.4]
        # Jitter is seeded -> reproducible sequences.
        a = RetryPolicy(jitter=0.5, rng=random.Random(7))
        b = RetryPolicy(jitter=0.5, rng=random.Random(7))
        seq_a = [a.backoff_s(i) for i in range(5)]
        seq_b = [b.backoff_s(i) for i in range(5)]
        assert seq_a == seq_b
        assert all(s >= base for s, base in
                   zip(seq_a, [0.025, 0.05, 0.1, 0.2, 0.4]))


# ------------------------------------------------------ admission control

class TestAdmission:
    def test_depth_bound_sheds_with_retry_after(self):
        from omero_ms_image_region_tpu.server.admission import (
            AdmissionController)
        from omero_ms_image_region_tpu.server.errors import (
            OverloadedError)

        adm = AdmissionController(max_queue=2, retry_after_s=1.5)
        t1, t2 = adm.admit(), adm.admit()
        with pytest.raises(OverloadedError) as ei:
            adm.admit()
        assert ei.value.retry_after_s >= 1.5
        assert adm.shed_total == 1
        adm.release(t1)
        adm.release(t2)
        assert adm.inflight == 0
        adm.release(adm.admit())          # slot freed: admits again

    def test_deadline_aware_shed(self):
        from omero_ms_image_region_tpu.server.admission import (
            AdmissionController)
        from omero_ms_image_region_tpu.server.errors import (
            OverloadedError)

        adm = AdmissionController(max_queue=100)
        # Teach the EWMA a 100 ms service time, with one slot occupied.
        t = adm.admit()
        adm.ewma_s = 0.1
        with deadline_scope(5.0):     # 5 ms budget, ~100 ms est. wait
            with pytest.raises(OverloadedError):
                adm.admit()
        with deadline_scope(5000.0):  # plenty of budget: admitted
            adm.release(adm.admit(), completed=False)
        adm.release(t)

    def test_failed_renders_do_not_feed_ewma(self):
        from omero_ms_image_region_tpu.server.admission import (
            AdmissionController)

        adm = AdmissionController(max_queue=4)
        adm.release(adm.admit(), completed=False)
        assert adm.ewma_s is None
        adm.release(adm.admit(), completed=True)
        assert adm.ewma_s is not None


# -------------------------------------------------------- fault injection

class TestFaultInjection:
    def test_seeded_determinism(self):
        cfg = faultinject.FaultInjectionConfig(
            seed=42, wire_drop_rate=0.3, wire_truncate_rate=0.2,
            device_error_rate=0.5)
        a = faultinject.FaultInjector(cfg)
        b = faultinject.FaultInjector(cfg)

        def schedule(inj):
            out = []
            for _ in range(50):
                out.append(inj.wire_fault())
                try:
                    inj.maybe_device_error()
                    out.append("ok")
                except faultinject.XlaRuntimeError:
                    out.append("boom")
            return out

        assert schedule(a) == schedule(b)
        assert a.snapshot() == b.snapshot()
        assert a.snapshot()        # the chaos actually happened

    def test_injected_error_is_classified_transient(self):
        from omero_ms_image_region_tpu.utils.transient import (
            is_transient_device_error)
        inj = faultinject.FaultInjector(faultinject.FaultInjectionConfig(
            seed=1, device_error_rate=1.0))
        with pytest.raises(faultinject.XlaRuntimeError) as ei:
            inj.maybe_device_error()
        # The production retry path must classify it exactly like a
        # real transport drop.
        assert is_transient_device_error(ei.value)

    def test_rates_validated(self):
        with pytest.raises(ValueError):
            faultinject.FaultInjectionConfig(
                seed=1, wire_drop_rate=1.5).validate()

    def test_install_guard(self):
        inj = faultinject.install(faultinject.FaultInjectionConfig(
            seed=9, wire_drop_rate=1.0))
        try:
            assert faultinject.active() is inj
        finally:
            faultinject.uninstall()
        assert faultinject.active() is None

    def test_seed_rejected_on_explicit_multihost_config(self):
        # Chaos on one pod process would diverge SPMD lockstep; the
        # combination must fail at config load, not hang a slice.
        from omero_ms_image_region_tpu.server.config import AppConfig
        raw = {"parallel": {"enabled": True,
                            "coordinator-address": "h0:8476",
                            "num-processes": 2, "process-id": 0},
               "fault-injection": {"seed": 1}}
        with pytest.raises(ValueError, match="multi-host"):
            AppConfig.from_dict(raw)
        raw["parallel"]["enabled"] = False
        AppConfig.from_dict(raw)        # single-host: allowed

    def test_die_after_requests_fires_once(self):
        inj = faultinject.FaultInjector(faultinject.FaultInjectionConfig(
            seed=1, die_after_requests=3))
        hits = [inj.sidecar_should_die() for _ in range(6)]
        assert hits == [False, False, True, False, False, False]


# ------------------------------------------- batcher deadline cancellation

def test_batcher_cancels_expired_queued_work():
    """A pending whose budget died in the queue is failed with
    DeadlineExceededError at dispatch pop — the device kernel never
    runs for it (batches_dispatched stays 0)."""
    from omero_ms_image_region_tpu.server.batcher import (
        BatchingRenderer)

    async def run():
        r = BatchingRenderer(max_batch=4, linger_ms=1.0)
        settings = {"cd_start": 0, "cd_end": 255,
                    "tables": np.zeros((1, 3), np.float32),
                    "window_start": np.zeros(1, np.float32),
                    "window_end": np.ones(1, np.float32),
                    "family": np.zeros(1, np.int32),
                    "coefficient": np.ones(1, np.float32),
                    "reverse": np.zeros(1, np.int32)}
        raw = np.zeros((1, 32, 32), np.uint16)
        try:
            with deadline_scope(0.0001):     # spent before the pop
                with pytest.raises(DeadlineExceededError):
                    await r.render(raw, settings)
            assert r.batches_dispatched == 0
        finally:
            await r.close()

    shed0 = telemetry.RESILIENCE.deadline_cancelled
    asyncio.run(run())
    assert telemetry.RESILIENCE.deadline_cancelled == shed0 + 1


def test_batcher_renders_within_budget():
    """Same path, generous budget: the render completes (the deadline
    plumbing must not fail work that still has time)."""
    from omero_ms_image_region_tpu.server.batcher import (
        BatchingRenderer)
    from omero_ms_image_region_tpu.ops.render import pack_settings
    from omero_ms_image_region_tpu.models.pixels import Pixels
    from omero_ms_image_region_tpu.models.rendering import (
        default_rendering_def)

    async def run():
        r = BatchingRenderer(max_batch=2, linger_ms=0.5)
        pixels = Pixels(image_id=1, pixels_type="uint16", size_x=32,
                        size_y=32, size_z=1, size_c=1, size_t=1)
        settings = pack_settings(default_rendering_def(pixels), None)
        raw = np.random.default_rng(0).integers(
            0, 60000, size=(1, 32, 32)).astype(np.float32)
        try:
            with deadline_scope(60000.0):
                out = await r.render(raw, settings)
            assert out.shape == (32, 32)
        finally:
            await r.close()

    asyncio.run(run())


# ------------------------------------------ single-flight follower budget

def test_single_flight_follower_deadline_leaves_leader_running():
    """A follower whose budget dies waiting gets its own 504; the
    shared render is NOT cancelled and still settles the leader."""
    from omero_ms_image_region_tpu.server.handler import SingleFlight

    async def run():
        sf = SingleFlight()
        release = asyncio.Event()

        async def producer():
            await release.wait()
            return b"bytes"

        leader = asyncio.ensure_future(sf.run("k", producer))
        await asyncio.sleep(0.01)      # leader task in flight

        async def follower():
            with deadline_scope(20.0):
                return await sf.run("k", producer)

        with pytest.raises(DeadlineExceededError):
            await follower()
        # The shared task survived the follower's timeout.
        assert sf.inflight() == 1
        release.set()
        result, coalesced = await leader
        assert result == b"bytes" and coalesced is False
        assert sf.hits == 1            # the follower did coalesce

    asyncio.run(run())


def test_single_flight_leader_budget_reaches_batcher():
    """Regression: the shared render inherits the LEADER's budget (it
    is the leader's admitted pipeline run) — a spent leader budget
    still cancels the queued work instead of being silently detached
    by the coalescing layer."""
    from omero_ms_image_region_tpu.server.batcher import (
        BatchingRenderer)
    from omero_ms_image_region_tpu.server.handler import SingleFlight

    async def run():
        r = BatchingRenderer(max_batch=4, linger_ms=1.0)
        sf = SingleFlight()
        settings = {"cd_start": 0, "cd_end": 255,
                    "tables": np.zeros((1, 3), np.float32)}
        raw = np.zeros((1, 32, 32), np.uint16)
        try:
            with deadline_scope(0.0001):     # leader budget: spent
                with pytest.raises(DeadlineExceededError):
                    await sf.run(
                        "k", lambda: r.render(raw, settings))
            assert r.batches_dispatched == 0
        finally:
            await r.close()

    asyncio.run(run())


# ------------------------------------------------------------- supervisor

def test_supervisor_restarts_killed_child_and_stops_cleanly():
    """Mechanism-level drill with a cheap child (the full device
    process drill lives in test_sidecar_faults.py): kill -9 the child,
    the supervisor respawns it with backoff; stop() terminates without
    a restart."""
    from omero_ms_image_region_tpu.server.sidecar import (
        SidecarSupervisor)

    spawned = []

    def spawn():
        proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(300)"])
        spawned.append(proc)
        return proc

    restarts0 = telemetry.RESILIENCE.supervisor_restarts
    sup = SidecarSupervisor(spawn, base_backoff_s=0.05,
                            max_backoff_s=0.2)
    first = sup.start()
    try:
        first.kill()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if sup.restarts >= 1 and sup.proc is not first \
                    and sup.proc.poll() is None:
                break
            time.sleep(0.05)
        assert sup.restarts >= 1, "supervisor never restarted the child"
        assert sup.proc is not first and sup.proc.poll() is None
        assert telemetry.RESILIENCE.supervisor_restarts > restarts0
    finally:
        sup.stop()
    # Deliberate shutdown: child terminated, and NOT restarted.
    assert sup.proc.poll() is not None
    time.sleep(0.3)
    assert all(p.poll() is not None for p in spawned)


# ----------------------------------------------- _Conn registration race

def test_conn_refuses_registration_after_death():
    """Regression for the enqueue/fail_pending race: a pending
    registered after the connection died must fail IMMEDIATELY, not
    hang forever on a future no read loop will ever resolve."""
    from omero_ms_image_region_tpu.server.sidecar import _Conn

    class DummyWriter:
        def is_closing(self):
            return True

        def close(self):
            pass

    async def run():
        conn = _Conn(reader=None, writer=DummyWriter())
        loop = asyncio.get_running_loop()
        parked = loop.create_future()
        conn.register(1, parked)
        conn.fail_pending(ConnectionError("sidecar went away"))
        # Already-parked waiters were failed...
        with pytest.raises(ConnectionError):
            parked.result()
        # ...and late registration is refused instead of stranded.
        with pytest.raises(ConnectionError):
            conn.register(2, loop.create_future())
        assert not conn.pending

    asyncio.run(run())
