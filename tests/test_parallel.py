"""Mesh-sharded render step: numerical parity with the single-device kernel.

The ``(data, chan)`` mesh splits the additive composite
(``Renderer.renderAsPackedInt``'s sum over active channels,
``ImageRegionRequestHandler.java:559``) into per-shard partial sums joined by
a ``psum`` — output must be bit-identical to the unsharded kernel.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omero_ms_image_region_tpu.models.pixels import Pixels
from omero_ms_image_region_tpu.models.rendering import (
    RenderingModel, default_rendering_def,
)
from omero_ms_image_region_tpu.ops.render import (
    pack_settings, render_tile, unpack_rgba,
)
from omero_ms_image_region_tpu.parallel.mesh import (
    make_mesh, render_step_sharded, resolve_devices, shard_batch,
)


def _settings(C):
    pixels = Pixels(image_id=1, size_x=256, size_y=256, size_z=1,
                    size_c=C, size_t=1, pixels_type="uint16")
    rdef = default_rendering_def(pixels)
    rdef.model = RenderingModel.RGB
    colors = [(255, 0, 0), (0, 255, 0), (0, 0, 255), (255, 0, 255)]
    for i, cb in enumerate(rdef.channel_bindings):
        cb.active = True
        cb.red, cb.green, cb.blue = colors[i % 4]
        cb.input_start, cb.input_end = 500.0, 30000.0
        cb.reverse_intensity = i == 1
    return rdef, pack_settings(rdef)


@pytest.mark.parametrize("n_devices,chan_parallel", [(8, 2), (8, 4), (4, 1)])
def test_sharded_matches_single_device(n_devices, chan_parallel):
    if len(resolve_devices(n_devices)) < n_devices:
        pytest.skip("needs virtual device mesh")
    C = max(chan_parallel, 4)
    B = (n_devices // chan_parallel) * 2
    H = W = 32
    rng = np.random.default_rng(42)
    raw = rng.integers(0, 65535, size=(B, C, H, W)).astype(np.float32)
    rdef, settings = _settings(C)

    mesh = make_mesh(n_devices, chan_parallel=chan_parallel)
    step = render_step_sharded(mesh)
    out = unpack_rgba(np.asarray(step(*shard_batch(mesh, raw, settings))))

    # Pin the single-device reference to the mesh's platform: bit-exact
    # parity is only guaranteed against the same backend's transcendentals.
    ref_device = mesh.devices.flat[0]
    for b in range(B):
        expect = render_tile(
            jax.device_put(raw[b], ref_device),
            settings["window_start"], settings["window_end"],
            settings["family"], settings["coefficient"], settings["reverse"],
            settings["cd_start"], settings["cd_end"], settings["tables"],
        )
        np.testing.assert_array_equal(out[b], expect)


def test_sharded_jpeg_step_matches_single_device():
    """The full mesh-sharded serving step emits the same JFIF bytes as the
    single-device sparse pipeline."""
    if len(resolve_devices(8)) < 8:
        pytest.skip("needs virtual device mesh")
    from omero_ms_image_region_tpu.flagship import batched_args
    from omero_ms_image_region_tpu.ops.jpegenc import (
        encode_sparse_buffers, max_sparse_cap, quant_tables,
        render_to_jpeg_sparse,
    )
    from omero_ms_image_region_tpu.parallel.mesh import (
        render_jpeg_step_sharded,
    )

    C, B, H, W = 4, 8, 32, 32
    cap = max_sparse_cap(H, W)
    rng = np.random.default_rng(11)
    raw = rng.integers(0, 65535, size=(B, C, H, W)).astype(np.float32)
    rdef, settings = _settings(C)

    mesh = make_mesh(8, chan_parallel=2)
    bufs = np.asarray(render_jpeg_step_sharded(mesh, quality=80, cap=cap)(
        *shard_batch(mesh, raw, settings)))
    sharded_jpegs = encode_sparse_buffers(bufs, W, H, 80, cap)

    ref_device = mesh.devices.flat[0]
    qy, qc = (np.asarray(t, np.int32) for t in quant_tables(80))
    args = batched_args(settings, raw)[1:]
    single = np.asarray(render_to_jpeg_sparse(
        jax.device_put(raw, ref_device), *args, qy, qc, cap=cap))
    single_jpegs = encode_sparse_buffers(single, W, H, 80, cap)
    assert sharded_jpegs == single_jpegs


def test_make_mesh_rejects_indivisible():
    if len(resolve_devices(8)) < 8:
        pytest.skip("needs virtual device mesh")
    with pytest.raises(ValueError):
        make_mesh(7, chan_parallel=2)


def test_make_mesh_rejects_too_few_devices():
    with pytest.raises(ValueError, match="only"):
        make_mesh(4096, chan_parallel=1)
