"""Edge-cache-grade conditional HTTP + the fleet-global byte tier.

Three contracts under test:

* **Golden ETag pin** — the ETag derivation is frozen byte-for-byte
  for a corpus of canonical requests.  A changed ETag silently
  invalidates every CDN edge at once, so derivation drift must fail
  THIS test loudly, never ship silently.
* **304/HEAD are free** — an ``If-None-Match`` hit answers 304 with
  ZERO render work, zero admission debit and zero session-token
  debit, asserted by counter deltas; error responses never carry the
  cache headers.
* **Peer byte tier** — the ``byte_probe``/``byte_fetch``/``byte_put``
  wire ops move already-rendered bytes between fleet members (ACL
  gated, digest verified), and the fleet drill proves a re-routed
  viewer is served the draining owner's bytes byte-identically with
  zero device work on the serving member.
"""

import asyncio
import json
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_image_region_tpu.io.store import build_pyramid
from omero_ms_image_region_tpu.server import httpcache
from omero_ms_image_region_tpu.server.app import (SERVICES_KEY,
                                                  create_app)
from omero_ms_image_region_tpu.server.config import (
    AppConfig, BatcherConfig, FleetConfig, RawCacheConfig,
    RendererConfig, SessionsConfig, SidecarConfig)
from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
from omero_ms_image_region_tpu.services.cache import CacheConfig
from omero_ms_image_region_tpu.utils import telemetry
from omero_ms_image_region_tpu.utils.stopwatch import \
    REGISTRY as SPAN_REG

IMG = 1
H = W = 64


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    SPAN_REG.reset()
    yield
    telemetry.reset()
    SPAN_REG.reset()


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(5)
    planes = rng.integers(0, 60000,
                          size=(2, 1, H, W)).astype(np.uint16)
    build_pyramid(planes, str(tmp_path / str(IMG)), chunk=(32, 32),
                  n_levels=1)
    return str(tmp_path)


def _config(data_dir, **kw):
    return AppConfig(
        data_dir=data_dir,
        batcher=BatcherConfig(enabled=False),
        raw_cache=RawCacheConfig(enabled=True, prefetch=False),
        renderer=RendererConfig(cpu_fallback_max_px=0), **kw)


URL = (f"/webgateway/render_image_region/{IMG}/0/0"
       f"?c=1|0:60000$FF0000&m=g&format=png")


def _renders() -> int:
    snap = SPAN_REG.snapshot()
    return (snap.get("Renderer.renderAsPackedInt", {}).get("count", 0)
            + snap.get("Renderer.renderAsPackedInt.cpu",
                       {}).get("count", 0))


# --------------------------------------------------------- golden pin

class TestGoldenEtagPin:
    """The derivation contract, frozen.  Every expected string below
    was computed once at introduction; a mismatch means the schema
    changed and EVERY deployed CDN edge would silently invalidate —
    bump the ``ir1`` schema prefix AND this corpus deliberately, never
    accidentally."""

    CORPUS = [
        # (params, expected ETag under epoch "0")
        ({"imageId": "1", "theZ": "0", "theT": "0",
          "tile": "0,0,0,256,256", "format": "png", "m": "c",
          "c": "1|0:60000$FF0000"},
         '"ir1-0-4f9e21d1808ee49b6e7bf962"'),
        # Identical params in a DIFFERENT insertion order: the
        # identity sorts params, so the ETag is the same.
        ({"c": "1|0:60000$FF0000", "m": "c", "format": "png",
          "tile": "0,0,0,256,256", "theT": "0", "theZ": "0",
          "imageId": "1"},
         '"ir1-0-4f9e21d1808ee49b6e7bf962"'),
        # Default-elision is a DISTINCT identity (the reference's key
        # hashes the raw params): format omitted != format=jpeg.
        # Pinned so the aliasing posture cannot drift silently.
        ({"imageId": "1", "theZ": "0", "theT": "0",
          "tile": "0,0,0,256,256", "m": "c",
          "c": "1|0:60000$FF0000"},
         '"ir1-0-1c5ffb3398d2b9ab7bbe690c"'),
        ({"imageId": "1", "theZ": "0", "theT": "0",
          "tile": "0,0,0,256,256", "format": "jpeg", "m": "c",
          "c": "1|0:60000$FF0000"},
         '"ir1-0-b5086cd2b74f1ef360cbdff4"'),
        ({"imageId": "7", "theZ": "3", "theT": "1",
          "region": "0,0,512,512", "q": "0.9",
          "c": "1|100:50000$00FF00,-2"},
         '"ir1-0-82d6b8e197630c9a14433631"'),
        ({"imageId": "2", "theZ": "0", "theT": "0", "p": "intmax|0:5",
          "c": "1|0:60000$FF0000", "m": "g"},
         '"ir1-0-6c69376b4a42213e77bffeec"'),
    ]

    def test_corpus_pinned(self):
        for params, expected in self.CORPUS:
            ctx = ImageRegionCtx.from_params(dict(params), None)
            assert httpcache.etag_for(ctx.cache_key, "0") == expected, \
                f"ETag derivation drifted for {params}"

    def test_epoch_rides_visibly_and_changes_the_tag(self):
        ctx = ImageRegionCtx.from_params(dict(self.CORPUS[0][0]), None)
        tagged = httpcache.etag_for(ctx.cache_key, "e9")
        assert tagged == '"ir1-e9-a9fa1176a832c5c518311691"'
        assert tagged != httpcache.etag_for(ctx.cache_key, "0")

    def test_trailing_slash_aliases_through_the_route(self, data_dir):
        """``/7/0/0/`` vs ``/7/0/0``: the wildcard tail never reaches
        the params, so both URLs carry ONE ETag — an edge caching by
        URL still revalidates either against the other's tag."""
        async def scenario():
            client = TestClient(TestServer(create_app(
                _config(data_dir))))
            await client.start_server()
            try:
                r1 = await client.get(URL)
                await r1.read()
                r2 = await client.get(URL.replace(
                    f"/{IMG}/0/0?", f"/{IMG}/0/0/?"))
                await r2.read()
                assert r1.status == r2.status == 200
                assert r1.headers["ETag"] == r2.headers["ETag"]
                return r1.headers["ETag"]
            finally:
                await client.close()

        etag = asyncio.run(scenario())
        assert etag.startswith('"ir1-0-')

    def test_if_none_match_grammar(self):
        etag = '"ir1-0-abc"'
        assert httpcache.if_none_match_matches(etag, etag)
        assert httpcache.if_none_match_matches("*", etag)
        assert httpcache.if_none_match_matches(
            f'"zzz", W/{etag} , "yyy"', etag)
        assert not httpcache.if_none_match_matches('"zzz"', etag)
        assert not httpcache.if_none_match_matches(None, etag)
        assert not httpcache.if_none_match_matches("", etag)


# ------------------------------------------------- 304 / HEAD are free

class TestConditionalAnswers:
    def test_304_zero_render_zero_admission_zero_tokens(self,
                                                        data_dir):
        """THE acceptance criterion: an If-None-Match hit answers 304
        with zero render work, zero admission debit and zero
        session-token debit — by counter delta, not by vibes."""
        config = _config(
            data_dir,
            sessions=SessionsConfig(enabled=True),
            session_store_type="static")

        async def scenario():
            app = create_app(config)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                services = app[SERVICES_KEY]
                admission = services.admission
                buckets = admission.session_buckets
                cookies = {"sessionid": "s1"}
                r = await client.get(URL, cookies=cookies)
                body = await r.read()
                assert r.status == 200 and body
                etag = r.headers["ETag"]
                renders = _renders()
                admitted = admission.admitted_total
                taken = buckets.taken_total
                r = await client.get(
                    URL, headers={"If-None-Match": etag},
                    cookies=cookies)
                body = await r.read()
                assert r.status == 304
                assert body == b""
                assert r.headers["ETag"] == etag
                # Zero work, by delta: no render span, no admission
                # slot, no fairness token.
                assert _renders() == renders
                assert admission.admitted_total == admitted
                assert buckets.taken_total == taken
                assert telemetry.HTTPCACHE.not_modified == 1
                # The family is on /metrics.
                m = await client.get("/metrics")
                text = await m.text()
                assert "imageregion_httpcache_304_total 1" in text
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_streaming_path_carries_and_revalidates_same_etag(
            self, data_dir):
        """The chunked path (wire.streaming on) emits the SAME ETag as
        the unary path and revalidates to the same 304."""
        config = _config(data_dir)
        assert config.wire.streaming   # default-on; the test rides it

        async def scenario():
            client = TestClient(TestServer(create_app(config)))
            await client.start_server()
            try:
                r = await client.get(URL)
                body = await r.read()
                assert r.status == 200 and body
                etag = r.headers["ETag"]
                r = await client.get(
                    URL, headers={"If-None-Match": etag})
                await r.read()
                assert r.status == 304
                return etag
            finally:
                await client.close()

        etag = asyncio.run(scenario())
        ctx = ImageRegionCtx.from_params({
            "imageId": str(IMG), "theZ": "0", "theT": "0",
            "c": "1|0:60000$FF0000", "m": "g", "format": "png"}, None)
        # The streamed response's tag IS the derivation's tag.
        assert etag == httpcache.etag_for(ctx.cache_key, "0")

    def test_head_is_renderless_and_matches_get_headers(self,
                                                        data_dir):
        async def scenario():
            client = TestClient(TestServer(create_app(
                _config(data_dir))))
            await client.start_server()
            try:
                r = await client.head(URL)
                assert r.status == 200
                assert await r.read() == b""
                assert r.headers["ETag"].startswith('"ir1-')
                assert "Cache-Control" in r.headers
                assert _renders() == 0          # never rendered
                assert telemetry.HTTPCACHE.head == 1
                # HEAD + If-None-Match revalidates like GET.
                r2 = await client.head(URL, headers={
                    "If-None-Match": r.headers["ETag"]})
                assert r2.status == 304
                # HEAD on a MISSING image keeps status fidelity: the
                # renderless answer is gated on the ACL/exists check.
                r3 = await client.head(
                    URL.replace(f"/{IMG}/0/0", "/999/0/0"))
                assert r3.status == 404
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_errors_carry_no_cache_headers(self, data_dir):
        """The satellite audit, locked in: 4xx/5xx responses carry
        neither Cache-Control nor ETag — an edge must never cache a
        failure under a render identity."""
        async def scenario():
            client = TestClient(TestServer(create_app(
                _config(data_dir))))
            await client.start_server()
            try:
                # 400 (malformed tile), 404 (missing image), and a
                # parse-level 400 (bad channel) — none cacheable.
                for path in (
                        f"/webgateway/render_image_region/{IMG}/0/0"
                        f"?tile=nope",
                        "/webgateway/render_image_region/999/0/0",
                        f"/webgateway/render_image_region/{IMG}/0/0"
                        f"?c=zz|",
                ):
                    r = await client.get(path)
                    await r.read()
                    assert r.status in (400, 404), path
                    assert "Cache-Control" not in r.headers, path
                    assert "ETag" not in r.headers, path
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_vary_posture_tracks_acl(self, data_dir):
        """Public images are ``public`` with NO Vary (a cookie-blind
        edge entry is safe for everyone); ACL-gated images are
        ``private`` + ``Vary: Cookie`` so a shared cache keys entries
        per session."""
        acl_path = os.path.join(data_dir, str(IMG), "acl.json")

        async def fetch_headers(session=None):
            client = TestClient(TestServer(create_app(_config(
                data_dir, session_store_type="static"))))
            await client.start_server()
            try:
                cookies = ({"sessionid": session} if session else None)
                r = await client.get(URL, cookies=cookies)
                await r.read()
                return r.status, dict(r.headers)
            finally:
                await client.close()

        status, headers = asyncio.run(fetch_headers())
        assert status == 200
        assert headers["Cache-Control"].startswith("public")
        assert "Vary" not in headers

        with open(acl_path, "w") as f:
            json.dump({"public": False, "sessions": ["s1"]}, f)
        try:
            status, headers = asyncio.run(fetch_headers(session="s1"))
            assert status == 200
            assert headers["Cache-Control"].startswith("private")
            assert headers["Vary"] == "Cookie"
        finally:
            os.unlink(acl_path)

    def test_quality_capped_response_is_never_cacheable(
            self, data_dir, monkeypatch):
        """A brownout-capped render must not be edge-cached under the
        permanent render identity: the ETag is URL-pure, so a cached
        degraded body would be 304-confirmed forever.  A capped 200
        drops ETag/Vary and answers no-store."""
        from omero_ms_image_region_tpu.server.handler import \
            ImageRegionHandler

        orig = ImageRegionHandler.render_image_region

        async def capped(self, ctx, **kw):
            data = await orig(self, ctx, **kw)
            ctx._pressure_quality_capped = True   # the ladder's mark
            return data

        monkeypatch.setattr(ImageRegionHandler, "render_image_region",
                            capped)

        async def scenario():
            client = TestClient(TestServer(create_app(
                _config(data_dir))))
            await client.start_server()
            try:
                r = await client.get(URL)
                body = await r.read()
                assert r.status == 200 and body
                assert "ETag" not in r.headers
                assert "Vary" not in r.headers
                assert r.headers["Cache-Control"] == "no-store"
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_legacy_cache_control_header_still_wins(self, data_dir):
        """An explicitly configured cache-control-header string stays
        the Cache-Control VALUE (operator policy); the ETag layer
        still applies on top."""
        async def scenario():
            client = TestClient(TestServer(create_app(_config(
                data_dir, cache_control_header="private, max-age=9"))))
            await client.start_server()
            try:
                r = await client.get(URL)
                await r.read()
                assert r.headers["Cache-Control"] == \
                    "private, max-age=9"
                assert "ETag" in r.headers
            finally:
                await client.close()

        asyncio.run(scenario())


# ---------------------------------------------------- peer byte tier

async def _wait_socket(sock, task):
    for _ in range(400):
        if task.done():
            raise AssertionError(
                f"sidecar died at startup: {task.exception()!r}")
        if os.path.exists(sock):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("sidecar socket never appeared")


class TestPeerByteTier:
    def _member_cfg(self, data_dir):
        return AppConfig(
            data_dir=data_dir,
            caches=CacheConfig.enabled_all(),
            batcher=BatcherConfig(enabled=False),
            raw_cache=RawCacheConfig(enabled=True, prefetch=False),
            renderer=RendererConfig(cpu_fallback_max_px=0))

    def test_byte_ops_roundtrip_acl_and_digest(self, data_dir,
                                               tmp_path):
        """The wire ops themselves: probe misses then hits, fetch is
        ACL-gated per session and 404s on a miss, put is digest-
        verified (a corrupt body can never poison the tier)."""
        import hashlib

        from omero_ms_image_region_tpu.server.sidecar import (
            SidecarClient, run_sidecar)

        sock = str(tmp_path / "peer.sock")
        acl_path = os.path.join(data_dir, str(IMG), "acl.json")
        with open(acl_path, "w") as f:
            json.dump({"public": False, "sessions": ["alice"]}, f)

        async def scenario():
            task = asyncio.create_task(
                run_sidecar(self._member_cfg(data_dir), sock))
            await _wait_socket(sock, task)
            client = SidecarClient(sock)
            try:
                value = b"rendered-bytes"
                digest = hashlib.blake2b(
                    value, digest_size=16).hexdigest()
                status, body = await client.call(
                    "byte_probe", {}, extra={"keys": ["k1", "k2"]})
                assert status == 200
                doc = json.loads(bytes(body).decode())
                assert doc == {"enabled": True,
                               "present": [False, False]}
                # put with a WRONG digest is refused (400), never
                # stored.
                status, err = await client.call(
                    "byte_put", {}, body=value,
                    extra={"key": "k1", "digest": "0" * 32})
                assert status == 400 and "digest" in str(err)
                # honest put stores; probe flips.
                status, body = await client.call(
                    "byte_put", {}, body=value,
                    extra={"key": "k1", "digest": digest})
                assert status == 200
                status, body = await client.call(
                    "byte_probe", {}, extra={"keys": ["k1", "k2"]})
                assert json.loads(bytes(body).decode())["present"] \
                    == [True, False]
                # fetch without ACL context returns the bytes.
                status, body = await client.call(
                    "byte_fetch", {}, extra={"key": "k1"})
                assert status == 200 and bytes(body) == value
                # ACL-gated fetch: the serving sidecar runs ITS gate
                # for the caller's session — alice reads, bob 404s.
                status, body = await client.call(
                    "byte_fetch", {},
                    extra={"key": "k1", "image_id": IMG,
                           "session": "alice"})
                assert status == 200 and bytes(body) == value
                status, _ = await client.call(
                    "byte_fetch", {},
                    extra={"key": "k1", "image_id": IMG,
                           "session": "bob"})
                assert status == 404
                # miss is 404, not an error.
                status, _ = await client.call(
                    "byte_fetch", {}, extra={"key": "nope"})
                assert status == 404
            finally:
                await client.close()
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)

        try:
            asyncio.run(scenario())
        finally:
            if os.path.exists(acl_path):
                os.unlink(acl_path)

    def test_fleet_drill_peer_serves_drained_owners_bytes(
            self, data_dir, tmp_path):
        """THE fleet acceptance drill: render on the ring owner, drain
        it, request again — the surviving member serves bytes
        BYTE-IDENTICAL to the origin render with zero device work
        (peer fetch, not re-render), and the owner's tier answers the
        probes."""
        from omero_ms_image_region_tpu.server.app import \
            FLEET_ROUTER_KEY
        from omero_ms_image_region_tpu.server.sidecar import \
            run_sidecar

        socks = [str(tmp_path / f"m{i}.sock") for i in range(2)]
        frontend_cfg = AppConfig(
            data_dir=data_dir,
            sidecar=SidecarConfig(role="frontend"),
            fleet=FleetConfig(enabled=True, sockets=tuple(socks)))

        params = [{
            "imageId": str(IMG), "theZ": "0", "theT": "0",
            "tile": f"0,{x},{y},32,32", "format": "png", "m": "g",
            "c": "1|0:60000$FF0000"} for x in range(2)
            for y in range(2)]

        def url_of(p):
            return (f"/webgateway/render_image_region/{IMG}/0/0"
                    f"?tile={p['tile']}&format=png&m=g"
                    f"&c=1|0:60000$FF0000")

        async def scenario():
            tasks = [asyncio.create_task(
                run_sidecar(self._member_cfg(data_dir), sock))
                for sock in socks]
            for sock, task in zip(socks, tasks):
                await _wait_socket(sock, task)
            app = create_app(frontend_cfg)
            client = TestClient(TestServer(app))
            await client.start_server()
            router = app[FLEET_ROUTER_KEY]
            try:
                ctxs = [ImageRegionCtx.from_params(dict(p), None)
                        for p in params]
                bodies = {}
                for p in params:
                    r = await client.get(url_of(p))
                    body = await r.read()
                    assert r.status == 200
                    bodies[p["tile"]] = body
                owners = {p["tile"]: router.owner_of(c)
                          for p, c in zip(params, ctxs)}
                victim = next(iter(set(owners.values())))
                owned = [p for p in params
                         if owners[p["tile"]] == victim]
                assert owned, "victim owns nothing at this grid size"
                await router.drain_member(victim, prestage=False,
                                          settle_timeout_s=5.0)
                renders = _renders()
                hits0 = telemetry.HTTPCACHE.peer_hits
                for p in owned:
                    r = await client.get(url_of(p))
                    body = await r.read()
                    assert r.status == 200
                    # Byte-identical to the origin render.
                    assert body == bodies[p["tile"]]
                # Zero device work anywhere: every re-routed request
                # was a peer byte fetch, not a re-render.
                assert _renders() == renders
                assert telemetry.HTTPCACHE.peer_hits - hits0 \
                    == len(owned)
                assert telemetry.HTTPCACHE.peer_fetches >= len(owned)
                router.undrain_member(victim)
            finally:
                await client.close()
                for task in tasks:
                    task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)

        asyncio.run(scenario())

    def test_mask_tier_is_namespaced_and_acl_gated(self, data_dir,
                                                   tmp_path):
        """The federated MASK byte tier rides the same wire ops with
        ``tier: "mask"``: keys are ``ShapeMaskCtx.cache_key()`` (the
        PR 11 ETag's storage identity), the shape-mask stack is
        namespaced from the render tier (same key, two stacks, no
        crosstalk), fetch gates on the Mask's OWN ACL (``obj:
        "Mask"``) and an unknown ACL object type is refused."""
        import hashlib

        from omero_ms_image_region_tpu.models.mask import Mask
        from omero_ms_image_region_tpu.server.ctx import ShapeMaskCtx
        from omero_ms_image_region_tpu.server.sidecar import (
            SidecarClient, run_sidecar)
        from omero_ms_image_region_tpu.services.metadata import \
            write_mask

        mask_id = 5
        bits = np.zeros(H * W, np.uint8)
        bits[: H * W // 2] = 1
        write_mask(data_dir, Mask(
            shape_id=mask_id, width=W, height=H,
            bytes_=np.packbits(bits).tobytes(), fill_color=None))
        with open(os.path.join(data_dir, "masks",
                               f"{mask_id}.acl.json"), "w") as f:
            json.dump({"public": False, "sessions": ["alice"]}, f)
        sock = str(tmp_path / "peer.sock")

        async def scenario():
            task = asyncio.create_task(
                run_sidecar(self._member_cfg(data_dir), sock))
            await _wait_socket(sock, task)
            client = SidecarClient(sock)
            try:
                ctx = ShapeMaskCtx.from_params(
                    {"shapeId": str(mask_id), "color": "FF0000"})
                key = ctx.cache_key()
                assert key == f"ome.model.roi.Mask:{mask_id}:FF0000"
                png = b"\x89PNG-mask-bytes"
                digest = hashlib.blake2b(
                    png, digest_size=16).hexdigest()
                status, body = await client.call(
                    "byte_probe", {},
                    extra={"keys": [key], "tier": "mask"})
                assert status == 200
                assert json.loads(bytes(body).decode()) == {
                    "enabled": True, "present": [False]}
                status, _ = await client.call(
                    "byte_put", {}, body=png,
                    extra={"key": key, "digest": digest,
                           "tier": "mask"})
                assert status == 200
                # The put flips the MASK probe, never the render
                # tier's view of the same key.
                status, body = await client.call(
                    "byte_probe", {},
                    extra={"keys": [key], "tier": "mask"})
                assert json.loads(
                    bytes(body).decode())["present"] == [True]
                status, body = await client.call(
                    "byte_probe", {}, extra={"keys": [key]})
                assert json.loads(
                    bytes(body).decode())["present"] == [False]
                # Fetch runs the MASK's own ACL for the caller.
                status, body = await client.call(
                    "byte_fetch", {},
                    extra={"key": key, "tier": "mask",
                           "image_id": mask_id, "obj": "Mask",
                           "session": "alice"})
                assert status == 200 and bytes(body) == png
                status, body = await client.call(
                    "byte_fetch", {},
                    extra={"key": key, "tier": "mask",
                           "image_id": mask_id, "obj": "Mask",
                           "session": "bob"})
                assert status == 404
                status, body = await client.call(
                    "byte_fetch", {},
                    extra={"key": key, "tier": "mask",
                           "image_id": mask_id, "obj": "Roi"})
                assert status == 400
            finally:
                await client.close()
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)

        asyncio.run(scenario())

    def test_fleet_drill_mask_rasterizes_once_fleet_wide(
            self, data_dir, tmp_path):
        """The mask drill: host A rasterizes an explicit-color mask
        and ships the PNG to its ring authority (fire-and-forget
        write-back); host B's local miss is then served the SAME
        bytes from the authority's mask tier — no second
        rasterize."""
        from omero_ms_image_region_tpu.models.mask import Mask
        from omero_ms_image_region_tpu.server.app import \
            FLEET_ROUTER_KEY
        from omero_ms_image_region_tpu.server.sidecar import \
            run_sidecar
        from omero_ms_image_region_tpu.services.metadata import \
            write_mask

        mask_id = 6
        bits = np.zeros(H * W, np.uint8)
        bits[: H * W // 3] = 1
        write_mask(data_dir, Mask(
            shape_id=mask_id, width=W, height=H,
            bytes_=np.packbits(bits).tobytes(), fill_color=None))
        socks = [str(tmp_path / f"m{i}.sock") for i in range(2)]
        url = (f"/webgateway/render_shape_mask/{mask_id}"
               f"?color=FF0000")

        def frontend_cfg():
            return AppConfig(
                data_dir=data_dir,
                sidecar=SidecarConfig(role="frontend"),
                fleet=FleetConfig(enabled=True,
                                  sockets=tuple(socks)))

        async def scenario():
            tasks = [asyncio.create_task(
                run_sidecar(self._member_cfg(data_dir), sock))
                for sock in socks]
            for sock, task in zip(socks, tasks):
                await _wait_socket(sock, task)
            host_a = TestClient(TestServer(create_app(frontend_cfg())))
            host_b = TestClient(TestServer(create_app(frontend_cfg())))
            await host_a.start_server()
            await host_b.start_server()
            try:
                r = await host_a.get(url)
                assert r.status == 200
                origin = await r.read()
                # Let the fire-and-forget write-back land on the
                # authority before the second host asks.
                router_a = host_a.app[FLEET_ROUTER_KEY]
                await asyncio.gather(*list(router_a._putback_tasks),
                                     return_exceptions=True)
                hits0 = telemetry.HTTPCACHE.peer_hits
                r = await host_b.get(url)
                assert r.status == 200
                assert await r.read() == origin
                assert telemetry.HTTPCACHE.peer_hits == hits0 + 1
            finally:
                await host_a.close()
                await host_b.close()
                for task in tasks:
                    task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)

        asyncio.run(scenario())


# ----------------------------------- Last-Modified / If-Modified-Since

class TestLastModified:
    """PR 11 follow-on: 200s carry Last-Modified (ingest/source mtime
    via the metadata path) and If-Modified-Since-only clients get the
    same zero-work 304 contract as If-None-Match — with the ETag
    winning whenever both are present (RFC 9110)."""

    def test_200_carries_last_modified_and_ims_304_is_renderless(
            self, data_dir):
        async def scenario():
            app = create_app(_config(data_dir))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                lm = r.headers.get("Last-Modified")
                assert lm, "200 must carry Last-Modified"
                # And it parses back to the source mtime class.
                assert httpcache.parse_http_date(lm) is not None

                renders = _renders()
                ims0 = telemetry.HTTPCACHE.ims_requests
                nm0 = telemetry.HTTPCACHE.not_modified
                r = await client.get(
                    URL, headers={"If-Modified-Since": lm})
                assert r.status == 304
                assert r.headers.get("Last-Modified") == lm
                assert r.headers.get("ETag")
                assert _renders() == renders, \
                    "IMS revalidation must be render-free"
                assert telemetry.HTTPCACHE.ims_requests == ims0 + 1
                assert telemetry.HTTPCACHE.not_modified == nm0 + 1

                # A stale IMS (source newer) renders the full 200.
                r = await client.get(URL, headers={
                    "If-Modified-Since":
                        "Thu, 01 Jan 1970 00:00:00 GMT"})
                assert r.status == 200
                await r.read()

                # Garbage IMS degrades to the full 200, never a 500.
                r = await client.get(
                    URL, headers={"If-Modified-Since": "not-a-date"})
                assert r.status == 200
                await r.read()
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_etag_wins_when_both_present(self, data_dir):
        async def scenario():
            app = create_app(_config(data_dir))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                etag = r.headers["ETag"]
                lm = r.headers["Last-Modified"]
                # Non-matching ETag + fresh IMS: the ETag verdict
                # (modified) WINS — full 200, the IMS freshness is
                # ignored per RFC 9110.
                r = await client.get(URL, headers={
                    "If-None-Match": '"ir1-0-000000000000000000000000"',
                    "If-Modified-Since": lm})
                assert r.status == 200
                await r.read()
                # Matching ETag + stale IMS: the ETag verdict
                # (unchanged) WINS — 304.
                r = await client.get(URL, headers={
                    "If-None-Match": etag,
                    "If-Modified-Since":
                        "Thu, 01 Jan 1970 00:00:00 GMT"})
                assert r.status == 304
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_proxy_frontends_skip_last_modified(self):
        # Device-free check of the helper contract: no services =>
        # no local source tree => no Last-Modified (the ETag still
        # gives those deployments free revalidation).
        from omero_ms_image_region_tpu.services.metadata import \
            LocalMetadataService
        svc = LocalMetadataService("/nonexistent-data-dir")
        assert svc.source_mtime(12345) is None


# ---------------------------------------------- http-cache.epoch: auto

class TestEpochAuto:
    GOLDEN_EPOCH = "m1700000000"
    GOLDEN_ETAG = '"ir1-m1700000000-9a40de0244ee35d685234ef0"'

    def _pin_tree(self, data_dir):
        for root, dirs, files in os.walk(data_dir, topdown=False):
            for name in files + dirs:
                os.utime(os.path.join(root, name),
                         (1700000000, 1700000000))
        os.utime(data_dir, (1700000000, 1700000000))

    def test_derivation_pinned(self, data_dir):
        """The golden derivation: a tree whose stamps all read
        1700000000 derives exactly this epoch — and the resulting
        ETag joins the golden corpus (drift fails loudly)."""
        self._pin_tree(data_dir)
        assert httpcache.derive_epoch(data_dir) == self.GOLDEN_EPOCH
        key = ImageRegionCtx.create_cache_key(
            {"imageId": "1", "theZ": "0", "theT": "0",
             "tile": "0,0,0,256,256", "format": "png", "m": "c",
             "c": "1|0:60000$FF0000"})
        assert httpcache.etag_for(key, self.GOLDEN_EPOCH) \
            == self.GOLDEN_ETAG

    def test_reingest_bumps_the_epoch(self, data_dir):
        self._pin_tree(data_dir)
        before = httpcache.derive_epoch(data_dir)
        os.utime(os.path.join(data_dir, str(IMG)),
                 (1800000000, 1800000000))
        after = httpcache.derive_epoch(data_dir)
        assert after != before
        assert after == "m1800000000"

    def test_missing_tree_derives_default(self, tmp_path):
        assert httpcache.derive_epoch(
            str(tmp_path / "nope")) == "0"

    def test_app_resolves_auto_and_serves_it(self, data_dir):
        from omero_ms_image_region_tpu.server.config import \
            HttpCacheConfig
        self._pin_tree(data_dir)
        cfg = _config(data_dir,
                      http_cache=HttpCacheConfig(epoch="auto"))

        async def scenario():
            app = create_app(cfg)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                assert f"-{self.GOLDEN_EPOCH}-" in r.headers["ETag"]
            finally:
                await client.close()

        asyncio.run(scenario())
        assert cfg.http_cache.epoch == self.GOLDEN_EPOCH

    def test_yaml_accepts_auto_and_explicit_override_wins(self):
        from omero_ms_image_region_tpu.server.config import AppConfig
        cfg = AppConfig.from_dict({"http-cache": {"epoch": "auto"}})
        assert cfg.http_cache.epoch == "auto"
        cfg = AppConfig.from_dict({"http-cache": {"epoch": "v7"}})
        assert cfg.http_cache.epoch == "v7"

    def test_auto_refused_on_deviceless_frontends(self, tmp_path):
        """A proxy/fleet frontend has no local source tree: epoch
        'auto' deriving '0' there would mean edge caches NEVER see an
        epoch bump — refused loudly at create_app."""
        from omero_ms_image_region_tpu.server.config import \
            HttpCacheConfig
        cfg = AppConfig(
            data_dir=str(tmp_path / "nothing-here"),
            sidecar=SidecarConfig(role="frontend",
                                  socket=str(tmp_path / "x.sock")),
            http_cache=HttpCacheConfig(epoch="auto"))
        with pytest.raises(ValueError, match="auto"):
            create_app(cfg)


class TestEpochFoldsIntoLastModified:
    """Bumping the epoch must stale If-Modified-Since-only clients
    exactly like it stales ETags — otherwise an IMS 304 against a
    pre-bump Last-Modified revives the very entries the bump killed."""

    def test_basis_vocabulary(self):
        basis = httpcache.last_modified_basis
        assert basis(100.0, "0") == 100.0
        assert basis(100.0, "m500") == 500.0     # bump moves LM fwd
        assert basis(900.0, "m500") == 900.0
        assert basis(100.0, "2026-08.r2") is None  # un-ordered epoch
        assert basis(None, "0") is None

    def test_operator_epoch_disarms_ims_leg(self, data_dir):
        from omero_ms_image_region_tpu.server.config import \
            HttpCacheConfig
        cfg = _config(data_dir,
                      http_cache=HttpCacheConfig(epoch="v2"))

        async def scenario():
            app = create_app(cfg)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                # No Last-Modified: an operator epoch cannot be
                # ordered against mtimes, so the IMS channel closes.
                assert "Last-Modified" not in r.headers
                r = await client.get(URL, headers={
                    "If-Modified-Since":
                        "Fri, 01 Jan 2100 00:00:00 GMT"})
                assert r.status == 200   # never a 304 on IMS alone
                await r.read()
            finally:
                await client.close()

        asyncio.run(scenario())

    def test_derived_epoch_bump_stales_stored_ims_dates(
            self, data_dir):
        from omero_ms_image_region_tpu.server.config import \
            HttpCacheConfig

        async def last_modified(epoch):
            cfg = _config(data_dir,
                          http_cache=HttpCacheConfig(epoch=epoch))
            app = create_app(cfg)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(URL)
                assert r.status == 200
                await r.read()
                return r.headers["Last-Modified"]
            finally:
                await client.close()

        async def scenario():
            lm_old = await last_modified("m1")
            # A derived-epoch bump FAR past the source mtime moves
            # Last-Modified forward, so a client that stored lm_old
            # revalidates to a fresh 200, not a stale 304.
            lm_new = await last_modified("m4000000000")
            assert httpcache.parse_http_date(lm_new) \
                > httpcache.parse_http_date(lm_old)
            cfg = _config(data_dir, http_cache=HttpCacheConfig(
                epoch="m4000000000"))
            app = create_app(cfg)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get(
                    URL, headers={"If-Modified-Since": lm_old})
                assert r.status == 200   # pre-bump date is stale
                await r.read()
                r = await client.get(
                    URL, headers={"If-Modified-Since": lm_new})
                assert r.status == 304   # post-bump date is fresh
            finally:
                await client.close()

        asyncio.run(scenario())
