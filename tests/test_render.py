"""Fused render kernel vs CPU reference: models, LUTs, reverse intensity,
composition, batching."""

import numpy as np

from omero_ms_image_region_tpu.models.pixels import Pixels
from omero_ms_image_region_tpu.models.rendering import (
    ChannelBinding,
    Family,
    QuantumDef,
    RenderingDef,
    RenderingModel,
    default_rendering_def,
)
from omero_ms_image_region_tpu.ops.lut import LutProvider
from omero_ms_image_region_tpu.ops.render import (
    pack_settings,
    render_tile,
    render_tile_batch,
)
from omero_ms_image_region_tpu.refimpl import render_ref


def _pixels(C=3, H=8, W=8, ptype="uint16"):
    return Pixels(image_id=1, pixels_type=ptype, size_x=W, size_y=H,
                  size_c=C)


def _rdef(C=3, model=RenderingModel.RGB, ptype="uint16"):
    rdef = default_rendering_def(_pixels(C=C, ptype=ptype))
    rdef.model = model
    colors = [(255, 0, 0, 255), (0, 255, 0, 255), (0, 0, 255, 255),
              (255, 255, 0, 255)]
    for c, cb in enumerate(rdef.channel_bindings):
        cb.red, cb.green, cb.blue, cb.alpha = colors[c % 4]
    return rdef


def _render_jax(raw, rdef, lut_provider=None):
    s = pack_settings(rdef, lut_provider)
    return np.asarray(render_tile(raw.astype(np.float32), **s))


def test_rgb_composite_matches_reference():
    rng = np.random.default_rng(0)
    raw = rng.uniform(0, 65535, size=(3, 8, 8)).astype(np.float32)
    rdef = _rdef()
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_greyscale_first_active_channel_only():
    raw = np.stack(
        [
            np.full((4, 4), 0, np.float32),
            np.full((4, 4), 65535, np.float32),
            np.full((4, 4), 30000, np.float32),
        ]
    )
    rdef = _rdef(model=RenderingModel.GREYSCALE)
    rdef.channel_bindings[0].active = False  # first ACTIVE is channel 1
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    np.testing.assert_array_equal(got, want)
    # channel 1 is saturated -> grey 255
    assert got[0, 0].tolist() == [255, 255, 255, 255]


def test_inactive_channels_do_not_contribute():
    raw = np.stack(
        [np.zeros((4, 4), np.float32), np.full((4, 4), 65535, np.float32)]
    )
    rdef = _rdef(C=2)
    rdef.channel_bindings[1].active = False
    got = _render_jax(raw, rdef)
    assert got[..., :3].max() == 0


def test_lut_channel():
    lp = LutProvider()
    table = np.zeros((256, 3), np.uint8)
    table[:, 1] = np.arange(256)  # green ramp
    lp.add("green_ramp.lut", table)

    rdef = _rdef(C=1)
    rdef.channel_bindings[0].lut = "green_ramp.lut"
    raw = np.full((1, 4, 4), 65535, np.float32)
    got = _render_jax(raw, rdef, lp)
    want = render_ref(raw, rdef, lp)
    np.testing.assert_array_equal(got, want)
    assert got[0, 0].tolist() == [0, 255, 0, 255]


def test_reverse_intensity():
    rdef = _rdef(C=1)
    rdef.channel_bindings[0].reverse_intensity = True
    raw = np.zeros((1, 4, 4), np.float32)  # min value -> reversed = max
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    np.testing.assert_array_equal(got, want)
    assert got[0, 0, 0] == 255  # red channel at full after reversal


def test_alpha_scales_contribution():
    rdef = _rdef(C=1)
    rdef.channel_bindings[0].alpha = 128
    raw = np.full((1, 4, 4), 65535, np.float32)
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1
    assert abs(int(got[0, 0, 0]) - 128) <= 1


def test_additive_composite_clamps():
    rdef = _rdef(C=2)
    for cb in rdef.channel_bindings:
        cb.red, cb.green, cb.blue = 255, 255, 255
    raw = np.full((2, 4, 4), 65535, np.float32)
    got = _render_jax(raw, rdef)
    assert got[..., :3].max() == 255


def test_families_per_channel_against_reference():
    rng = np.random.default_rng(7)
    raw = rng.uniform(0, 65535, size=(4, 6, 6)).astype(np.float32)
    rdef = _rdef(C=4)
    fams = [Family.LINEAR, Family.POLYNOMIAL, Family.LOGARITHMIC,
            Family.EXPONENTIAL]
    for cb, fam in zip(rdef.channel_bindings, fams):
        cb.family = fam
        cb.coefficient = 1.5 if fam == Family.POLYNOMIAL else 1.0
        cb.active = True
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 2


def test_batch_render_matches_single():
    rng = np.random.default_rng(3)
    B, C, H, W = 4, 3, 8, 8
    raw = rng.uniform(0, 65535, size=(B, C, H, W)).astype(np.float32)
    rdef = _rdef()
    s = pack_settings(rdef)
    batched = np.asarray(
        render_tile_batch(
            raw,
            np.tile(s["window_start"], (B, 1)),
            np.tile(s["window_end"], (B, 1)),
            np.tile(s["family"], (B, 1)),
            np.tile(s["coefficient"], (B, 1)),
            np.tile(s["reverse"], (B, 1)),
            s["cd_start"],
            s["cd_end"],
            np.tile(s["tables"], (B,) + (1,) * s["tables"].ndim),
        )
    )
    for b in range(B):
        single = np.asarray(render_tile(raw[b], **s))
        np.testing.assert_array_equal(batched[b], single)


def test_custom_codomain_interval():
    # QuantumDef with a narrowed codomain must cap quantized output —
    # and the reverse-intensity mirror must respect it too.
    rdef = _rdef(C=1)
    rdef.quantum = QuantumDef(cd_start=0, cd_end=127)
    raw = np.full((1, 4, 4), 65535, np.float32)
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1
    assert abs(int(got[0, 0, 0]) - 127) <= 1  # red capped at cd_end

    rdef.channel_bindings[0].reverse_intensity = True
    zero = np.zeros((1, 4, 4), np.float32)
    got_rev = _render_jax(zero, rdef)
    want_rev = render_ref(zero, rdef)
    assert np.abs(got_rev.astype(int) - want_rev.astype(int)).max() <= 1
    assert abs(int(got_rev[0, 0, 0]) - 127) <= 1  # mirrored within [0,127]


def test_log_family_degenerate_unit_window():
    # log over [0, 1] collapses both endpoints to 0: step function, not NaN.
    rdef = _rdef(C=1, ptype="float")
    cb = rdef.channel_bindings[0]
    cb.family = Family.LOGARITHMIC
    cb.input_start, cb.input_end = 0.0, 1.0
    raw = np.array([[[0.0, 0.5, 1.0, 2.0]]], np.float32)
    got = _render_jax(raw, rdef)
    want = render_ref(raw, rdef)
    np.testing.assert_array_equal(got, want)
    assert got[0, 0, 0] == 0 and got[0, 2, 0] == 255


def test_default_rendering_def_matches_reference_defaults():
    rdef = default_rendering_def(_pixels(C=5))
    # First three channels active, linear family, type-range window, red.
    assert [cb.active for cb in rdef.channel_bindings] == [
        True, True, True, False, False,
    ]
    cb = rdef.channel_bindings[0]
    assert cb.family == Family.LINEAR
    assert (cb.input_start, cb.input_end) == (0.0, 65535.0)
    assert (cb.red, cb.green, cb.blue, cb.alpha) == (255, 0, 0, 255)
    assert rdef.model == RenderingModel.GREYSCALE
    assert rdef.quantum.cd_start == 0 and rdef.quantum.cd_end == 255
