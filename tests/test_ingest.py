"""Ingest CLI: backend conversions round-trip losslessly."""

import numpy as np

from omero_ms_image_region_tpu.ingest import main
from omero_ms_image_region_tpu.io.ometiff import OmeTiffSource
from omero_ms_image_region_tpu.io.store import (ChunkedPyramidStore,
                                                build_pyramid)
from omero_ms_image_region_tpu.io.tiffwrite import write_ome_tiff
from omero_ms_image_region_tpu.server.region import RegionDef


def test_roundtrip_both_directions(tmp_path, capsys):
    rng = np.random.default_rng(30)
    planes = rng.integers(0, 60000, size=(2, 3, 150, 200)).astype(
        np.uint16)
    tiff1 = str(tmp_path / "in.ome.tiff")
    write_ome_tiff(planes, tiff1, tile=(64, 64), n_levels=1)

    store_dir = str(tmp_path / "5")
    assert main(["tiff-to-store", tiff1, store_dir, "--tile", "64"]) == 0
    store = ChunkedPyramidStore(store_dir)
    full = RegionDef(0, 0, 200, 150)
    for c in range(2):
        for z in range(3):
            assert np.array_equal(store.get_region(z, c, 0, full, 0),
                                  planes[c, z])
    store.close()

    tiff2 = str(tmp_path / "out.ome.tiff")
    assert main(["store-to-tiff", store_dir, tiff2, "--tile", "64"]) == 0
    back = OmeTiffSource(tiff2)
    for c in range(2):
        for z in range(3):
            assert np.array_equal(back.get_region(z, c, 0, full, 0),
                                  planes[c, z])
    back.close()

    assert main(["info", store_dir]) == 0
    out = capsys.readouterr().out
    assert "chunked" in out and "200 x 150" in out and "uint16" in out
    assert main(["info", tiff2]) == 0
    assert "ome-tiff" in capsys.readouterr().out
