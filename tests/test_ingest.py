"""Ingest CLI: backend conversions round-trip losslessly."""

import numpy as np

from omero_ms_image_region_tpu.ingest import main
from omero_ms_image_region_tpu.io.ometiff import OmeTiffSource
from omero_ms_image_region_tpu.io.store import (ChunkedPyramidStore,
                                                build_pyramid)
from omero_ms_image_region_tpu.io.tiffwrite import write_ome_tiff
from omero_ms_image_region_tpu.server.region import RegionDef


def test_roundtrip_both_directions(tmp_path, capsys):
    rng = np.random.default_rng(30)
    planes = rng.integers(0, 60000, size=(2, 3, 150, 200)).astype(
        np.uint16)
    tiff1 = str(tmp_path / "in.ome.tiff")
    write_ome_tiff(planes, tiff1, tile=(64, 64), n_levels=1)

    store_dir = str(tmp_path / "5")
    assert main(["tiff-to-store", tiff1, store_dir, "--tile", "64"]) == 0
    store = ChunkedPyramidStore(store_dir)
    full = RegionDef(0, 0, 200, 150)
    for c in range(2):
        for z in range(3):
            assert np.array_equal(store.get_region(z, c, 0, full, 0),
                                  planes[c, z])
    store.close()

    tiff2 = str(tmp_path / "out.ome.tiff")
    assert main(["store-to-tiff", store_dir, tiff2, "--tile", "64"]) == 0
    back = OmeTiffSource(tiff2)
    for c in range(2):
        for z in range(3):
            assert np.array_equal(back.get_region(z, c, 0, full, 0),
                                  planes[c, z])
    back.close()

    assert main(["info", store_dir]) == 0
    out = capsys.readouterr().out
    assert "chunked" in out and "200 x 150" in out and "uint16" in out
    assert main(["info", tiff2]) == 0
    assert "ome-tiff" in capsys.readouterr().out


def test_tiff_to_store_from_multi_file_set(tmp_path, capsys):
    """Ingest resolves multi-file OME-TIFF sets (TiffData FileName)."""
    rng = np.random.default_rng(31)
    W, H, Z, C = 64, 48, 2, 2
    planes = rng.integers(0, 60000, size=(C, Z, H, W)).astype(np.uint16)
    names = ["i0.ome.tiff", "i1.ome.tiff"]
    NS = 'xmlns="http://www.openmicroscopy.org/Schemas/OME/2016-06"'
    tds = "".join(
        f'<TiffData FirstZ="0" FirstC="{c}" FirstT="0" IFD="0" '
        f'PlaneCount="{Z}"><UUID FileName="{names[c]}">u{c}</UUID>'
        f'</TiffData>' for c in range(C))
    xml = (f'<?xml version="1.0"?><OME {NS}><Image ID="Image:0">'
           f'<Pixels ID="Pixels:0" DimensionOrder="XYZCT" Type="uint16" '
           f'SizeX="{W}" SizeY="{H}" SizeZ="{Z}" SizeC="{C}" SizeT="1" '
           f'BigEndian="false">{tds}</Pixels></Image></OME>')
    for c in range(C):
        write_ome_tiff(planes[c][None], str(tmp_path / names[c]),
                       tile=(32, 32), n_levels=1, description=xml)
    store_dir = str(tmp_path / "8")
    assert main(["tiff-to-store", str(tmp_path / names[0]), store_dir,
                 "--tile", "32"]) == 0
    store = ChunkedPyramidStore(store_dir)
    full = RegionDef(0, 0, W, H)
    for c in range(C):
        for z in range(Z):
            assert np.array_equal(store.get_region(z, c, 0, full, 0),
                                  planes[c, z])
    store.close()


def test_vendor_jp2k_tiff_converts_to_store(tmp_path, capsys):
    """The documented hot-WSI workflow: an Aperio-style JPEG 2000 TIFF
    converts to the chunked store via the ingest CLI and serves
    pixel-identically from it (lossless tiles -> exact)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from vendor_tiff import smooth_rgb as _smooth_rgb
    from vendor_tiff import write_jp2k_tiff as _write_jp2k_tiff

    arr = _smooth_rgb(150, 200)
    src_tiff = str(tmp_path / "wsi.tif")
    _write_jp2k_tiff(src_tiff, arr, 33005, tile=64)

    store_dir = str(tmp_path / "9")
    assert main(["tiff-to-store", src_tiff, store_dir,
                 "--tile", "64"]) == 0
    store = ChunkedPyramidStore(store_dir)
    for c in range(3):
        got = store.get_region(0, c, 0, RegionDef(0, 0, 200, 150), 0)
        np.testing.assert_array_equal(got, arr[:, :, c])
    store.close()
