"""Request-context parsing vs ImageRegionCtxTest.java:121-394, plus the
JSON wire round-trip the reference locks via Jackson."""

import pytest

from omero_ms_image_region_tpu.models.rendering import Projection
from omero_ms_image_region_tpu.server.ctx import (
    BadRequestError,
    ImageRegionCtx,
    ShapeMaskCtx,
)

BASE = {"imageId": "123", "theZ": "0", "theT": "1"}


def _params(**extra):
    p = dict(BASE)
    p.update(extra)
    return p


def _roundtrip(ctx: ImageRegionCtx) -> ImageRegionCtx:
    return ImageRegionCtx.from_json(ctx.to_json())


# ------------------------------------------------------- required params

@pytest.mark.parametrize("missing", ["imageId", "theZ", "theT"])
def test_missing_required_param(missing):
    p = dict(BASE)
    del p[missing]
    with pytest.raises(BadRequestError, match=f"Missing parameter '{missing}'"):
        ImageRegionCtx.from_params(p)


@pytest.mark.parametrize("key", ["imageId", "theZ", "theT"])
def test_bad_number_format(key):
    with pytest.raises(BadRequestError, match="Incorrect format"):
        ImageRegionCtx.from_params(_params(**{key: "abc"}))


def test_region_format_error():
    with pytest.raises(BadRequestError):
        ImageRegionCtx.from_params(_params(region="1,2,3"))


def test_channel_format_error():
    with pytest.raises(BadRequestError, match="Failed to parse channel"):
        ImageRegionCtx.from_params(_params(c="a|0:100$FF0000"))


def test_channel_range_format_error():
    with pytest.raises(BadRequestError, match="Failed to parse channel"):
        ImageRegionCtx.from_params(_params(c="1|a:100$FF0000"))


def test_quality_format_error():
    with pytest.raises(BadRequestError, match="Incorrect format"):
        ImageRegionCtx.from_params(_params(q="a"))


# --------------------------------------------------------------- tile

def test_tile_short_form():
    ctx = _roundtrip(ImageRegionCtx.from_params(_params(tile="1,2,3")))
    assert ctx.resolution == 1
    assert ctx.tile.x == 2 and ctx.tile.y == 3
    assert ctx.tile.width == 0 and ctx.tile.height == 0


def test_tile_long_form():
    ctx = _roundtrip(
        ImageRegionCtx.from_params(_params(tile="0,1,2,1024,2048")))
    assert ctx.resolution == 0
    assert ctx.tile.as_tuple() == (1, 2, 1024, 2048)


def test_region_parse():
    ctx = _roundtrip(ImageRegionCtx.from_params(_params(region="1,2,3,4")))
    assert ctx.region.as_tuple() == (1, 2, 3, 4)


# ------------------------------------------------------------- channels

def test_channel_parse_full():
    ctx = _roundtrip(ImageRegionCtx.from_params(
        _params(c="-1|0:65535$0000FF,2|1755:51199$00FF00,3|3218:26623$FF0000")
    ))
    assert ctx.channels == [-1, 2, 3]
    assert ctx.windows == [(0.0, 65535.0), (1755.0, 51199.0),
                           (3218.0, 26623.0)]
    assert ctx.colors == ["0000FF", "00FF00", "FF0000"]


def test_channel_active_only():
    ctx = ImageRegionCtx.from_params(_params(c="1,2,-3"))
    assert ctx.channels == [1, 2, -3]
    assert ctx.windows == [(None, None)] * 3
    assert ctx.colors == [None] * 3


def test_channel_window_without_color_rejected():
    # Reference quirk: a "|" clause without "$color" NPEs into a 400.
    with pytest.raises(BadRequestError):
        ImageRegionCtx.from_params(_params(c="1|0:65535"))


# ------------------------------------------------------------ projection

def test_projection_intmax():
    ctx = _roundtrip(ImageRegionCtx.from_params(_params(p="intmax")))
    assert ctx.projection == int(Projection.MAXIMUM_INTENSITY)
    assert ctx.projection_start is None and ctx.projection_end is None


def test_projection_intmean():
    ctx = ImageRegionCtx.from_params(_params(p="intmean"))
    assert ctx.projection == int(Projection.MEAN_INTENSITY)


def test_projection_intsum():
    ctx = ImageRegionCtx.from_params(_params(p="intsum"))
    assert ctx.projection == int(Projection.SUM_INTENSITY)


def test_projection_normal_ignored():
    ctx = ImageRegionCtx.from_params(_params(p="normal"))
    assert ctx.projection is None


def test_projection_with_range():
    ctx = _roundtrip(ImageRegionCtx.from_params(_params(p="intmean|0:31")))
    assert ctx.projection == int(Projection.MEAN_INTENSITY)
    assert ctx.projection_start == 0 and ctx.projection_end == 31


def test_projection_malformed_range_tolerated():
    ctx = ImageRegionCtx.from_params(_params(p="intmean|a:31"))
    assert ctx.projection == int(Projection.MEAN_INTENSITY)
    assert ctx.projection_start is None and ctx.projection_end is None


# --------------------------------------------------------------- misc

def test_codomain_maps():
    ctx = _roundtrip(ImageRegionCtx.from_params(
        _params(maps='[{"reverse": {"enabled": true}}, null]')))
    assert ctx.maps[0]["reverse"]["enabled"] is True
    assert ctx.maps[1] is None


def test_malformed_maps_rejected():
    with pytest.raises(BadRequestError):
        ImageRegionCtx.from_params(_params(maps="{not json"))


def test_color_model():
    assert ImageRegionCtx.from_params(_params(m="g")).m == "greyscale"
    assert ImageRegionCtx.from_params(_params(m="c")).m == "rgb"
    assert ImageRegionCtx.from_params(_params(m="x")).m is None
    assert ImageRegionCtx.from_params(_params()).m is None


def test_flip_flags():
    ctx = ImageRegionCtx.from_params(_params(flip="HV"))
    assert ctx.flip_horizontal and ctx.flip_vertical
    ctx = ImageRegionCtx.from_params(_params())
    assert not ctx.flip_horizontal and not ctx.flip_vertical


def test_format_defaults_to_jpeg():
    assert ImageRegionCtx.from_params(_params()).format == "jpeg"
    assert ImageRegionCtx.from_params(_params(format="png")).format == "png"


# ------------------------------------------------------------ cache key

def test_cache_key_order_insensitivity():
    a = ImageRegionCtx.from_params(
        {"imageId": "1", "theZ": "0", "theT": "0", "c": "1|0:255$FF0000"})
    b = ImageRegionCtx.from_params(
        {"c": "1|0:255$FF0000", "theT": "0", "theZ": "0", "imageId": "1"})
    assert a.cache_key == b.cache_key
    assert len(a.cache_key) == 16  # 64-bit hex


def test_cache_key_differs_on_params():
    a = ImageRegionCtx.from_params(_params())
    b = ImageRegionCtx.from_params(_params(theT="2"))
    assert a.cache_key != b.cache_key


def test_pixels_metadata_cache_key():
    assert (ImageRegionCtx.pixels_metadata_cache_key(7)
            == "ome.model.core.Pixels:Image:7")


# ------------------------------------------------------------ shape mask

def test_shape_mask_ctx():
    ctx = ShapeMaskCtx.from_params(
        {"shapeId": "42", "color": "FF0000", "flip": "h"})
    assert ctx.shape_id == 42
    assert ctx.color == "FF0000"
    assert ctx.flip_horizontal and not ctx.flip_vertical
    assert ctx.cache_key() == "ome.model.roi.Mask:42:FF0000"


def test_shape_mask_ctx_no_color():
    ctx = ShapeMaskCtx.from_params({"shapeId": "42"})
    assert ctx.cache_key() == "ome.model.roi.Mask:42:null"
    assert ShapeMaskCtx.from_json(ctx.to_json()) == ctx


def test_shape_mask_missing_id():
    with pytest.raises(BadRequestError):
        ShapeMaskCtx.from_params({})
