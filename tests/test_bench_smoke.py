"""bench.py --smoke as a tier-1 gate: cache and pipeline regressions
fail tests here instead of waiting for the next BENCH round."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_bench_smoke_hot_path(capsys):
    import bench

    t0 = time.monotonic()
    out = bench.bench_smoke(duration_s=1.5)
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"smoke bench took {elapsed:.0f}s (budget 60)"

    # Throughput through the full app at smoke scale.
    assert out["value"] > 0
    # Acceptance path: a repeated identical request answers from the
    # byte cache with ZERO new device dispatches.
    assert out["warm_repeat_cached"] is True
    # The single-flight probe ran (the rate itself is timing-dependent;
    # determinism for the mechanism lives in test_singleflight.py).
    assert out["dedup_hit_rate"] is not None
    assert 0.0 <= out["dedup_hit_rate"] <= 1.0
    # The two-stage pipeline recorded device-execute coverage.
    assert out["overlap_efficiency"] is not None
    assert out["overlap_efficiency"] > 0
    # Plane-digest staging accounting is live.
    assert out["planecache_misses"] is not None
    assert out["planecache_misses"] > 0
    # Per-request cost attribution is live: the most expensive request
    # of the window carries a ledger that says where its time went.
    assert "device_ms" in out["cost_ledger_keys"]
    assert "queue_ms" in out["cost_ledger_keys"]
    assert "wire_bytes" in out["cost_ledger_keys"]

    # Pay-for-what-you-use: every cross-cutting feature's hot-path
    # guard (trace span, cost-ledger flush, deadline check, admission
    # admit+release, write-behind enqueue) stays micro-seconds scale.
    # The budget is deliberately loose for CI-host jitter — the class
    # it catches is a lock round-trip becoming a directory scan or a
    # JSON encode (100x-1000x moves), not a 2x wobble.
    overhead = out["overhead_ns_per_op"]
    assert set(overhead) == {"trace", "ledger", "deadline",
                             "admission", "write_behind", "sentinel"}
    for name, ns in overhead.items():
        assert ns < 100_000, \
            f"hot-path overhead {name} = {ns:.0f} ns/op (budget 100µs)"
    # The perf sentinel's named top-level copy (the record-diff key)
    # matches the table and meets the per-op budget on its own.
    assert out["sentinel_overhead_ns_per_op"] == overhead["sentinel"]
    assert out["sentinel_overhead_ns_per_op"] < 100_000

    # Wire v3 gates (the probes ran the real split posture over a unix
    # socket with streaming + coalescing + shm ring live):
    # * first BODY byte lands strictly before the burst's batch
    #   completion — the first-tile-out + chunk-frame path is alive;
    assert out["p50_first_tile_byte_ms"] is not None
    assert out["p50_batch_complete_ms"] is not None
    assert out["p50_first_tile_byte_ms"] < out["p50_batch_complete_ms"]
    # * the coalescer amortized frames under concurrent load;
    assert out["wire_frames_per_flush"] > 1.0, \
        f"no frame coalescing: {out['wire_frames_per_flush']}"
    # * ring negotiation happened, eligible bodies actually rode it
    #   (upload bodies + tile chunks), and the ring's isolated wire
    #   leg beat the socket path (interleaved best-of-3 per path; the
    #   measured margin is ~2.5-3x on an idle host, so a same-or-worse
    #   reading means the ring is broken, not that CI was noisy).
    assert out["wire_ring_negotiated"] >= 1
    assert out["shm_ring_hit_rate"] is not None
    assert out["shm_ring_hit_rate"] > 0.5
    assert out["shm_upload_mb_per_sec"] > out["socket_upload_mb_per_sec"]
    # Streamed responses really went out as chunk frames.
    assert out["wire_streams"] >= 1

    # Fleet gates (N=4 virtual members served a mixed-digest burst
    # through the real router + member stacks):
    # * the routing layer scales — aggregate throughput >= 2.5x one
    #   member (measured ~3.5x; the virtual exec occupancy makes the
    #   ratio a property of the ROUTER, not of CI core count);
    assert out["fleet_members"] == 4
    assert out["fleet_speedup"] >= 2.5, \
        f"fleet does not scale: {out['fleet_speedup']}x"
    # * the HBM tier SHARDS: total fleet plane residency ~= 1x the
    #   working set, every resident plane on exactly ONE member.
    #   Slightly under is legal — a plane whose every render of the
    #   burst was STOLEN stays unstaged (stealing is cache-neutral by
    #   design) — but over would mean duplication, which never is.
    ws = out["fleet_working_set_planes"]
    assert ws - 3 <= out["fleet_resident_planes"] <= ws, \
        f"sharded residency {out['fleet_resident_planes']}/{ws}"
    assert out["fleet_duplicate_staged_planes"] == 0, \
        f"HBM duplicated: {out['fleet_duplicate_staged_planes']} " \
        f"planes staged on >1 member"
    # * every request was routed, and membership spans the fleet.
    assert out["fleet_routed_total"] >= \
        out["fleet_working_set_planes"]
    assert set(out["fleet_member_planes"]) == {"m0", "m1", "m2", "m3"}

    # The printed line is the machine-readable contract.
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["metric"] == "smoke_hotpath_tiles_per_sec"


def test_bench_smoke_sessions(capsys):
    """The multi-user serving gate (bench.py --smoke --sessions):
    N panning viewer sessions + ONE hostile bulk client over a real
    2-member fleet.  With the session tier live (token buckets +
    weighted QoS dequeue), the hostile must not move interactive
    per-session p99 past 2x the no-bulk baseline and Jain's fairness
    index must hold >= 0.8; the A/B leg with QoS OFF must regress
    BOTH (the mechanism, proven, not assumed).  The prefetch leg
    replays a deterministic pan trace: predictive hit rate >= 0.5,
    zero duplicate-staged planes (digest dedup preserved)."""
    import bench
    from omero_ms_image_region_tpu.utils import telemetry

    telemetry.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_sessions_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 120.0, \
            f"sessions smoke took {elapsed:.0f}s (budget 120)"

        # QoS on: the hostile is contained.  The p99 bound is judged
        # against max(baseline, one bulk render of head-of-line
        # blocking) — below that floor the comparison is CI noise.
        baseline = out["sessions_baseline_p99_ms"]
        floor = max(2 * baseline, out["sessions_bulk_exec_ms"])
        assert out["sessions_interactive_p99_ms"] <= floor, \
            f"interactive p99 {out['sessions_interactive_p99_ms']} " \
            f"vs no-bulk baseline {baseline}"
        assert out["sessions_fairness_index"] >= 0.8
        # The hostile's overrun really shed with the fairness reason.
        assert out["sessions_bulk_shed"] > 0
        assert out["sessions_fairness_sheds"] > 0
        # ...but was never starved outright: its in-budget trickle
        # (burst + refill) still served.
        assert out["sessions_bulk_served"] + \
            out["sessions_bulk_shed"] > 0

        # A/B leg, QoS off: the identical hostile convoys the fleet —
        # both gates REGRESS to failure, proving the mechanism.
        assert out["sessions_qos_off_p99_ms"] > floor
        assert out["sessions_fairness_index_off"] < 0.8

        # Predictive prefetch over the deterministic pan trace.
        assert out["prefetch_hit_rate"] is not None
        assert out["prefetch_hit_rate"] >= 0.5
        assert out["prefetch_staged_planes"] > 0
        assert out["prefetch_duplicate_staged_planes"] == 0

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "sessions_smoke"
    finally:
        telemetry.reset()


def test_bench_smoke_overload_brownout(capsys):
    """The worst-hour gate (bench.py --smoke --overload): a 10x
    capacity burst with the pressure governor live must brown out in
    ORDER, serve-or-shed everything (zero 5xx-without-shed), keep p99
    bounded, and recover with hysteresis — engage/release exactly once
    per step, release in exact reverse."""
    import bench
    from omero_ms_image_region_tpu.server import pressure
    from omero_ms_image_region_tpu.utils import telemetry

    telemetry.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_overload_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 60.0, \
            f"overload smoke took {elapsed:.0f}s (budget 60)"

        # Zero 5xx-without-shed: every request served or shed 503.
        assert out["overload_unshed_failures"] == 0
        assert out["overload_served"] + out["overload_sheds"] == \
            out["burst"]
        assert out["overload_served"] > 0
        # The ladder actually walked (the burst is sized to make the
        # governor work, not to tickle one step).
        assert len(out["overload_steps_engaged"]) >= 3
        # Ordered engage, reverse release, full recovery, no flapping.
        assert out["overload_ladder_order_ok"] is True
        assert out["overload_release_reverse_ok"] is True
        assert out["overload_released_all"] is True
        assert out["overload_flapping"] is False
        # PR 10: the continuous prefetch budget scaled DOWN (the
        # level's cut, in (0,1)) strictly before the binary
        # pause_prefetch step floored it, and the release walk
        # restored it fully.
        assert out["overload_budget_scaled_before_pause"] is True
        assert out["overload_budget_restored"] is True
        # Bounded p99: the burst is ~1.6 s of virtual device time at
        # full parallelism; an order of magnitude covers CI jitter —
        # the class this catches is an UNBOUNDED tail (no shedding,
        # no brownout: p99 -> the whole burst behind one lane).
        assert out["overload_p99_ms"] is not None
        assert out["overload_p99_ms"] < 20_000.0

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "overload_smoke"
        # The governor uninstalled cleanly (no cross-test leakage).
        assert pressure.active() is None
    finally:
        telemetry.reset()


def test_bench_smoke_capacity(capsys):
    """The capacity-knee gate (bench.py --smoke --capacity): an
    OPEN-loop arrival process (services.loadmodel) swept across
    offered loads and fleet sizes must find a knee per size, the knee
    must scale with the fleet, and the closed-loop A/B on the same
    past-knee arrivals must report a LOWER (flattering) p99 — the
    regression test that keeps future bench legs from quietly
    reverting to closed-loop arrivals."""
    import bench
    from omero_ms_image_region_tpu.utils import telemetry

    telemetry.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_capacity_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 90.0, \
            f"capacity smoke took {elapsed:.0f}s (budget 90)"

        # A knee exists per fleet size, inside the measured sweep
        # (not censored: the top load factor must violate the SLO).
        for size in out["capacity_fleet_sizes"]:
            knee = out[f"capacity_knee_offered_tps_m{size}"]
            assert knee is not None and knee > 0, out
            points = out["capacity_curve"][f"m{size}"]
            assert len(points) >= 3
            offered = [p["offered_tps"] for p in points]
            assert offered == sorted(offered)
        assert out["capacity_knee_censored"] is False
        # The knee at the headline (widest) fleet, and its p99 meets
        # the SLO by construction.
        assert out["capacity_knee_offered_tps"] == \
            out["capacity_knee_offered_tps_m4"]
        assert out["p99_at_knee_ms"] <= out["capacity_slo_ms"]
        # Capacity SCALES with fleet size (the curve the autoscaler's
        # floor/ceiling sizing reads).  The bound is loose for small
        # CI hosts — the class it catches is a router that stopped
        # scaling at all.
        assert out["capacity_knee_offered_tps_m4"] >= \
            1.5 * out["capacity_knee_offered_tps_m1"], out
        # Open-loop honesty: the SAME past-knee offered load replayed
        # closed-loop must flatter (workers that wait self-throttle
        # to the service rate and never see the queueing collapse).
        assert out["openloop_p99_past_knee_ms"] is not None
        assert out["closedloop_p99_past_knee_ms"] is not None
        assert out["openloop_p99_past_knee_ms"] > \
            1.5 * out["closedloop_p99_past_knee_ms"], out
        # Mask-class arrivals really ran (the committed synthetic
        # fixtures under tests/data/masks through the real mask
        # endpoint) and every offered mask completed — a broken
        # fixture or mask path fails loudly here, never by silently
        # thinning the measured mix.
        assert out["capacity_mask_fraction"] > 0
        assert out["capacity_mask_offered"] > 0, out
        assert out["capacity_mask_completed"] == \
            out["capacity_mask_offered"], out

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "capacity_smoke"
    finally:
        telemetry.reset()


def test_bench_smoke_hotkey(capsys):
    """The hot-plane replication gate (bench.py --smoke --hotkey):
    a zipf storm on a 2-member fleet must retain >= 0.7x the uniform
    mix's throughput WITH replication, the replication-disabled A/B
    must measure LESS, replica staging must never duplicate-stage,
    and heat decay must demote the viral route back to R=1 — all
    read from live counters, not from the bench's own claims."""
    import bench
    from omero_ms_image_region_tpu.utils import decisions, telemetry

    telemetry.reset()
    decisions.LEDGER.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_hotkey_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 60.0, \
            f"hotkey smoke took {elapsed:.0f}s (budget 60)"

        # The storm survived: throughput under the viral-plane skew
        # held >= 0.7x the uniform mix on the SAME fleet.
        assert out["hotkey_storm_ratio"] >= 0.7, out
        # The replication-disabled A/B measured LESS — the honesty
        # leg that proves the tier earns its complexity (a storm a
        # plain ring absorbs equally means the drill measured
        # nothing).
        assert out["hotkey_disabled_tps"] < out["hotkey_storm_tps"], \
            out
        assert out["hotkey_replication_gain"] > 1.0, out
        # The lifecycle actually ran, from live counters: promotion,
        # balanced reads off the ring owner, replica staging with
        # ZERO duplicate stagings, and the shard report classifying
        # the hot plane as replicated — never duplicate.
        assert out["hotkey_promotions"] >= 1, out
        assert out["hotkey_balanced_reads"] >= 1, out
        assert out["hotkey_duplicate_staged"] == 0, out
        assert out["hotkey_shard_duplicates"] == 0, out
        # Decay demoted the viral route back to R=1 after the storm
        # (swept on the live dispatch path, not by the bench).
        assert out["hotkey_demoted_after_decay"] is True, out
        assert out["hotkey_hot_routes_after_decay"] == 0, out
        assert out["hotkey_demotions"] >= 1, out
        # The autoscaler read replica pressure as a scale signal: at
        # the fleet ceiling the want-up it forces is refused, and
        # that decision record carries the signal (the ledger line an
        # operator reads during a real storm).
        assert out["hotkey_autoscaler_signal"] is True, out
        assert out["hotkey_ledger_promotions"] >= 1, out
        assert out["hotkey_peak_replica_pressure"] > 0, out

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "hotkey_smoke"
    finally:
        decisions.LEDGER.reset()
        telemetry.reset()


def test_bench_smoke_partition(capsys):
    """The netsplit chaos gate (bench.py --smoke --partition): a
    3-host fleet (two REAL sidecar processes) driven through
    partition -> fence -> heal -> rejoin under sustained load, with a
    two-phase epoch roll committed mid-partition.  The majority side
    must fail NOTHING without counting it shed; the minority must
    fence (with counted refusals), restore, converge to the committed
    epoch with no operator action, and agree bit-exactly after heal."""
    import bench
    from omero_ms_image_region_tpu.utils import decisions, telemetry

    telemetry.reset()
    decisions.LEDGER.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_partition_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 120.0, \
            f"partition smoke took {elapsed:.0f}s (budget 120)"

        # Join-time manifest agreement (digest + the peers' OWN ring
        # math on the golden probe keys) before any chaos.
        assert out["part_manifest_agreed"] == 1, out
        # Majority availability: the load loop never saw a failure
        # that was not counted shed — the drill's headline contract.
        assert out["part_load_requests"] > 0, out
        assert out["part_majority_5xx"] == 0, out
        # The minority fenced within the drill's polling budget and
        # refused state-changing ops while dark (each one counted).
        assert out["part_fence_ms"] > 0, out
        assert out["part_minority_refusals"] >= 2, out
        # The mid-partition roll committed on strict-majority acks
        # (A + B of 3 hosts) — a dark minority cannot block an epoch.
        assert out["part_roll_committed"] == 1, out
        assert out["part_roll_acks"] == 2, out
        # Heal: restore, anti-entropy convergence to epoch 2, full
        # digest + probe-owner agreement, byte-identical round-trip,
        # and the fenced/restored pair in C's own decision ledger.
        assert out["part_restore_ms"] > 0, out
        assert out["part_rejoin_epoch"] == 2, out
        assert out["part_postheal_agree"] == 1, out
        assert out["part_byte_agree"] == 1, out
        assert out["part_quorum_ledger"] >= 2, out

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "partition_smoke"
    finally:
        decisions.LEDGER.reset()
        telemetry.reset()


def test_bench_smoke_sentinel(capsys):
    """The induced-drift sentinel gate (bench.py --smoke --sentinel):
    a deterministic latency step on a virtual clock through a real
    2-member fleet must yield EXACTLY ONE confirmed drift (on the
    stepped member, never its healthy peer), EXACTLY ONE complete
    incident bundle (manifest listing profile + flight + costs +
    sketch diff + exemplars), one kind=sentinel ledger record, and a
    recovery that clears the verdict — the whole confirm/capture/
    recover cycle, with the strong assertions living inside the
    drill itself."""
    import bench
    from omero_ms_image_region_tpu.utils import decisions, telemetry

    telemetry.reset()
    decisions.LEDGER.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_sentinel_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 60.0, \
            f"sentinel smoke took {elapsed:.0f}s (budget 60)"

        assert out["sentinel_drift_confirms"] == 1, out
        assert out["sentinel_drifting_member"] == "m1", out
        assert out["sentinel_bundles"] == 1, out
        assert set(out["sentinel_bundle_files"]) == {
            "profile", "flight", "costs", "sketch_diff",
            "exemplars"}, out
        assert out["sentinel_recovered"] is True, out
        assert out["sentinel_merged_members"] == ["m0", "m1"], out
        assert out["sentinel_drift_keys"], out

        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["metric"] == "sentinel_smoke"
    finally:
        decisions.LEDGER.reset()
        telemetry.reset()


def test_bench_smoke_offload(capsys):
    """The repeat-viewer offload gate (bench.py --smoke --offload):
    over a real 2-sidecar remote fleet, the edge ladder (warm-local
    byte hit -> warm-peer byte fetch -> If-None-Match 304) absorbs
    >= 0.8 of the repeat mix with zero device renders, 304s land at
    least 10x below the cold render p50, and the re-routed working
    set serves byte-identical peer bytes."""
    import bench

    t0 = time.monotonic()
    out = bench.bench_offload_smoke()
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, \
        f"offload bench took {elapsed:.0f}s (budget 60)"

    # THE acceptance gates (issue 11): repeat viewers mostly never
    # touch the renderer, and revalidation is an order of magnitude
    # cheaper than a render.
    assert out["origin_offload_ratio"] >= 0.8, out
    assert out["p50_304_ms"] * 10.0 <= out["p50_service_tile_ms"], out
    # The warm-peer leg really re-routed work and served it from the
    # draining owner's byte tier (byte-identity is asserted inside
    # the run; a zero peer_working_set would prove nothing).
    assert out["peer_working_set"] > 0
    assert out["peer_hit_rate"] >= 0.8, out
    assert out["warm_renders"] == 0
    assert out["n_304"] > 0

    # One parseable JSON line on stdout for the driver.
    line = capsys.readouterr().out.strip().splitlines()[-1]
    doc = json.loads(line)
    assert doc["metric"] == "offload_smoke"
    assert doc["origin_offload_ratio"] == out["origin_offload_ratio"]


def test_bench_smoke_workloads(capsys):
    """The device-workloads gate (bench.py --smoke --workloads): the
    batched device mask path serves bytes IDENTICAL to the host
    rasterizer across the committed fixtures and flip lanes, the
    overlay composite matches the refimpl golden, the pyramid job
    commits a readable NGFF group, and the animation strip streams
    every frame in order then cancels cleanly on a mid-stream close
    — all asserted inside the run; the keys feed the WORKLOADS
    record family."""
    import bench
    from omero_ms_image_region_tpu.utils import telemetry

    telemetry.reset()
    try:
        t0 = time.monotonic()
        out = bench.bench_workloads_smoke()
        elapsed = time.monotonic() - t0
        assert elapsed < 60.0, \
            f"workloads bench took {elapsed:.0f}s (budget 60)"

        assert out["mask_parity_ok"] is True
        assert out["mask_renders"] >= 12, out
        assert out["overlay_parity_ok"] is True
        assert out["pyramid_levels"] >= 2, out
        assert out["pyramid_readable_levels"] == \
            out["pyramid_levels"], out
        assert out["anim_frames"] >= 8, out
        assert out["anim_first_frame_ms"] <= out["anim_total_ms"], out
        assert out["anim_cancel_ok"] is True

        line = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(line)
        assert doc["metric"] == "workloads_smoke"
        assert doc["mask_renders"] == out["mask_renders"]
    finally:
        telemetry.reset()
