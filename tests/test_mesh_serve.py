"""Mesh-sharded serving: MeshRenderer parity + HTTP integration.

Runs on the 8-device virtual host mesh (``resolve_devices`` falls back to
it when the default platform is narrower), exactly as the driver's
multi-chip dryrun does.
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omero_ms_image_region_tpu.parallel.mesh import make_mesh, resolve_devices


def _mesh(chan_parallel=2):
    if len(resolve_devices(8)) < 8:
        pytest.skip("no 8-wide device pool (real or virtual) available")
    return make_mesh(8, chan_parallel=chan_parallel)


def _settings(C, windows):
    from omero_ms_image_region_tpu.flagship import flagship_rdef
    from omero_ms_image_region_tpu.ops.render import pack_settings

    rdef = flagship_rdef(C)
    for cb, w in zip(rdef.channel_bindings, windows):
        cb.input_start, cb.input_end = w
    return pack_settings(rdef)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestMeshRenderer:
    def test_render_parity_with_single_device(self):
        from omero_ms_image_region_tpu.ops.render import (
            render_tile_packed)
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        mesh = _mesh(chan_parallel=2)
        renderer = MeshRenderer(mesh, linger_ms=0.0)
        rng = np.random.default_rng(0)
        # Mixed per-request settings; C=3 forces chan padding (3 -> 4).
        tiles = [rng.integers(0, 60000, (3, 40, 56)).astype(np.float32)
                 for _ in range(3)]
        settings = [_settings(3, [(0, 30000 + 10000 * i)] * 3)
                    for i in range(3)]

        async def go():
            return await asyncio.gather(*(
                renderer.render(t, s) for t, s in zip(tiles, settings)))

        outs = run(go())
        assert renderer.batches_dispatched >= 1
        # Compute the expectation on the mesh's own platform: the mesh may
        # have fallen back to the virtual CPU pool while the default
        # platform is a lone TPU, and float rounding at packed-int
        # boundaries differs across platforms.
        with jax.default_device(next(iter(mesh.devices.flat))):
            for t, s, out in zip(tiles, settings, outs):
                expect = np.asarray(render_tile_packed(
                    t, s["window_start"], s["window_end"], s["family"],
                    s["coefficient"], s["reverse"], s["cd_start"],
                    s["cd_end"], s["tables"]))
                np.testing.assert_array_equal(out, expect)

    def test_render_parity_with_full_lut_tables(self):
        """The [B, C, 256, 3] gather-table path through the mesh (ramp
        weights cover the other branch)."""
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import (
            build_channel_tables, pack_settings, render_tile_packed)
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        mesh = _mesh(chan_parallel=2)
        renderer = MeshRenderer(mesh, linger_ms=0.0)
        rng = np.random.default_rng(7)
        rdef = flagship_rdef(2)
        for cb in rdef.channel_bindings:
            cb.reverse_intensity = True   # defeat the ramp-weight fold
        s = pack_settings(rdef)
        if s["tables"].ndim == 2:
            s = dict(s, tables=build_channel_tables(rdef))
        assert s["tables"].ndim == 3      # full [C, 256, 3] tables
        tile = rng.integers(0, 60000, (2, 32, 48)).astype(np.float32)

        async def go():
            return await renderer.render(tile, s)

        out = run(go())
        with jax.default_device(next(iter(mesh.devices.flat))):
            expect = np.asarray(render_tile_packed(
                tile, s["window_start"], s["window_end"], s["family"],
                s["coefficient"], s["reverse"], s["cd_start"],
                s["cd_end"], s["tables"]))
        np.testing.assert_array_equal(out, expect)

    def test_render_jpeg_produces_decodable_tiles(self):
        import io

        from PIL import Image

        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        mesh = _mesh(chan_parallel=1)
        renderer = MeshRenderer(mesh, linger_ms=0.0)
        rng = np.random.default_rng(1)
        tiles = [rng.integers(0, 60000, (2, 24, 40)).astype(np.float32)
                 for _ in range(2)]
        settings = [_settings(2, [(0, 50000)] * 2) for _ in range(2)]

        async def go():
            return await asyncio.gather(*(
                renderer.render_jpeg(t, s, 85, t.shape[2], t.shape[1])
                for t, s in zip(tiles, settings)))

        jpegs = run(go())
        for t, j in zip(tiles, jpegs):
            img = Image.open(io.BytesIO(j))
            assert img.size == (t.shape[2], t.shape[1])

    def test_render_jpeg_huffman_engine_matches_sparse_pixels(self):
        """The mesh huffman engine entropy-codes the SAME quantized
        coefficients as the sparse engine, so both decode to identical
        pixels (the wire bytes differ: fixed vs optimal tables)."""
        import io

        from PIL import Image

        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        mesh = _mesh(chan_parallel=2)
        sparse = MeshRenderer(mesh, linger_ms=0.0)
        huff = MeshRenderer(mesh, linger_ms=0.0, jpeg_engine="huffman")
        assert huff.jpeg_engine == "huffman"
        rng = np.random.default_rng(3)
        # 32x48 is MCU-grid-exact, so the group takes the packed stream.
        tiles = [rng.integers(0, 60000, (2, 32, 48)).astype(np.float32)
                 for _ in range(2)]
        settings = [_settings(2, [(0, 50000)] * 2) for _ in range(2)]

        def go(renderer):
            async def inner():
                return await asyncio.gather(*(
                    renderer.render_jpeg(t, s, 85, t.shape[2], t.shape[1])
                    for t, s in zip(tiles, settings)))
            return run(inner())

        sp_jpegs, hf_jpegs = go(sparse), go(huff)
        for sj, hj in zip(sp_jpegs, hf_jpegs):
            a = np.asarray(Image.open(io.BytesIO(sj)).convert("RGB"))
            b = np.asarray(Image.open(io.BytesIO(hj)).convert("RGB"))
            np.testing.assert_array_equal(a, b)


class TestMeshRendererTorture:
    def test_mixed_concurrent_load(self):
        """Mixed sizes, channel counts, packed + JPEG, simultaneously:
        every request completes with its own correct result (the group
        builder must never cross-contaminate padded batches)."""
        import io

        from PIL import Image

        from omero_ms_image_region_tpu.ops.render import render_tile_packed
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        mesh = _mesh(chan_parallel=2)
        renderer = MeshRenderer(mesh, linger_ms=1.0)
        rng = np.random.default_rng(11)
        jobs = []
        for i in range(12):
            # Decorrelate channel count from the packed/JPEG flag so both
            # paths see both C=2 (no chan padding) and C=3 (3 -> 4 pad).
            C = 2 + ((i // 2) % 2)
            h, w = [(16, 16), (24, 40), (32, 48)][i % 3]
            tile = rng.integers(0, 60000, (C, h, w)).astype(np.float32)
            s = _settings(C, [(0, 30000 + 5000 * (i % 4))] * C)
            jobs.append((tile, s, i % 2 == 0))  # alternate packed/JPEG

        async def go():
            async def one(tile, s, packed):
                if packed:
                    return await renderer.render(tile, s)
                return await renderer.render_jpeg(
                    tile, s, 85, tile.shape[2], tile.shape[1])
            return await asyncio.gather(*(one(*j) for j in jobs))

        outs = run(go())
        with jax.default_device(next(iter(mesh.devices.flat))):
            for (tile, s, packed), out in zip(jobs, outs):
                if packed:
                    expect = np.asarray(render_tile_packed(
                        tile, s["window_start"], s["window_end"],
                        s["family"], s["coefficient"], s["reverse"],
                        s["cd_start"], s["cd_end"], s["tables"]))
                    np.testing.assert_array_equal(out, expect)
                else:
                    img = Image.open(io.BytesIO(out))
                    assert img.size == (tile.shape[2], tile.shape[1])
        assert renderer.tiles_rendered == len(jobs)


class TestMeshServingHTTP:
    def test_request_served_by_mesh_renderer(self, tmp_path):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.io.store import build_pyramid
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer
        from omero_ms_image_region_tpu.server.app import (SERVICES_KEY,
                                                          create_app)
        from omero_ms_image_region_tpu.server.config import (
            AppConfig, ParallelConfig, RendererConfig)

        if len(resolve_devices(8)) < 8:
            pytest.skip("no 8-wide device pool (real or virtual)")

        rng = np.random.default_rng(5)
        planes = rng.integers(0, 60000, (2, 1, 64, 64)).astype(np.uint16)
        build_pyramid(planes, str(tmp_path / "1"), n_levels=1)

        config = AppConfig(
            data_dir=str(tmp_path),
            parallel=ParallelConfig(enabled=True, chan_parallel=2,
                                    n_devices=8),
            renderer=RendererConfig(cpu_fallback_max_px=0),
        )

        async def go():
            app = create_app(config)
            services = app[SERVICES_KEY]
            assert isinstance(services.renderer, MeshRenderer)
            assert services.renderer.mesh.size == 8
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                resp = await client.get(
                    "/webgateway/render_image_region/1/0/0"
                    "?tile=0,0,0,32,32&format=jpeg&m=c"
                    "&c=1|0:60000$FF0000,2|0:60000$00FF00")
                body = await resp.read()
                return resp.status, body, services.renderer
            finally:
                await client.close()

        status, body, renderer = run(go())
        assert status == 200
        assert body[:2] == b"\xff\xd8"
        assert renderer.batches_dispatched >= 1
        assert renderer.tiles_rendered >= 1

    def test_mesh_honors_huffman_engine_config(self, tmp_path):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_image_region_tpu.io.store import build_pyramid
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer
        from omero_ms_image_region_tpu.server.app import (SERVICES_KEY,
                                                          create_app)
        from omero_ms_image_region_tpu.server.config import (
            AppConfig, ParallelConfig, RendererConfig)

        if len(resolve_devices(8)) < 8:
            pytest.skip("no 8-wide device pool (real or virtual)")

        rng = np.random.default_rng(6)
        planes = rng.integers(0, 60000, (2, 1, 64, 64)).astype(np.uint16)
        build_pyramid(planes, str(tmp_path / "1"), n_levels=1)

        config = AppConfig(
            data_dir=str(tmp_path),
            parallel=ParallelConfig(enabled=True, chan_parallel=2,
                                    n_devices=8),
            renderer=RendererConfig(cpu_fallback_max_px=0,
                                    jpeg_engine="huffman"),
        )

        async def go():
            app = create_app(config)
            services = app[SERVICES_KEY]
            assert isinstance(services.renderer, MeshRenderer)
            assert services.renderer.jpeg_engine == "huffman"
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                resp = await client.get(
                    "/webgateway/render_image_region/1/0/0"
                    "?tile=0,0,0,32,32&format=jpeg&m=c"
                    "&c=1|0:60000$FF0000,2|0:60000$00FF00")
                return resp.status, await resp.read()
            finally:
                await client.close()

        status, body = run(go())
        assert status == 200
        assert body[:2] == b"\xff\xd8"


class TestMeshOverflowLockstep:
    """Wire-cap overflow on the 8-device mesh: the one-shot cap-
    widening rescue must produce a DETERMINISTIC launch sequence
    (base cap, then 2x, then memo-started 2x) and byte-identical
    output to the single-device serving path — the property multi-host
    lockstep rests on (``parallel/serve.py`` cap memos driven by
    replicated totals)."""

    B, C, H, W = 8, 4, 64, 64

    def _overflow_group(self, quality=85):
        """Deterministic mid-density content whose wire totals land in
        (cap, 2*cap] for every tile (probed: band=10 noise columns over
        a flat background, seed 7)."""
        from omero_ms_image_region_tpu.flagship import flagship_rdef
        from omero_ms_image_region_tpu.ops.render import pack_settings
        from omero_ms_image_region_tpu.server.batcher import _Pending

        rng = np.random.default_rng(7)
        flat = np.full((self.C, self.H, self.W), 20000, np.float32)
        settings = pack_settings(flagship_rdef(self.C))
        group = []
        for _ in range(self.B):
            raw = flat.copy()
            raw[:, :, :10] = rng.uniform(
                0, 60000, (self.C, self.H, 10)).astype(np.float32)
            group.append(_Pending(raw=raw, settings=settings,
                                  h=self.H, w=self.W, quality=quality))
        return group

    @pytest.mark.parametrize("engine", ["huffman", "sparse"])
    def test_overflow_rescue_launch_sequence_and_parity(self, engine):
        from omero_ms_image_region_tpu.ops import jpegenc as je
        from omero_ms_image_region_tpu.flagship import batched_args
        from omero_ms_image_region_tpu.parallel.serve import MeshRenderer

        je._CAP_MEMO.clear()
        renderer = MeshRenderer(_mesh(), jpeg_engine=engine)
        launches = []
        orig = MeshRenderer._jpeg_step

        def spy(self, quality, cap, engine_="sparse", cap_words=None):
            step = orig(self, quality, cap, engine_, cap_words)

            def wrapped(*args):
                launches.append((engine_, quality, cap, cap_words))
                return step(*args)
            return wrapped

        MeshRenderer._jpeg_step = spy
        try:
            jpegs1 = renderer._render_group_jpeg(self._overflow_group())
            jpegs2 = renderer._render_group_jpeg(self._overflow_group())
        finally:
            MeshRenderer._jpeg_step = orig
        base_cap = je.default_sparse_cap(self.H, self.W, 85)
        base_words = je.default_words_cap(self.H, self.W, 85)
        if engine == "huffman":
            want = [("huffman", 85, base_cap, base_words),
                    ("huffman", 85, 2 * base_cap, 2 * base_words),
                    ("huffman", 85, 2 * base_cap, 2 * base_words)]
        else:
            want = [("sparse", 85, base_cap, None),
                    ("sparse", 85, 2 * base_cap, None),
                    ("sparse", 85, 2 * base_cap, None)]
        # Group 1: base dispatch + one rescue at 2x; group 2: the memo
        # starts at 2x directly.  NO dense fallbacks (rescue covered
        # every tile) and NO extra launches.
        assert launches == want

        # Byte parity with the single-device serving path on the same
        # pixels/settings (its own memo key; fresh = same rescue).
        group = self._overflow_group()
        raw = np.stack([p.raw for p in group])
        s = group[0].settings
        args = batched_args(s, raw)
        plain = je.render_batch_to_jpeg(
            raw, *args[1:], quality=85,
            dims=[(self.W, self.H)] * self.B, engine=engine)
        assert plain == jpegs1 == jpegs2
        run(renderer.close())
        je._CAP_MEMO.clear()
