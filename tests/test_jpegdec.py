"""JPEG-in-TIFF (compression 7) decode: pure-Python + native decoders,
JPEGTables merge, tiled/page-pyramid containers, HTTP e2e, fuzz.

The capability the reference gets from Bio-Formats behind
``PixelsService.getPixelBuffer`` (``build.gradle:81-83``) — SVS-class
vendor WSI pyramids are JPEG-in-TIFF.
"""

import asyncio
import io
import os
import struct

import numpy as np
import pytest
from PIL import Image

from omero_ms_image_region_tpu.io.jpegdec import (JpegError,
                                                  decode_baseline_jpeg,
                                                  decode_tiff_jpeg,
                                                  parse_jpeg_tables,
                                                  ycbcr_to_rgb)
from omero_ms_image_region_tpu.io.ometiff import OmeTiffSource
from omero_ms_image_region_tpu.io.tiff import TiffFile
from omero_ms_image_region_tpu.server.region import RegionDef


from vendor_tiff import smooth_rgb as _smooth_rgb  # noqa: E402


def _jfif(arr, quality=90):
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "jpeg", quality=quality)
    return buf.getvalue()


# ------------------------------------------------------- stream decoder

def test_decode_matches_pil_rgb():
    a = _smooth_rgb(120, 200)
    jf = _jfif(a, 92)
    got = decode_baseline_jpeg(jf)
    # PIL's JpegImagePlugin converts YCbCr->RGB itself; ours returns raw
    # components, so convert the same way for comparison.
    from omero_ms_image_region_tpu.io.jpegdec import ycbcr_to_rgb
    got = ycbcr_to_rgb(got)
    ref = np.asarray(Image.open(io.BytesIO(jf)).convert("RGB"))
    d = np.abs(got.astype(int) - ref.astype(int))
    # IDCT + chroma-upsampling implementations differ; smooth content
    # keeps the gap tiny.
    assert d.max() <= 8 and d.mean() < 1.0


def test_decode_matches_pil_grayscale():
    g = ((np.mgrid[0:90, 0:110][0] * 2.3) % 256).astype(np.uint8)
    jf = _jfif(g, 88)
    got = decode_baseline_jpeg(jf)
    ref = np.asarray(Image.open(io.BytesIO(jf)))
    d = np.abs(got[:, :, 0].astype(int) - ref.astype(int))
    assert got.shape == (90, 110, 1)
    assert d.max() <= 2


def test_native_matches_python():
    native = pytest.importorskip(
        "omero_ms_image_region_tpu.native")
    if not hasattr(native, "jpeg_decode_baseline"):
        pytest.skip("native decoder missing")
    try:
        native._load_jpegdec()
    except ImportError:
        pytest.skip("no toolchain for native decoder")
    a = _smooth_rgb(144, 176)
    jf = _jfif(a, 85)
    nat = native.jpeg_decode_baseline(jf, None)
    py = decode_baseline_jpeg(jf)
    assert np.abs(nat.astype(int) - py.astype(int)).max() <= 1


def test_restart_markers():
    a = _smooth_rgb(64, 96)
    buf = io.BytesIO()
    Image.fromarray(a).save(buf, "jpeg", quality=90, restart_marker_rows=1)
    jf = buf.getvalue()
    assert b"\xff\xdd" in jf          # DRI present
    got = decode_baseline_jpeg(jf)
    from omero_ms_image_region_tpu.io.jpegdec import ycbcr_to_rgb
    ref = np.asarray(Image.open(io.BytesIO(jf)).convert("RGB"))
    assert np.abs(ycbcr_to_rgb(got).astype(int)
                  - ref.astype(int)).max() <= 8


class TestProgressive:
    """Progressive (SOF2) decode — spectral-selection +
    successive-approximation scans, cross-validated against PIL's own
    libjpeg decode (the pure-Python path here; the native decoder's
    byte parity with it is pinned by TestProgressiveNativeParity)."""

    def test_gray_and_444_match_pil_exactly(self):
        a = _smooth_rgb(61, 83)
        for mode, img, conv in (("L", a[..., 0], None), ("RGB", a, 0)):
            buf = io.BytesIO()
            kw = {} if conv is None else {"subsampling": conv}
            Image.fromarray(img).save(buf, "jpeg", quality=88,
                                      progressive=True, **kw)
            ours = decode_baseline_jpeg(buf.getvalue())
            if mode == "RGB":
                ours = ycbcr_to_rgb(ours)
            else:
                ours = ours[..., 0]
            pil = np.asarray(Image.open(buf).convert(mode))
            # Same IDCT envelope as the baseline tests: +-2.
            assert np.abs(ours.astype(int) - pil.astype(int)).max() <= 2

    def test_420_matches_pil_within_upsample_envelope(self):
        # 4:2:0 differs from libjpeg only by chroma upsampling
        # (replication vs fancy) — the identical envelope the baseline
        # path has (see test_pil_jpeg_tiff_roundtrip's tolerance).
        a = _smooth_rgb(96, 96)
        for progressive in (True, False):
            buf = io.BytesIO()
            Image.fromarray(a).save(buf, "jpeg", quality=85,
                                    progressive=progressive,
                                    subsampling=2)
            ours = ycbcr_to_rgb(decode_baseline_jpeg(buf.getvalue()))
            pil = np.asarray(Image.open(buf).convert("RGB"))
            d = np.abs(ours.astype(int) - pil.astype(int))
            assert d.max() <= 20 and d.mean() <= 4

    def test_progressive_tiff_serves(self, tmp_path):
        """A progressive-JPEG TIFF reads through the TIFF layer
        (native-first, Python fallback — both decode SOF2)."""
        a = _smooth_rgb(64, 64)
        # PIL's TIFF writer can't emit progressive; build a minimal
        # strip TIFF holding one full progressive JFIF stream
        # (compression 7, interchange layout — decoders accept it).
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=92,
                                progressive=True, subsampling=0)
        payload = buf.getvalue()
        from omero_ms_image_region_tpu.io.tiffwrite import _TiffOut
        path = str(tmp_path / "prog.tif")
        with open(path, "wb") as f:
            out = _TiffOut(f, big=False)
            off = out.write(payload)
            ifd, _ = out.write_ifd([
                (256, 3, [64]), (257, 3, [64]), (258, 3, [8, 8, 8]),
                (259, 3, [7]), (262, 3, [6]), (277, 3, [3]),
                (278, 3, [64]), (273, 4, [off]), (279, 4, [len(payload)]),
            ])
            out.patch_first_ifd(ifd)
        tf = TiffFile(path)
        got = tf.read_segment(tf.ifds[0], 0, 0)
        tf.close()
        pil = np.asarray(Image.open(io.BytesIO(payload)).convert("RGB"))
        assert np.abs(got.astype(int) - pil.astype(int)).max() <= 2

    def test_truncated_progressive_fails_cleanly(self):
        a = _smooth_rgb(48, 48)
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=90,
                                progressive=True)
        data = buf.getvalue()
        for cut in (8, 40, len(data) // 3, len(data) // 2,
                    len(data) - 6):
            try:
                decode_baseline_jpeg(data[:cut])
            except JpegError:
                pass


# ---------------------------------------------------------- TIFF layer

def test_pil_jpeg_tiff_roundtrip(tmp_path):
    """PIL/libtiff writes compression 7 with a JPEGTables tag and
    abbreviated per-strip streams — the exact SVS layout."""
    a = _smooth_rgb(150, 220)
    path = str(tmp_path / "j.tif")
    Image.fromarray(a).save(path, compression="jpeg", quality=95)
    tf = TiffFile(path)
    from omero_ms_image_region_tpu.io.tiff import COMPRESSION, JPEG_TABLES
    assert int(tf.ifds[0].one(COMPRESSION)) == 7
    assert tf.ifds[0].get(JPEG_TABLES) is not None
    ref = np.asarray(Image.open(path).convert("RGB"))
    _, _, grid_y, _ = tf.segment_grid(tf.ifds[0])
    got = np.concatenate([tf.read_segment(tf.ifds[0], gy, 0)
                          for gy in range(grid_y)], axis=0)
    d = np.abs(got[:150, :220].astype(int) - ref.astype(int))
    assert d.max() <= 8 and d.mean() < 1.0
    tf.close()


def test_jpeg_tiff_through_ome_source(tmp_path):
    a = _smooth_rgb(100, 140)
    path = str(tmp_path / "j.tif")
    Image.fromarray(a).save(path, compression="jpeg", quality=95)
    src = OmeTiffSource(path)
    assert src.size_c == 3
    for c in range(3):
        got = src.get_region(0, c, 0, RegionDef(10, 20, 60, 50), 0)
        ref = np.asarray(Image.open(path).convert("RGB"))[20:70, 10:70, c]
        assert np.abs(got.astype(int) - ref.astype(int)).max() <= 8
    src.close()


def _write_tiled_jpeg_tiff(path, arr, tile=128, levels=1, quality=92):
    """Hand-built tiled JPEG TIFF pyramid: every tile holds a complete
    JFIF stream (tag 347 absent — both layouts are legal; the PIL file
    in the tests above covers the JPEGTables one); pyramid levels are
    following pages flagged NewSubfileType=1 (the vips/openslide
    export style)."""

    def ent(tag, ftype, count, value):
        return struct.pack("<HHI4s", tag, ftype, count, value)

    s = lambda v: struct.pack("<HH", v, 0)
    l = lambda v: struct.pack("<I", v)

    pages = []
    cur = arr
    for _ in range(levels):
        pages.append(cur)
        cur = cur[::2, ::2]
    out = bytearray(b"II" + struct.pack("<HI", 42, 8))
    ifd_starts, next_ptr_pos = [], []
    for li, page in enumerate(pages):
        h, w = page.shape[:2]
        ty, tx = -(-h // tile), -(-w // tile)
        ntiles = ty * tx
        tiles = []
        for gy in range(ty):
            for gx in range(tx):
                t = np.zeros((tile, tile, 3), np.uint8)
                seg = page[gy * tile:(gy + 1) * tile,
                           gx * tile:(gx + 1) * tile]
                t[:seg.shape[0], :seg.shape[1]] = seg
                # Edge-replicate the padding so it stays smooth.
                t[seg.shape[0]:] = t[max(seg.shape[0] - 1, 0)]
                t[:, seg.shape[1]:] = \
                    t[:, max(seg.shape[1] - 1, 0):seg.shape[1]]
                tiles.append(_jfif(np.ascontiguousarray(t), quality))
        n = 10 + (1 if li > 0 else 0)
        ifd_off = len(out)
        ifd_starts.append(ifd_off)
        bps_off = ifd_off + 2 + n * 12 + 4
        arrs_off = bps_off + 8
        if ntiles > 1:
            toffs_off = arrs_off
            tcnts_off = toffs_off + 4 * ntiles
            data_off = tcnts_off + 4 * ntiles
        else:
            data_off = arrs_off
        offs, cnts, cur_off = [], [], data_off
        for t in tiles:
            offs.append(cur_off)
            cnts.append(len(t))
            cur_off += len(t)
        entries = []
        if li > 0:
            entries.append(ent(254, 4, 1, l(1)))   # reduced-resolution
        entries += [
            ent(256, 3, 1, s(w)), ent(257, 3, 1, s(h)),
            ent(258, 3, 3, l(bps_off)), ent(259, 3, 1, s(7)),
            ent(262, 3, 1, s(6)), ent(277, 3, 1, s(3)),
            ent(322, 3, 1, s(tile)), ent(323, 3, 1, s(tile)),
        ]
        if ntiles > 1:
            entries += [ent(324, 4, ntiles, l(toffs_off)),
                        ent(325, 4, ntiles, l(tcnts_off))]
        else:
            entries += [ent(324, 4, 1, l(offs[0])),
                        ent(325, 4, 1, l(cnts[0]))]
        out += struct.pack("<H", n) + b"".join(entries)
        next_ptr_pos.append(len(out))
        out += l(0)
        out += struct.pack("<HHH", 8, 8, 8) + b"\0\0"
        if ntiles > 1:
            out += b"".join(l(o) for o in offs)
            out += b"".join(l(c) for c in cnts)
        for t in tiles:
            out += t
    for i, p in enumerate(next_ptr_pos[:-1]):
        out[p:p + 4] = struct.pack("<I", ifd_starts[i + 1])
    with open(path, "wb") as f:
        f.write(out)


def test_tiled_jpeg_pyramid_e2e(tmp_path):
    """Hand-built tiled JPEG pyramid (full-JFIF tiles, photometric 6,
    2 pages) serves through the HTTP app with pixel tolerance."""
    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_image_region_tpu.server.app import create_app
    from omero_ms_image_region_tpu.server.config import AppConfig

    arr = _smooth_rgb(300, 400)
    d = tmp_path / "1"
    os.makedirs(d)
    path = str(d / "wsi.tif")
    _write_tiled_jpeg_tiff(path, arr, tile=128, levels=2, quality=95)

    src = OmeTiffSource(path)
    assert src.resolution_levels() == 2
    got = src.get_region(0, 0, 0, RegionDef(0, 0, 400, 300), 0)
    assert np.abs(got.astype(int) - arr[:, :, 0].astype(int)).max() <= 10
    src.close()

    config = AppConfig(data_dir=str(tmp_path))

    async def fetch():
        app = create_app(config)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.get(
                "/webgateway/render_image_region/1/0/0"
                "?tile=0,0,0,128,128"
                "&c=1|0:255$FF0000,2|0:255$00FF00,3|0:255$0000FF&m=c"
                "&format=png")
            assert resp.status == 200
            return await resp.read()
        finally:
            await client.close()

    body = asyncio.run(fetch())
    png = np.asarray(Image.open(io.BytesIO(body)).convert("RGB"))
    # Additive composite of the 3 channels over full windows ==
    # (approximately) the original RGB tile.
    ref = arr[:128, :128]
    assert np.abs(png.astype(int) - ref.astype(int)).max() <= 12


# --------------------------------------------------------------- fuzz

def test_truncated_streams_fail_cleanly():
    a = _smooth_rgb(64, 64)
    jf = _jfif(a, 90)
    sos = jf.index(b"\xff\xda")
    # Cuts inside the header MUST raise.
    for cut in (2, 4, 20, sos - 1, sos + 1):
        with pytest.raises((JpegError, ValueError)):
            decode_baseline_jpeg(jf[:cut])
    # Cuts inside the entropy body must never crash: either a clean
    # JpegError or a right-shaped partial decode (1-pad tail bits).
    for cut in (sos + 40, len(jf) // 2, len(jf) - 3):
        try:
            arr = decode_baseline_jpeg(jf[:cut])
        except (JpegError, ValueError):
            continue
        assert arr.shape == (64, 64, 3)


def test_truncated_tables_fail_cleanly(tmp_path):
    a = _smooth_rgb(80, 80)
    path = str(tmp_path / "j.tif")
    Image.fromarray(a).save(path, compression="jpeg", quality=90)
    tf = TiffFile(path)
    from omero_ms_image_region_tpu.io.tiff import JPEG_TABLES
    tables = bytes(tf.ifds[0].get(JPEG_TABLES))
    tf.close()
    for cut in (1, 3, 10, len(tables) - 2):
        with pytest.raises((JpegError, ValueError)):
            parse_jpeg_tables(tables[:cut])


def test_garbage_bytes_fail_cleanly():
    rng = np.random.default_rng(5)
    for n in (0, 1, 2, 64, 1024):
        blob = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        with pytest.raises((JpegError, ValueError)):
            decode_baseline_jpeg(b"\xff\xd8" + blob)


def test_native_rejects_truncated():
    native = pytest.importorskip("omero_ms_image_region_tpu.native")
    try:
        native._load_jpegdec()
    except ImportError:
        pytest.skip("no toolchain")
    a = _smooth_rgb(64, 64)
    jf = _jfif(a, 90)
    for cut in (2, 4, 20):
        with pytest.raises(ValueError):
            native.jpeg_decode_baseline(jf[:cut], None)


def test_one_by_one_frame_decodes():
    """Sizing-call contract: a 1x1 frame (need == 1 byte) must not be
    mistaken for an error code by the native wrapper."""
    g = np.array([[137]], np.uint8)
    jf = _jfif(g, 90)
    assert decode_baseline_jpeg(jf).shape == (1, 1, 1)
    native = pytest.importorskip("omero_ms_image_region_tpu.native")
    try:
        native._load_jpegdec()
    except ImportError:
        pytest.skip("no toolchain")
    nat = native.jpeg_decode_baseline(jf, None)
    assert nat.shape == (1, 1, 1)
    assert abs(int(nat[0, 0, 0]) - 137) <= 3


def test_malformed_headers_raise_jpeg_error():
    """Crafted header shapes must raise JpegError (a ValueError), never
    IndexError/struct.error — the server maps ValueError to 4xx."""
    cases = [
        b"\xff\xd8\xff\xda\x00\x02",          # SOS with empty body
        b"\xff\xd8\xff\xc0\x00\x04\x08\x00",  # SOF shorter than 6
        b"\xff\xd8\xff\xdd\x00\x02",          # DRI with empty body
        # SOF claiming 4 components with no component bytes:
        b"\xff\xd8\xff\xc0\x00\x08\x08\x00\x10\x00\x10\x04",
    ]
    for blob in cases:
        with pytest.raises(ValueError):
            decode_baseline_jpeg(blob)


def test_svs_style_layout(tmp_path):
    """Unflagged vendor layout (Aperio SVS): tiled baseline + smaller
    tiled levels + stripped thumbnail/label pages — levels attach as a
    pyramid, associated images are skipped, Z stays 1."""
    arr = _smooth_rgb(288, 384)
    d = tmp_path / "1"
    os.makedirs(d)
    path = str(d / "svs_like.tif")

    def ent(tag, ftype, count, value):
        return struct.pack("<HHI4s", tag, ftype, count, value)

    s = lambda v: struct.pack("<HH", v, 0)
    l = lambda v: struct.pack("<I", v)

    pages = [
        ("tiled", arr),                     # baseline
        ("strip", arr[::8, ::8]),           # thumbnail (stripped)
        ("tiled", arr[::2, ::2]),           # level 1
        ("strip", arr[:40, :100]),          # label (stripped)
    ]
    out = bytearray(b"II" + struct.pack("<HI", 42, 8))
    starts, ptrs = [], []
    for kind, page in pages:
        h, w = page.shape[:2]
        if kind == "tiled":
            th = h + (-h) % 16
            tw = w + (-w) % 16
            t = np.zeros((th, tw, 3), np.uint8)
            t[:h, :w] = page
            data = _jfif(np.ascontiguousarray(t), 95)
            tags = [(256, s(w)), (257, s(h)), (259, s(7)),
                    (262, s(6)), (277, s(3)),
                    (322, s(tw)), (323, s(th))]
            data_tags = [(324, None), (325, None)]
        else:
            data = np.ascontiguousarray(page).tobytes()
            tags = [(256, s(w)), (257, s(h)), (259, s(1)),
                    (262, s(2)), (277, s(3)), (278, s(h))]
            data_tags = [(273, None), (279, None)]
        n = len(tags) + len(data_tags) + 1     # +1 for BitsPerSample
        ifd_off = len(out)
        starts.append(ifd_off)
        bps_off = ifd_off + 2 + n * 12 + 4
        data_off = bps_off + 8
        entries = []
        all_tags = tags + [(258, l(bps_off)),
                           (data_tags[0][0], l(data_off)),
                           (data_tags[1][0], l(len(data)))]
        for tag, val in sorted(all_tags):
            ftype = 3 if len(val) == 4 and tag not in (
                258, 273, 279, 324, 325) else (3 if tag == 258 else 4)
            count = 3 if tag == 258 else 1
            entries.append(ent(tag, ftype, count, val))
        out += struct.pack("<H", n) + b"".join(entries)
        ptrs.append(len(out))
        out += l(0)
        out += struct.pack("<HHH", 8, 8, 8) + b"\0\0"
        out += data
    for i, p in enumerate(ptrs[:-1]):
        out[p:p + 4] = struct.pack("<I", starts[i + 1])
    with open(path, "wb") as f:
        f.write(bytes(out))

    src = OmeTiffSource(path)
    assert (src.size_z, src.size_c) == (1, 3)
    assert src.resolution_levels() == 2
    assert src.resolution_descriptions() == [(384, 288), (192, 144)]
    got = src.get_region(0, 0, 0, RegionDef(0, 0, 384, 288), 0)
    assert np.abs(got.astype(int) - arr[:, :, 0].astype(int)).max() <= 8
    lvl1 = src.get_region(0, 1, 0, RegionDef(0, 0, 192, 144), 1)
    assert np.abs(lvl1.astype(int)
                  - arr[::2, ::2, 1].astype(int)).max() <= 8
    src.close()


def test_svs_style_layout_without_levels(tmp_path):
    """Tiled baseline + stripped associated images but NO tiled levels:
    the associated pages still must not masquerade as Z sections."""
    import omero_ms_image_region_tpu.io.ometiff as om

    arr = _smooth_rgb(144, 192)
    path = str(tmp_path / "flat_svs.tif")

    def ent(tag, ftype, count, value):
        return struct.pack("<HHI4s", tag, ftype, count, value)

    s = lambda v: struct.pack("<HH", v, 0)
    l = lambda v: struct.pack("<I", v)
    out = bytearray(b"II" + struct.pack("<HI", 42, 8))
    starts, ptrs = [], []
    pages = [("tiled", arr), ("strip", arr[::4, ::4])]
    for kind, page in pages:
        h, w = page.shape[:2]
        if kind == "tiled":
            th, tw = h + (-h) % 16, w + (-w) % 16
            t = np.zeros((th, tw, 3), np.uint8)
            t[:h, :w] = page
            data = _jfif(np.ascontiguousarray(t), 95)
            tags = [(256, 3, s(w)), (257, 3, s(h)), (259, 3, s(7)),
                    (262, 3, s(6)), (277, 3, s(3)), (322, 3, s(tw)),
                    (323, 3, s(th))]
            dt = [(324, 4), (325, 4)]
        else:
            data = np.ascontiguousarray(page).tobytes()
            tags = [(256, 3, s(w)), (257, 3, s(h)), (259, 3, s(1)),
                    (262, 3, s(2)), (277, 3, s(3)), (278, 3, s(h))]
            dt = [(273, 4), (279, 4)]
        n = len(tags) + 3
        ifd_off = len(out)
        starts.append(ifd_off)
        bps_off = ifd_off + 2 + n * 12 + 4
        data_off = bps_off + 8
        all_tags = tags + [(258, 3, l(bps_off)),
                           (dt[0][0], 4, l(data_off)),
                           (dt[1][0], 4, l(len(data)))]
        entries = [ent(tag, ftype, 3 if tag == 258 else 1, val)
                   for tag, ftype, val in sorted(all_tags)]
        out += struct.pack("<H", n) + b"".join(entries)
        ptrs.append(len(out))
        out += l(0)
        out += struct.pack("<HHH", 8, 8, 8) + b"\0\0"
        out += data
    for i, p in enumerate(ptrs[:-1]):
        out[p:p + 4] = struct.pack("<I", starts[i + 1])
    with open(path, "wb") as f:
        f.write(bytes(out))

    src = OmeTiffSource(path)
    assert (src.size_z, src.size_c) == (1, 3)
    assert src.resolution_levels() == 1
    got = src.get_region(0, 2, 0, RegionDef(0, 0, 192, 144), 0)
    assert np.abs(got.astype(int) - arr[:, :, 2].astype(int)).max() <= 8
    src.close()


def _write_old_jpeg_tiff(path, arr, rows_per_strip=None):
    """Old-style JPEG (compression 6), interchange-format layout: tags
    513/514 point at one complete JFIF stream for the whole image."""
    jf = _jfif(arr, 95)
    h, w = arr.shape[:2]
    rps = rows_per_strip or h
    nstrips = -(-h // rps)

    def ent(tag, ftype, count, value):
        return struct.pack("<HHI4s", tag, ftype, count, value)

    s = lambda v: struct.pack("<HH", v, 0)
    l = lambda v: struct.pack("<I", v)
    n = 11
    ifd_off = 8
    bps_off = ifd_off + 2 + n * 12 + 4
    arrs_off = bps_off + 8
    if nstrips > 1:
        soff_off = arrs_off
        scnt_off = soff_off + 4 * nstrips
        data_off = scnt_off + 4 * nstrips
    else:
        data_off = arrs_off
    entries = [
        ent(256, 3, 1, s(w)), ent(257, 3, 1, s(h)),
        ent(258, 3, 3, l(bps_off)), ent(259, 3, 1, s(6)),
        ent(262, 3, 1, s(6)), ent(277, 3, 1, s(3)),
        ent(278, 3, 1, s(rps)),
        # Strip offsets/counts are nominal (readers use 513/514).
        (ent(273, 4, nstrips, l(soff_off)) if nstrips > 1
         else ent(273, 4, 1, l(data_off))),
        (ent(279, 4, nstrips, l(scnt_off)) if nstrips > 1
         else ent(279, 4, 1, l(len(jf)))),
        ent(513, 4, 1, l(data_off)),
        ent(514, 4, 1, l(len(jf))),
    ]
    with open(path, "wb") as f:
        f.write(b"II" + struct.pack("<HI", 42, 8))
        f.write(struct.pack("<H", n) + b"".join(entries) + l(0))
        f.write(struct.pack("<HHH", 8, 8, 8) + b"\0\0")
        if nstrips > 1:
            f.write(b"".join(l(data_off) for _ in range(nstrips)))
            f.write(b"".join(l(len(jf)) for _ in range(nstrips)))
        f.write(jf)


def test_old_style_jpeg_interchange(tmp_path):
    a = _smooth_rgb(90, 120)
    path = str(tmp_path / "old.tif")
    _write_old_jpeg_tiff(path, a)
    src = OmeTiffSource(path)
    got = src.get_region(0, 0, 0, RegionDef(0, 0, 120, 90), 0)
    assert np.abs(got.astype(int) - a[:, :, 0].astype(int)).max() <= 8
    src.close()


def test_old_style_jpeg_multi_strip_slices(tmp_path):
    a = _smooth_rgb(90, 120)
    path = str(tmp_path / "old2.tif")
    _write_old_jpeg_tiff(path, a, rows_per_strip=32)
    tf = TiffFile(path)
    seg = tf.read_segment(tf.ifds[0], 2, 0)    # rows 64..89 (short)
    assert seg.shape == (26, 120, 3)
    assert np.abs(seg.astype(int) - a[64:90].astype(int)).max() <= 8
    tf.close()


def test_old_style_jpeg_without_interchange_rejected(tmp_path):
    a = _smooth_rgb(32, 32)
    path = str(tmp_path / "old3.tif")
    _write_old_jpeg_tiff(path, a)
    # Strip tags 513/514 to simulate the unsupported tables variant.
    data = bytearray(open(path, "rb").read())
    n = struct.unpack("<H", data[8:10])[0]
    for i in range(n):
        off = 10 + i * 12
        tag = struct.unpack("<H", data[off:off + 2])[0]
        if tag in (513, 514):
            struct.pack_into("<H", data, off, 60000 + tag)  # junk tag
    open(path, "wb").write(bytes(data))
    tf = TiffFile(path)
    with pytest.raises(ValueError, match="JPEGInterchangeFormat"):
        tf.read_segment(tf.ifds[0], 0, 0)
    tf.close()


def test_old_style_jpeg_missing_strip_tags(tmp_path):
    """Real compression-6 files often omit 273/279 entirely (the
    pointer lives in 513/514); they must still decode."""
    a = _smooth_rgb(48, 64)
    path = str(tmp_path / "old4.tif")
    _write_old_jpeg_tiff(path, a)
    data = bytearray(open(path, "rb").read())
    n = struct.unpack("<H", data[8:10])[0]
    for i in range(n):
        off = 10 + i * 12
        tag = struct.unpack("<H", data[off:off + 2])[0]
        if tag in (273, 279):
            struct.pack_into("<H", data, off, 60000 + tag)
    open(path, "wb").write(bytes(data))
    tf = TiffFile(path)
    got = tf.read_segment(tf.ifds[0], 0, 0)
    assert np.abs(got.astype(int) - a.astype(int)).max() <= 8
    tf.close()


def test_old_style_jpeg_decodes_once_per_ifd(tmp_path):
    """Strip reads share ONE full-image decode (memoized per IFD)."""
    import omero_ms_image_region_tpu.io.jpegdec as jd

    a = _smooth_rgb(96, 64)
    path = str(tmp_path / "old5.tif")
    _write_old_jpeg_tiff(path, a, rows_per_strip=16)
    calls = []
    orig = jd.decode_baseline_jpeg

    def spy(data, tables=None):
        calls.append(1)
        return orig(data, tables)

    jd.decode_baseline_jpeg = spy
    native_off = None
    try:
        # Force the python path so the spy sees the decode count.
        import omero_ms_image_region_tpu.native as native
        native_off = native.jpeg_decode_baseline
        def _no_native(*a_, **k_):
            raise ImportError("disabled for test")
        native.jpeg_decode_baseline = _no_native
        tf = TiffFile(path)
        for gy in range(6):
            tf.read_segment(tf.ifds[0], gy, 0)
        tf.close()
    finally:
        jd.decode_baseline_jpeg = orig
        if native_off is not None:
            native.jpeg_decode_baseline = native_off
    assert len(calls) == 1


def test_hostile_sof_dimensions_rejected():
    """Corrupt SOF claiming a huge frame must not drive allocations
    (python and native agree)."""
    sof = (b"\xff\xd8\xff\xc0\x00\x11\x08\xff\xff\xff\xff\x04"
           + b"\x01\x22\x00\x02\x11\x00\x03\x11\x00\x04\x11\x00")
    with pytest.raises(ValueError):
        decode_baseline_jpeg(sof)
    native = pytest.importorskip("omero_ms_image_region_tpu.native")
    try:
        native._load_jpegdec()
    except ImportError:
        pytest.skip("no toolchain")
    with pytest.raises(ValueError):
        native.jpeg_decode_baseline(sof, None)


def test_twelve_bit_precision_rejected():
    blob = bytearray(_jfif(_smooth_rgb(16, 16), 90))
    i = blob.index(b"\xff\xc0")
    blob[i + 4] = 12                    # SOF precision byte
    with pytest.raises(ValueError, match="precision"):
        decode_baseline_jpeg(bytes(blob))


def test_multi_scan_rejected():
    """ns != frame component count (non-interleaved baseline)."""
    blob = bytearray(_jfif(_smooth_rgb(16, 16), 90))
    i = blob.index(b"\xff\xda")
    blob[i + 4] = 1                     # SOS ns: 3 -> 1 (len now lies,
    with pytest.raises(ValueError):     # either check may fire first)
        decode_baseline_jpeg(bytes(blob))


def test_progressive_block_budget_bounds_hostile_streams(monkeypatch):
    """A tiny stream declaring a large SOF2 frame plus many scans must
    die on the CUMULATIVE block budget - scan count alone is no work
    bound, since each scan re-walks the whole declared frame off the
    reader's padding bits with almost no Huffman data.  The scan script
    here is VALID (succession checks pass: DC first, then per-band AC
    first scans at Al=13, then refinements) so the budget itself is
    what fires; the budget floor is patched small but the frame-scaled
    term (64 full walks of the declared 640^2 frame) is what bounds
    this stream."""
    import time

    from omero_ms_image_region_tpu.io import jpegdec

    def seg(marker, body):
        return (bytes([0xFF, marker])
                + struct.pack(">H", len(body) + 2) + body)

    def hostile(side):
        # 1-component frame; two codes of length 1 put value 0 on code
        # '1', so every scan decodes entirely off padding bits (DC
        # category 0; AC rs=0 -> immediate EOB run).  Scan script is
        # valid: DC first, per-band AC firsts at Al=13, then per-band
        # refinement chains 13..1.
        dqt = seg(0xDB, bytes([0]) + bytes([16] * 64))
        dht_dc = seg(0xC4, bytes([0x00]) + bytes([2] + [0] * 15)
                     + bytes([0, 0]))
        dht_ac = seg(0xC4, bytes([0x10]) + bytes([2] + [0] * 15)
                     + bytes([0, 0]))
        sof = seg(0xC2, bytes([8]) + struct.pack(">HH", side, side)
                  + bytes([1, 1, 0x11, 0]))
        scans = [seg(0xDA, bytes([1, 1, 0x00, 0, 0, 0x00]))]
        scans += [seg(0xDA, bytes([1, 1, 0x00, k, k, 0x0D]))
                  for k in range(1, 64)]
        scans += [seg(0xDA, bytes([1, 1, 0x00, k, k,
                                   (a << 4) | (a - 1)]))
                  for k in range(1, 64)
                  for a in range(13, 0, -1)]
        return (b"\xff\xd8" + dqt + dht_dc + dht_ac + sof
                + b"".join(scans[:250]) + b"\xff\xd9")

    # Python: floor patched small; the frame-scaled term (64 walks of
    # the 640^2 frame = 409,600 visits) fires at scan 65 of 250.
    monkeypatch.setattr(jpegdec, "_MAX_BLOCK_VISITS", 25_000)
    t0 = time.perf_counter()
    with pytest.raises(JpegError, match="block budget"):
        decode_baseline_jpeg(hostile(640))
    assert time.perf_counter() - t0 < 30
    # Native: same rule with the compiled-in 8M floor — a declared
    # 2048^2 frame (65,536 blocks/scan) exceeds it at scan 128.
    from omero_ms_image_region_tpu.native import (
        jpeg_decode_baseline, jpeg_native_available)
    if jpeg_native_available():
        with pytest.raises(ValueError):
            jpeg_decode_baseline(hostile(2048), None)


def test_progressive_frame_scaled_budget_allows_deep_scripts():
    """The frame-scaled budget term must NOT reject a legitimate deep
    scan script over a large frame: a PIL 10-scan progressive at a size
    whose visits exceed the old fixed 8M budget would have been
    rejected before the scaling rule."""
    from omero_ms_image_region_tpu.io import jpegdec

    # Claim: frame-scaling admits >= 64 full walks regardless of size.
    # (A real 4096^2 decode is too slow for a unit test; assert the
    # arithmetic instead of the walk.)
    mcux = mcuy = 4096 // 8
    total_blocks = mcux * mcuy
    assert 64 * total_blocks > jpegdec._MAX_BLOCK_VISITS
    assert max(jpegdec._MAX_BLOCK_VISITS, 64 * total_blocks) \
        >= 12 * total_blocks   # a rich 12-scan script fits


class TestProgressiveNativeParity:
    """The native SOF2 path against the Python decoder: identical
    coefficient reconstruction up to the float-IDCT rounding envelope
    (+-1, the same contract the baseline decoders share in
    test_native_matches_python), identical validation behavior."""

    def _both(self, data, tables=None):
        from omero_ms_image_region_tpu.io import jpegdec
        from omero_ms_image_region_tpu.native import (
            jpeg_decode_baseline, jpeg_native_available)
        if not jpeg_native_available():
            pytest.skip("no native toolchain")
        ts = jpegdec.parse_jpeg_tables(tables) if tables else None
        py = jpegdec.decode_baseline_jpeg(data, ts)
        nat = jpeg_decode_baseline(data, tables)
        return py, nat

    @pytest.mark.parametrize("subsampling,quality", [
        (0, 92), (1, 85), (2, 75)])
    def test_rgb_parity(self, subsampling, quality):
        a = _smooth_rgb(83, 61)
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=quality,
                                progressive=True,
                                subsampling=subsampling)
        py, nat = self._both(buf.getvalue())
        assert np.abs(py.astype(int) - nat.astype(int)).max() <= 1

    def test_gray_parity(self):
        a = _smooth_rgb(64, 96)[..., 0]
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=88,
                                progressive=True)
        py, nat = self._both(buf.getvalue())
        assert np.abs(py.astype(int) - nat.astype(int)).max() <= 1

    def test_restart_interval_parity(self):
        a = _smooth_rgb(96, 80)
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=80,
                                progressive=True, subsampling=2,
                                restart_marker_blocks=2)
        py, nat = self._both(buf.getvalue())
        assert np.abs(py.astype(int) - nat.astype(int)).max() <= 1

    def test_native_rejects_what_python_rejects(self):
        """Validation parity on malformed scripts: a refinement whose
        Ah does not continue the band's Al fails BOTH decoders."""
        from omero_ms_image_region_tpu.io.jpegdec import (
            JpegError, decode_baseline_jpeg)
        from omero_ms_image_region_tpu.native import (
            jpeg_decode_baseline, jpeg_native_available)
        a = _smooth_rgb(48, 48)
        buf = io.BytesIO()
        Image.fromarray(a).save(buf, "jpeg", quality=85,
                                progressive=True, subsampling=0)
        blob = bytearray(buf.getvalue())
        # Find the SECOND SOS and corrupt its Ah/Al byte to a level
        # that cannot continue any band (Ah=9, Al=3).
        first = blob.index(b"\xff\xda")
        second = blob.index(b"\xff\xda", first + 2)
        seglen = struct.unpack(">H", blob[second + 2:second + 4])[0]
        blob[second + 2 + seglen - 1] = 0x93
        with pytest.raises(JpegError):
            decode_baseline_jpeg(bytes(blob))
        if jpeg_native_available():
            with pytest.raises(ValueError):
                jpeg_decode_baseline(bytes(blob), None)


class TestExtended12Bit:
    """12-bit extended-sequential JPEG (SOF1, T.81 Table B.2): the
    precision-over-8 class some vendor microscopy exports use and the
    reference's Bio-Formats path reads.  Decodes to uint16 with the
    2048 level shift; lossless (SOF3) and 16-bit precision reject with
    errors naming the variant."""

    @staticmethod
    def _seg(marker, body):
        return (bytes([0xFF, marker])
                + struct.pack(">H", len(body) + 2) + body)

    def _stream12(self, diff=1000):
        seg = self._seg
        # Quant table 0, Pq=1 (16-bit entries), all ones.
        dqt = seg(0xDB, bytes([0x10]) + b"\x00\x01" * 64)
        # One DC code '0' (len 1) -> category 10; one AC code '0' -> EOB.
        dht_dc = seg(0xC4, bytes([0x00]) + bytes([1] + [0] * 15)
                     + bytes([10]))
        dht_ac = seg(0xC4, bytes([0x10]) + bytes([1] + [0] * 15)
                     + bytes([0]))
        sof = seg(0xC1, bytes([12]) + struct.pack(">HH", 8, 8)
                  + bytes([1, 1, 0x11, 0]))
        sos = seg(0xDA, bytes([1, 1, 0x00, 0, 63, 0]))
        # Entropy: DC code '0', 10 magnitude bits of `diff`, AC EOB '0',
        # padded with 1s.
        bits = "0" + format(diff, "010b") + "0"
        bits += "1" * (-len(bits) % 8)
        entropy = bytes(int(bits[i:i + 8], 2)
                        for i in range(0, len(bits), 8))
        return (b"\xff\xd8" + dqt + dht_dc + dht_ac + sof + sos
                + entropy + b"\xff\xd9")

    def test_12bit_decodes_to_uint16(self):
        out = decode_baseline_jpeg(self._stream12())
        assert out.dtype == np.uint16
        assert out.shape == (8, 8, 1)
        # DC-only block: IDCT gives coeff/8 everywhere, +2048 shift.
        np.testing.assert_array_equal(out[..., 0],
                                      np.full((8, 8), 1000 // 8 + 2048))

    def test_12bit_through_tiff_decode_path(self):
        # decode_tiff_jpeg routes 12-bit around the 8-bit native
        # decoder and serves uint16 components (photometric 1).
        out = decode_tiff_jpeg(self._stream12(), None, photometric=1)
        assert out.dtype == np.uint16
        assert int(out[0, 0, 0]) == 1000 // 8 + 2048

    def test_12bit_ycbcr_rejected_with_named_error(self):
        # Single-component stream trips the component-count check; the
        # dtype guard ("12-bit YCbCr") covers the 3-component case.
        with pytest.raises(JpegError, match="YCbCr"):
            decode_tiff_jpeg(self._stream12(), None, photometric=6)

    def test_baseline_sof0_stays_8bit(self):
        blob = bytearray(self._stream12())
        i = blob.index(b"\xff\xc1")
        blob[i + 1] = 0xC0
        with pytest.raises(JpegError, match="baseline SOF0"):
            decode_baseline_jpeg(bytes(blob))

    def test_16bit_precision_rejected_named(self):
        blob = bytearray(self._stream12())
        i = blob.index(b"\xff\xc1")
        blob[i + 4] = 16
        with pytest.raises(JpegError, match="8-bit and 12-bit"):
            decode_baseline_jpeg(bytes(blob))

    def test_lossless_sof3_rejected_named(self):
        blob = bytearray(self._stream12())
        i = blob.index(b"\xff\xc1")
        blob[i + 1] = 0xC3
        with pytest.raises(JpegError, match="lossless"):
            decode_baseline_jpeg(bytes(blob))

    def test_12bit_tiff_declared_12_serves_uint16(self, tmp_path):
        """BitsPerSample=12 + compression 7: opens, serves uint16."""
        from omero_ms_image_region_tpu.io.tiffwrite import _TiffOut
        payload = self._stream12()
        path = str(tmp_path / "t12.tif")
        with open(path, "wb") as f:
            out = _TiffOut(f, big=False)
            off = out.write(payload)
            ifd, _ = out.write_ifd([
                (256, 3, [8]), (257, 3, [8]), (258, 3, [12]),
                (259, 3, [7]), (262, 3, [1]), (277, 3, [1]),
                (278, 3, [8]), (273, 4, [off]),
                (279, 4, [len(payload)]),
            ])
            out.patch_first_ifd(ifd)
        tf = TiffFile(path)
        assert tf.ifds[0].dtype() == np.uint16
        got = tf.read_segment(tf.ifds[0], 0, 0)
        tf.close()
        assert got.dtype == np.uint16
        assert int(got[0, 0, 0]) == 1000 // 8 + 2048

    def test_12bit_stream_in_8bit_tiff_fails_loudly(self, tmp_path):
        """Declared 8-bit + 12-bit stream: declaration mismatch must
        fail, not serve mod-256-wrapped pixels."""
        from omero_ms_image_region_tpu.io.tiffwrite import _TiffOut
        payload = self._stream12()
        path = str(tmp_path / "bad.tif")
        with open(path, "wb") as f:
            out = _TiffOut(f, big=False)
            off = out.write(payload)
            ifd, _ = out.write_ifd([
                (256, 3, [8]), (257, 3, [8]), (258, 3, [8]),
                (259, 3, [7]), (262, 3, [1]), (277, 3, [1]),
                (278, 3, [8]), (273, 4, [off]),
                (279, 4, [len(payload)]),
            ])
            out.patch_first_ifd(ifd)
        tf = TiffFile(path)
        with pytest.raises(ValueError, match="does not match declared"):
            tf.read_segment(tf.ifds[0], 0, 0)
        tf.close()
