"""HBM-resident raw tile cache: identity, eviction, handler integration."""

import asyncio

import numpy as np
import pytest

from omero_ms_image_region_tpu.io.devicecache import (
    DeviceRawCache, region_key,
)


def test_same_key_loads_once_and_counts():
    cache = DeviceRawCache(max_bytes=1 << 30)
    calls = []

    def loader():
        calls.append(1)
        return np.ones((2, 8, 8), np.float32)

    key = region_key(1, 0, 0, 0, (0, 0, 8, 8), (0, 1))
    a = cache.get_or_load(key, loader)
    b = cache.get_or_load(key, loader)
    assert len(calls) == 1
    assert a is b
    assert cache.hits == 1 and cache.misses == 1
    np.testing.assert_array_equal(np.asarray(a), 1.0)


def test_eviction_respects_byte_budget():
    # DISTINCT content per key: identical content would alias one
    # device buffer (content-digest dedup) and fit the budget forever.
    tile_bytes = 2 * 8 * 8 * 4
    cache = DeviceRawCache(max_bytes=tile_bytes * 2)
    for i in range(4):
        cache.get_or_load(("k", i),
                          lambda i=i: np.full((2, 8, 8), float(i),
                                              np.float32))
    assert len(cache) == 2                       # oldest two evicted
    assert cache.size_bytes == tile_bytes * 2
    assert cache.evictions == 2
    # Oldest keys are gone: reloading key 0 is a miss.
    misses = cache.misses
    cache.get_or_load(("k", 0),
                      lambda: np.full((2, 8, 8), 0.0, np.float32))
    assert cache.misses == misses + 1


def test_digest_aliases_share_buffer_and_bytes():
    """Identical content under many keys holds ONE device buffer and
    ONE byte-budget charge; the bytes leave only with the last alias."""
    tile_bytes = 2 * 8 * 8 * 4
    cache = DeviceRawCache(max_bytes=tile_bytes * 4)
    arrs = [cache.get_or_load(("k", i),
                              lambda: np.zeros((2, 8, 8), np.float32))
            for i in range(3)]
    assert arrs[0] is arrs[1] is arrs[2]     # one buffer, three keys
    assert len(cache) == 3
    assert cache.size_bytes == tile_bytes    # accounted once
    assert cache.plane_hits == 2 and cache.plane_misses == 1
    # Distinct content pushes the shared buffer's aliases out one by
    # one; the shared bytes leave the budget only with the LAST alias.
    for i in range(3):
        cache.get_or_load(("fresh", i),
                          lambda i=i: np.full((2, 8, 8), 1.0 + i,
                                              np.float32))
    assert cache.size_bytes <= tile_bytes * 4


def test_racing_identical_content_misses_share_one_buffer():
    """Two threads key-missing concurrently on identical content must
    converge on ONE device buffer (the in-lock digest re-probe): no
    unaccounted second HBM allocation survives in the cache."""
    import threading

    cache = DeviceRawCache()
    content = np.arange(2 * 8 * 8, dtype=np.uint16).reshape(2, 8, 8)
    barrier = threading.Barrier(2, timeout=10)

    def load():
        barrier.wait()      # both threads inside the miss path at once
        return content.copy()

    outs = [None, None]

    def worker(i):
        outs[i] = cache.get_or_load(("r", i), load)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outs[0] is outs[1]               # loser adopted the winner's
    assert cache.size_bytes == content.nbytes
    assert len(cache) == 2                  # both keys present, aliased


def test_wire_probe_counts_hits_only():
    """One actual upload = exactly one plane_misses increment: the
    probe counts only hits (uploads that never happen); the miss is
    recorded by the staging itself."""
    from omero_ms_image_region_tpu.io.staging import stage_deduped

    cache = DeviceRawCache()
    arr = np.arange(128, dtype=np.uint16).reshape(2, 8, 8)
    from omero_ms_image_region_tpu.io.devicecache import plane_digest
    digest = plane_digest(arr)
    assert cache.resident_digest(digest) is False     # probe: cold
    assert cache.plane_misses == 0                    # not yet an upload
    stage_deduped(arr, cache, digest=digest)          # the upload
    assert cache.plane_misses == 1
    assert cache.resident_digest(digest) is True      # probe: warm
    assert cache.plane_hits == 1


def test_prefetcher_stages_neighbor_tiles(tmp_path):
    """Serving one tile schedules its lattice neighbors into the device
    cache, so the next pan step's raw planes are already resident."""
    from omero_ms_image_region_tpu.io.service import PixelsService
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.ops.lut import LutProvider
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.handler import (
        ImageRegionHandler, ImageRegionServices, Renderer,
    )
    from omero_ms_image_region_tpu.services.cache import (
        CacheConfig, Caches,
    )
    from omero_ms_image_region_tpu.services.metadata import (
        CanReadMemo, LocalMetadataService,
    )
    from omero_ms_image_region_tpu.services.prefetch import TilePrefetcher

    rng = np.random.default_rng(1)
    planes = rng.integers(0, 60000, size=(1, 1, 64, 64)).astype(np.uint16)
    build_pyramid(planes, str(tmp_path / "4"), chunk=(16, 16), n_levels=1)
    cache = DeviceRawCache()
    prefetcher = TilePrefetcher(cache)
    services = ImageRegionServices(
        pixels_service=PixelsService(str(tmp_path)),
        metadata=LocalMetadataService(str(tmp_path)),
        caches=Caches.from_config(CacheConfig.enabled_all()),
        can_read_memo=CanReadMemo(),
        renderer=Renderer(),
        lut_provider=LutProvider(),
        raw_cache=cache,
        prefetcher=prefetcher,
        cpu_fallback_max_px=0,   # small test tiles must use the device path
    )
    handler = ImageRegionHandler(services)
    ctx = ImageRegionCtx.from_params({
        "imageId": "4", "theZ": "0", "theT": "0", "m": "c",
        "tile": "0,1,1,16,16", "c": "1|0:60000$FF0000", "format": "png",
    })
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(handler.render_image_region(ctx))
    finally:
        loop.close()
    prefetcher.flush()
    # Interior tile: all four lattice neighbors staged + the tile itself.
    assert prefetcher.scheduled == 4
    assert len(cache) == 5
    # Warm viewport: resident neighbors schedule no new pool work.
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(handler.render_image_region(
            ImageRegionCtx.from_params({
                "imageId": "4", "theZ": "0", "theT": "0", "m": "c",
                "tile": "0,1,1,16,16", "c": "1|0:50000$FF0000",
                "format": "png",
            })))
    finally:
        loop.close()
    prefetcher.flush()
    assert prefetcher.scheduled == 4
    prefetcher.close()


def test_settings_change_rerenders_from_device(tmp_path):
    """Two requests for one tile with different windows: the raw read and
    the host->device transfer happen once."""
    from omero_ms_image_region_tpu.io.service import PixelsService
    from omero_ms_image_region_tpu.io.store import build_pyramid
    from omero_ms_image_region_tpu.ops.lut import LutProvider
    from omero_ms_image_region_tpu.server.ctx import ImageRegionCtx
    from omero_ms_image_region_tpu.server.handler import (
        ImageRegionHandler, ImageRegionServices, Renderer,
    )
    from omero_ms_image_region_tpu.services.cache import (
        CacheConfig, Caches,
    )
    from omero_ms_image_region_tpu.services.metadata import (
        CanReadMemo, LocalMetadataService,
    )

    rng = np.random.default_rng(0)
    planes = rng.integers(0, 60000, size=(2, 1, 32, 32)).astype(np.uint16)
    build_pyramid(planes, str(tmp_path / "3"), chunk=(16, 16), n_levels=1)
    cache = DeviceRawCache()
    services = ImageRegionServices(
        pixels_service=PixelsService(str(tmp_path)),
        metadata=LocalMetadataService(str(tmp_path)),
        caches=Caches.from_config(CacheConfig.enabled_all()),
        can_read_memo=CanReadMemo(),
        renderer=Renderer(),
        lut_provider=LutProvider(),
        raw_cache=cache,
        cpu_fallback_max_px=0,   # small test tiles must use the device path
    )
    handler = ImageRegionHandler(services)

    def ctx(window):
        return ImageRegionCtx.from_params({
            "imageId": "3", "theZ": "0", "theT": "0", "m": "c",
            "c": f"1|0:{window}$FF0000", "format": "jpeg",
        })

    loop = asyncio.new_event_loop()
    try:
        first = loop.run_until_complete(
            handler.render_image_region(ctx(60000)))
        second = loop.run_until_complete(
            handler.render_image_region(ctx(30000)))
    finally:
        loop.close()
    assert first[:2] == second[:2] == b"\xff\xd8"
    assert first != second                 # different windows, new render
    assert cache.misses == 1 and cache.hits == 1
