"""Region/pyramid geometry vs the reference's own test expectations
(ImageRegionRequestHandlerTest.java:202-618)."""

import pytest

from omero_ms_image_region_tpu.server.region import (
    RegionDef,
    clamp_region_to_plane,
    flip_region,
    get_region_def,
    truncate_region,
)

LEVELS_1024 = [[1024, 1024]]
MAX_TILE = 2048


def test_tile_default_size():
    # testGetRegionDefCtxTile: tile (2,2) with no w/h uses image tile size.
    rd = get_region_def(LEVELS_1024, None, RegionDef(2, 2, 0, 0), None,
                        (256, 256), MAX_TILE)
    assert rd.as_tuple() == (512, 512, 256, 256)


def test_tile_with_width_and_height():
    rd = get_region_def(LEVELS_1024, None, RegionDef(2, 2, 64, 128), None,
                        (64, 128), MAX_TILE)
    assert rd.as_tuple() == (128, 256, 64, 128)


def test_tile_clamped_to_max_tile_length():
    rd = get_region_def([[8192, 8192]], None, RegionDef(0, 0, 4096, 4096),
                        None, (256, 256), MAX_TILE)
    assert rd.width == MAX_TILE and rd.height == MAX_TILE


def test_region_passthrough():
    rd = get_region_def(LEVELS_1024, None, None, RegionDef(512, 512, 256, 256),
                        (256, 256), MAX_TILE)
    assert rd.as_tuple() == (512, 512, 256, 256)


def test_no_tile_or_region_full_plane():
    rd = get_region_def(LEVELS_1024, None, None, None, (256, 256), MAX_TILE)
    assert rd.as_tuple() == (0, 0, 1024, 1024)


def test_full_plane_uses_selected_resolution():
    rd = get_region_def([[256, 256], [1024, 1024]], 0, None, None,
                        (256, 256), MAX_TILE)
    assert rd.as_tuple() == (0, 0, 256, 256)


@pytest.mark.parametrize(
    "region,expect",
    [
        # testGetRegionDefCtxRegionTruncX/Y/XY at 1024^2
        (RegionDef(768, 0, 512, 512), (768, 0, 256, 512)),
        (RegionDef(0, 768, 512, 512), (0, 768, 512, 256)),
        (RegionDef(768, 768, 512, 512), (768, 768, 256, 256)),
    ],
)
def test_region_truncation(region, expect):
    rd = get_region_def(LEVELS_1024, None, None, region, (256, 256), MAX_TILE)
    assert rd.as_tuple() == expect


def test_tile_truncation():
    # Edge tile of a non-tile-aligned dimension.
    rd = get_region_def(LEVELS_1024, None, RegionDef(3, 0, 0, 0), None,
                        (300, 300), MAX_TILE)
    assert rd.as_tuple() == (900, 0, 124, 300)


def test_flip_region_h():
    rd = RegionDef(0, 0, 256, 256)
    flip_region(1024, 1024, rd, True, False)
    assert rd.as_tuple() == (768, 0, 256, 256)


def test_flip_region_v():
    rd = RegionDef(0, 0, 256, 256)
    flip_region(1024, 1024, rd, False, True)
    assert rd.as_tuple() == (0, 768, 256, 256)


def test_flip_region_hv():
    rd = RegionDef(128, 256, 256, 128)
    flip_region(1024, 1024, rd, True, True)
    assert rd.as_tuple() == (640, 640, 256, 128)


def test_flip_mirror_x_edge_non_aligned():
    """testFlipRegionDefMirorXEdge: 768^2 image, 512-tiles, flip H —
    truncation happens BEFORE mirroring, so edge tiles land at x=0."""
    levels = [[768, 768]]
    cases = [
        (RegionDef(0, 0, 1024, 1024), (0, 0, 768, 768)),
        (RegionDef(512, 0, 512, 512), (0, 0, 256, 512)),
        (RegionDef(0, 512, 512, 512), (256, 512, 512, 256)),
        (RegionDef(512, 512, 512, 512), (0, 512, 256, 256)),
    ]
    for region, expect in cases:
        rd = get_region_def(levels, None, None, region, (512, 512),
                            MAX_TILE, flip_horizontal=True)
        assert rd.as_tuple() == expect, (region, rd)


def test_flip_mirror_y_edge_non_aligned():
    levels = [[768, 768]]
    rd = get_region_def(levels, None, None, RegionDef(0, 512, 512, 512),
                        (512, 512), MAX_TILE, flip_vertical=True)
    assert rd.as_tuple() == (0, 0, 512, 256)


def test_region_def_indexes_levels_largest_first():
    # The reference's testSelectResolution: a largest-first level list is
    # indexed directly by the request resolution (its n-res-1 inversion is
    # buffer-order-specific and intentionally absent here; see
    # server.region NOTE).
    levels = [[1024, 1024], [256, 512]]
    rd = get_region_def(levels, 1, None, RegionDef(100, 200, 400, 500),
                        (800, 800), MAX_TILE)
    assert rd.as_tuple() == (100, 200, 256 - 100, 512 - 200)


def test_clamp_region_to_plane():
    rd = RegionDef(512, 0, 1024, 1024)
    clamp_region_to_plane([[1024, 768]], None, rd)
    assert rd.as_tuple() == (512, 0, 512, 768)
    assert clamp_region_to_plane([[64, 64]], None, None) is None


def test_truncate_region_noop_when_inside():
    rd = RegionDef(0, 0, 100, 100)
    truncate_region(1024, 1024, rd)
    assert rd.as_tuple() == (0, 0, 100, 100)
